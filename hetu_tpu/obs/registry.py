"""Process-wide metrics registry: labeled counters, gauges, histograms.

The always-on telemetry layer the reference stack lacks a TPU-native
equivalent of: Hetu ships per-node timer subexecutors and an op-level
profiler (SURVEY §5.1) — offline tools — while the HET cache-enabled PS
(VLDB'22) lives or dies by hit-rate and staleness telemetry in
*production*.  This registry is the scrapeable surface for all of it:

- ``Counter`` / ``Gauge`` / ``Histogram`` families, optionally labeled;
  children are cached per label-value tuple, so the hot path is one dict
  hit plus a guarded add.
- ``snapshot()`` flattens every sample into a ``{sample_key: value}``
  dict (histograms expand into ``_bucket``/``_sum``/``_count`` samples);
  ``delta(new, old)`` subtracts monotonic samples and passes gauges
  through — the form chaos tests assert exact values on.
- ``render_prometheus()`` emits text exposition format 0.0.4 (scraped by
  the ``obs.server`` ``/metrics`` endpoint).
- ``export_jsonl()`` appends one timestamped snapshot line per call.

Disabling (``obs.disable()`` or ``HETU_OBS=0``) turns every mutator into
an immediate return — one module-global load and branch — so the
instrumented production seams (PS RPCs, ``Trainer.step``, checkpoint
writes) cost nothing measurable when telemetry is off.  Counters count
*events*, so under a seeded ``FaultPlan`` two runs produce identical
snapshots (latency histograms share bucket *counts* only when the
workload is deterministic; their ``_sum`` is wall time and is not).
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from typing import Iterable, Optional, Sequence

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "get_registry", "enabled", "enable", "disable",
           "DEFAULT_BUCKETS"]

# Master switch.  Checked by every mutator (and by the instrumentation
# sites before they do any timing work), so disabled telemetry is one
# global load + branch on the hot paths.
_ENABLED = os.environ.get("HETU_OBS", "1") not in ("0", "false", "off")


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


# Latency-oriented default buckets (seconds): 100 µs .. 10 s, roughly
# log-spaced, matching the spread from a cache-hit RPC to a jit compile.
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, +Inf/NaN spelled."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def _sample_key(name: str, labelnames: Sequence[str],
                labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in zip(labelnames, labelvalues))
    return f"{name}{{{inner}}}"


class _Child:
    """One (family, label-values) time series."""

    __slots__ = ("_lock", "_labelvalues")

    def __init__(self, labelvalues: tuple):
        self._lock = threading.Lock()
        self._labelvalues = labelvalues


class Counter(_Child):
    """Monotonic counter.  ``set_total`` mirrors an external cumulative
    source (the C cache engine's hit/miss counters) without losing
    counter semantics in the exposition."""

    __slots__ = ("_value",)

    def __init__(self, labelvalues: tuple = ()):
        super().__init__(labelvalues)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not _ENABLED:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    def set_total(self, total: float) -> None:
        """Adopt an externally-maintained cumulative total (must be
        monotonic from the source's side; values below the current one
        are kept — the source restarted, the series must not go back)."""
        if not _ENABLED:
            return
        with self._lock:
            if total > self._value:
                self._value = float(total)

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Child):
    __slots__ = ("_value",)

    def __init__(self, labelvalues: tuple = ()):
        super().__init__(labelvalues)
        self._value = 0.0

    def set(self, value: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Histogram(_Child):
    """Fixed-bucket histogram (cumulative ``le`` buckets, Prometheus
    style).  Bucket bounds are frozen at family creation."""

    __slots__ = ("_bounds", "_counts", "_sum", "_count")

    def __init__(self, labelvalues: tuple = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(labelvalues)
        self._bounds = tuple(buckets)
        self._counts = [0] * (len(self._bounds) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        if not _ENABLED:
            return
        v = float(value)
        i = 0
        for i, b in enumerate(self._bounds):  # noqa: B007
            if v <= b:
                break
        else:
            i = len(self._bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> list:
        """[(le_bound, cumulative_count)] including the +Inf bucket."""
        out, acc = [], 0
        with self._lock:
            for b, c in zip(self._bounds, self._counts):
                acc += c
                out.append((b, acc))
            out.append((math.inf, acc + self._counts[-1]))
        return out

    @staticmethod
    def quantile_from_cumulative(cum_before, cum_after, q: float):
        """Quantile from the delta of two :meth:`cumulative` snapshots.
        Prometheus-style linear interpolation inside the winning bucket.
        Edge semantics are pinned down (this now backs both the bench
        and the serve ``/stats`` SLO summary, so "whatever falls out"
        is not acceptable):

        - an EMPTY delta (nothing observed) returns ``nan`` — never a
          number a dashboard could mistake for a latency;
        - the +Inf bucket reports its lower edge (the largest finite
          bound, or 0.0 for a bucketless histogram) — deterministic,
          never +Inf itself;
        - a single-bucket histogram degenerates to interpolation inside
          that one bucket, its upper bound at q=1.

        The single quantile implementation in the tree — ``bench.py
        --mode serve`` and the serving ``/stats`` summary both call
        through here."""
        delta = [(le, a - b)
                 for (le, a), (_, b) in zip(cum_after, cum_before)]
        total = delta[-1][1]
        if total <= 0:
            return math.nan
        rank = q * total
        prev_le, prev_c = 0.0, 0
        for le, c in delta:
            if c >= rank:
                if le == math.inf:
                    return prev_le
                if c == prev_c:
                    return le
                return prev_le + (le - prev_le) * (rank - prev_c) / (c - prev_c)
            prev_le, prev_c = (le if le != math.inf else prev_le), c
        return delta[-1][0]

    def quantile(self, q: float, since=None):
        """Quantile over everything observed since ``since`` (a
        :meth:`cumulative` snapshot taken earlier; default: since the
        histogram was created)."""
        cum = self.cumulative()
        if since is None:
            since = [(le, 0) for le, _c in cum]
        return self.quantile_from_cumulative(since, cum, q)


_CHILD_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """A named metric with a fixed label schema; children per label-value
    tuple.  An unlabeled family proxies its single child's mutators, so
    ``reg.counter("x").inc()`` works without a ``labels()`` hop."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Sequence[str],
                 buckets: Optional[Sequence[float]] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets is not None else None
        self._children: dict = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            self.labels()  # materialize the single child eagerly

    def _resolve(self, values, kv) -> tuple:
        if kv:
            if values:
                raise ValueError("pass label values positionally or by "
                                 "name, not both")
            if set(kv) != set(self.labelnames):
                raise ValueError(
                    f"{self.name}: expected labels {self.labelnames}, "
                    f"got {tuple(kv)}")
            values = tuple(str(kv[ln]) for ln in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}")
        return values

    def labels(self, *values, **kv) -> _Child:
        values = self._resolve(values, kv)
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.get(values)
                if child is None:
                    if self.kind == "histogram":
                        child = Histogram(values, self.buckets)
                    else:
                        child = _CHILD_TYPES[self.kind](values)
                    self._children[values] = child
        return child

    def remove(self, *values, **kv) -> bool:
        """Drop the child time series for these label values — elastic
        membership support: a worker that left the gang should disappear
        from scrapes and snapshots instead of freezing at its last value.
        Returns True when a child existed.  A later ``labels()`` call
        with the same values starts a fresh series from zero (correct
        for a *rejoining* member's gauges; do not use this on counters
        whose continuity matters)."""
        values = self._resolve(values, kv)
        with self._lock:
            return self._children.pop(values, None) is not None

    # unlabeled convenience proxies
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def set_total(self, total: float) -> None:
        self.labels().set_total(total)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    @property
    def value(self) -> float:
        return self.labels().value


class MetricsRegistry:
    """Thread-safe collection of metric families.

    ``counter``/``gauge``/``histogram`` are idempotent: asking for an
    existing name returns the existing family (and raises if the kind or
    label schema disagrees), so instrumentation sites can declare their
    metrics lazily without coordinating.
    """

    def __init__(self):
        self._families: dict = {}
        self._lock = threading.Lock()

    def _family(self, name: str, kind: str, help: str,
                labelnames: Sequence[str],
                buckets: Optional[Sequence[float]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = _Family(name, kind, help, labelnames, buckets)
                    self._families[name] = fam
                    return fam
        if fam.kind != kind or fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind} with "
                f"labels {fam.labelnames}; cannot re-register as {kind} "
                f"with labels {tuple(labelnames)}")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, labelnames, buckets)

    def clear(self) -> None:
        """Drop every family (tests; production registries only grow)."""
        with self._lock:
            self._families.clear()

    # -- read side ----------------------------------------------------------

    def snapshot(self) -> dict:
        """Flat ``{sample_key: value}`` over every sample, in registration
        order.  Histogram children expand the same way the text exposition
        does: ``name_bucket{le=...}``, ``name_sum``, ``name_count``."""
        out: dict = {}
        for fam in list(self._families.values()):
            with fam._lock:  # vs. concurrent labels() child creation
                children = sorted(fam._children.items())
            for values, child in children:
                if fam.kind == "histogram":
                    for le, acc in child.cumulative():
                        key = _sample_key(
                            fam.name + "_bucket",
                            fam.labelnames + ("le",),
                            values + (_fmt(le),))
                        out[key] = float(acc)
                    out[_sample_key(fam.name + "_sum", fam.labelnames,
                                    values)] = child.sum
                    out[_sample_key(fam.name + "_count", fam.labelnames,
                                    values)] = float(child.count)
                else:
                    out[_sample_key(fam.name, fam.labelnames,
                                    values)] = child.value
        return out

    def dump(self) -> dict:
        """Structured, JSON-serializable export of every family — schema
        (kind, help, label names, histogram bucket bounds) plus raw child
        state (per-bucket counts, not cumulative).  This is the form one
        process can hand another for re-aggregation: ``obs.fleet``
        publishes it in worker snapshots and merges it back under a
        ``worker`` label, which the flat :meth:`snapshot` sample keys
        could only support by re-parsing."""
        fams = []
        for fam in list(self._families.values()):
            with fam._lock:  # vs. concurrent labels() child creation
                children = sorted(fam._children.items())
            ent = {"name": fam.name, "kind": fam.kind, "help": fam.help,
                   "labelnames": list(fam.labelnames)}
            if fam.kind == "histogram":
                ent["buckets"] = list(fam.buckets)
            kids = []
            for values, child in children:
                if fam.kind == "histogram":
                    with child._lock:
                        kids.append({"labels": list(values),
                                     "counts": list(child._counts),
                                     "sum": child._sum,
                                     "count": child._count})
                else:
                    kids.append({"labels": list(values),
                                 "value": child.value})
            ent["children"] = kids
            fams.append(ent)
        return {"families": fams}

    def delta(self, new: dict, old: dict) -> dict:
        """Difference of two :meth:`snapshot` dicts: monotonic samples
        (counters, histogram buckets/sums/counts) subtract, gauges pass
        through at their new value.  Samples absent from ``old`` count
        from zero."""
        gauge_names = {f.name for f in self._families.values()
                       if f.kind == "gauge"}
        out = {}
        for key, val in new.items():
            base = key.split("{", 1)[0]
            if base in gauge_names:
                out[key] = val
            else:
                out[key] = val - old.get(key, 0.0)
        return out

    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4 (``/metrics``)."""
        lines = []
        for fam in list(self._families.values()):
            if fam.help:
                help_text = fam.help.replace("\\", "\\\\").replace(
                    "\n", "\\n")
                lines.append(f"# HELP {fam.name} {help_text}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            with fam._lock:  # vs. concurrent labels() child creation
                children = sorted(fam._children.items())
            for values, child in children:
                if fam.kind == "histogram":
                    for le, acc in child.cumulative():
                        lines.append(
                            f"{_sample_key(fam.name + '_bucket', fam.labelnames + ('le',), values + (_fmt(le),))}"
                            f" {acc}")
                    lines.append(
                        f"{_sample_key(fam.name + '_sum', fam.labelnames, values)} {_fmt(child.sum)}")
                    lines.append(
                        f"{_sample_key(fam.name + '_count', fam.labelnames, values)} {child.count}")
                else:
                    lines.append(
                        f"{_sample_key(fam.name, fam.labelnames, values)} "
                        f"{_fmt(child.value)}")
        return "\n".join(lines) + "\n"

    def export_jsonl(self, path_or_file, extra: Optional[dict] = None) -> dict:
        """Append one JSON line — ``{"ts": ..., "metrics": snapshot()}``
        plus ``extra`` keys — to ``path_or_file``; returns the record.
        Call on a cadence for a poor-man's on-disk time series."""
        rec = {"ts": time.time(), "metrics": self.snapshot()}
        if extra:
            rec.update(extra)
        line = json.dumps(rec) + "\n"
        if hasattr(path_or_file, "write"):
            path_or_file.write(line)
        else:
            with open(path_or_file, "a") as f:
                f.write(line)
        return rec


# The process-wide default registry every instrumentation seam writes to.
_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default
