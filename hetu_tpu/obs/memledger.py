"""The HBM ledger: exact, bitwise-deterministic device-memory attribution.

The reference stack dedicates a whole subsystem (``src/memory_pool/``, a
BFC allocator) to knowing where device memory lives, because at scale
HBM is the binding constraint.  This module is the rebuild's equivalent,
built observability-first: a process-wide :class:`MemoryLedger` that
attributes every accounted HBM byte to a **component**

- ``kv_pool`` — :class:`~hetu_tpu.serve.kv_cache.KVCachePool` pages, by
  class (``active | shared_prefix | export_hold | scratch | free``, the
  exact partition ``KVCachePool.page_classes`` computes) and by owner
  (per-tenant table-page holds, the PR 16 identity);
- ``embed_hbm`` — :class:`~hetu_tpu.embed.tier.TieredEmbedding` resident
  hot rows (rows × dim × 4, the f32 HBM tier);
- ``train_weights`` / ``train_optimizer`` — the train step's pytree
  (every array leaf's ``size × itemsize``);
- ``compile`` — executable + temp bytes per instrumented jit site, from
  ``compiled.memory_analysis()`` (``obs.compile.InstrumentedJit``);

fed through instrumented seams (:func:`note_kv`, :func:`note_embed`,
:func:`note_compile`, :func:`note_train_state`) that follow the obs
overhead contract: with no ledger installed (or telemetry disabled) each
seam is one module-global load and a branch.

The ledger is **exact by construction**: every :meth:`~MemoryLedger.
snapshot` asserts that the per-class KV bytes sum to the pool's array
bytes (``k.nbytes + v.nbytes``) — attribution can never silently drop or
double-count a page.  It carries per-component high-water marks, a
free-list fragmentation gauge, and an alloc/free-balance **leak
watchdog**: the seams post alloc/free *events*, the ledger integrates
the balance and cross-checks it against the pool's own live-sequence
count; a drift sustained for ``leak_grace`` snapshots journals
``mem_leak_suspect`` naming the component — an unledgered free path (or
a skipped free) is named, not inferred from an OOM hours later.

Served at ``/memory`` (``obs.server.telemetry_routes``), fleet-merged at
``/fleet/memory`` (``obs.fleet.FleetAggregator.memory``), reconciled
against ``mem.estimator`` predictions via :meth:`~MemoryLedger.
reconcile` (extending PR 12's ``reconcile`` → ``mem_estimate_drift``),
ingested into the calibration :class:`~hetu_tpu.obs.calibration.
ProfileStore` via ``ingest_memory``, and exposed to the
:class:`~hetu_tpu.exec.controller.RuntimeController` as the
:meth:`~MemoryLedger.memory_pressure` signal its ``memory_pressure``
remediation loop acts on (defrag, then shed).

Snapshots contain no wall-clock state and iterate every map in sorted
order, so same-seed replays produce bitwise-identical snapshots — the
chaos acceptance bar.
"""

from __future__ import annotations

import contextlib
import weakref
from typing import Optional

import numpy as np

from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import registry as _registry

__all__ = ["MemoryLedger", "get_ledger", "install_ledger", "use",
           "note_kv", "note_embed", "note_compile", "note_train_state",
           "KV_PAGE_CLASSES"]

#: The exact KV page partition (KVCachePool.page_classes): every physical
#: page lands in exactly one class, counts sum to ``num_pages``.
KV_PAGE_CLASSES = ("active", "shared_prefix", "export_hold", "scratch",
                   "free")

# Ledger metric families, built on first publication (never while
# telemetry is disabled — the disabled path must register nothing).
_led_metrics = None


def _led_m() -> dict:
    global _led_metrics
    if _led_metrics is None:
        reg = _registry.get_registry()
        _led_metrics = {
            "component": reg.gauge(
                "hetu_memledger_component_bytes",
                "ledger-attributed resident device bytes by component "
                "(kv_pool, embed_hbm, compile, train_weights, "
                "train_optimizer)", ("component",)),
            "hwm": reg.gauge(
                "hetu_memledger_hwm_bytes",
                "per-component high-water mark of the ledger-attributed "
                "bytes since install (plus the 'total' series)",
                ("component",)),
            "kv_class": reg.gauge(
                "hetu_memledger_kv_class_bytes",
                "KV-pool bytes by page class, summed across tracked "
                "pools — the exact partition (classes sum to the pool "
                "arrays' bytes)", ("klass",)),
            "frag": reg.gauge(
                "hetu_memledger_kv_fragmentation",
                "free-list fragmentation of the worst tracked pool: "
                "1 - longest contiguous free run / free pages (0 = one "
                "contiguous run or an empty free list)"),
            "total": reg.gauge(
                "hetu_memledger_total_bytes",
                "sum of all ledger-attributed component bytes"),
            "pressure": reg.gauge(
                "hetu_memledger_pressure",
                "worst-pool used-page fraction — the ledger-backed "
                "signal the controller's memory_pressure loop acts on"),
            "allocs": reg.counter(
                "hetu_memledger_allocs_total",
                "sequence allocations the instrumented seams posted, by "
                "component", ("component",)),
            "frees": reg.counter(
                "hetu_memledger_frees_total",
                "sequence frees the instrumented seams posted, by "
                "component", ("component",)),
            "leaks": reg.counter(
                "hetu_memledger_leak_suspects_total",
                "mem_leak_suspect verdicts the watchdog journaled, by "
                "component", ("component",)),
        }
    return _led_metrics


def _fragmentation(free_sorted) -> float:
    """1 - longest contiguous run / free count over an ascending free
    list (0.0 when empty or fully contiguous) — the defrag trigger."""
    n = len(free_sorted)
    if n == 0:
        return 0.0
    longest = run = 1
    for a, b in zip(free_sorted, free_sorted[1:]):
        run = run + 1 if b == a + 1 else 1
        if run > longest:
            longest = run
    return 1.0 - longest / n


def _pool_page_bytes(pool) -> int:
    """Device bytes one physical page holds across k AND v."""
    itemsize = int(np.dtype(pool.k.dtype).itemsize)
    return (pool.num_layers * pool.page_size * pool.num_heads
            * pool.head_dim * itemsize * 2)


def _tree_bytes(tree) -> int:
    """size × itemsize over every array leaf of a pytree."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * int(np.dtype(dtype).itemsize)
    return total


class MemoryLedger:
    """Process-wide device-byte attribution (see module doc).

    State is integrated from the seams (alloc/free events, embed
    residency, compile memory analyses, train-state bytes) plus live
    reads of the tracked pools at snapshot time — so the byte
    attribution is exact by construction, while the event balance
    cross-check catches code paths that mutate a pool without posting.
    Pools are keyed by ARRIVAL ORDER per ledger (``"0"``, ``"1"``, …),
    so a fresh ledger per same-seed replay yields identical keys.
    """

    def __init__(self, *, leak_grace: int = 3):
        if leak_grace < 1:
            raise ValueError(f"leak_grace must be >= 1, got {leak_grace}")
        self.leak_grace = int(leak_grace)
        self._pools: list = []        # weakref.ref, arrival order
        self._pool_index: dict = {}   # id(pool) -> index
        self._kv_events: dict = {}    # index -> {"allocs", "frees"}
        self._embed: dict = {}        # table -> {"rows", "bytes"}
        self._compile: dict = {}      # site -> {"executable_bytes",
        #                                        "temp_bytes", "programs"}
        self._train = {"weights_bytes": 0, "optimizer_bytes": 0}
        self._hwm: dict = {}          # component -> bytes
        self._leak_streak: dict = {}  # component -> drifting snapshots
        self._leak_flagged: set = set()
        self.leak_suspects: list = []
        self.snapshots = 0

    # -- the seams' write side ----------------------------------------------

    def _track(self, pool) -> int:
        idx = self._pool_index.get(id(pool))
        if idx is not None and self._pools[idx]() is pool:
            return idx
        # new pool (or a reused id after gc): next arrival-order slot
        idx = len(self._pools)
        self._pools.append(weakref.ref(pool))
        self._pool_index[id(pool)] = idx
        self._kv_events[idx] = {"allocs": 0, "frees": 0,
                                "peak_used_pages": 0,
                                "peak_shared_pages": 0}
        return idx

    def note_kv(self, pool, *, alloc: int = 0, free: int = 0) -> None:
        """One KV-pool mutation: track the pool, integrate alloc/free
        events (the watchdog's balance), and advance the peak-occupancy
        mark.  Byte attribution itself is read live from the pool at
        snapshot time.  The shared-page count (an O(live pages) scan) is
        taken only when a NEW peak is set — peaks are monotone, so the
        scan runs at most ``num_pages`` times over a pool's lifetime."""
        ev = self._kv_events[self._track(pool)]
        ev["allocs"] += int(alloc)
        ev["frees"] += int(free)
        used = (pool.num_pages - 1) - pool.free_pages
        if used > ev["peak_used_pages"]:
            ev["peak_used_pages"] = int(used)
            ev["peak_shared_pages"] = sum(
                1 for rc in pool._refcount.values() if rc > 1)

    def note_embed(self, table: str, rows: int, nbytes: int) -> None:
        """Resident HBM hot rows of one embedding table (exact: the
        staging protocol's own residency map)."""
        self._embed[str(table)] = {"rows": int(rows), "bytes": int(nbytes)}

    def note_compile(self, site: str, memory: dict) -> None:
        """One compiled program at an instrumented jit site: executable
        bytes ACCUMULATE (every program stays resident in the AOT
        cache), temp bytes take the site max (transient workspace of the
        largest program)."""
        ent = self._compile.setdefault(
            str(site), {"executable_bytes": 0, "temp_bytes": 0,
                        "programs": 0})
        ent["executable_bytes"] += int(memory.get("generated_code", 0))
        ent["temp_bytes"] = max(ent["temp_bytes"],
                                int(memory.get("temp", 0)))
        ent["programs"] += 1

    def note_train_state(self, state) -> None:
        """Model weights + optimizer state bytes from the train state's
        pytree (every array leaf's ``size × itemsize``)."""
        self._train = {
            "weights_bytes": _tree_bytes(state.model),
            "optimizer_bytes": _tree_bytes(state.opt_state),
        }

    # -- the read side -------------------------------------------------------

    def _live_pools(self) -> list:
        return [(i, p) for i, r in enumerate(self._pools)
                if (p := r()) is not None]

    def memory_pressure(self) -> float:
        """Worst-pool used-page fraction in [0, 1] (0.0 with no tracked
        pools) — the controller's remediation signal."""
        worst = 0.0
        for _i, pool in self._live_pools():
            cap = pool.num_pages - 1
            if cap > 0:
                worst = max(worst, (cap - pool.free_pages) / cap)
        return worst

    def _watchdog(self, component: str, balance: int, drift: int) -> None:
        if drift != 0:
            streak = self._leak_streak.get(component, 0) + 1
            self._leak_streak[component] = streak
            if streak >= self.leak_grace \
                    and component not in self._leak_flagged:
                self._leak_flagged.add(component)
                suspect = {"component": component, "drift": int(drift),
                           "balance": int(balance)}
                self.leak_suspects.append(suspect)
                _journal.record("mem_leak_suspect", **suspect)
                if _registry.enabled():
                    _led_m()["leaks"].labels(component=component).inc()
        else:
            self._leak_streak[component] = 0
            self._leak_flagged.discard(component)

    def snapshot(self) -> dict:
        """The ``/memory`` payload: per-component bytes, per-pool page
        classes / tenants / fragmentation / event balance, high-water
        marks, and the watchdog's suspects — with the exactness
        invariant ASSERTED (attributed bytes == pool array bytes).
        Deterministic: sorted iteration, integer bytes, no wall clock —
        same-seed replays snapshot bitwise-identically."""
        self.snapshots += 1
        kv_pools: dict = {}
        class_bytes = {c: 0 for c in KV_PAGE_CLASSES}
        kv_total = 0
        frag_worst = 0.0
        for idx, pool in self._live_pools():
            page_bytes = _pool_page_bytes(pool)
            classes = pool.page_classes()
            array_bytes = int(pool.k.nbytes) + int(pool.v.nbytes)
            attributed = sum(classes.values()) * page_bytes
            assert attributed == pool.num_pages * page_bytes \
                == array_bytes, \
                (f"ledger attribution leak on pool {idx}: "
                 f"{sum(classes.values())} classed pages x {page_bytes} "
                 f"= {attributed} bytes != pool arrays' {array_bytes}")
            ev = self._kv_events[idx]
            balance = ev["allocs"] - ev["frees"]
            drift = balance - pool.live_sequences
            frag = _fragmentation(pool._free)
            frag_worst = max(frag_worst, frag)
            cap = pool.num_pages - 1
            used = cap - pool.free_pages
            kv_pools[str(idx)] = {
                "page_bytes": int(page_bytes),
                "bytes_total": int(array_bytes),
                "pages_by_class": {c: int(classes[c])
                                   for c in KV_PAGE_CLASSES},
                "bytes_by_class": {c: int(classes[c] * page_bytes)
                                   for c in KV_PAGE_CLASSES},
                "pages_by_tenant": pool.pages_by_tenant(),
                "used_fraction": used / cap if cap else 0.0,
                "peak_used_pages": int(ev["peak_used_pages"]),
                "peak_shared_pages": int(ev["peak_shared_pages"]),
                "peak_used_fraction": (ev["peak_used_pages"] / cap
                                       if cap else 0.0),
                "fragmentation": frag,
                "allocs": int(ev["allocs"]),
                "frees": int(ev["frees"]),
                "balance": int(balance),
                "live_sequences": int(pool.live_sequences),
                "drift": int(drift),
            }
            for c in KV_PAGE_CLASSES:
                class_bytes[c] += int(classes[c] * page_bytes)
            kv_total += array_bytes
            self._watchdog(f"kv_pool:{idx}", balance, drift)
        components = {
            "compile": sum(e["executable_bytes"] + e["temp_bytes"]
                           for e in self._compile.values()),
            "embed_hbm": sum(e["bytes"] for e in self._embed.values()),
            "kv_pool": int(kv_total),
            "train_optimizer": int(self._train["optimizer_bytes"]),
            "train_weights": int(self._train["weights_bytes"]),
        }
        total = sum(components.values())
        for comp, b in list(components.items()) + [("total", total)]:
            if b > self._hwm.get(comp, 0):
                self._hwm[comp] = int(b)
        pressure = self.memory_pressure()
        if _registry.enabled():
            m = _led_m()
            for comp in sorted(components):
                m["component"].labels(component=comp).set(
                    float(components[comp]))
            for comp in sorted(self._hwm):
                m["hwm"].labels(component=comp).set(
                    float(self._hwm[comp]))
            for c in KV_PAGE_CLASSES:
                m["kv_class"].labels(klass=c).set(float(class_bytes[c]))
            m["frag"].set(frag_worst)
            m["total"].set(float(total))
            m["pressure"].set(pressure)
            for idx, _pool in self._live_pools():
                ev = self._kv_events[idx]
                comp = f"kv_pool:{idx}"
                m["allocs"].labels(component=comp).set_total(
                    float(ev["allocs"]))
                m["frees"].labels(component=comp).set_total(
                    float(ev["frees"]))
        return {
            "installed": True,
            "snapshots": int(self.snapshots),
            "total_bytes": int(total),
            "components": {c: int(components[c])
                           for c in sorted(components)},
            "hwm_bytes": {c: int(self._hwm[c])
                          for c in sorted(self._hwm)},
            "kv_class_bytes": {c: int(class_bytes[c])
                               for c in KV_PAGE_CLASSES},
            "kv_pools": kv_pools,
            "embed": {t: dict(self._embed[t])
                      for t in sorted(self._embed)},
            "compile_sites": {s: dict(self._compile[s])
                              for s in sorted(self._compile)},
            "train": {k: int(v) for k, v in sorted(self._train.items())},
            "fragmentation": frag_worst,
            "pressure": pressure,
            "leak_suspects": [dict(s) for s in self.leak_suspects],
        }

    def reconcile(self, predicted_bytes: float, *,
                  component: str = "kv_pool", band: Optional[float] = None,
                  model_sig: str = "") -> dict:
        """Reconcile a planner/estimator byte prediction against the
        LEDGER-measured bytes of ``component`` — the same closing move
        (gauge + ``mem_estimate_drift`` outside the band + a calibration
        ``mem`` record) PR 12's :func:`hetu_tpu.mem.estimator.reconcile`
        runs against XLA's ``memory_analysis``, with the ledger as the
        measured side."""
        from hetu_tpu.mem import estimator as _estimator
        snap = self.snapshot()
        measured = snap["components"].get(component, 0)
        kw: dict = {"model_sig": model_sig}
        if band is not None:
            kw["band"] = float(band)
        out = _estimator.reconcile(float(predicted_bytes),
                                   float(measured), **kw)
        out["component"] = component
        out["measured_bytes"] = int(measured)
        return out


# --------------------------------------------------- process-wide seams

_active: Optional[MemoryLedger] = None


def get_ledger() -> Optional[MemoryLedger]:
    return _active


def install_ledger(ledger: Optional[MemoryLedger]
                   ) -> Optional[MemoryLedger]:
    """Install ``ledger`` process-wide (None uninstalls): the sink the
    instrumented seams post to and the object ``/memory`` serves."""
    global _active
    _active = ledger
    return ledger


@contextlib.contextmanager
def use(ledger: MemoryLedger):
    """Install for the block, restore the previous ledger on exit."""
    global _active
    prev = _active
    _active = ledger
    try:
        yield ledger
    finally:
        _active = prev


def note_kv(pool, *, alloc: int = 0, free: int = 0) -> None:
    """The KV-pool mutator seam (alloc/free/retain/release/CoW/defrag/
    export-hold call sites): one module-global load and a branch when no
    ledger is installed or telemetry is disabled."""
    led = _active
    if led is None or not _registry.enabled():
        return
    led.note_kv(pool, alloc=alloc, free=free)


def note_embed(embedding) -> None:
    """The TieredEmbedding.stage seam: resident-row bytes of the HBM
    tier (rows × dim × 4 — the f32 device cache).  Residency is only
    computed past the one-load-and-branch guard."""
    led = _active
    if led is None or not _registry.enabled():
        return
    h = embedding._handle
    rows = int((h.id_of >= 0).sum())
    led.note_embed(embedding.name, rows, rows * int(embedding.dim) * 4)


def note_compile(site: str, memory: dict) -> None:
    """The InstrumentedJit._compile seam: one program's
    ``memory_analysis`` bytes."""
    led = _active
    if led is None or not _registry.enabled():
        return
    if memory:
        led.note_compile(site, memory)


def note_train_state(state) -> None:
    """The Trainer seam (init + state rebind): weights/optimizer bytes
    from the state pytree — walked only past the guard."""
    led = _active
    if led is None or not _registry.enabled():
        return
    led.note_train_state(state)
