"""SLO decomposition engine: per-request stage accounting, burn rates,
and the shed-pressure signal.

``obs.goodput`` made *training* efficiency a scrape by classifying every
unit of step time into buckets that sum to total by construction.  This
module applies the same discipline per serving request: every resolved
:class:`~hetu_tpu.obs.reqtrace.RequestTimeline` is decomposed into the
``queue``/``prefill``/``decode``/``emit`` stages (exact partition — see
reqtrace), graded against the TTFT / TPOT / queue-age targets, and
folded into rolling short+long violation windows from which burn rates
and a shed-pressure gauge are derived.

Targets (:class:`SLOTargets`) come from the constructor or environment:

=========================  ============================================
``HETU_TPU_SLO_TTFT``      time-to-first-token target, seconds
``HETU_TPU_SLO_TPOT``      time-per-output-token target, seconds
                           (decode stage / decode tokens)
``HETU_TPU_SLO_QUEUE``     queue-age target, seconds (admission wait;
                           expiries count against it by definition)
``HETU_TPU_SLO_OBJECTIVE`` the SLO fraction (default 0.99: 1% of
                           requests may violate before the budget is
                           spent)
=========================  ============================================

**Burn rate** is the SRE multi-window form: over a window, ``burn =
violating_fraction / (1 - objective)`` — 1.0 means the error budget is
being consumed exactly at the sustainable rate, N means N× too fast.
Both a short window (default 60 s — fast detection) and a long window
(default 600 s — deduced sustained damage) are kept per target; the
**shed-pressure** gauge is ``clip(max_target min(short, long) /
shed_burn, 0, 1)`` — both windows must burn (the short window alone
spikes on one slow request; the long window alone lags), which is the
standard guard against paging on noise.  1.0 means "shed now"; the
future multi-replica router reads this gauge for placement and
admission decisions, and ``/slo`` (per process) and ``/fleet/slo``
(aggregated) publish it.

**Per-tenant grading** (multi-tenant front door): every timeline whose
attrs carry a ``tenant`` id is additionally folded into per-(tenant,
class) violation windows, yielding :meth:`SLOEngine.tenant_shed_pressure`
— the scoped signal the controller's shed actuator uses to shed the
tenant *causing* the burn instead of everyone.  The aggregate windows
above are untouched (single-tenant runs produce bit-identical burn
state and ``/slo`` payloads); the tenants section appears in
:meth:`SLOEngine.summary` and the ``hetu_tenant_shed_pressure`` gauge
only once a non-default tenant has been observed.

Everything is clock-injectable (the serving engine passes its own
clock), so deterministic tests drive the windows exactly.  All metrics
are lazily registered and no-ops while telemetry is disabled.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Callable, Optional

from hetu_tpu.obs import registry as _registry
from hetu_tpu.obs.reqtrace import STAGES, RequestTimeline

__all__ = ["SLOTargets", "SLOEngine"]

_ENV = {"ttft_s": "HETU_TPU_SLO_TTFT", "tpot_s": "HETU_TPU_SLO_TPOT",
        "queue_age_s": "HETU_TPU_SLO_QUEUE",
        "objective": "HETU_TPU_SLO_OBJECTIVE"}


@dataclasses.dataclass(frozen=True)
class SLOTargets:
    """The serving SLO: latency targets plus the objective fraction."""

    ttft_s: float = 0.5
    tpot_s: float = 0.1
    queue_age_s: float = 0.25
    objective: float = 0.99

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")
        for f in ("ttft_s", "tpot_s", "queue_age_s"):
            if getattr(self, f) <= 0:
                raise ValueError(f"{f} must be positive")

    @classmethod
    def from_env(cls, **overrides) -> "SLOTargets":
        """Targets from the environment (``HETU_TPU_SLO_*``), explicit
        ``overrides`` winning — the production wiring, so a fleet's SLO
        is deployment config, not code."""
        kw = {}
        for field, env in _ENV.items():
            raw = os.environ.get(env)
            if raw is not None:
                kw[field] = float(raw)
        kw.update(overrides)
        return cls(**kw)


#: the graded dimensions, each with its own violation window pair
TARGETS = ("ttft", "tpot", "queue_age")


class _Window:
    """Rolling (timestamp, violated) record over a fixed horizon."""

    __slots__ = ("horizon", "events")

    def __init__(self, horizon: float):
        self.horizon = float(horizon)
        self.events: collections.deque = collections.deque()

    def add(self, now: float, violated: bool) -> None:
        self.events.append((now, bool(violated)))
        self.trim(now)

    def trim(self, now: float) -> None:
        while self.events and now - self.events[0][0] > self.horizon:
            self.events.popleft()

    def fraction(self, now: float) -> float:
        self.trim(now)
        if not self.events:
            return 0.0
        return sum(1 for _, v in self.events if v) / len(self.events)


class SLOEngine:
    """Grades resolved request timelines against the targets and keeps
    the burn-rate / shed-pressure state.  One per serving engine; writes
    to the process registry (``hetu_slo_*``)."""

    def __init__(self, targets: Optional[SLOTargets] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 short_window_s: float = 60.0, long_window_s: float = 600.0,
                 shed_burn: float = 2.0,
                 registry: Optional[_registry.MetricsRegistry] = None):
        self.targets = targets if targets is not None \
            else SLOTargets.from_env()
        self.clock = clock
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        # the burn rate at which shed pressure saturates to 1.0 (burning
        # the error budget `shed_burn`x too fast on BOTH windows)
        self.shed_burn = float(shed_burn)
        self._windows = {t: (_Window(short_window_s), _Window(long_window_s))
                         for t in TARGETS}
        self.stage_totals = dict.fromkeys(STAGES, 0.0)
        self.requests = 0
        self.violations = dict.fromkeys(TARGETS, 0)
        # per-tenant scoped burn state: tenant id -> {target: (short,
        # long)} window pairs, plus class / request / violation rosters.
        # Populated lazily from timeline attrs; a pre-tenant deployment
        # only ever materializes the "default" row.
        self._tenant_windows: dict = {}
        self._tenant_class: dict = {}
        self._tenant_requests: dict = {}
        self._tenant_violations: dict = {}
        self._reg = registry
        self._m = None
        self._lock = threading.Lock()

    def _metrics(self):
        if self._m is None:
            reg = self._reg if self._reg is not None \
                else _registry.get_registry()
            self._m = {
                "stage": reg.counter(
                    "hetu_slo_stage_seconds_total",
                    "request wall time by stage (queue, prefill, decode, "
                    "emit); per request the stages partition wall time "
                    "exactly, so this is total request-seconds by where "
                    "they went", ("stage",)),
                "requests": reg.counter(
                    "hetu_slo_requests_total",
                    "requests graded against the SLO targets, by verdict",
                    ("verdict",)),
                "violations": reg.counter(
                    "hetu_slo_violations_total",
                    "per-target SLO violations (one request can violate "
                    "several targets)", ("target",)),
                "burn": reg.gauge(
                    "hetu_slo_burn_rate",
                    "error-budget burn rate per target and window "
                    "(violating fraction / (1 - objective); 1.0 = "
                    "sustainable)", ("target", "window")),
                "shed": reg.gauge(
                    "hetu_slo_shed_pressure",
                    "admission shed signal in [0, 1]: max over targets of "
                    "min(short, long) burn, normalized by the shed burn "
                    "threshold — the router/admission input"),
                "tenant_shed": reg.gauge(
                    "hetu_tenant_shed_pressure",
                    "per-(tenant, class) admission shed signal in [0, 1] "
                    "— the controller's scoped-shed input; published only "
                    "once a non-default tenant has been observed",
                    ("tenant", "klass")),
            }
        return self._m

    # -- grading ------------------------------------------------------------

    def grade(self, tl: RequestTimeline) -> dict:
        """The per-request verdict WITHOUT recording it (pure): stage
        split, derived latencies, and per-target violation flags."""
        stages = tl.stage_seconds()
        ttft = stages["queue"] + stages["prefill"]
        decode_tokens = max(tl.decode_count() - 1, 0)
        tpot = (stages["decode"] / decode_tokens) if decode_tokens else 0.0
        t = self.targets
        violated = {
            # a never-admitted expiry spent its whole life in the queue:
            # it violates queue_age by definition even if the deadline
            # was short.  A RUNNING-stage expiry does not — charging it
            # here would point the burn rates at admission when the
            # regression is decode.
            "queue_age": (stages["queue"] > t.queue_age_s
                          or (tl.outcome == "expired"
                              and tl.admitted_at is None)),
            "ttft": tl.first_token_at is not None and ttft > t.ttft_s,
            "tpot": tpot > t.tpot_s,
        }
        return {"stages_s": stages, "ttft_s": ttft, "tpot_s": tpot,
                "violated": violated}

    def observe(self, tl: RequestTimeline) -> dict:
        """Grade one resolved timeline and fold it into the counters and
        burn windows; returns the grade."""
        g = self.grade(tl)
        now = self.clock()
        with self._lock:
            enabled = _registry.enabled()
            m = self._metrics() if enabled else None
            self.requests += 1
            any_violation = False
            for stage, dt in g["stages_s"].items():
                self.stage_totals[stage] += dt
                if enabled and dt:
                    m["stage"].labels(stage=stage).inc(dt)
            tid = str(tl.attrs.get("tenant", "default"))
            tw = self._tenant_windows.get(tid)
            if tw is None:
                tw = {t: (_Window(self.short_window_s),
                          _Window(self.long_window_s)) for t in TARGETS}
                self._tenant_windows[tid] = tw
                self._tenant_class[tid] = str(
                    tl.attrs.get("tenant_class", "latency"))
                self._tenant_requests[tid] = 0
                self._tenant_violations[tid] = dict.fromkeys(TARGETS, 0)
            self._tenant_requests[tid] += 1
            for target in TARGETS:
                v = bool(g["violated"][target])
                any_violation |= v
                if v:
                    self.violations[target] += 1
                    self._tenant_violations[tid][target] += 1
                    if enabled:
                        m["violations"].labels(target=target).inc()
                for w in self._windows[target]:
                    w.add(now, v)
                for w in tw[target]:
                    w.add(now, v)
            if enabled:
                m["requests"].labels(
                    verdict="violated" if any_violation else "ok").inc()
                self._publish(now, m)
        return g

    # -- burn / shed --------------------------------------------------------

    def _budget(self) -> float:
        return 1.0 - self.targets.objective

    def burn_rates(self, now: Optional[float] = None) -> dict:
        """``{target: {"short": rate, "long": rate}}`` at ``now``."""
        now = self.clock() if now is None else now
        budget = self._budget()
        with self._lock:
            return {t: {"short": short.fraction(now) / budget,
                        "long": long.fraction(now) / budget}
                    for t, (short, long) in self._windows.items()}

    def shed_pressure(self, now: Optional[float] = None) -> float:
        """max over targets of min(short, long) burn, normalized by
        ``shed_burn`` and clipped to [0, 1]."""
        rates = self.burn_rates(now)
        worst = max((min(r["short"], r["long"]) for r in rates.values()),
                    default=0.0)
        return min(max(worst / self.shed_burn, 0.0), 1.0)

    def _pressure_of(self, windows: dict, now: float) -> float:
        # caller holds self._lock
        budget = self._budget()
        worst = max((min(short.fraction(now), long.fraction(now)) / budget
                     for short, long in windows.values()), default=0.0)
        return min(max(worst / self.shed_burn, 0.0), 1.0)

    def tenant_shed_pressure(self, tenant_id: str,
                             now: Optional[float] = None) -> float:
        """The scoped shed signal: :meth:`shed_pressure` computed over
        ONE tenant's violation windows (0.0 for a never-observed
        tenant).  The controller's surgical actuator reads this so a
        flooding tenant's burn cannot shed a victim."""
        now = self.clock() if now is None else now
        with self._lock:
            tw = self._tenant_windows.get(str(tenant_id))
            return self._pressure_of(tw, now) if tw is not None else 0.0

    def observed_tenants(self) -> dict:
        """Tenants seen so far (id -> priority class)."""
        with self._lock:
            return dict(self._tenant_class)

    @property
    def multi_tenant(self) -> bool:
        """True once any non-default tenant has been graded — the
        monotone switch the controller uses to pick the scoped shed
        policy over the legacy global one."""
        with self._lock:
            return any(tid != "default" for tid in self._tenant_windows)

    def _publish(self, now: float, m: dict) -> None:
        # caller holds self._lock; recompute without re-locking
        budget = self._budget()
        worst = 0.0
        for target, (short, long) in self._windows.items():
            s, l_ = short.fraction(now) / budget, long.fraction(now) / budget
            m["burn"].labels(target=target, window="short").set(s)
            m["burn"].labels(target=target, window="long").set(l_)
            worst = max(worst, min(s, l_))
        m["shed"].set(min(max(worst / self.shed_burn, 0.0), 1.0))
        # the per-tenant gauge only once real multi-tenant traffic
        # exists — a pre-tenant deployment's metric surface is unchanged
        if any(tid != "default" for tid in self._tenant_windows):
            for tid, tw in self._tenant_windows.items():
                m["tenant_shed"].labels(
                    tenant=tid, klass=self._tenant_class[tid]).set(
                        self._pressure_of(tw, now))

    # -- read side ----------------------------------------------------------

    def stage_summary(self) -> dict:
        """Total + per-request-mean + fractional split per stage — the
        ``bench.py --mode serve`` attribution payload (a regression shows
        up as a stage's share moving, not just a ratio)."""
        with self._lock:
            total = sum(self.stage_totals.values())
            n = self.requests
            return {s: {"total_s": self.stage_totals[s],
                        "mean_s": self.stage_totals[s] / n if n else 0.0,
                        "fraction": (self.stage_totals[s] / total
                                     if total > 0 else 0.0)}
                    for s in STAGES}

    def summary(self) -> dict:
        """The ``/slo`` payload."""
        now = self.clock()
        rates = self.burn_rates(now)
        with self._lock:
            total = sum(self.stage_totals.values())
            body = {
                "targets": dataclasses.asdict(self.targets),
                "windows_s": {"short": self.short_window_s,
                              "long": self.long_window_s},
                "requests": self.requests,
                "violations": dict(self.violations),
                "stages": {s: {"total_s": self.stage_totals[s],
                               "fraction": (self.stage_totals[s] / total
                                            if total > 0 else 0.0)}
                           for s in STAGES},
                "burn_rates": rates,
            }
        worst = max((min(r["short"], r["long"]) for r in rates.values()),
                    default=0.0)
        body["shed_pressure"] = min(max(worst / self.shed_burn, 0.0), 1.0)
        with self._lock:
            if any(tid != "default" for tid in self._tenant_windows):
                budget = self._budget()
                body["tenants"] = {
                    tid: {"class": self._tenant_class[tid],
                          "requests": self._tenant_requests[tid],
                          "violations": dict(self._tenant_violations[tid]),
                          "burn_rates": {
                              t: {"short": short.fraction(now) / budget,
                                  "long": long.fraction(now) / budget}
                              for t, (short, long) in tw.items()},
                          "shed_pressure": self._pressure_of(tw, now)}
                    for tid, tw in sorted(self._tenant_windows.items())}
        return body
