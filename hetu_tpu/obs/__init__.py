"""Unified runtime telemetry: metrics registry, tracing spans, event
journal, and the ``/metrics``+``/healthz`` scrape endpoint.

The reference stack's observability is offline (timer subexecutors,
per-op re-execution profiling — SURVEY §5.1); the HET architecture it
headlines (cache-enabled PS, VLDB'22) is operated on *live* cache-hit
and staleness telemetry.  This package is the always-on layer the
production seams write to:

- :mod:`~hetu_tpu.obs.registry` — thread-safe process-wide
  ``MetricsRegistry`` (labeled counters/gauges/histograms, ``snapshot``
  deltas, Prometheus text exposition, JSONL export);
- :mod:`~hetu_tpu.obs.tracing` — cross-layer spans (trace/span/parent
  ids, context propagation, deterministic clock) exporting Chrome
  trace-event JSON mergeable with XProf traces;
- :mod:`~hetu_tpu.obs.journal` — append-only JSONL resilience event
  journal with monotonic sequence numbers;
- :mod:`~hetu_tpu.obs.server` — stdlib-HTTP ``/metrics`` / ``/healthz``
  endpoint (the ``exec/graphboard.py`` server pattern);
- :mod:`~hetu_tpu.obs.fleet` — the cross-worker plane: per-worker atomic
  snapshot publication into the gang dir, rank-0 aggregation under a
  ``worker`` label, merged journals, stitched traces, and the
  ``/fleet/*`` endpoints;
- :mod:`~hetu_tpu.obs.goodput` — online goodput buckets (useful /
  straggler-wait / rollback / rescale / checkpoint / retune / compile)
  and a rolling MFU gauge from the bench's own flops model;
- :mod:`~hetu_tpu.obs.reqtrace` — request-scope serving timelines: one
  exact stage decomposition + span tree per request, kept in a bounded
  ring with slowest-N exemplar retention, queryable via
  ``/trace/<request_id>`` and stitchable with the fleet traces;
- :mod:`~hetu_tpu.obs.slo` — the serving SLO engine: per-request
  TTFT/TPOT/queue-age grading against env-configurable targets,
  short+long-window burn rates, and the ``/slo`` shed-pressure gauge
  (``/fleet/slo`` aggregates it);
- :mod:`~hetu_tpu.obs.compile` — XLA compilation telemetry: exact
  compile counting at the jit seams (serving step fns AOT,
  ``Trainer.step`` watch-only), per-shape-signature compile cost and
  ``memory_analysis`` bytes, ``recompile`` journal events carrying the
  triggering shape delta, and a recompile-storm gauge;
- :mod:`~hetu_tpu.obs.calibration` — the performance calibration
  plane: a versioned CRC+signed ``ProfileStore`` of calibration
  records ingested from the signals above, a fit layer emitting
  measured ``TimeCostModel``/``MemoryCostModel`` constants (consumed
  via ``dp_search(calibration=)`` / ``plan_memory(calibration=)``),
  and a perf-regression sentinel journaling ``perf_regression`` and
  flipping a ``/healthz`` red flag (``/calibration`` +
  ``/fleet/calibration``).

Instrumented seams: ``embed.net.RemoteEmbeddingTable._rpc`` (latency,
bytes, redials, errors), the HET caches (hit/miss), ``Trainer.step``
(latency, examples/s, grad-norm), ``exec.checkpoint`` (write duration/
bytes/CRC + journal), ``exec.resilience`` (journal events), and
``launch.simulate_workers`` (heartbeat-age straggler gauges).  All of it
is disabled in one switch — ``obs.disable()`` or ``HETU_OBS=0`` — and
the disabled path is a single global load + branch per seam.
"""

from hetu_tpu.obs.calibration import (Calibration, CalibrationKey,
                                      FittedConstant, ProfileStore,
                                      RegressionSentinel, fit_calibration,
                                      get_store, install_store)
from hetu_tpu.obs.compile import (InstrumentedJit, StormDetector,
                                  compile_report, instrument, watch)
from hetu_tpu.obs.divergence import (DivergenceDetector, FingerprintBoard,
                                     compare_fleet)
from hetu_tpu.obs.numerics import (FlightRecorder, first_nonfinite,
                                   fingerprint, group_stats,
                                   host_fingerprint, host_fingerprint_ints,
                                   host_group_stats, install_recorder,
                                   loss_provenance, tree_fingerprints)
from hetu_tpu.obs.fleet import (FleetAggregator, SnapshotPublisher,
                                fleet_routes, serve_fleet)
from hetu_tpu.obs.goodput import GoodputMeter
from hetu_tpu.obs.reqtrace import ReqTraceBuffer, RequestTimeline
from hetu_tpu.obs.slo import SLOEngine, SLOTargets
from hetu_tpu.obs.journal import (EventJournal, get_journal, record,
                                  set_journal, use)
from hetu_tpu.obs.registry import (DEFAULT_BUCKETS, Counter, Gauge,
                                   Histogram, MetricsRegistry, disable,
                                   enable, enabled, get_registry)
from hetu_tpu.obs.server import (Routes, RoutedHTTPServer, TelemetryServer,
                                 serve, telemetry_routes)
from hetu_tpu.obs.tracing import (Span, Tracer, current_span, get_tracer,
                                  span)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "DEFAULT_BUCKETS",
    "get_registry", "enabled", "enable", "disable",
    "Tracer", "Span", "get_tracer", "span", "current_span",
    "EventJournal", "get_journal", "set_journal", "use", "record",
    "TelemetryServer", "serve", "Routes", "RoutedHTTPServer",
    "telemetry_routes",
    "SnapshotPublisher", "FleetAggregator", "fleet_routes", "serve_fleet",
    "GoodputMeter",
    "RequestTimeline", "ReqTraceBuffer",
    "SLOEngine", "SLOTargets",
    "InstrumentedJit", "StormDetector", "instrument", "watch",
    "compile_report",
    "FlightRecorder", "install_recorder", "fingerprint", "group_stats",
    "tree_fingerprints", "host_fingerprint", "host_fingerprint_ints",
    "host_group_stats", "first_nonfinite", "loss_provenance",
    "DivergenceDetector", "FingerprintBoard", "compare_fleet",
    "ProfileStore", "CalibrationKey", "Calibration", "FittedConstant",
    "RegressionSentinel", "fit_calibration", "install_store", "get_store",
]
