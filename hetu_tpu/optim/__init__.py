from hetu_tpu.optim.optimizers import (
    AdaGradOptimizer,
    AdamOptimizer,
    AdamWOptimizer,
    LambOptimizer,
    MomentumOptimizer,
    Optimizer,
    SGDOptimizer,
)
from hetu_tpu.optim.schedulers import (
    ExponentialScheduler,
    FixedScheduler,
    MultiStepScheduler,
    ReduceOnPlateauScheduler,
    StepScheduler,
    WarmupCosineScheduler,
    WarmupLinearScheduler,
)
