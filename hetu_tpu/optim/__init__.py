from hetu_tpu.optim.optimizers import (
    AdaGradOptimizer,
    AdamOptimizer,
    AdamWOptimizer,
    LambOptimizer,
    MomentumOptimizer,
    Optimizer,
    SGDOptimizer,
    clip_by_global_norm,
    clip_by_value,
    global_norm,
)
from hetu_tpu.optim.schedulers import (
    ExponentialScheduler,
    FixedScheduler,
    MultiStepScheduler,
    ReduceOnPlateauScheduler,
    StepScheduler,
    WarmupCosineScheduler,
    WarmupLinearScheduler,
)
