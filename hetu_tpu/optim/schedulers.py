"""Learning-rate schedulers.

TPU-native equivalents of the reference schedulers
(reference: python/hetu/lr_scheduler.py — Fixed/Step/MultiStep/Exponential/
ReduceOnPlateau), plus warmup-linear and warmup-cosine which the reference's
BERT example implements ad hoc.

Each scheduler is a callable ``step -> lr`` safe to trace under jit
(except ReduceOnPlateau, which is inherently host-driven and stateful).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp

__all__ = [
    "FixedScheduler", "StepScheduler", "MultiStepScheduler",
    "ExponentialScheduler", "ReduceOnPlateauScheduler",
    "WarmupLinearScheduler", "WarmupCosineScheduler",
]


@dataclasses.dataclass
class FixedScheduler:
    learning_rate: float = 0.01

    def __call__(self, step):
        return self.learning_rate


@dataclasses.dataclass
class StepScheduler:
    """lr * gamma^(step // step_size)."""

    learning_rate: float = 0.01
    step_size: int = 1000
    gamma: float = 0.1

    def __call__(self, step):
        return self.learning_rate * self.gamma ** (step // self.step_size)


@dataclasses.dataclass
class MultiStepScheduler:
    """Decay by gamma at each milestone."""

    learning_rate: float = 0.01
    milestones: Sequence[int] = (1000,)
    gamma: float = 0.1

    def __call__(self, step):
        k = jnp.sum(step >= jnp.asarray(list(self.milestones)))
        return self.learning_rate * self.gamma ** k


@dataclasses.dataclass
class ExponentialScheduler:
    learning_rate: float = 0.01
    gamma: float = 0.99

    def __call__(self, step):
        return self.learning_rate * self.gamma ** step


@dataclasses.dataclass
class WarmupLinearScheduler:
    """Linear warmup then linear decay to zero (reference BERT recipe)."""

    learning_rate: float = 1e-4
    warmup_steps: int = 1000
    total_steps: int = 100000

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / jnp.maximum(1.0, self.warmup_steps)
        decay = jnp.maximum(
            0.0,
            (self.total_steps - step)
            / jnp.maximum(1.0, self.total_steps - self.warmup_steps),
        )
        return self.learning_rate * jnp.minimum(warm, decay)


@dataclasses.dataclass
class WarmupCosineScheduler:
    learning_rate: float = 1e-4
    warmup_steps: int = 1000
    total_steps: int = 100000
    final_fraction: float = 0.0

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / jnp.maximum(1.0, self.warmup_steps)
        progress = jnp.clip(
            (step - self.warmup_steps)
            / jnp.maximum(1.0, self.total_steps - self.warmup_steps),
            0.0, 1.0,
        )
        cos = self.final_fraction + (1 - self.final_fraction) * 0.5 * (
            1 + jnp.cos(jnp.pi * progress)
        )
        return self.learning_rate * jnp.minimum(warm, 1.0) * jnp.where(
            step < self.warmup_steps, 1.0, cos
        )


class ReduceOnPlateauScheduler:
    """Host-side stateful plateau scheduler (lr_scheduler.py ReduceOnPlateau)."""

    def __init__(self, learning_rate=0.01, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, cooldown=0, min_lr=0.0):
        assert mode in ("min", "max")
        self.lr = float(learning_rate)
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.bad_steps = 0
        self.cooldown_left = 0

    def record(self, metric: float) -> float:
        """Feed a new metric value; returns the (possibly reduced) lr."""
        metric = float(metric)
        improved = (
            self.best is None
            or (self.mode == "min" and metric < self.best - self.threshold)
            or (self.mode == "max" and metric > self.best + self.threshold)
        )
        if improved:
            self.best = metric
            self.bad_steps = 0
        elif self.cooldown_left > 0:
            self.cooldown_left -= 1
        else:
            self.bad_steps += 1
            if self.bad_steps > self.patience:
                self.lr = max(self.lr * self.factor, self.min_lr)
                self.bad_steps = 0
                self.cooldown_left = self.cooldown
        return self.lr

    def __call__(self, step):
        return self.lr
