"""Optimizers.

TPU-native equivalents of the reference optimizer family
(reference: python/hetu/optimizer.py — SGDUpdateOp:203, MomentumUpdateOp:289,
AdaGradUpdateOp:335, AdamUpdateOp:462, AdamWUpdateOp:629, LambUpdateOp:686,
plus sparse variants e.g. AdamSparseUpdateOp:553; CUDA kernels
src/ops/Optimizers.cu, OptimizersSparse.cu).

Design: each optimizer is a pure pytree transform —
``init(params) -> state`` and ``update(grads, state, params) ->
(new_params, new_state)`` — so the whole update jits into the train step and
shards with the params (ZeRO partitioning is just a sharding rule on the
state pytree, hetu_tpu/parallel/zero.py).  Learning rates may be floats or
schedules (step -> lr callables, hetu_tpu/optim/schedulers.py).

Sparse semantics: ``IndexedSlices`` gradients (embedding rows) are applied
row-wise, matching the reference's *lazy* sparse updates (only touched rows'
moments advance — optimizer.py:553 AdamSparse).  Dense pytrees and pytrees
containing IndexedSlices leaves both work.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp

from hetu_tpu.ops.sparse import IndexedSlices

__all__ = [
    "Optimizer", "SGDOptimizer", "MomentumOptimizer", "AdaGradOptimizer",
    "AdamOptimizer", "AdamWOptimizer", "LambOptimizer",
    "global_norm", "clip_by_global_norm", "clip_by_value",
]

ScheduleOrFloat = Union[float, Callable[[Any], Any]]


def _lr_at(lr: ScheduleOrFloat, step):
    return lr(step) if callable(lr) else lr


def _is_leaf(x):
    return isinstance(x, IndexedSlices)


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees, is_leaf=_is_leaf)


def _grad_sq_sum(g):
    if isinstance(g, IndexedSlices):
        return jnp.sum(jnp.square(g.values.astype(jnp.float32)))
    return jnp.sum(jnp.square(g.astype(jnp.float32)))


def global_norm(grads):
    """L2 norm over the whole gradient pytree (IndexedSlices counted by
    their values; None leaves skipped)."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads, is_leaf=_is_leaf)
              if g is not None]
    return jnp.sqrt(sum(_grad_sq_sum(g) for g in leaves))


def _scale_grad(g, s):
    if isinstance(g, IndexedSlices):
        return dataclasses.replace(g, values=g.values * s.astype(g.values.dtype))
    return g * s.astype(g.dtype)


def clip_by_global_norm(grads, max_norm: float):
    """Scale the whole gradient tree so its global L2 norm is <= max_norm
    (the standard BERT/GPT pretraining clip; reference models clip via
    optimizer kernels' l2 machinery)."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(
        lambda g: None if g is None else _scale_grad(g, scale), grads,
        is_leaf=lambda x: _is_leaf(x) or x is None)


def clip_by_value(grads, min_value: float, max_value: float):
    """Per-element value clip (reference gpu_ops/ParamClip.py semantics
    applied to gradients)."""
    def clip(g):
        if g is None:
            return None
        if isinstance(g, IndexedSlices):
            return dataclasses.replace(
                g, values=jnp.clip(g.values, min_value, max_value))
        return jnp.clip(g, min_value, max_value)

    return jax.tree_util.tree_map(
        clip, grads, is_leaf=lambda x: _is_leaf(x) or x is None)


def _zeros_slot(p):
    # Slots live in fp32 regardless of param dtype (bf16 moments destroy Adam
    # numerics, and dtype-stable state pytrees are required for scan/donation).
    if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
        return jnp.zeros(jnp.shape(p), jnp.float32)
    return jnp.zeros_like(p)


@dataclasses.dataclass
class Optimizer:
    """Base class.  Subclasses implement ``_dense`` and ``_sparse`` row updates."""

    learning_rate: ScheduleOrFloat = 0.01
    l2reg: float = 0.0
    # gradient clipping, applied over the whole grad tree before the update:
    # clip_norm > 0 = global-L2-norm clip; clip_value > 0 = |g| value clip
    clip_norm: float = 0.0
    clip_value: float = 0.0

    def init(self, params) -> dict:
        return {
            "step": jnp.zeros((), jnp.int32),
            **{k: jax.tree_util.tree_map(_zeros_slot, params) for k in self.slot_names()},
        }

    def slot_names(self) -> tuple:
        return ()

    # -- single-leaf updates --------------------------------------------------
    def _dense(self, g, p, slots: dict, lr, step):
        raise NotImplementedError

    def _sparse(self, s: IndexedSlices, p, slots: dict, lr, step):
        """Default sparse path: apply the dense rule on gathered rows only
        (lazy semantics — untouched rows' params and moments don't advance,
        reference optimizer.py:553 AdamSparseUpdateOp)."""
        s = s.dedup()
        idx = s.indices
        valid = (idx >= 0)[:, None]
        old_rows = {k: v[idx] for k, v in slots.items()}
        p_rows = p[idx]
        g_rows = s.values
        if self.l2reg > 0.0:
            g_rows = g_rows + self.l2reg * p_rows
        new_rows, new_slot_rows = self._dense(g_rows, p_rows, dict(old_rows), lr, step)
        upd = jnp.where(valid, (new_rows - p_rows).astype(p.dtype), 0)
        p = p.at[idx].add(upd, mode="drop")
        for k in slots:
            slot_upd = jnp.where(
                valid, (new_slot_rows[k] - old_rows[k]).astype(slots[k].dtype), 0
            )
            slots[k] = slots[k].at[idx].add(slot_upd, mode="drop")
        return p, slots

    # -- pytree update --------------------------------------------------------
    def update(self, grads, state, params, mask=None):
        """Apply one update.  ``mask`` (optional) is a params-congruent pytree
        of bools — False marks non-trainable leaves (BatchNorm statistics
        etc., see core.module.trainable_mask) which are passed through
        untouched (no weight decay, no moment update)."""
        step = state["step"] + 1
        lr = _lr_at(self.learning_rate, step)
        slot_names = self.slot_names()
        if self.clip_norm > 0.0:
            grads = clip_by_global_norm(grads, self.clip_norm)
        if self.clip_value > 0.0:
            grads = clip_by_value(grads, -self.clip_value, self.clip_value)

        # None grads mark frozen params; keep them as leaves so the treedefs
        # of grads and params stay congruent.
        is_leaf = lambda x: _is_leaf(x) or x is None  # noqa: E731
        leaves_g, treedef = jax.tree_util.tree_flatten(grads, is_leaf=is_leaf)
        leaves_p = treedef.flatten_up_to(params)
        leaves_slots = {k: treedef.flatten_up_to(state[k]) for k in slot_names}
        leaves_m = (
            treedef.flatten_up_to(mask) if mask is not None else [True] * len(leaves_g)
        )

        new_p, new_slots = [], {k: [] for k in slot_names}
        for i, (g, p) in enumerate(zip(leaves_g, leaves_p)):
            slots = {k: leaves_slots[k][i] for k in slot_names}
            if g is None or not bool(leaves_m[i]):
                np_, ns = p, slots
            elif isinstance(g, IndexedSlices):
                np_, ns = self._sparse(g, p, dict(slots), lr, step)
            else:
                if self.l2reg > 0.0:
                    g = g + self.l2reg * p
                np_, ns = self._dense(g, p, dict(slots), lr, step)
                np_ = np_.astype(p.dtype)
                ns = {k: v.astype(slots[k].dtype) for k, v in ns.items()}
            new_p.append(np_)
            for k in slot_names:
                new_slots[k].append(ns[k])

        new_params = jax.tree_util.tree_unflatten(treedef, new_p)
        new_state = {"step": step}
        for k in slot_names:
            new_state[k] = jax.tree_util.tree_unflatten(treedef, new_slots[k])
        return new_params, new_state

    # Facade matching the reference Optimizer.minimize (optimizer.py:66): the
    # graph-building role is subsumed by jax.grad; exec.Trainer wires it up.


@dataclasses.dataclass
class SGDOptimizer(Optimizer):
    """Plain SGD (optimizer.py:203 SGDUpdateOp; src/ops/Optimizers.cu sgd_update)."""

    def _dense(self, g, p, slots, lr, step):
        return p.astype(jnp.float32) - lr * g.astype(jnp.float32), slots


@dataclasses.dataclass
class MomentumOptimizer(Optimizer):
    """(Nesterov) momentum (optimizer.py:289 MomentumUpdateOp)."""

    momentum: float = 0.9
    nesterov: bool = False

    def slot_names(self):
        return ("velocity",)

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        v = self.momentum * slots["velocity"] - lr * g32
        if self.nesterov:
            p32 = p32 + self.momentum * v - lr * g32
        else:
            p32 = p32 + v
        slots["velocity"] = v
        return p32, slots


@dataclasses.dataclass
class AdaGradOptimizer(Optimizer):
    """AdaGrad (optimizer.py:335 AdaGradUpdateOp)."""

    initial_accumulator_value: float = 0.0
    eps: float = 1e-7

    def slot_names(self):
        return ("accum",)

    def init(self, params):
        state = super().init(params)
        if self.initial_accumulator_value:
            state["accum"] = jax.tree_util.tree_map(
                lambda a: a + self.initial_accumulator_value, state["accum"]
            )
        return state

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        acc = slots["accum"] + jnp.square(g32)
        p = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc) + self.eps)
        slots["accum"] = acc
        return p, slots


@dataclasses.dataclass
class AdamOptimizer(Optimizer):
    """Adam (optimizer.py:462 AdamUpdateOp), with optional AMSGrad."""

    learning_rate: ScheduleOrFloat = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-7
    amsgrad: bool = False

    def slot_names(self):
        return ("m", "v") + (("vhat",) if self.amsgrad else ())

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g32
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g32)
        stepf = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1**stepf)
        vhat = v / (1 - self.beta2**stepf)
        if self.amsgrad:
            vmax = jnp.maximum(slots["vhat"], vhat)
            slots["vhat"] = vmax
            denom = jnp.sqrt(vmax) + self.eps
        else:
            denom = jnp.sqrt(vhat) + self.eps
        p = (p.astype(jnp.float32) - lr * mhat / denom).astype(p.dtype)
        slots["m"], slots["v"] = m, v
        return p, slots


@dataclasses.dataclass
class AdamWOptimizer(AdamOptimizer):
    """AdamW — decoupled weight decay (optimizer.py:629 AdamWUpdateOp)."""

    weight_decay: float = 0.01

    def _dense(self, g, p, slots, lr, step):
        new_p, slots = super()._dense(g, p, slots, lr, step)
        return new_p - lr * self.weight_decay * p, slots


@dataclasses.dataclass
class LambOptimizer(AdamOptimizer):
    """LAMB — layerwise trust-ratio AdamW (optimizer.py:686 LambUpdateOp)."""

    weight_decay: float = 0.01

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g32
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g32)
        stepf = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1**stepf)
        vhat = v / (1 - self.beta2**stepf)
        update = mhat / (jnp.sqrt(vhat) + self.eps) + self.weight_decay * p.astype(jnp.float32)
        wnorm = jnp.linalg.norm(p.astype(jnp.float32))
        unorm = jnp.linalg.norm(update)
        trust = jnp.where(
            (wnorm > 0) & (unorm > 0), wnorm / unorm, jnp.ones_like(wnorm)
        )
        p = (p.astype(jnp.float32) - lr * trust * update).astype(p.dtype)
        slots["m"], slots["v"] = m, v
        return p, slots
