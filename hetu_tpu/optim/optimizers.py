"""Optimizers.

TPU-native equivalents of the reference optimizer family
(reference: python/hetu/optimizer.py — SGDUpdateOp:203, MomentumUpdateOp:289,
AdaGradUpdateOp:335, AdamUpdateOp:462, AdamWUpdateOp:629, LambUpdateOp:686,
plus sparse variants e.g. AdamSparseUpdateOp:553; CUDA kernels
src/ops/Optimizers.cu, OptimizersSparse.cu).

Design: each optimizer is a pure pytree transform —
``init(params) -> state`` and ``update(grads, state, params) ->
(new_params, new_state)`` — so the whole update jits into the train step and
shards with the params (ZeRO partitioning is just a sharding rule on the
state pytree, hetu_tpu/parallel/zero.py).  Learning rates may be floats or
schedules (step -> lr callables, hetu_tpu/optim/schedulers.py).

Sparse semantics: ``IndexedSlices`` gradients (embedding rows) are applied
row-wise, matching the reference's *lazy* sparse updates (only touched rows'
moments advance — optimizer.py:553 AdamSparse).  Dense pytrees and pytrees
containing IndexedSlices leaves both work.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp

from hetu_tpu.ops.sparse import IndexedSlices

__all__ = [
    "Optimizer", "SGDOptimizer", "MomentumOptimizer", "AdaGradOptimizer",
    "AdamOptimizer", "AdamWOptimizer", "LambOptimizer",
]

ScheduleOrFloat = Union[float, Callable[[Any], Any]]


def _lr_at(lr: ScheduleOrFloat, step):
    return lr(step) if callable(lr) else lr


def _is_leaf(x):
    return isinstance(x, IndexedSlices)


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees, is_leaf=_is_leaf)


def _zeros_slot(p):
    # Slots live in fp32 regardless of param dtype (bf16 moments destroy Adam
    # numerics, and dtype-stable state pytrees are required for scan/donation).
    if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
        return jnp.zeros(jnp.shape(p), jnp.float32)
    return jnp.zeros_like(p)


@dataclasses.dataclass
class Optimizer:
    """Base class.  Subclasses implement ``_dense`` and ``_sparse`` row updates."""

    learning_rate: ScheduleOrFloat = 0.01
    l2reg: float = 0.0

    def init(self, params) -> dict:
        return {
            "step": jnp.zeros((), jnp.int32),
            **{k: jax.tree_util.tree_map(_zeros_slot, params) for k in self.slot_names()},
        }

    def slot_names(self) -> tuple:
        return ()

    # -- single-leaf updates --------------------------------------------------
    def _dense(self, g, p, slots: dict, lr, step):
        raise NotImplementedError

    def _sparse(self, s: IndexedSlices, p, slots: dict, lr, step):
        """Default sparse path: apply the dense rule on gathered rows only
        (lazy semantics — untouched rows' params and moments don't advance,
        reference optimizer.py:553 AdamSparseUpdateOp)."""
        s = s.dedup()
        idx = s.indices
        valid = (idx >= 0)[:, None]
        old_rows = {k: v[idx] for k, v in slots.items()}
        p_rows = p[idx]
        g_rows = s.values
        if self.l2reg > 0.0:
            g_rows = g_rows + self.l2reg * p_rows
        new_rows, new_slot_rows = self._dense(g_rows, p_rows, dict(old_rows), lr, step)
        upd = jnp.where(valid, (new_rows - p_rows).astype(p.dtype), 0)
        p = p.at[idx].add(upd, mode="drop")
        for k in slots:
            slot_upd = jnp.where(
                valid, (new_slot_rows[k] - old_rows[k]).astype(slots[k].dtype), 0
            )
            slots[k] = slots[k].at[idx].add(slot_upd, mode="drop")
        return p, slots

    # -- pytree update --------------------------------------------------------
    def update(self, grads, state, params, mask=None):
        """Apply one update.  ``mask`` (optional) is a params-congruent pytree
        of bools — False marks non-trainable leaves (BatchNorm statistics
        etc., see core.module.trainable_mask) which are passed through
        untouched (no weight decay, no moment update)."""
        step = state["step"] + 1
        lr = _lr_at(self.learning_rate, step)
        slot_names = self.slot_names()

        # None grads mark frozen params; keep them as leaves so the treedefs
        # of grads and params stay congruent.
        is_leaf = lambda x: _is_leaf(x) or x is None  # noqa: E731
        leaves_g, treedef = jax.tree_util.tree_flatten(grads, is_leaf=is_leaf)
        leaves_p = treedef.flatten_up_to(params)
        leaves_slots = {k: treedef.flatten_up_to(state[k]) for k in slot_names}
        leaves_m = (
            treedef.flatten_up_to(mask) if mask is not None else [True] * len(leaves_g)
        )

        new_p, new_slots = [], {k: [] for k in slot_names}
        for i, (g, p) in enumerate(zip(leaves_g, leaves_p)):
            slots = {k: leaves_slots[k][i] for k in slot_names}
            if g is None or not bool(leaves_m[i]):
                np_, ns = p, slots
            elif isinstance(g, IndexedSlices):
                np_, ns = self._sparse(g, p, dict(slots), lr, step)
            else:
                if self.l2reg > 0.0:
                    g = g + self.l2reg * p
                np_, ns = self._dense(g, p, dict(slots), lr, step)
                np_ = np_.astype(p.dtype)
                ns = {k: v.astype(slots[k].dtype) for k, v in ns.items()}
            new_p.append(np_)
            for k in slot_names:
                new_slots[k].append(ns[k])

        new_params = jax.tree_util.tree_unflatten(treedef, new_p)
        new_state = {"step": step}
        for k in slot_names:
            new_state[k] = jax.tree_util.tree_unflatten(treedef, new_slots[k])
        return new_params, new_state

    # Facade matching the reference Optimizer.minimize (optimizer.py:66): the
    # graph-building role is subsumed by jax.grad; exec.Trainer wires it up.


@dataclasses.dataclass
class SGDOptimizer(Optimizer):
    """Plain SGD (optimizer.py:203 SGDUpdateOp; src/ops/Optimizers.cu sgd_update)."""

    def _dense(self, g, p, slots, lr, step):
        return p.astype(jnp.float32) - lr * g.astype(jnp.float32), slots


@dataclasses.dataclass
class MomentumOptimizer(Optimizer):
    """(Nesterov) momentum (optimizer.py:289 MomentumUpdateOp)."""

    momentum: float = 0.9
    nesterov: bool = False

    def slot_names(self):
        return ("velocity",)

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        v = self.momentum * slots["velocity"] - lr * g32
        if self.nesterov:
            p32 = p32 + self.momentum * v - lr * g32
        else:
            p32 = p32 + v
        slots["velocity"] = v
        return p32, slots


@dataclasses.dataclass
class AdaGradOptimizer(Optimizer):
    """AdaGrad (optimizer.py:335 AdaGradUpdateOp)."""

    initial_accumulator_value: float = 0.0
    eps: float = 1e-7

    def slot_names(self):
        return ("accum",)

    def init(self, params):
        state = super().init(params)
        if self.initial_accumulator_value:
            state["accum"] = jax.tree_util.tree_map(
                lambda a: a + self.initial_accumulator_value, state["accum"]
            )
        return state

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        acc = slots["accum"] + jnp.square(g32)
        p = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc) + self.eps)
        slots["accum"] = acc
        return p, slots


@dataclasses.dataclass
class AdamOptimizer(Optimizer):
    """Adam (optimizer.py:462 AdamUpdateOp), with optional AMSGrad."""

    learning_rate: ScheduleOrFloat = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-7
    amsgrad: bool = False

    def slot_names(self):
        return ("m", "v") + (("vhat",) if self.amsgrad else ())

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g32
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g32)
        stepf = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1**stepf)
        vhat = v / (1 - self.beta2**stepf)
        if self.amsgrad:
            vmax = jnp.maximum(slots["vhat"], vhat)
            slots["vhat"] = vmax
            denom = jnp.sqrt(vmax) + self.eps
        else:
            denom = jnp.sqrt(vhat) + self.eps
        p = (p.astype(jnp.float32) - lr * mhat / denom).astype(p.dtype)
        slots["m"], slots["v"] = m, v
        return p, slots


@dataclasses.dataclass
class AdamWOptimizer(AdamOptimizer):
    """AdamW — decoupled weight decay (optimizer.py:629 AdamWUpdateOp)."""

    weight_decay: float = 0.01

    def _dense(self, g, p, slots, lr, step):
        new_p, slots = super()._dense(g, p, slots, lr, step)
        return new_p - lr * self.weight_decay * p, slots


@dataclasses.dataclass
class LambOptimizer(AdamOptimizer):
    """LAMB — layerwise trust-ratio AdamW (optimizer.py:686 LambUpdateOp)."""

    weight_decay: float = 0.01

    def _dense(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g32
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g32)
        stepf = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1**stepf)
        vhat = v / (1 - self.beta2**stepf)
        update = mhat / (jnp.sqrt(vhat) + self.eps) + self.weight_decay * p.astype(jnp.float32)
        wnorm = jnp.linalg.norm(p.astype(jnp.float32))
        unorm = jnp.linalg.norm(update)
        trust = jnp.where(
            (wnorm > 0) & (unorm > 0), wnorm / unorm, jnp.ones_like(wnorm)
        )
        p = (p.astype(jnp.float32) - lr * trust * update).astype(p.dtype)
        slots["m"], slots["v"] = m, v
        return p, slots
