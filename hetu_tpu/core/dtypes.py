"""Mixed-precision policy for TPU.

The reference runs fp32 throughout (CUDA kernels in src/ops are float-only).
On TPU the MXU natively consumes bfloat16, so hetu-tpu makes the precision
policy explicit and defaults compute to bf16 with fp32 params/reductions —
the standard TPU recipe.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["Policy", "DEFAULT_POLICY", "FP32_POLICY", "cast_to_compute", "cast_to_param", "cast_to_output"]


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    output_dtype: jnp.dtype = jnp.float32

    def cast_to_compute(self, tree):
        return cast_tree(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return cast_tree(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return cast_tree(tree, self.output_dtype)


def cast_tree(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


DEFAULT_POLICY = Policy()
FP32_POLICY = Policy(jnp.float32, jnp.float32, jnp.float32)


def cast_to_compute(tree, policy: Policy = DEFAULT_POLICY):
    return policy.cast_to_compute(tree)


def cast_to_param(tree, policy: Policy = DEFAULT_POLICY):
    return policy.cast_to_param(tree)


def cast_to_output(tree, policy: Policy = DEFAULT_POLICY):
    return policy.cast_to_output(tree)
