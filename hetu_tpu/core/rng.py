"""Reproducible global RNG: seed + sequence number.

Mirrors the capability of the reference's stateful RNG (reference:
src/common/random.cc — ``SetRandomSeed``/``StepSeqNum``; Python binding
python/hetu/random.py:14-43): a global seed plus a monotonically increasing
sequence number, checkpointed together so that training resumed from a
checkpoint replays the identical random stream.

TPU-natively this is a thin facade over ``jax.random``: each draw folds the
next sequence number into a key derived from the seed.  The (seed, seqnum)
pair round-trips through ``state()``/``load_state()`` and is stored in
checkpoints by ``hetu_tpu.exec.checkpoint``.
"""

from __future__ import annotations

import threading

import jax
import jax.random as jrandom

__all__ = ["set_random_seed", "get_seed_status", "next_key", "next_keys", "reset_seed_seqnum"]

_lock = threading.Lock()
_seed: int = 0
_seqnum: int = 0


def set_random_seed(seed: int) -> None:
    """Set the global seed and reset the sequence number (random.py:14)."""
    global _seed, _seqnum
    with _lock:
        _seed = int(seed)
        _seqnum = 0


def get_seed_status() -> tuple[int, int]:
    """Return (seed, seqnum) — the checkpointable RNG state (random.py:31)."""
    return _seed, _seqnum


def reset_seed_seqnum(seed: int, seqnum: int) -> None:
    """Restore RNG state from a checkpoint (random.py:36)."""
    global _seed, _seqnum
    with _lock:
        _seed = int(seed)
        _seqnum = int(seqnum)


def next_key() -> jax.Array:
    """Return a fresh PRNG key; advances the global sequence number."""
    global _seqnum
    with _lock:
        seq = _seqnum
        _seqnum += 1
    return jrandom.fold_in(jrandom.key(_seed), seq)


def next_keys(n: int) -> jax.Array:
    """Return ``n`` fresh PRNG keys as a stacked array."""
    global _seqnum
    with _lock:
        seq = _seqnum
        _seqnum += n
    base = jrandom.key(_seed)
    return jax.vmap(lambda i: jrandom.fold_in(base, i))(
        jax.numpy.arange(seq, seq + n)
    )
