from hetu_tpu.core.module import (
    FrozenDict,
    Module,
    logical_axes,
    maybe_remat,
    named_parameters,
    param_count,
    trainable_mask,
    tree_replace,
)
from hetu_tpu.core.rng import (
    get_seed_status,
    next_key,
    next_keys,
    reset_seed_seqnum,
    set_random_seed,
)
from hetu_tpu.core.dtypes import DEFAULT_POLICY, FP32_POLICY, Policy
