"""Pytree-based module system — the structural core of hetu-tpu.

The reference frames models as define-then-run dataflow graphs of ``Op`` nodes
(reference: python/hetu/gpu_ops/Node.py:20) with hand-built autodiff
(executor.py:1265), shape inference, and scheduling.  On TPU, ``jax.jit``
supplies graph capture, ``jax.grad`` the autodiff, and XLA the scheduling — so
the module system here only needs to

1. organize parameters/state as pytrees so jit/grad/pjit see them natively,
2. carry *logical sharding axes* per parameter, consumed by the strategy layer
   (``hetu_tpu/parallel/spec.py`` — the ``NodeStatus`` equivalent of
   reference python/hetu/context.py:248).

Conventions
-----------
* A ``Module`` subclass assigns attributes in ``__init__``.  Attributes holding
  jax/numpy arrays, sub-``Module``s, or containers thereof become pytree
  children; everything else is static metadata (must be hashable; lists are
  frozen to tuples at flatten time).
* A static attribute ``<name>_axes = ('logical0', 'logical1', ...)`` declares
  the logical sharding axes of array attribute ``<name>``.  ``logical_axes``
  collects them into a module-shaped pytree of ``PartitionSpec`` leaves.
* A static attribute/class attribute ``_state_fields: tuple[str, ...]`` names
  attributes that are *mutable state* (e.g. batch-norm statistics), not
  trainable parameters.  ``trainable_mask`` exposes this to optimizers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
from jax.sharding import PartitionSpec as P

__all__ = [
    "Module",
    "FrozenDict",
    "is_array",
    "logical_axes",
    "maybe_remat",
    "trainable_mask",
    "tree_replace",
    "named_parameters",
    "param_count",
]


def maybe_remat(call, remat):
    """``call(block, x, key) -> x'`` wrapped under a named remat policy —
    the one place per-block rematerialization lives (BertConfig/GPTConfig/
    T5Config ``remat='full'``): exact numerics, the policy decides which
    activations are recomputed in the backward instead of saved.

    ``remat`` is a policy name from the :mod:`hetu_tpu.mem.policy`
    registry ('none', 'full', 'dots_saveable', 'offload_dots', ...), a
    raw ``jax.checkpoint`` policy callable, or — deprecated — a boolean
    (``True`` -> 'full', ``False`` -> 'none')."""
    from hetu_tpu.mem.policy import apply_policy

    return apply_policy(call, remat)


def is_array(x: Any) -> bool:
    return isinstance(x, (jax.Array, np.ndarray, jax.ShapeDtypeStruct))


def _is_dynamic(v: Any) -> bool:
    """True if ``v`` belongs in the pytree-children partition."""
    if isinstance(v, Module):
        return True
    if isinstance(v, (jax.Array, np.ndarray, jax.ShapeDtypeStruct)):
        return True
    # PartitionSpec/Sharding leaves keep spec-trees (logical_axes /
    # named_shardings output) congruent with the module trees they mirror.
    if isinstance(v, (P, jax.sharding.Sharding)):
        return True
    if isinstance(v, (list, tuple)):
        return any(_is_dynamic(x) for x in v)
    if isinstance(v, dict):
        return any(_is_dynamic(x) for x in v.values())
    # registered-dataclass pytrees carrying arrays (ops.CSRMatrix,
    # ops.IndexedSlices) are children too — e.g. the CSR inference-form
    # embedding stores one as its table
    if dataclasses.is_dataclass(v) and any(
            isinstance(l, (jax.Array, np.ndarray))
            for l in jtu.tree_leaves(v)):
        return True
    return False


class FrozenDict(dict):
    """Hashable dict used for static metadata in pytree aux data."""

    def __hash__(self):  # type: ignore[override]
        return hash(tuple(sorted((k, _try_hash(v)) for k, v in self.items())))

    def __setitem__(self, *a):
        raise TypeError("FrozenDict is immutable")


def _try_hash(v):
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


def _freeze(v: Any) -> Any:
    """Make static metadata hashable (lists -> tuples, dicts -> FrozenDict)."""
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, FrozenDict):
        return v
    if isinstance(v, dict):
        return FrozenDict({k: _freeze(x) for k, x in v.items()})
    return v


def _flatten_module(m: "Module"):
    """Flatten with a *value-independent* structure.

    The dynamic-key set is decided once (by value inspection on the first
    flatten after __init__) and then pinned via ``_dyn_keys`` so that
    unflatten→flatten round-trips preserve structure for ANY leaf values —
    jax's prefix-tree machinery (jit in_shardings/in_layouts) rebuilds trees
    with None/sentinel leaves and requires this invariant.
    """
    d = m.__dict__
    dyn = d.get("_dyn_keys")
    if dyn is None:
        dyn = tuple(k for k in sorted(d) if _is_dynamic(d[k]))
        d["_dyn_keys"] = dyn  # pin: structure is now value-independent
    dyn_set = set(dyn)
    children = [d[k] for k in dyn]
    static = tuple(
        (k, _freeze(d[k]))
        for k in sorted(d)
        if k not in dyn_set and k != "_dyn_keys"
    )
    return children, (dyn, static)


def _flatten_module_with_keys(m: "Module"):
    children, aux = _flatten_module(m)
    keyed = [(jtu.GetAttrKey(k), c) for k, c in zip(aux[0], children)]
    return keyed, aux


def _unflatten_module(cls, aux, children):
    m = object.__new__(cls)
    keys, static = aux
    d = m.__dict__
    d["_dyn_keys"] = keys
    for k, v in zip(keys, children):
        d[k] = v
    for k, v in static:
        d[k] = v
    return m


class Module:
    """Base class; every subclass is automatically a registered pytree node."""

    _state_fields: tuple = ()

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        jtu.register_pytree_with_keys(
            cls,
            _flatten_module_with_keys,
            lambda aux, children, cls=cls: _unflatten_module(cls, aux, children),
            flatten_func=_flatten_module,
        )

    # -- functional update ----------------------------------------------------
    def replace(self, **updates) -> "Module":
        """Return a shallow copy with the given attributes replaced.

        If the flatten structure is already pinned (``_dyn_keys``), newly
        added dynamic attributes extend the pinned set; attributes already
        pinned stay dynamic even when set to None (tree_map semantics).
        """
        m = object.__new__(type(self))
        m.__dict__.update(self.__dict__)
        m.__dict__.update(updates)
        pinned = m.__dict__.get("_dyn_keys")
        if pinned is not None:
            extra = [
                k for k, v in updates.items()
                if k not in pinned and _is_dynamic(v)
            ]
            if extra:
                m.__dict__["_dyn_keys"] = tuple(sorted((*pinned, *extra)))
        return m

    # -- convenience ----------------------------------------------------------
    def __repr__(self) -> str:
        parts = []
        for k in sorted(self.__dict__):
            v = self.__dict__[k]
            if isinstance(v, (jax.Array, np.ndarray)):
                parts.append(f"{k}={v.dtype}{list(v.shape)}")
            elif isinstance(v, Module):
                parts.append(f"{k}={type(v).__name__}(...)")
        return f"{type(self).__name__}({', '.join(parts)})"


# -----------------------------------------------------------------------------
# Tree utilities over modules
# -----------------------------------------------------------------------------


def _axes_for(m: Module, name: str, default=None):
    ax = m.__dict__.get(f"{name}_axes", default)
    if ax is None:
        return None
    return tuple(ax)


def logical_axes(tree: Any) -> Any:
    """Replace every array leaf with a logical ``PartitionSpec``.

    Arrays annotated via ``<name>_axes`` get ``P(*axes)`` (``None`` entries
    allowed for unsharded dims); unannotated arrays get ``P()`` (replicate).
    The result has the same treedef as ``tree``, with ``PartitionSpec`` leaves.
    """

    def rec(node, axes):
        if isinstance(node, Module):
            children, aux = _flatten_module(node)
            keys = aux[0]
            new_children = [
                rec(c, _axes_for(node, k)) for k, c in zip(keys, children)
            ]
            return _unflatten_module(type(node), aux, new_children)
        if isinstance(node, (list, tuple)):
            return type(node)(rec(c, axes) for c in node)
        if isinstance(node, dict):
            return {k: rec(v, axes) for k, v in node.items()}
        # array leaf
        if axes is None:
            return P()
        spec = tuple(a if a else None for a in axes)
        return P(*spec)

    return rec(tree, None)


def trainable_mask(tree: Any) -> Any:
    """Module-shaped pytree of bools: True for trainable params, False for state."""

    def rec(node, is_state):
        if isinstance(node, Module):
            children, aux = _flatten_module(node)
            keys = aux[0]
            state_fields = set(node.__dict__.get("_state_fields", ()) or ()) | set(
                getattr(type(node), "_state_fields", ()) or ()
            )
            new_children = [
                rec(c, is_state or (k in state_fields))
                for k, c in zip(keys, children)
            ]
            return _unflatten_module(type(node), aux, new_children)
        if isinstance(node, (list, tuple)):
            return type(node)(rec(c, is_state) for c in node)
        if isinstance(node, dict):
            return {k: rec(v, is_state) for k, v in node.items()}
        return np.asarray(not is_state)

    return rec(tree, False)


def tree_replace(tree: Any, where: Callable[[Any], Any], new: Any) -> Any:
    """Functional update: replace the subtree selected by ``where(tree)``.

    ``where`` must return a node (by identity) contained in ``tree``.
    """
    target = where(tree)

    def rec(node):
        if node is target:
            return new
        if isinstance(node, Module):
            children, aux = _flatten_module(node)
            return _unflatten_module(type(node), aux, [rec(c) for c in children])
        if isinstance(node, (list, tuple)):
            return type(node)(rec(c) for c in node)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return node

    return rec(tree)


def named_parameters(tree: Any) -> list[tuple[str, Any]]:
    """Flat list of (dotted-path, array) pairs, analogous to a state dict."""
    out = []
    for path, leaf in jtu.tree_flatten_with_path(tree)[0]:
        name = ".".join(
            str(getattr(k, "name", getattr(k, "idx", getattr(k, "key", k))))
            for k in path
        )
        out.append((name, leaf))
    return out


def param_count(tree: Any) -> int:
    return sum(
        int(np.prod(x.shape)) for x in jtu.tree_leaves(tree) if hasattr(x, "shape")
    )
