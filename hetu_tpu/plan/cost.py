"""One cost-model interface over training, serving, and embedding.

Adapters around the substrates the earlier PRs built: the training
model wraps :class:`~hetu_tpu.parallel.autoparallel.cost_model.
TimeCostModel` / ``MemoryCostModel`` (Galvatron-style per-layer
arithmetic), the serving-throughput model consumes the SLO stage
decomposition the ProfileStore's ``serve`` records carry, and the
embedding-traffic model consumes the ``embed`` records'
hit-rate/pull-bytes signals.  Every constant is drawn from
:func:`~hetu_tpu.obs.calibration.fit_calibration` with the named
defaults in :data:`~hetu_tpu.obs.calibration.DEFAULT_CONSTANTS` when
uncalibrated (the 0.4/0.7 idiom) — a fresh checkout plans
deterministically, a calibrated store plans from measurements.

Predictions are plain dicts of named floats; :class:`UnifiedCostModel`
merges the three adapters and reduces them to the (slo_feasible, cost)
pair the lexicographic search ranks on.
"""

from __future__ import annotations

import math

from hetu_tpu.obs.calibration import DEFAULT_CONSTANTS
from hetu_tpu.parallel.autoparallel.cost_model import (
    ClusterSpec, MemoryCostModel, ParallelChoice, TimeCostModel,
    transformer_layer_spec)

__all__ = [
    "CostModel", "TrainCostModel", "ServingCostModel",
    "EmbeddingCostModel", "UnifiedCostModel", "constant", "ladder_bucket",
]


def constant(calibration, name: str) -> float:
    """One cost-model constant: the calibrated fit when available, the
    named default otherwise (every name must be in DEFAULT_CONSTANTS —
    an unnamed constant has no uncalibrated behavior and is a bug)."""
    if calibration is not None:
        v = calibration.get(name)
        if v is not None:
            return float(v)
    return float(DEFAULT_CONSTANTS[name])


def ladder_bucket(ladder, prompt_len: int) -> int:
    """The bucket a prompt pads to: smallest rung >= the prompt, else
    the top rung (the ContinuousBatcher's clipping rule)."""
    rungs = sorted(int(b) for b in ladder)
    if not rungs:
        return int(prompt_len)
    for b in rungs:
        if b >= prompt_len:
            return b
    return rungs[-1]


class CostModel:
    """Interface: ``predict(spec, plan) -> {name: float}``.  Adapters
    return {} when their axis is not deployed, so the unified model's
    merge covers train-only, serve-only, and hybrid plans."""

    def predict(self, spec, plan) -> dict:
        raise NotImplementedError


class TrainCostModel(CostModel):
    """Training step time + per-device peak bytes for the plan's mesh,
    via the autoparallel cost models (calibrated mfu/dp_overlap/
    activation_scale when fitted)."""

    def __init__(self, calibration=None):
        self.calibration = calibration

    def predict(self, spec, plan) -> dict:
        if plan.gang_size < 1 or spec.train_devices < 1:
            return {}
        cluster = ClusterSpec(n_devices=plan.gang_size,
                              hbm_bytes=spec.hbm_bytes,
                              peak_flops=spec.peak_flops)
        tm = TimeCostModel(cluster, calibration=self.calibration)
        mm = MemoryCostModel(cluster, calibration=self.calibration)
        layer = transformer_layer_spec(spec.hidden_size, spec.seq_len,
                                       spec.mlp_ratio)
        choice = ParallelChoice(dp=plan.dp, tp=plan.tp, zero=plan.zero)
        batch_per_replica = max(1, spec.global_batch // max(plan.dp, 1))
        layers_per_stage = math.ceil(spec.n_layers / max(plan.pp, 1))
        micro = max(plan.microbatch, 1)
        t_layer = tm.layer_time(layer, choice, batch_per_replica,
                                plan.remat_policy)
        stage_t = t_layer * layers_per_stage
        if plan.pp > 1:
            # pipeline fill/drain bubble over the microbatch train
            step = stage_t / micro * (micro + plan.pp - 1)
        else:
            step = stage_t
        peak = mm.layer_bytes(layer, choice, batch_per_replica, micro,
                              plan.remat_policy) * layers_per_stage
        return {
            "step_time_s": round(step, 12),
            "train_peak_bytes": round(peak, 3),
        }


class ServingCostModel(CostModel):
    """Fleet throughput and tail latency from the SLO calibration: the
    per-stage means the ``serve`` records fit (``prefill_mean_s``,
    ``decode_mean_s``, ``queue_mean_s``) plus the speculative
    acceptance rate, applied to the plan's replica/role-split/ladder/
    pool/spec_k axes."""

    def __init__(self, calibration=None):
        self.calibration = calibration

    def predict(self, spec, plan) -> dict:
        if plan.replicas < 1:
            return {}
        cal = self.calibration
        prefill_s = constant(cal, "prefill_mean_s")
        decode_s = constant(cal, "decode_mean_s")
        queue_s = constant(cal, "queue_mean_s")
        accept = constant(cal, "spec_accept_rate")
        slots = max(plan.slots_per_replica, 1)
        # per-token decode latency: the calibrated per-request decode
        # mean spread over the workload's mean generation length
        tok_s = decode_s / max(spec.decode_len, 1)
        speedup = 1.0 + plan.spec_k * accept if plan.spec_k > 0 else 1.0
        decode_engines = plan.decode_workers or plan.replicas
        prefill_engines = plan.prefill_workers or plan.replicas
        decode_tps = decode_engines * slots * speedup / tok_s
        # prompt padding the ladder costs at the tail
        bucket = ladder_bucket(plan.bucket_ladder, spec.prompt_p99)
        pad = bucket / max(spec.prompt_p99, 1)
        prefill_rps = prefill_engines * slots / max(prefill_s * pad, 1e-12)
        util = (spec.requests_per_s / prefill_rps
                if prefill_rps > 0 else 0.0)
        ttft = queue_s + prefill_s * pad
        if util >= 1.0:
            # offered load exceeds prefill capacity: the queue diverges
            ttft = float(spec.ttft_p99_s) + 1e9
        # KV pool sufficiency: every slot must hold its padded prompt
        # plus the full generation without stealing pages
        seq_tokens = min(spec.seq_len, bucket + spec.decode_len)
        need_pages = slots * math.ceil(
            seq_tokens / max(plan.page_size, 1)) + 1
        pool_pages = plan.kv_pool_pages if plan.kv_pool_pages > 0 \
            else need_pages
        kv_token_bytes = 4.0 * spec.n_layers * spec.hidden_size  # K+V bf16
        return {
            "decode_tps": round(decode_tps, 6),
            "ttft_p99_s": round(ttft, 12),
            "serve_util": round(util, 12),
            "serve_pool_ok": 1.0 if pool_pages >= need_pages else 0.0,
            "serve_kv_bytes": round(
                pool_pages * plan.page_size * kv_token_bytes, 3),
        }


class EmbeddingCostModel(CostModel):
    """Host-pull traffic and HBM residency for the plan's tiered-
    embedding axes, from the ``embed`` calibration records (hit-rate
    ceiling, pull bytes): the HBM hot-row budget buys hit rate up to
    the measured ceiling; misses pull f32 rows from the host tier."""

    def __init__(self, calibration=None):
        self.calibration = calibration

    def predict(self, spec, plan) -> dict:
        if spec.embed_rows < 1 or spec.embed_dim < 1:
            return {}
        cal = self.calibration
        hit_ceiling = constant(cal, "embed_hbm_hit_rate")
        hot_rows = max(spec.embed_hot_fraction * spec.embed_rows, 1.0)
        coverage = min(1.0, plan.embed_hbm_rows / hot_rows)
        hbm_hit = hit_ceiling * coverage
        row_bytes = spec.embed_dim * (1.0 if plan.embed_storage == "int8"
                                      else 4.0)
        lookups = float(spec.global_batch)
        pull = (1.0 - hbm_hit) * lookups * spec.embed_dim * 4.0
        return {
            "embed_hbm_hit_rate": round(hbm_hit, 12),
            "embed_hbm_bytes": round(plan.embed_hbm_rows * row_bytes, 3),
            "embed_pull_bytes_per_stage": round(pull, 3),
        }


class UnifiedCostModel(CostModel):
    """The composition the search ranks on: merge the three adapters'
    predictions, then reduce to memory feasibility, SLO feasibility,
    and one scalar cost (lower is better).  All pure float arithmetic
    on (spec, plan, calibration) — bitwise-replayable."""

    def __init__(self, calibration=None):
        self.calibration = calibration
        self.models = (TrainCostModel(calibration),
                       ServingCostModel(calibration),
                       EmbeddingCostModel(calibration))

    def predict(self, spec, plan) -> dict:
        out: dict = {}
        for m in self.models:
            out.update(m.predict(spec, plan))
        return out

    # -- feasibility -------------------------------------------------------

    def serve_device_bytes(self, spec, plan, pred) -> float:
        """Per-serving-device HBM demand: inference weights (bf16) +
        the KV pool + the embedding hot tier."""
        layer = transformer_layer_spec(spec.hidden_size, spec.seq_len,
                                       spec.mlp_ratio)
        params = layer.params * spec.n_layers \
            + spec.vocab_size * spec.hidden_size
        return (2.0 * params + pred.get("serve_kv_bytes", 0.0)
                + pred.get("embed_hbm_bytes", 0.0))

    def memory_feasible(self, spec, plan, pred) -> bool:
        if pred.get("train_peak_bytes", 0.0) > spec.hbm_bytes:
            return False
        if plan.replicas > 0:
            if pred.get("serve_pool_ok", 1.0) < 1.0:
                return False
            if self.serve_device_bytes(spec, plan, pred) > spec.hbm_bytes:
                return False
        return True

    def slo_feasible(self, spec, plan, pred) -> bool:
        if plan.replicas > 0:
            if pred.get("ttft_p99_s", 0.0) > spec.ttft_p99_s:
                return False
            if spec.decode_tps > 0 \
                    and pred.get("decode_tps", 0.0) < spec.decode_tps:
                return False
            if pred.get("serve_util", 0.0) >= 1.0:
                return False
        return self.memory_feasible(spec, plan, pred)

    def cost(self, spec, plan, pred) -> float:
        """The scalar the lexicographic search minimizes among
        SLO-feasible candidates: training step time + per-request
        serving latency inflated by utilization + embedding pull
        traffic at host-link seconds."""
        total = pred.get("step_time_s", 0.0)
        if plan.replicas > 0:
            tok_s = constant(self.calibration, "decode_mean_s") \
                / max(spec.decode_len, 1)
            speedup = (1.0 + plan.spec_k
                       * constant(self.calibration, "spec_accept_rate")
                       if plan.spec_k > 0 else 1.0)
            request_s = pred.get("ttft_p99_s", 0.0) \
                + spec.decode_len * tok_s / speedup
            total += request_s * (1.0 + pred.get("serve_util", 0.0))
        total += pred.get("embed_pull_bytes_per_stage", 0.0) * 1e-9
        return round(total, 12)
