"""The deterministic staged search: one DeploymentSpec in, one Plan out.

Stages (each pure, each totally ordered):

1. **Parallelism** — ``dp_search`` over the training carve-out of the
   fleet (Galvatron-style per-layer DP, already calibration-aware)
   picks mesh/pipeline/remat/microbatch; an optional ``memory_probe``
   (loss_fn, model_builder, batch_builder) refines remat + microbatch
   through :func:`~hetu_tpu.mem.planner.plan_memory` against the real
   traced peak.
2. **Serving × embedding enumeration** — a canonical, sorted candidate
   grid over replicas, prefill/decode role split, bucket ladder, KV
   pool pages, ``spec_k``, and the embedding hot-tier axes.
3. **Prune + rank** — memory-infeasible candidates drop first, then
   lexicographic (SLO-feasible, predicted cost) with the candidate's
   own canonical tuple as the total-order tie-break, so equal-cost
   frontiers resolve identically on every run.

Exactly one Plan comes out; the decision is journaled as ``plan_emit``
with the considered-frontier summary (candidates scored, memory-pruned,
SLO-feasible count) and counted on the ``hetu_plan_*`` families.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math

from hetu_tpu.mem.policy import policy_names
from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import registry as _obs
from hetu_tpu.parallel.autoparallel.cost_model import (
    ClusterSpec, transformer_layer_spec)
from hetu_tpu.parallel.autoparallel.search import dp_search
from hetu_tpu.plan.cost import UnifiedCostModel, ladder_bucket
from hetu_tpu.plan.spec import DeploymentSpec, Plan

__all__ = ["plan_deployment", "DeploymentPlanner"]

_plan_metrics = None


def _plan_m() -> dict:
    global _plan_metrics
    if _plan_metrics is None:
        reg = _obs.get_registry()
        _plan_metrics = {
            "emitted": reg.counter(
                "hetu_plan_emitted_total",
                "deployment plans emitted by the unified planner, by "
                "trigger (initial, gang_rescale, quarantine, slo_burn)",
                ("trigger",)),
            "candidates": reg.gauge(
                "hetu_plan_candidates",
                "candidate configurations scored by the last "
                "unified-planner search"),
            "slo_feasible": reg.gauge(
                "hetu_plan_slo_feasible",
                "1 when the last emitted plan predicts the spec's SLO "
                "targets are met, else 0"),
            "applies": reg.counter(
                "hetu_plan_applies_total",
                "plans applied to a running system, by trigger",
                ("trigger",)),
        }
    return _plan_metrics


def _calibration_sha(calibration) -> str:
    if calibration is None:
        return ""
    return hashlib.sha256(calibration.to_json().encode()).hexdigest()


# --------------------------------------------------------- stage 1: mesh

def _train_axes(spec: DeploymentSpec, calibration, memory_probe) -> dict:
    """Parallelism via the autoparallel DP; returns the Plan's training
    fields.  No training carve-out -> no gang, defaults throughout."""
    out = dict(dp=1, tp=1, pp=1, schedule="none", virtual_stages=1,
               remat_policy="none", microbatch=1, zero=False,
               gang_size=0, partial_deadline_s=0.0, train_feasible=True)
    if spec.train_devices < 1:
        return out
    cluster = ClusterSpec(n_devices=spec.train_devices,
                          hbm_bytes=spec.hbm_bytes,
                          peak_flops=spec.peak_flops)
    layer = transformer_layer_spec(spec.hidden_size, spec.seq_len,
                                   spec.mlp_ratio)
    ap = dp_search([layer] * spec.n_layers, cluster, spec.global_batch,
                   remat_policies=policy_names(), calibration=calibration)
    choice = ap.dominant
    out.update(dp=choice.dp, tp=choice.tp, pp=ap.pp,
               schedule="1f1b" if ap.pp > 1 else "none",
               virtual_stages=ap.virtual_stages,
               remat_policy=ap.remat_policy, microbatch=ap.n_microbatches,
               zero=choice.zero, gang_size=spec.train_devices,
               partial_deadline_s=spec.partial_deadline_s,
               train_feasible=ap.feasible)
    if memory_probe is not None:
        # refine against the TRACED peak (plan_memory divides by the
        # calibrated estimator-error ratio), not just the closed form
        from hetu_tpu.mem.planner import plan_memory
        loss_fn, model_builder, batch_builder = memory_probe
        mp = plan_memory(loss_fn, model_builder, batch_builder,
                         spec.hbm_bytes, policies=policy_names(),
                         microbatch_options=(1, 2, 4, 8),
                         calibration=calibration)
        out.update(remat_policy=mp.policy, microbatch=mp.microbatch,
                   train_feasible=out["train_feasible"] and mp.fits)
    return out


# -------------------------------------------- stage 2: candidate grids

def _pow2_at_least(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


def _ladder_candidates(spec: DeploymentSpec) -> list:
    """Canonical bucket-ladder grid from the workload's prompt stats:
    a dense power-of-two ladder, a two-rung p50/p99 ladder, and a
    single-bucket ladder — all clipped to the model's context."""
    p50 = min(_pow2_at_least(max(spec.prompt_p50, 8)), spec.seq_len)
    p99 = min(_pow2_at_least(max(spec.prompt_p99, p50)), spec.seq_len)
    dense, b = [], 8
    while b < p99:
        dense.append(b)
        b *= 2
    dense.append(p99)
    cands = {tuple(dense), (p50, p99) if p50 < p99 else (p99,), (p99,)}
    return sorted(cands)


def _pool_page_candidates(spec: DeploymentSpec, ladder: tuple) -> list:
    """KV pool sizes: exactly-sufficient for the padded tail, and a
    1.5x headroom variant (plus 0 = the engine's own default sizing)."""
    bucket = ladder_bucket(ladder, spec.prompt_p99)
    seq_tokens = min(spec.seq_len, bucket + spec.decode_len)
    need = spec.slots_per_replica * math.ceil(
        seq_tokens / spec.page_size) + 1
    return sorted({0, need, math.ceil(need * 1.5)})


def _serve_candidates(spec: DeploymentSpec) -> list:
    """The sorted serving grid: (replicas, prefill, decode, ladder,
    pool_pages, spec_k) tuples; one all-zero row when no devices are
    carved out for serving."""
    if spec.serve_devices < 1:
        return [(0, 0, 0, (), 0, 0)]
    out = []
    spec_ks = (0, 2, 4) if spec.speculative else (0,)
    for r in range(1, spec.serve_devices + 1):
        splits = [(0, 0)]
        if r >= 2:
            splits += [(p, r - p) for p in range(1, r)]
        for ladder in _ladder_candidates(spec):
            for pages in _pool_page_candidates(spec, ladder):
                for (p, d) in sorted(splits):
                    for k in spec_ks:
                        out.append((r, p, d, ladder, pages, k))
    return sorted(out)


def _embed_candidates(spec: DeploymentSpec) -> list:
    """The sorted embedding grid: (hbm_rows, host_rows, storage,
    promote_touches, demote_idle); one all-off row when the workload
    has no embedding tables."""
    if spec.embed_rows < 1 or spec.embed_dim < 1:
        return [(0, 0, "f32", 2, 0)]
    hot = max(int(math.ceil(spec.embed_hot_fraction * spec.embed_rows)),
              1)
    out = []
    for rows in sorted({max(hot // 2, 1), hot}):
        for storage in ("f32", "int8"):
            for touches in (1, 2):
                out.append((rows, min(4 * rows, spec.embed_rows),
                            storage, touches, 0))
    return sorted(out)


# ------------------------------------------------- stage 3: prune + rank

def plan_deployment(spec: DeploymentSpec, *, calibration=None,
                    memory_probe=None, trigger: str = "initial") -> Plan:
    """Emit exactly one signed Plan for ``spec`` — a pure function of
    (spec, calibration): byte-identical ``Plan.to_json()`` from
    identical inputs.  Journals ``plan_emit`` with the frontier
    summary."""
    train = _train_axes(spec, calibration, memory_probe)
    train_feasible = train.pop("train_feasible")
    model = UnifiedCostModel(calibration)

    best = None
    n_cands = n_mem_pruned = n_slo = 0
    for cand in _serve_candidates(spec):
        (r, p, d, ladder, pages, k) = cand
        for emb in _embed_candidates(spec):
            (rows, host_rows, storage, touches, idle) = emb
            n_cands += 1
            plan = Plan(
                replicas=r, prefill_workers=p, decode_workers=d,
                slots_per_replica=spec.slots_per_replica,
                bucket_ladder=ladder, kv_pool_pages=pages,
                page_size=spec.page_size, spec_k=k,
                embed_hbm_rows=rows, embed_host_rows=host_rows,
                embed_storage=storage, promote_touches=touches,
                demote_idle=idle, **train)
            pred = model.predict(spec, plan)
            if not model.memory_feasible(spec, plan, pred):
                n_mem_pruned += 1
                continue
            slo_ok = model.slo_feasible(spec, plan, pred)
            n_slo += slo_ok
            # lexicographic (SLO-feasible, cost) with the candidate's
            # canonical tuple as the deterministic total-order tie-break
            key = (not slo_ok, model.cost(spec, plan, pred), cand, emb)
            if best is None or key < best[0]:
                best = (key, plan, pred, slo_ok)
    if best is None:
        # every candidate was memory-infeasible: surface the bare-axes
        # plan rather than nothing, marked infeasible
        plan = Plan(slots_per_replica=spec.slots_per_replica,
                    page_size=spec.page_size, **train)
        pred, slo_ok = model.predict(spec, plan), False
    else:
        (_, plan, pred, slo_ok) = best
    plan = dataclasses.replace(
        plan,
        spec_sha256=spec.signature(),
        calibration_sha256=_calibration_sha(calibration),
        predicted=tuple(sorted(pred.items())),
        feasible=bool(train_feasible and best is not None))
    _journal.record("plan_emit", sha256=plan.sha256, candidates=n_cands,
                    slo_feasible=int(n_slo), mem_pruned=n_mem_pruned,
                    trigger=trigger,
                    cost=(best[0][1] if best is not None else -1.0))
    if _obs.enabled():
        m = _plan_m()
        m["emitted"].labels(trigger=trigger).inc()
        m["candidates"].set(float(n_cands))
        m["slo_feasible"].set(1.0 if slo_ok else 0.0)
    return plan


class DeploymentPlanner:
    """The stateful wrapper the runtime hooks call: holds (spec,
    calibration, probe), tracks the current Plan, and re-plans against
    a surviving fleet on demand (quarantine, rescale, SLO burn)."""

    def __init__(self, spec: DeploymentSpec, *, calibration=None,
                 memory_probe=None):
        self.spec = spec
        self.calibration = calibration
        self.memory_probe = memory_probe
        self.current = None

    def plan(self, trigger: str = "initial") -> Plan:
        self.current = plan_deployment(
            self.spec, calibration=self.calibration,
            memory_probe=self.memory_probe, trigger=trigger)
        return self.current

    def replan(self, *, n_devices: int = None, serve_devices: int = None,
               trigger: str = "replan") -> Plan:
        """Re-plan against a changed fleet shape (the surviving world
        after an eviction, a shrunk serving carve-out under SLO burn).
        The adjusted spec becomes the planner's new baseline, so
        successive shrinks compound."""
        changes = {}
        if n_devices is not None:
            changes["n_devices"] = int(n_devices)
            changes["serve_devices"] = min(
                self.spec.serve_devices, int(n_devices))
        if serve_devices is not None:
            changes["serve_devices"] = int(serve_devices)
        if changes:
            self.spec = dataclasses.replace(self.spec, **changes)
        return self.plan(trigger=trigger)
