"""Frozen deployment inputs and the signed, versioned ``Plan``.

The unified planner (ROADMAP item 1's composition layer) turns one
:class:`DeploymentSpec` — model signature, fleet shape, HBM budget, SLO
targets, workload mix — into exactly one :class:`Plan` covering every
axis the last six PRs made tunable: the training mesh (dp × tp × pp,
pipeline schedule, remat policy, microbatch), the gang (size,
partial-reduce deadline), the serving tier (replica count,
prefill/decode role split, bucket ladder, KV pool pages, speculative
``spec_k``) and the embedding tier (HBM hot-row budget, promote/demote
thresholds, host cache capacity, int8 vs f32 storage).

Both dataclasses are frozen and serialize through the ProfileStore's
canonical-envelope idiom (``obs/calibration.py``): a canonical JSON body
(sorted keys, canonical separators) wrapped with a CRC32 and a sha256
signature over a format-versioned sign key, so identical inputs yield
byte-identical ``to_json`` output and a torn write, a stray editor, or
bit rot is diagnosed by name (:class:`PlanError`) rather than half-read.
Older-format plans (``hetu-plan-v0``) load with the missing axes filled
from the dataclass defaults — a plan file outlives the planner version
that wrote it.

Determinism bar: this package never touches wall clocks or entropy (the
plan-determinism lint in ``tests/test_obs.py`` rejects ``time``/
``random`` imports and unsorted dict iteration in ``hetu_tpu/plan/``),
so a Plan is a pure function of (spec, calibration).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import pathlib
import zlib

__all__ = [
    "PLAN_FORMAT", "PlanError", "DeploymentSpec", "Plan",
]

PLAN_FORMAT = "hetu-plan-v1"
# older envelope formats still accepted by Plan.from_json (missing
# fields fill from the dataclass defaults)
_COMPAT_FORMATS = ("hetu-plan-v0",)
# content signature over the canonical plan body (the gang-manifest /
# calibration-store discipline): not a secret — the key is in the repo —
# but a torn write or an edited file cannot produce a plan whose
# signature still verifies.
_SIGN_KEYS = {
    "hetu-plan-v1": b"hetu-tpu-plan-v1:",
    "hetu-plan-v0": b"hetu-tpu-plan-v0:",
}


class PlanError(Exception):
    """A plan could not be loaded or verified (torn write, CRC mismatch,
    signature mismatch, alien format) — the diagnosis names which."""


def _canon(body: dict) -> str:
    return json.dumps(body, sort_keys=True, separators=(",", ":"))


@dataclasses.dataclass(frozen=True)
class DeploymentSpec:
    """Everything the planner is allowed to know, frozen.

    One spec = one deployment question: this model, on this fleet,
    under this HBM budget, serving this workload mix against these SLO
    targets.  The planner is a pure function of (spec, calibration);
    anything not in the spec cannot influence the emitted plan.
    """

    # -- model -------------------------------------------------------------
    model_sig: str = "model"
    n_layers: int = 2
    hidden_size: int = 64
    seq_len: int = 128
    vocab_size: int = 32000
    mlp_ratio: int = 4
    global_batch: int = 8

    # -- fleet shape / HBM budget -----------------------------------------
    n_devices: int = 8
    serve_devices: int = 0          # devices carved out for the serving fleet
    hbm_bytes: float = 16e9         # per-device budget
    peak_flops: float = 197e12
    device_kind: str = ""

    # -- SLO targets -------------------------------------------------------
    ttft_p99_s: float = 0.5
    decode_tps: float = 0.0         # fleet decode-throughput floor (0 = none)

    # -- serving workload mix ----------------------------------------------
    requests_per_s: float = 0.0
    prompt_p50: int = 16
    prompt_p99: int = 64
    decode_len: int = 16            # mean generated tokens per request
    slots_per_replica: int = 8
    page_size: int = 16
    speculative: bool = False       # a draft model exists: search spec_k > 0

    # -- embedding workload ------------------------------------------------
    embed_rows: int = 0
    embed_dim: int = 0
    embed_hot_fraction: float = 0.05

    # -- training-side baseline -------------------------------------------
    partial_deadline_s: float = 0.0   # 0 = synchronous barrier

    def __post_init__(self):
        for name in ("n_layers", "hidden_size", "seq_len", "vocab_size",
                     "mlp_ratio", "global_batch", "n_devices",
                     "slots_per_replica", "page_size"):
            if int(getattr(self, name)) < 1:
                raise ValueError(f"{name} must be >= 1, "
                                 f"got {getattr(self, name)}")
        for name in ("serve_devices", "embed_rows", "embed_dim",
                     "prompt_p50", "prompt_p99", "decode_len"):
            if int(getattr(self, name)) < 0:
                raise ValueError(f"{name} must be >= 0, "
                                 f"got {getattr(self, name)}")
        if self.serve_devices > self.n_devices:
            raise ValueError(
                f"serve_devices ({self.serve_devices}) exceeds the fleet "
                f"({self.n_devices})")
        if not 0.0 <= self.embed_hot_fraction <= 1.0:
            raise ValueError("embed_hot_fraction must be in [0, 1], "
                             f"got {self.embed_hot_fraction}")
        if self.hbm_bytes <= 0 or self.peak_flops <= 0:
            raise ValueError("hbm_bytes and peak_flops must be positive")

    @property
    def train_devices(self) -> int:
        return self.n_devices - self.serve_devices

    def to_json(self) -> str:
        """Canonical serialization — byte-identical for identical specs."""
        return _canon(dataclasses.asdict(self))

    def signature(self) -> str:
        """sha256 over the canonical body: the spec identity the emitted
        plan's provenance (``spec_sha256``) and journal events carry."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()


@dataclasses.dataclass(frozen=True)
class Plan:
    """One deployment decision, frozen and signed.

    Every axis the runtime consumes lives here; ``apply.py`` maps the
    serving axes onto ``ServingEngine`` kwargs and the training axes
    onto the gang's actuators.  Zero values mean "axis not deployed"
    (``gang_size=0`` = no training gang, ``replicas=0`` = no serving
    fleet, ``embed_hbm_rows=0`` = no tiered embedding), so one Plan
    type covers train-only, serve-only, and hybrid deployments.
    """

    # -- parallelism / training axes --------------------------------------
    dp: int = 1
    tp: int = 1
    pp: int = 1
    schedule: str = "none"          # "none" | "gpipe" | "1f1b" | "interleaved"
    virtual_stages: int = 1
    remat_policy: str = "none"
    microbatch: int = 1
    zero: bool = False
    gang_size: int = 0
    partial_deadline_s: float = 0.0

    # -- serving axes ------------------------------------------------------
    replicas: int = 0
    prefill_workers: int = 0        # 0/0 split = colocated replicas
    decode_workers: int = 0
    slots_per_replica: int = 8
    bucket_ladder: tuple = ()
    kv_pool_pages: int = 0          # 0 = engine default sizing
    page_size: int = 16
    spec_k: int = 0                 # 0 = no speculative decoding

    # -- embedding axes ----------------------------------------------------
    embed_hbm_rows: int = 0
    embed_host_rows: int = 0
    embed_storage: str = "f32"      # "f32" | "int8"
    promote_touches: int = 2
    demote_idle: int = 0

    # -- provenance / predictions -----------------------------------------
    spec_sha256: str = ""
    calibration_sha256: str = ""
    predicted: tuple = ()           # sorted ((name, value), ...) pairs
    feasible: bool = True

    def __post_init__(self):
        if self.embed_storage not in ("f32", "int8"):
            raise ValueError(f"embed_storage must be 'f32' or 'int8', "
                             f"got {self.embed_storage!r}")
        if self.schedule not in ("none", "gpipe", "1f1b", "interleaved"):
            raise ValueError(f"unknown pipeline schedule "
                             f"{self.schedule!r}")
        if self.prefill_workers + self.decode_workers not in (
                0, self.replicas):
            raise ValueError(
                f"role split {self.prefill_workers}+{self.decode_workers} "
                f"does not cover replicas={self.replicas} (0/0 = "
                f"colocated)")
        # normalize sequence fields so hand-built and deserialized plans
        # compare (and serialize) identically
        object.__setattr__(self, "bucket_ladder",
                           tuple(int(b) for b in self.bucket_ladder))
        object.__setattr__(
            self, "predicted",
            tuple(sorted((str(k), float(v)) for k, v in self.predicted)))

    # -- canonical serialization ------------------------------------------

    def _body(self) -> dict:
        plan = dataclasses.asdict(self)
        plan["bucket_ladder"] = list(self.bucket_ladder)
        plan["predicted"] = [[k, v] for k, v in self.predicted]
        return {"format": PLAN_FORMAT, "plan": plan}

    @property
    def sha256(self) -> str:
        """The plan identity: sha256 over the canonical body (what
        ``plan_emit`` / ``plan_apply`` journal and the bench line
        carries)."""
        return hashlib.sha256(_canon(self._body()).encode()).hexdigest()

    def to_json(self) -> bytes:
        """The exact on-disk bytes: canonical body + CRC32 + sha256
        signature over it.  Byte-identical from identical inputs."""
        canon = _canon(self._body())
        key = _SIGN_KEYS[PLAN_FORMAT]
        envelope = {
            "body": json.loads(canon),
            "crc32": zlib.crc32(canon.encode()) & 0xFFFFFFFF,
            "sha256": hashlib.sha256(key + canon.encode()).hexdigest(),
        }
        return json.dumps(envelope, sort_keys=True,
                          separators=(",", ":")).encode()

    @classmethod
    def from_json(cls, raw: bytes, where: str = "<memory>") -> "Plan":
        """Parse + verify an envelope; raises :class:`PlanError` naming
        the failure (torn write, alien format, CRC, signature).  Bodies
        in an older accepted format load with missing axes defaulted."""
        try:
            envelope = json.loads(
                raw.decode() if isinstance(raw, bytes) else raw)
        except (ValueError, UnicodeDecodeError) as e:
            raise PlanError(
                f"plan {where}: not valid JSON ({e}) — torn write or "
                f"alien file") from e
        body = envelope.get("body") if isinstance(envelope, dict) else None
        if not isinstance(body, dict) or body.get("format") not in (
                (PLAN_FORMAT,) + _COMPAT_FORMATS):
            raise PlanError(
                f"plan {where}: format is not {PLAN_FORMAT} (or a "
                f"compatible older version)")
        fmt = body["format"]
        canon = _canon(body)
        if envelope.get("crc32") != (zlib.crc32(canon.encode())
                                     & 0xFFFFFFFF):
            raise PlanError(
                f"plan {where}: CRC32 mismatch — the bytes were damaged "
                f"after writing")
        expect = hashlib.sha256(
            _SIGN_KEYS[fmt] + canon.encode()).hexdigest()
        if envelope.get("sha256") != expect:
            raise PlanError(
                f"plan {where}: signature mismatch — the file was "
                f"modified after signing")
        plan = body.get("plan")
        if not isinstance(plan, dict):
            raise PlanError(f"plan {where}: body carries no plan")
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: plan[k] for k in sorted(plan) if k in known}
        try:
            return cls(**kwargs)
        except (TypeError, ValueError) as e:
            raise PlanError(f"plan {where}: invalid field values "
                            f"({e})") from e

    def save(self, path: str) -> str:
        """Atomic write (tmp + replace) of the signed envelope."""
        p = pathlib.Path(path)
        tmp = p.with_name(p.name + ".tmp")
        tmp.write_bytes(self.to_json())
        tmp.replace(p)
        return str(p)

    @classmethod
    def load(cls, path: str) -> "Plan":
        try:
            raw = pathlib.Path(path).read_bytes()
        except OSError as e:
            raise PlanError(f"plan {path}: unreadable ({e})") from e
        return cls.from_json(raw, where=str(path))

    def describe(self) -> str:
        """One human line (the ``/plan`` payload headline)."""
        mesh = f"dp{self.dp}tp{self.tp}pp{self.pp}"
        serve = (f"{self.replicas}r"
                 + (f"({self.prefill_workers}p/{self.decode_workers}d)"
                    if self.prefill_workers or self.decode_workers
                    else "") if self.replicas else "-")
        embed = (f"{self.embed_hbm_rows}rows/{self.embed_storage}"
                 if self.embed_hbm_rows else "-")
        return (f"mesh={mesh} sched={self.schedule} "
                f"remat={self.remat_policy} micro={self.microbatch} "
                f"gang={self.gang_size} serve={serve} embed={embed} "
                f"feasible={self.feasible}")
