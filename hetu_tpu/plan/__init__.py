"""Unified deployment planner (ROADMAP item 1's composition layer).

One deterministic search over parallelism × memory × serving ×
embedding, fed by calibration: a frozen :class:`DeploymentSpec` in, one
signed, versioned :class:`Plan` out.

- :mod:`~hetu_tpu.plan.spec` — the frozen inputs and the signed Plan
  (canonical envelope: CRC32 + sha256, byte-identical from identical
  inputs);
- :mod:`~hetu_tpu.plan.cost` — one ``CostModel`` interface adapting
  the autoparallel time/memory models plus serving-throughput and
  embedding-traffic models, every constant from ``fit_calibration``
  with named defaults when uncalibrated;
- :mod:`~hetu_tpu.plan.search` — the staged deterministic search
  (memory prune, then lexicographic (SLO-feasible, cost) with
  total-order tie-breaks), journaling ``plan_emit``;
- :mod:`~hetu_tpu.plan.apply` — Plan-bearing engine/fleet construction
  and the replan hooks the gang and the runtime controller fire
  (``plan_apply`` journaled, dry-run decides identically and actuates
  nothing).

Determinism bar: nothing in this package reads a clock or entropy, and
every dict iteration is explicitly sorted (the plan-determinism lint in
``tests/test_obs.py`` enforces all three), so a Plan is a pure function
of (spec, calibration).
"""

from hetu_tpu.plan.apply import (PlanApplier, apply_plan, build_fleet,
                                 engine_kwargs)
from hetu_tpu.plan.cost import (CostModel, EmbeddingCostModel,
                                ServingCostModel, TrainCostModel,
                                UnifiedCostModel)
from hetu_tpu.plan.search import DeploymentPlanner, plan_deployment
from hetu_tpu.plan.spec import (PLAN_FORMAT, DeploymentSpec, Plan,
                                PlanError)

__all__ = [
    "PLAN_FORMAT", "DeploymentSpec", "Plan", "PlanError",
    "CostModel", "TrainCostModel", "ServingCostModel",
    "EmbeddingCostModel", "UnifiedCostModel",
    "plan_deployment", "DeploymentPlanner",
    "engine_kwargs", "build_fleet", "apply_plan", "PlanApplier",
]
