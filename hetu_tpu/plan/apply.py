"""Plan actuation: engines built FROM a Plan, replans wired INTO the
runtime's remediation seams.

Construction side: :func:`engine_kwargs` maps a Plan's serving axes
onto ``ServingEngine`` keyword arguments (``ServingEngine(model,
plan=plan)`` does this internally); :func:`build_fleet` constructs the
whole replica set — colocated behind a ``FleetRouter``, or the plan's
prefill/decode role split behind a ``DisaggRouter``.

Runtime side: :class:`PlanApplier` is the replan hook the
``ElasticGang`` (``planner=`` kwarg, fired from ``_rescale`` against
the surviving world) and the ``RuntimeController`` (fired on a
quarantine decision and on a sustained-SLO-burn shed engage) call into.
Every replan journals ``plan_apply`` naming the trigger; in dry-run the
decision — the emitted plan and its sha — is identical, and nothing is
actuated (the controller discipline).
"""

from __future__ import annotations

from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import registry as _obs
from hetu_tpu.plan.search import DeploymentPlanner, _plan_m
from hetu_tpu.plan.spec import Plan

__all__ = ["engine_kwargs", "build_fleet", "apply_plan", "PlanApplier"]


def engine_kwargs(plan: Plan, *, role: str = None) -> dict:
    """The ``ServingEngine`` keyword arguments a Plan's serving axes
    pin.  Zero-valued axes are omitted (the engine's own defaults
    apply), so a partial plan composes with explicit caller kwargs."""
    kw = {"num_slots": plan.slots_per_replica,
          "page_size": plan.page_size}
    if plan.bucket_ladder:
        kw["prompt_buckets"] = plan.bucket_ladder
    if plan.kv_pool_pages > 0:
        kw["num_pages"] = plan.kv_pool_pages
    if plan.spec_k > 0:
        kw["spec_k"] = plan.spec_k
    if role is not None:
        kw["role"] = role
    return kw


def _roles(plan: Plan) -> list:
    if plan.prefill_workers or plan.decode_workers:
        return ["prefill"] * plan.prefill_workers \
            + ["decode"] * plan.decode_workers
    return ["colocated"] * plan.replicas


def build_fleet(model, plan: Plan, *, max_retries: int = None,
                **extra_kwargs):
    """Construct the plan's whole serving tier: ``plan.replicas``
    engines with the plan's ladder/pool/slots (role split -> a
    ``DisaggRouter``, colocated -> a ``FleetRouter``).  ``extra_kwargs``
    (clock, slo_targets, draft_model, tenants, ...) pass through to
    every engine."""
    from hetu_tpu.serve.engine import ServingEngine
    if plan.replicas < 1:
        raise ValueError("plan deploys no serving tier "
                         "(replicas=0) — nothing to build")
    roles = _roles(plan)
    disagg = any(r != "colocated" for r in roles)
    engines = [ServingEngine(model, plan=plan, role=role, **extra_kwargs)
               for role in roles]
    if disagg:
        from hetu_tpu.serve.fleet.disagg import DisaggRouter
        return DisaggRouter(engines, max_retries=max_retries)
    from hetu_tpu.serve.fleet.router import FleetRouter
    return FleetRouter(engines, max_retries=max_retries)


def apply_plan(plan: Plan, *, gang=None, dry_run: bool = False,
               trigger: str = "apply") -> list:
    """Actuate a Plan against a live system and journal ``plan_apply``.

    Actuations are the runtime-safe knobs only (today: the gang's
    partial-reduce deadline); structural axes — mesh shape, replica
    count, pool geometry — take effect at the next construction from
    the plan.  Dry-run journals the identical decision and actuates
    nothing.  Returns the list of actions actuated (empty in
    dry-run)."""
    actions = []
    if gang is not None and plan.partial_deadline_s > 0 \
            and getattr(gang, "partial", None) is not None:
        if not dry_run:
            gang.set_partial_deadline(plan.partial_deadline_s,
                                      source="planner")
        actions.append("partial_deadline")
    _journal.record("plan_apply", sha256=plan.sha256, trigger=trigger,
                    dry_run=bool(dry_run),
                    actions=sorted(actions) if not dry_run else [])
    if _obs.enabled():
        _plan_m()["applies"].labels(trigger=trigger).inc()
    return actions if not dry_run else []


class PlanApplier:
    """The remediation-seam hook: owns a :class:`DeploymentPlanner`
    and re-plans against the surviving fleet when the runtime asks.

    Wire it as ``ElasticGang(..., planner=applier)`` (fires on every
    rescale with the survivors' world) and/or
    ``RuntimeController(..., planner=applier)`` (fires on a quarantine
    decision and on a sustained-SLO-burn shed engage).  The decision
    path is identical under ``dry_run`` — same spec adjustment, same
    emitted plan, same journaled sha — but nothing actuates.
    """

    def __init__(self, planner: DeploymentPlanner, *,
                 dry_run: bool = False):
        self.planner = planner
        self.dry_run = bool(dry_run)

    @property
    def current(self):
        return self.planner.current

    def _dry(self, dry_run) -> bool:
        return self.dry_run if dry_run is None else bool(dry_run)

    def replan_for_gang(self, gang, *, trigger: str = "gang_rescale",
                        dry_run: bool = None,
                        train_world: int = None) -> Plan:
        """Re-plan against the gang's surviving world (the serving
        carve-out is unchanged — an evicted trainer is not a lost
        serving device) and actuate the gang-side knobs.
        ``train_world`` overrides the observed ``gang.live_world`` (the
        dry-run controller passes its shadow-eviction count so the
        decision stream matches an active controller's)."""
        spec = self.planner.spec
        world = int(gang.live_world if train_world is None
                    else train_world)
        plan = self.planner.replan(
            n_devices=world + spec.serve_devices, trigger=trigger)
        apply_plan(plan, gang=gang, dry_run=self._dry(dry_run),
                   trigger=trigger)
        return plan

    def replan_for_lease(self, gang=None, *, serve_devices: int,
                         trigger: str = "lease_grant",
                         dry_run: bool = None) -> Plan:
        """Re-plan when the capacity broker (hetu_tpu/broker) moves
        chips between roles: the total inventory is UNCHANGED — the
        serving carve-out grows (a grant) or shrinks (a reclaim)
        inside it, and the training side gets whatever is left.  The
        emitted plan's sha rides on the lease record, so the journal
        ties every chip movement to the signed deployment it served."""
        plan = self.planner.replan(serve_devices=int(serve_devices),
                                   trigger=trigger)
        apply_plan(plan, gang=gang, dry_run=self._dry(dry_run),
                   trigger=trigger)
        return plan

    def replan_for_engine(self, engine, *, trigger: str = "slo_burn",
                          dry_run: bool = None) -> Plan:
        """Re-plan under serving distress.  The decision is journaled
        immediately; the structural serving axes (replicas, ladder,
        pool) take effect at the next :func:`build_fleet` from
        ``applier.current`` — a live engine's geometry cannot be
        re-shaped under traffic."""
        plan = self.planner.replan(trigger=trigger)
        apply_plan(plan, dry_run=self._dry(dry_run), trigger=trigger)
        return plan
