"""Jaxpr live-range estimator: predict a program's peak device bytes
without compiling it.

The reference's memory planner walks its op DAG and assigns BFC-allocator
blocks ahead of execution (src/memory_pool/); XLA does that job here, so
the *planning* problem becomes prediction: given a step function, how many
temp bytes will XLA's buffer assignment peak at?  This module answers by
simulating buffer live ranges over the traced jaxpr:

- every equation output allocates its aval's bytes at the equation and
  frees after its last use (ideal liveness — XLA's buffer assignment
  reuses dead buffers the same way);
- XLA's fusion makes most *cheap elementwise* values never materialize:
  an output of a fusible elementwise primitive with a single consumer is
  fused into that consumer and costs nothing; view-like primitives
  (reshape/convert/broadcast-of-scalar) alias and always cost nothing;
- nested jaxprs (pjit, checkpoint/remat, scan, cond) are *scoped*: their
  internal peak is charged while the equation runs, and only their
  declared outputs (e.g. a remat region's policy-saved residuals) stay
  live after — which is exactly how ``jax.checkpoint`` policies reduce
  peak memory.

Cross-checked against ``compiled.memory_analysis()`` (tests assert the
prediction lands within 25% of XLA's own number on GPT and BERT training
steps).  Rematerialized programs are *relatively* ordered correctly but
systematically flattered: XLA schedules remat regions less tightly than
ideal liveness assumes, so treat remat predictions as lower bounds (the
planner's budget is the guard rail).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

__all__ = ["MemoryEstimate", "estimate_peak_bytes", "estimate_train_peak",
           "cross_check", "record_memory_gauges", "reconcile",
           "ERROR_BAND"]

#: The estimator's documented accuracy band vs ``memory_analysis()``:
#: the tests assert predictions land within 25% of XLA's number, and
#: :func:`reconcile` journals ``mem_estimate_drift`` when a production
#: cross-check leaves it — the band is a runtime contract now, not just
#: a test constant.
ERROR_BAND = 0.25


# Elementwise primitives XLA freely duplicates into consumers: with one
# consumer the value fuses away and never materializes.
_CHEAP_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "neg", "max", "min", "select_n", "and", "or",
    "not", "xor", "eq", "ne", "lt", "le", "gt", "ge", "sign",
    "broadcast_in_dim", "integer_pow", "iota", "abs", "floor", "ceil",
    "round", "is_finite", "pow", "square", "clamp",
})

# View-like / freely elided primitives: never materialize a new buffer.
_ALIASING = frozenset({
    "reshape", "squeeze", "expand_dims", "stop_gradient", "copy",
    "convert_element_type",
})


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape, initial=1)) * aval.dtype.itemsize
    except Exception:  # abstract tokens, effects
        return 0


def _sub_jaxprs(eqn):
    """Inner jaxprs of a higher-order equation ([] for first-order)."""
    p = eqn.params
    out = []
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                "body_jaxpr"):
        j = p.get(key)
        if j is not None:
            out.append(j.jaxpr if hasattr(j, "jaxpr") else j)
    for b in p.get("branches", ()) or ():
        out.append(b.jaxpr if hasattr(b, "jaxpr") else b)
    return out


def _simulate(jaxpr) -> int:
    """Peak temp bytes of one jaxpr body (invars live externally)."""
    from jax import core as jcore

    last_use: dict = {}
    fanout: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                last_use[v] = i
                fanout[v] = fanout.get(v, 0) + 1
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var):
            last_use[v] = len(jaxpr.eqns)
            fanout[v] = fanout.get(v, 0) + 1

    # free-list index: eqn i -> vars whose last use is i (O(eqns + vars),
    # not a full last_use rescan per equation)
    frees: dict = {}
    for v, li in last_use.items():
        frees.setdefault(li, []).append(v)

    live = 0
    peak = 0
    alive: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        prim = eqn.primitive.name
        inner_peak = 0
        for sub in _sub_jaxprs(eqn):
            inner_peak = max(inner_peak, _simulate(sub))
        for v in eqn.outvars:
            if isinstance(v, jcore.Var) and v in last_use:
                b = _aval_bytes(v.aval)
                if prim in _ALIASING or (prim in _CHEAP_ELEMENTWISE
                                         and fanout.get(v, 0) <= 1):
                    b = 0
                alive[v] = b
                live += b
        if live + inner_peak > peak:
            peak = live + inner_peak
        for v in frees.get(i, ()):
            if v in alive:
                live -= alive.pop(v)
    return peak


@dataclasses.dataclass(frozen=True)
class MemoryEstimate:
    """Predicted per-device memory of one traced program."""

    argument_bytes: int      # inputs resident for the whole program
    output_bytes: int        # outputs (alias arguments under donation)
    temp_peak_bytes: int     # predicted peak of XLA temp allocations
    n_eqns: int

    @property
    def device_peak_bytes(self) -> int:
        """Conservative resident peak: arguments + temps (outputs alias
        donated arguments in a well-formed train step)."""
        return self.argument_bytes + self.temp_peak_bytes

    def describe(self) -> str:
        return (f"args={self.argument_bytes / 1e6:.1f}MB "
                f"out={self.output_bytes / 1e6:.1f}MB "
                f"temp_peak={self.temp_peak_bytes / 1e6:.1f}MB "
                f"device_peak={self.device_peak_bytes / 1e6:.1f}MB")


def estimate_peak_bytes(fn: Callable, *example_args, **example_kwargs
                        ) -> MemoryEstimate:
    """Trace ``fn`` to a jaxpr and simulate buffer live ranges.

    Deterministic: same function and example avals -> same numbers (pure
    jaxpr walk, no compilation, no clock).
    """
    import jax

    closed = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
    jaxpr = closed.jaxpr
    args = sum(_aval_bytes(v.aval) for v in jaxpr.invars)
    args += sum(_aval_bytes(getattr(c, "aval", None) or _FakeAval(c))
                for c in closed.consts)
    outs = sum(_aval_bytes(v.aval) for v in jaxpr.outvars
               if hasattr(v, "aval"))
    return MemoryEstimate(int(args), int(outs), int(_simulate(jaxpr)),
                          len(jaxpr.eqns))


class _FakeAval:
    """Shape/dtype view over a raw constant (closed-jaxpr consts are
    concrete arrays, not avals)."""

    def __init__(self, c):
        self.shape = getattr(c, "shape", ())
        self.dtype = getattr(c, "dtype", np.dtype(np.float32))


def estimate_train_peak(loss_fn: Callable, *example_args) -> MemoryEstimate:
    """Estimate for the full training step ``value_and_grad(loss_fn)`` —
    the number the planner budgets against (params + grads + activation
    residuals + transients)."""
    import jax

    return estimate_peak_bytes(jax.value_and_grad(loss_fn), *example_args)


def cross_check(fn: Callable, *example_args) -> dict:
    """Predicted vs XLA-reported memory for ``fn`` — compiles once and
    reads ``compiled.memory_analysis()``.  Publishes both sides as obs
    gauges (``hetu_mem_predicted_peak_bytes`` / ``hetu_mem_xla_*``) so
    /metrics shows prediction drift in production.

    Returns {predicted_temp_bytes, xla_temp_bytes, xla_argument_bytes,
    xla_output_bytes, ratio}; XLA keys are 0.0 on backends without
    memory analysis (the ratio is then 0.0 too — absent, not infinite).
    """
    import jax

    from hetu_tpu.exec.profiler import _memory_stats

    est = estimate_peak_bytes(fn, *example_args)
    out = {"predicted_temp_bytes": float(est.temp_peak_bytes),
           "predicted_device_peak_bytes": float(est.device_peak_bytes),
           "xla_temp_bytes": 0.0, "xla_argument_bytes": 0.0,
           "xla_output_bytes": 0.0, "ratio": 0.0}
    try:
        compiled = jax.jit(fn).lower(*example_args).compile()
        stats = _memory_stats(compiled)  # the one XLA memory-stats reader
    except Exception:
        stats = {}
    if stats:
        out["xla_temp_bytes"] = stats.get("temp_bytes", 0.0)
        out["xla_argument_bytes"] = stats.get("argument_bytes", 0.0)
        out["xla_output_bytes"] = stats.get("output_bytes", 0.0)
        if out["xla_temp_bytes"]:
            out["ratio"] = reconcile(out["predicted_temp_bytes"],
                                     out["xla_temp_bytes"])["ratio"]
    record_memory_gauges(predicted=est.temp_peak_bytes, xla=out)
    return out


def reconcile(predicted_bytes: float, xla_bytes: float, *,
              band: float = ERROR_BAND, model_sig: str = "") -> dict:
    """Reconcile an estimator prediction against XLA's own
    ``memory_analysis`` bytes — the measure→calibrate closing move for
    the memory model:

    - publishes the ``hetu_mem_estimator_error_ratio`` gauge
      (predicted / XLA-reported; 1.0 = perfect);
    - journals ``mem_estimate_drift`` when the ratio leaves the
      ``band`` (default the tests' 25% cross-check band — until now
      that band only existed inside tests);
    - feeds the installed calibration
      :class:`~hetu_tpu.obs.calibration.ProfileStore` a ``mem`` record,
      which ``fit_calibration`` turns into the ``mem_error_ratio``
      constant ``plan_memory(calibration=...)`` corrects by.

    Returns ``{"ratio", "within_band"}``; a non-positive ``xla_bytes``
    yields ratio 0.0 (absent, not infinite) and no drift event."""
    predicted_bytes = float(predicted_bytes)
    xla_bytes = float(xla_bytes)
    if xla_bytes <= 0.0:
        return {"ratio": 0.0, "within_band": True}
    ratio = predicted_bytes / xla_bytes
    within = abs(ratio - 1.0) <= float(band)
    from hetu_tpu.obs import registry as _obs
    if _obs.enabled():
        _mem_gauges()["error_ratio"].set(ratio)
    if not within:
        from hetu_tpu.obs import journal as _obs_journal
        _obs_journal.record(
            "mem_estimate_drift", predicted_bytes=predicted_bytes,
            xla_bytes=xla_bytes, ratio=round(ratio, 6),
            band=float(band))
    from hetu_tpu.obs.calibration import note_mem
    note_mem(predicted_bytes, xla_bytes, ratio, model_sig=model_sig)
    return {"ratio": ratio, "within_band": within}


# ---------------------------------------------------------------------------
# Observability
# ---------------------------------------------------------------------------

_gauges = None


def _mem_gauges():
    global _gauges
    if _gauges is None:
        from hetu_tpu.obs import registry as _obs
        reg = _obs.get_registry()
        _gauges = {
            "predicted": reg.gauge(
                "hetu_mem_predicted_peak_bytes",
                "estimator-predicted peak temp bytes of the last "
                "estimated program (mem.estimator)"),
            "xla_temp": reg.gauge(
                "hetu_mem_xla_temp_bytes",
                "XLA-reported temp bytes of the last profiled/cross-"
                "checked executable (compiled.memory_analysis)"),
            "xla_args": reg.gauge(
                "hetu_mem_xla_argument_bytes",
                "XLA-reported argument bytes of the last profiled "
                "executable"),
            "xla_out": reg.gauge(
                "hetu_mem_xla_output_bytes",
                "XLA-reported output bytes of the last profiled "
                "executable"),
            "error_ratio": reg.gauge(
                "hetu_mem_estimator_error_ratio",
                "estimator-predicted / XLA-reported bytes of the last "
                "reconciled program (1.0 = perfect; leaving the 25% "
                "band journals mem_estimate_drift)"),
        }
    return _gauges


def record_memory_gauges(predicted=None, xla: dict | None = None) -> None:
    """Publish predicted / XLA-reported peak bytes to the metrics
    registry (no-op with telemetry disabled)."""
    from hetu_tpu.obs import registry as _obs
    if not _obs.enabled():
        return
    g = _mem_gauges()
    if predicted is not None:
        g["predicted"].set(float(predicted))
    if xla:
        # first PRESENT key wins; a reported 0 is a real value and must
        # overwrite the previous program's gauge, not leave it stale
        for gauge, keys in (("xla_temp", ("xla_temp_bytes", "temp_bytes")),
                            ("xla_args", ("xla_argument_bytes",
                                          "argument_bytes")),
                            ("xla_out", ("xla_output_bytes",
                                         "output_bytes"))):
            for k in keys:
                if xla.get(k) is not None:
                    g[gauge].set(float(xla[k]))
                    break
