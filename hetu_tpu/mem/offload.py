"""Host-offload helpers: optimizer state and activation residuals in host
memory via XLA memory kinds.

The reference swaps oversized tensors to host through its BFC allocator's
swap path (src/memory_pool/); on TPU the same capability is ``jax.device_put``
with a ``TransferToMemoryKind('pinned_host')`` sharding — XLA then stages
the transfer.  Every helper here degrades safely on backends without a
host memory space (the CPU test mesh): the tree is returned unchanged and
``supports_host_offload()`` reports False, so callers — and the
``offload_dots`` remat policy — can gate on capability instead of
platform strings.
"""

from __future__ import annotations

import functools
from typing import Any

__all__ = [
    "supports_host_offload", "host_memory_kind", "offload_to_host",
    "restore_to_device", "offload_optimizer_state",
]


@functools.lru_cache(maxsize=None)
def _memory_kinds() -> tuple:
    import jax

    try:
        dev = jax.local_devices()[0]
        return tuple(m.kind for m in dev.addressable_memories())
    except Exception:
        return ()


def supports_host_offload() -> bool:
    """True when the default backend exposes a ``pinned_host`` memory
    space (the kind jax.checkpoint offload policies require)."""
    return "pinned_host" in _memory_kinds()


def host_memory_kind() -> str | None:
    """Best available host memory kind (``pinned_host`` preferred,
    ``unpinned_host`` accepted), or None when the backend has neither."""
    kinds = _memory_kinds()
    for k in ("pinned_host", "unpinned_host"):
        if k in kinds:
            return k
    return None


def _transfer(tree: Any, kind: str | None) -> Any:
    import jax

    if kind is None:
        return tree

    def move(x):
        if not isinstance(x, jax.Array):
            return x
        try:
            return jax.device_put(
                x, jax.sharding.TransferToMemoryKind(kind))
        except Exception:
            return x  # backend refused the kind: keep the array in place

    return jax.tree_util.tree_map(move, tree)


def offload_to_host(tree: Any) -> Any:
    """Every jax array leaf moved to host memory (no-op tree passthrough
    on backends without a host memory space)."""
    return _transfer(tree, host_memory_kind())


def restore_to_device(tree: Any) -> Any:
    """Inverse of :func:`offload_to_host`: leaves moved back to the
    default device memory space."""
    import jax

    kinds = _memory_kinds()
    if not kinds:
        return tree
    # 'device' is the default space name on TPU/GPU; CPU backends name
    # their default space unpinned_host
    kind = "device" if "device" in kinds else kinds[0]
    return _transfer(tree, kind)


def offload_optimizer_state(opt_state: Any) -> Any:
    """Optimizer-state host offload (Adam m/v + master weights are 6x the
    bf16 params — the reference's swap-to-host case).  The state must be
    restored (or re-fetched by XLA on use) before the next update; with
    donation the transfer overlaps the step."""
    return offload_to_host(opt_state)
