"""Deterministic memory planner: pick the cheapest (remat policy,
microbatch) pair whose predicted peak fits the per-device HBM budget.

The Galvatron searcher prunes parallelism strategies that exceed HBM
(reference tools/Galvatron cost_model.py); Checkmate frames the remaining
freedom — *what to save per layer* — as an optimization problem.  This
planner is the executable version of both for the jit runtime: it
enumerates the registered remat policies x candidate microbatch sizes,
predicts each pair's device peak with the jaxpr live-range estimator
(:mod:`hetu_tpu.mem.estimator`), and returns the pair with the least
recompute overhead (preferring larger microbatches — fewer steps per
batch) whose prediction fits the budget.

Everything is deterministic: candidates are enumerated in sorted order,
the estimator is a pure jaxpr walk, and ``MemoryPlan.to_json()``
serializes with sorted keys — the same (config, mesh, budget) input
yields a byte-identical plan across runs (tested).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Optional, Sequence

from hetu_tpu.mem.estimator import estimate_train_peak, record_memory_gauges
from hetu_tpu.mem.policy import get_policy, policy_names

__all__ = ["CandidateEval", "MemoryPlan", "MemoryPlanner", "plan_memory"]


@dataclasses.dataclass(frozen=True)
class CandidateEval:
    """One evaluated (policy, microbatch) point."""

    policy: str
    microbatch: int
    predicted_peak_bytes: int
    recompute_factor: float
    fits: bool


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """The planner's decision, with the full candidate table for audit."""

    policy: str
    microbatch: int
    predicted_peak_bytes: int
    budget_bytes: int
    fits: bool
    candidates: tuple = ()

    def describe(self) -> str:
        verdict = "fits" if self.fits else "OVER BUDGET"
        return (f"policy={self.policy} microbatch={self.microbatch} "
                f"predicted={self.predicted_peak_bytes / 1e6:.1f}MB "
                f"budget={self.budget_bytes / 1e6:.1f}MB ({verdict})")

    def to_json(self) -> str:
        """Canonical serialization: sorted keys, integral bytes — byte-
        identical across runs for identical inputs."""
        return json.dumps(dataclasses.asdict(self), sort_keys=True,
                          separators=(",", ":"))


def plan_memory(loss_fn: Callable, model_builder: Callable,
                batch_builder: Callable, budget_bytes: float, *,
                policies: Optional[Sequence[str]] = None,
                microbatch_options: Sequence[int] = (1,),
                calibration=None) -> MemoryPlan:
    """Search (policy, microbatch) for the cheapest pair under budget.

    ``model_builder(policy_name) -> model`` builds the model with that
    remat policy (e.g. ``lambda p: GPT(dataclasses.replace(cfg,
    remat=p))``); ``batch_builder(microbatch) -> batch`` builds an
    example batch of that size; ``loss_fn(model, batch) -> scalar`` is
    the training loss.  Prediction covers the full
    ``value_and_grad(loss_fn)`` step (params + grads + residuals +
    transients).

    Cost order: larger microbatch beats smaller (fewer accumulation
    steps), then lower recompute factor, then policy name — so 'none'
    wins whenever it fits, and heavier recompute is bought only when the
    budget demands it.  Returns the minimum-memory candidate flagged
    ``fits=False`` when nothing fits.

    ``calibration`` (a fitted
    :class:`~hetu_tpu.obs.calibration.Calibration`) corrects every
    prediction by the estimator's MEASURED error ratio
    (``mem_error_ratio`` = predicted / XLA-reported bytes, fitted from
    the ``mem.estimator.reconcile`` records): a systematically
    over-predicting estimator stops rejecting configs that actually
    fit, and an under-predicting one stops approving OOMs.  The
    correction is a deterministic scalar divide, so plans stay
    byte-identical for identical (inputs, calibration).
    """
    ratio = None
    if calibration is not None:
        r = calibration.mem_error_ratio
        ratio = float(r) if r is not None and r > 0 else None
    names = list(policies) if policies is not None else list(policy_names())
    for n in names:
        get_policy(n)  # validate up front, with the registered names
    micros = sorted(set(int(m) for m in microbatch_options))
    if not micros or micros[0] < 1:
        raise ValueError(f"microbatch_options must be >= 1: {micros}")

    # one example batch per microbatch size — batch construction may load
    # real data, only the per-(policy, mb) trace is inherent to the grid
    batches = {mb: batch_builder(mb) for mb in micros}
    evals = []
    for policy in sorted(names):
        model = model_builder(policy)
        # cost_knobs: the recompute factor of the policy the backend
        # actually executes (offload policies degrade off-host)
        rc = get_policy(policy).cost_knobs()[1]
        for mb in micros:
            est = estimate_train_peak(loss_fn, model, batches[mb])
            peak = est.device_peak_bytes
            if ratio is not None:
                peak = int(round(peak / ratio))
            evals.append(CandidateEval(policy, mb, int(peak), rc,
                                       peak <= budget_bytes))

    # deterministic preference: biggest microbatch, least recompute, name
    ranked = sorted(evals, key=lambda e: (-e.microbatch,
                                          e.recompute_factor, e.policy))
    chosen = next((e for e in ranked if e.fits), None)
    if chosen is None:  # nothing fits: surface the min-memory point
        chosen = min(evals, key=lambda e: (e.predicted_peak_bytes,
                                           -e.microbatch, e.policy))
    plan = MemoryPlan(chosen.policy, chosen.microbatch,
                      chosen.predicted_peak_bytes, int(budget_bytes),
                      chosen.fits, tuple(sorted(
                          evals, key=lambda e: (e.policy, e.microbatch))))
    record_memory_gauges(predicted=plan.predicted_peak_bytes)
    return plan


class MemoryPlanner:
    """Reusable planner handle: the (budget, policies, microbatches,
    calibration) configuration held once, :meth:`plan` run per model —
    the form the unified deployment planner (ROADMAP item 4) composes,
    and the ``MemoryPlanner(calibration=...)`` consumption surface of
    the calibration plane.

    >>> planner = MemoryPlanner(budget_bytes=16e9,
    ...                         calibration=fit_calibration(store, ...))
    >>> plan = planner.plan(loss_fn, model_builder, batch_builder)
    """

    def __init__(self, budget_bytes: float, *,
                 policies: Optional[Sequence[str]] = None,
                 microbatch_options: Sequence[int] = (1,),
                 calibration=None):
        self.budget_bytes = float(budget_bytes)
        self.policies = list(policies) if policies is not None else None
        self.microbatch_options = tuple(microbatch_options)
        self.calibration = calibration

    def plan(self, loss_fn: Callable, model_builder: Callable,
             batch_builder: Callable) -> MemoryPlan:
        return plan_memory(loss_fn, model_builder, batch_builder,
                           self.budget_bytes, policies=self.policies,
                           microbatch_options=self.microbatch_options,
                           calibration=self.calibration)
