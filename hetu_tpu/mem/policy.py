"""Named rematerialization-policy registry.

The reference runtime treats memory as a first-class runtime layer (BFC
allocator in src/memory_pool/, swap-to-host for oversized tensors); its
Galvatron searcher treats per-device memory as a hard constraint.  On TPU
the allocator is XLA's, so the controllable surface is *what the backward
saves*: ``jax.checkpoint`` policies.  This module replaces the blind
``remat: bool`` switch with a registry of named policies — each carrying
the two numbers the analytic cost model needs (fraction of per-layer
activations still resident, extra forward fraction recomputed in the
backward) — so model configs, ``Pipelined`` stages, and the Galvatron
search all speak the same policy vocabulary.

Every policy is *exact*: ``jax.checkpoint`` replays the forward with the
same primitives, so loss and gradients are bitwise-identical across all
registered policies (tested in tests/test_mem.py).

Offload policies store residuals in host memory via XLA memory kinds;
on backends without a ``pinned_host`` memory space (CPU) they fall back
to their on-device equivalent, so programs stay portable.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Callable, Optional

__all__ = [
    "RematPolicy", "register_policy", "get_policy", "policy_names",
    "available_policies", "normalize_remat", "normalize_remat_field",
    "apply_policy",
]


@dataclasses.dataclass(frozen=True)
class RematPolicy:
    """One named remat policy.

    ``jax_policy``: the ``jax.checkpoint`` policy callable (None for the
    two degenerate cases: identity for 'none', default-save-nothing for
    'full').  ``activation_fraction``/``recompute_factor`` are the
    analytic cost-model knobs: fraction of a layer's saved-activation
    bytes still resident on device, and extra forward fraction recomputed
    in the backward (0 = none, 1 = the whole forward again).
    """

    name: str
    activation_fraction: float
    recompute_factor: float
    doc: str = ""
    # lazily resolved: () -> Optional[jax policy callable]; lazy because
    # offload policies must probe the backend's memory kinds first
    _resolve: Optional[Callable] = None
    identity: bool = False
    # policy this one silently degrades to on backends without host
    # offload — the analytic cost knobs must degrade with it, or the
    # Galvatron search would mark plans feasible at the optimistic
    # offload numbers while the runtime executes the fallback
    fallback: Optional[str] = None

    def cost_knobs(self) -> tuple:
        """(activation_fraction, recompute_factor) as the CURRENT backend
        will actually execute this policy — the fallback's numbers when
        host offload is required but unavailable."""
        if self.fallback is not None:
            from hetu_tpu.mem.offload import supports_host_offload
            if not supports_host_offload():
                return get_policy(self.fallback).cost_knobs()
        return (self.activation_fraction, self.recompute_factor)

    def wrap(self, call: Callable) -> Callable:
        """``call`` wrapped under this policy (identity for 'none')."""
        import jax

        if self.identity:
            return call
        pol = self._resolve() if self._resolve is not None else None
        if pol is None:
            return jax.checkpoint(call)
        return jax.checkpoint(call, policy=pol)


_REGISTRY: dict[str, RematPolicy] = {}


def register_policy(name: str, *, activation_fraction: float,
                    recompute_factor: float, resolve: Optional[Callable] = None,
                    identity: bool = False, doc: str = "",
                    fallback: Optional[str] = None) -> RematPolicy:
    """Register (or replace) a named policy.  ``resolve`` is a zero-arg
    callable returning the ``jax.checkpoint`` policy (or None for the
    save-nothing default); called at wrap time so backend probes (host
    offload support) happen late.  ``fallback`` names the policy this
    one degrades to on backends without host offload (its cost knobs
    degrade too — see :meth:`RematPolicy.cost_knobs`)."""
    pol = RematPolicy(name, float(activation_fraction),
                      float(recompute_factor), doc, resolve, identity,
                      fallback)
    _REGISTRY[name] = pol
    return pol


def get_policy(name: str) -> RematPolicy:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown remat policy {name!r}; registered: {policy_names()}"
        ) from None


def policy_names() -> tuple:
    """Registered policy names, sorted — the planner's deterministic
    candidate order."""
    return tuple(sorted(_REGISTRY))


def available_policies() -> dict:
    """name -> RematPolicy snapshot of the registry."""
    return dict(_REGISTRY)


def normalize_remat(value, *, warn: bool = True) -> str:
    """Canonicalize a config's ``remat`` field to a policy name.

    Accepts the legacy boolean form (``True`` -> ``"full"``, ``False`` ->
    ``"none"``; deprecation-warned), ``None`` (-> ``"none"``), or a
    registered policy name (validated).  Callables (raw ``jax.checkpoint``
    policies) pass through untouched for power users.
    """
    if value is None:
        return "none"
    if isinstance(value, bool):
        if warn:
            warnings.warn(
                "boolean `remat` is deprecated: use a policy name "
                f"(True -> 'full', False -> 'none'; registered: "
                f"{policy_names()})", DeprecationWarning, stacklevel=3)
        return "full" if value else "none"
    if isinstance(value, str):
        get_policy(value)  # validate, raising with the known names
        return value
    if callable(value):
        return value
    raise TypeError(f"remat must be a policy name, bool, None, or a "
                    f"jax.checkpoint policy callable; got {type(value)}")


def normalize_remat_field(cfg) -> None:
    """``__post_init__`` helper shared by the frozen model-config
    dataclasses (GPT/BERT/T5/ViT/Swin/MoELM): canonicalize ``cfg.remat``
    in place so an unknown policy fails at construction, not trace
    time."""
    object.__setattr__(cfg, "remat", normalize_remat(cfg.remat))


def apply_policy(call: Callable, policy) -> Callable:
    """``call`` wrapped under ``policy`` — a registered name, legacy bool,
    None, or a raw ``jax.checkpoint`` policy callable."""
    import jax

    policy = normalize_remat(policy)
    if callable(policy):
        return jax.checkpoint(call, policy=policy)
    return get_policy(policy).wrap(call)


# ---------------------------------------------------------------------------
# Built-in policies
# ---------------------------------------------------------------------------

def _jax_policies():
    import jax
    return jax.checkpoint_policies


def _offload_dots_policy():
    """Residual dots offloaded to host memory; falls back to the on-device
    equivalent on backends without a pinned_host memory space (CPU)."""
    from hetu_tpu.mem.offload import supports_host_offload
    cp = _jax_policies()
    if supports_host_offload():
        return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
    return cp.dots_with_no_batch_dims_saveable


register_policy(
    "none", activation_fraction=1.0, recompute_factor=0.0, identity=True,
    doc="save every activation (no checkpoint); fastest backward, "
        "O(layers x seq x hidden) activation memory")
register_policy(
    "full", activation_fraction=0.08, recompute_factor=1.0,
    doc="jax.checkpoint default: save only block inputs, recompute the "
        "whole block forward in the backward (~1/3 more step FLOPs)")
register_policy(
    "save_nothing", activation_fraction=0.08, recompute_factor=1.0,
    resolve=lambda: _jax_policies().nothing_saveable,
    doc="explicit nothing_saveable policy — same trade as 'full'")
register_policy(
    "dots_saveable", activation_fraction=0.55, recompute_factor=0.45,
    resolve=lambda: _jax_policies().dots_saveable,
    doc="save matmul outputs, recompute elementwise chains — the cheap "
        "middle ground (Checkmate's save-the-expensive-ops heuristic)")
register_policy(
    "dots_no_batch", activation_fraction=0.35, recompute_factor=0.6,
    resolve=lambda: _jax_policies().dots_with_no_batch_dims_saveable,
    doc="save only batch-free matmuls (weight-stationary contractions); "
        "activation-shaped dots are recomputed")
register_policy(
    "offload_dots", activation_fraction=0.10, recompute_factor=0.6,
    resolve=_offload_dots_policy, fallback="dots_no_batch",
    doc="batch-free matmul residuals offloaded to pinned host memory "
        "(jax memory kinds); on-device dots_no_batch fallback on CPU")
