"""Memory planning: live-range estimation, remat policies, host offload.

The capability the reference devotes a runtime layer to (BFC allocator +
swap-to-host in src/memory_pool/, memory-constrained Galvatron search)
rebuilt for the XLA runtime, where the allocator is the compiler's and
the controllable surface is *what the backward saves*:

- :mod:`~hetu_tpu.mem.estimator` — jaxpr live-range simulation predicting
  a step's peak temp bytes without compiling, cross-checked against
  ``compiled.memory_analysis()``;
- :mod:`~hetu_tpu.mem.policy` — named remat-policy registry ('none',
  'full', 'dots_saveable', 'offload_dots', ...) replacing the boolean
  ``remat`` flag on model configs and pipeline stages; every policy is
  numerically exact (``jax.checkpoint``);
- :mod:`~hetu_tpu.mem.planner` — deterministic search for the cheapest
  (policy, microbatch) pair whose predicted peak fits a per-device HBM
  budget; the same policy vocabulary feeds the Galvatron search's memory
  cost model (``parallel/autoparallel``) so OOM configs are pruned or
  rescued by remat instead of scoring as "fast";
- :mod:`~hetu_tpu.mem.offload` — optimizer-state / activation host
  offload via XLA memory kinds, with a CPU-safe fallback.

Predicted and XLA-reported peak bytes are published as ``hetu_mem_*``
gauges on ``/metrics`` (``obs``).
"""

from hetu_tpu.mem.estimator import (ERROR_BAND, MemoryEstimate, cross_check,
                                    estimate_peak_bytes,
                                    estimate_train_peak, reconcile,
                                    record_memory_gauges)
from hetu_tpu.mem.offload import (host_memory_kind, offload_optimizer_state,
                                  offload_to_host, restore_to_device,
                                  supports_host_offload)
from hetu_tpu.mem.planner import (CandidateEval, MemoryPlan, MemoryPlanner,
                                  plan_memory)
from hetu_tpu.mem.policy import (RematPolicy, apply_policy,
                                 available_policies, get_policy,
                                 normalize_remat, normalize_remat_field,
                                 policy_names, register_policy)

__all__ = [
    "MemoryEstimate", "estimate_peak_bytes", "estimate_train_peak",
    "cross_check", "record_memory_gauges", "reconcile", "ERROR_BAND",
    "RematPolicy", "register_policy", "get_policy", "policy_names",
    "available_policies", "normalize_remat", "normalize_remat_field",
    "apply_policy",
    "MemoryPlan", "MemoryPlanner", "CandidateEval", "plan_memory",
    "supports_host_offload", "host_memory_kind", "offload_to_host",
    "restore_to_device", "offload_optimizer_state",
]
