"""MoE transformer LM — the expert-parallel benchmark model family
(reference: examples/moe/test_moe_top.py:44-56 — model_dim 2048 decoder with
per-device experts and (H)AllToAll; gates from examples/moe/scripts/).

TPU-native composition: one definition serves dp/ep/sp simultaneously —
experts shard over ``ep`` (layers/moe.py), attention optionally runs
ring/Ulysses sequence parallelism over ``sp`` (parallel/ring_attention.py),
the batch shards over ``dp`` (and ``ep``), all in one jitted train step.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module, maybe_remat
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import normal
from hetu_tpu.layers import Embedding, LayerNorm, MultiHeadAttention
from hetu_tpu.layers.moe import MoELayer, moe_transformer_mlp
from hetu_tpu.ops import softmax_cross_entropy_sparse

__all__ = ["MoELMConfig", "MoEBlock", "MoELM"]


@dataclasses.dataclass(frozen=True)
class MoELMConfig:
    vocab_size: int = 32000
    hidden_size: int = 512
    num_layers: int = 4
    num_heads: int = 8
    num_experts: int = 8
    mlp_ratio: int = 4
    top_k: int = 2
    capacity_factor: float = 1.25
    max_seq_len: int = 1024
    aux_weight: float = 0.01
    initializer_range: float = 0.02
    # thread per-step routing observability (capacity-overflow fraction +
    # expert-load entropy, layer-averaged) into the loss metrics, where
    # the Trainer/Logger pick them up — the numbers that catch silent
    # router collapse or capacity starvation (layers.moe.routing_stats)
    log_routing_stats: bool = False
    # per-block rematerialization policy (hetu_tpu.mem.policy registry):
    # exact numerics; the backward recomputes what the policy drops,
    # including the expert dispatch.  Legacy booleans deprecation-warned.
    remat: object = "none"
    dtype: object = jnp.float32

    def __post_init__(self):
        from hetu_tpu.mem.policy import normalize_remat_field
        normalize_remat_field(self)


class MoEBlock(Module):
    """Pre-LN attention + MoE FFN (reference moe examples replace every
    FFN; every-other-layer variants just pass moe=None)."""

    def __init__(self, cfg: MoELMConfig, *, mesh=None, attn_fn=None,
                 use_moe: bool = True):
        d = cfg.hidden_size
        self.ln1 = LayerNorm(d)
        self.attn = MultiHeadAttention(d, cfg.num_heads, causal=True,
                                       attn_fn=attn_fn, dtype=cfg.dtype)
        self.ln2 = LayerNorm(d)
        self.moe = moe_transformer_mlp(
            d, cfg.mlp_ratio * d, cfg.num_experts, k=cfg.top_k,
            capacity_factor=cfg.capacity_factor, mesh=mesh, dtype=cfg.dtype,
        ) if use_moe else None

    def __call__(self, x, *, training: bool = False,
                 with_stats: bool = False):
        x = x + self.attn(self.ln1(x))
        if self.moe is None:
            zero = jnp.float32(0.0)
            return x, ((zero, None) if with_stats else zero)
        y, aux = self.moe(self.ln2(x), training=training,
                          with_stats=with_stats)
        return x + y, aux


class MoELM(Module):
    """Decoder-only MoE LM; returns (logits, total_aux_loss)."""

    def __init__(self, cfg: MoELMConfig, *, mesh=None, attn_fn=None):
        init = normal(stddev=cfg.initializer_range)
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size,
                             initializer=init, dtype=cfg.dtype)
        self.wpe = Embedding(cfg.max_seq_len, cfg.hidden_size,
                             initializer=init, dtype=cfg.dtype,
                             axes=(None, "embed"))
        self.blocks = [
            MoEBlock(cfg, mesh=mesh, attn_fn=attn_fn)
            for _ in range(cfg.num_layers)
        ]
        self.ln_f = LayerNorm(cfg.hidden_size)
        self.config = cfg

    def __call__(self, input_ids, *, training: bool = False,
                 with_stats: bool = False):
        s = input_ids.shape[-1]
        x = self.wte(input_ids) + self.wpe(jnp.arange(s))
        aux_total = 0.0
        stats_acc, n_moe = None, 0
        step = maybe_remat(
            lambda b, xx: b(xx, training=training, with_stats=with_stats),
            self.config.remat)
        for blk in self.blocks:
            x, aux = step(blk, x)
            if with_stats:
                aux, stats = aux
                if stats is not None:
                    n_moe += 1
                    stats_acc = stats if stats_acc is None else {
                        k: stats_acc[k] + v for k, v in stats.items()}
            aux_total = aux_total + aux
        x = self.ln_f(x)
        logits = x @ self.wte.weight.T.astype(x.dtype)
        if with_stats:
            stats = ({k: v / n_moe for k, v in stats_acc.items()}
                     if stats_acc else {})
            return logits, (aux_total, stats)
        return logits, aux_total

    def loss(self, input_ids, *, training: bool = True):
        with_stats = self.config.log_routing_stats
        out = self(input_ids, training=training, with_stats=with_stats)
        metrics = {}
        if with_stats:
            logits, (aux, stats) = out
            metrics.update(stats)  # overflow_frac, load_entropy
        else:
            logits, aux = out
        nll = softmax_cross_entropy_sparse(logits[:, :-1], input_ids[:, 1:])
        metrics["aux"] = aux
        return nll.mean() + self.config.aux_weight * aux, metrics
