"""GNN subsystem: GCN layers, models, and 1.5D-partitioned distributed
aggregation.

Reference: gpu_ops/DistGCN_15d.py (1.5D partitioned GCN spmm with staged
broadcasts over row/column process groups, CAGNET-style), examples/gnn
(GCN/GraphSAGE training over GraphMix sampling servers), tests/test_DistGCN.

TPU-native: the 1.5D scheme maps onto a ('gr', 'gc') mesh — device (i, j)
holds adjacency block A[i, j] and feature shard X[j]; the local matmul is a
dense MXU op and the partial-sum reduction is one ``psum`` over the column
axis (the reference's hand-staged broadcast loop becomes a single XLA
collective).  Sparse graphs aggregate via ``segment_sum`` over an edge list
instead of cuSPARSE csrmm.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import xavier_uniform, zeros

__all__ = ["normalize_adjacency", "spmm_edges", "GraphConv", "GCN",
           "dist_spmm_15d", "DistGCN15D", "GraphIndex", "sample_subgraph"]


def normalize_adjacency(edge_index, num_nodes: int, *, add_self_loops=True):
    """Symmetric GCN normalization D^-1/2 (A+I) D^-1/2 as (edges, weights).

    edge_index: [2, E] (src, dst) int array.
    """
    src, dst = np.asarray(edge_index)
    if add_self_loops:
        loops = np.arange(num_nodes)
        src = np.concatenate([src, loops])
        dst = np.concatenate([dst, loops])
    deg = np.bincount(dst, minlength=num_nodes).astype(np.float64)
    dinv = 1.0 / np.sqrt(np.maximum(deg, 1.0))
    w = dinv[src] * dinv[dst]
    return (jnp.asarray(np.stack([src, dst]), jnp.int32),
            jnp.asarray(w, jnp.float32))


def spmm_edges(edge_index, edge_weight, x, num_nodes: int):
    """A @ x via gather + segment_sum (the sparse aggregation path; the
    reference uses CuSparseCsrmm, src/ops/CuSparse.cu)."""
    src, dst = edge_index
    msgs = jnp.take(x, src, axis=0) * edge_weight[:, None].astype(x.dtype)
    return jax.ops.segment_sum(msgs, dst, num_segments=num_nodes)


def dense_adjacency(edge_index, edge_weight, num_nodes: int):
    a = jnp.zeros((num_nodes, num_nodes), edge_weight.dtype)
    return a.at[edge_index[1], edge_index[0]].add(edge_weight)


class GraphConv(Module):
    """GCN layer: act(Â H W + b) (Kipf & Welling; examples/gnn gnn_model)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 initializer=None, dtype=jnp.float32):
        init = initializer or xavier_uniform()
        self.w = init(next_key(), (in_features, out_features), dtype)
        self.w_axes = (None, "embed")
        self.b = zeros(None, (out_features,), dtype) if bias else None
        self.b_axes = ("embed",)
        self.in_features = in_features
        self.out_features = out_features

    def __call__(self, x, edge_index, edge_weight, *, num_nodes=None):
        n = num_nodes or x.shape[0]
        h = x @ self.w.astype(x.dtype)          # transform first: E F << N^2
        h = spmm_edges(edge_index, edge_weight, h, n)
        if self.b is not None:
            h = h + self.b.astype(h.dtype)
        return h


class GCN(Module):
    """Multi-layer GCN classifier (examples/gnn/gnn_model/GCN.py shape)."""

    def __init__(self, in_features: int, hidden: int, num_classes: int,
                 num_layers: int = 2, dropout_rate: float = 0.5,
                 dtype=jnp.float32):
        dims = [in_features] + [hidden] * (num_layers - 1) + [num_classes]
        self.convs = [GraphConv(dims[i], dims[i + 1], dtype=dtype)
                      for i in range(num_layers)]
        self.dropout_rate = dropout_rate

    def __call__(self, x, edge_index, edge_weight, *, key=None,
                 training: bool = False):
        for i, conv in enumerate(self.convs):
            x = conv(x, edge_index, edge_weight)
            if i < len(self.convs) - 1:
                x = jax.nn.relu(x)
                if training and key is not None and self.dropout_rate > 0:
                    from hetu_tpu.ops.nn import dropout
                    key, sub = jax.random.split(key)
                    x = dropout(x, self.dropout_rate, sub, training=True)
        return x


# -- 1.5D distributed aggregation ---------------------------------------------


def dist_spmm_15d(a_dense, x, mesh, *, row_axis: str = "gr",
                  col_axis: str = "gc"):
    """1.5D partitioned Z = A @ X over a (row x col) device grid
    (DistGCN_15d.py broad_func, CAGNET 1.5D algorithm).

    Device (i, j) holds A block [N/r, N/c] and X shard [N/c, F] (replicated
    along rows); each computes its partial product and one psum over the
    column axis yields the row-sharded Z — the reference's staged
    broadcast/compute loop collapses into a single XLA collective that
    rides ICI.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    def body(a_blk, x_blk):
        partial_z = a_blk @ x_blk
        return jax.lax.psum(partial_z, col_axis)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(row_axis, col_axis), P(col_axis, None)),
        out_specs=P(row_axis, None),
    )(a_dense, x)


class DistGCN15D(Module):
    """GCN whose aggregation runs 1.5D-partitioned over a device grid.

    Dense-block variant (adjacency materialized as [N, N] blocks): right for
    the mid-size graphs the reference's DistGCN examples target, where the
    per-device block is MXU-sized.
    """

    def __init__(self, in_features: int, hidden: int, num_classes: int,
                 mesh, num_layers: int = 2, row_axis: str = "gr",
                 col_axis: str = "gc", dtype=jnp.float32):
        dims = [in_features] + [hidden] * (num_layers - 1) + [num_classes]
        init = xavier_uniform()
        self.ws = [init(next_key(), (dims[i], dims[i + 1]), dtype)
                   for i in range(num_layers)]
        self.ws_axes = [(None, None)] * num_layers
        self.bs = [zeros(None, (dims[i + 1],), dtype)
                   for i in range(num_layers)]
        self.bs_axes = [(None,)] * num_layers
        self.mesh = mesh
        self.row_axis = row_axis
        self.col_axis = col_axis

    def __call__(self, a_dense, x):
        n_layers = len(self.ws)
        for i, (w, b) in enumerate(zip(self.ws, self.bs)):
            x = x @ w.astype(x.dtype)
            x = dist_spmm_15d(a_dense, x, self.mesh,
                              row_axis=self.row_axis, col_axis=self.col_axis)
            x = x + b.astype(x.dtype)  # post-aggregation, matching GraphConv
            if i < n_layers - 1:
                x = jax.nn.relu(x)
        return x


# -- host-side neighbor sampling (GraphMix-server capability, light) ----------


class GraphIndex:
    """CSR-style in-neighbor index built ONCE per graph and reused across
    minibatch sampling calls (the per-call work then touches only the
    sampled neighborhood, not the whole edge list)."""

    def __init__(self, edge_index):
        self.src, self.dst = (np.asarray(a) for a in edge_index)
        if self.src.size:
            self.order = np.argsort(self.dst, kind="stable")
            sorted_dst = self.dst[self.order]
            self.starts = np.searchsorted(
                sorted_dst, np.arange(int(sorted_dst.max()) + 2))
        else:
            self.order = np.zeros((0,), np.int64)
            self.starts = np.zeros((1,), np.int64)

    def in_neighbors(self, v: int) -> np.ndarray:
        if v + 1 >= len(self.starts):
            return self.src[:0]
        lo, hi = self.starts[v], self.starts[v + 1]
        return self.src[self.order[lo:hi]]


def sample_subgraph(edge_index, seed_nodes, num_hops: int = 2,
                    fanout: int = 10,
                    rng: Optional[np.random.Generator] = None,
                    index: Optional[GraphIndex] = None):
    """Uniform neighbor sampling producing an induced subgraph + relabeled
    edges (the role GraphMix sampling servers play for examples/gnn;
    dataloader.py:253 GNNDataLoaderOp feeds such blocks).

    Pass a prebuilt ``GraphIndex`` when sampling repeatedly from the same
    graph — building it is the only O(E log E) step.
    Returns (node_ids [M], sub_edge_index [2, E'], seed positions).
    """
    rng = rng or np.random.default_rng()
    index = index or GraphIndex(edge_index)
    src, dst = index.src, index.dst
    seeds = np.unique(np.asarray(seed_nodes))
    if src.size == 0:
        node_ids = np.sort(seeds).astype(np.int64)
        seed_pos = np.searchsorted(node_ids, np.asarray(seed_nodes))
        return node_ids, np.zeros((2, 0), np.int32), seed_pos.astype(np.int32)
    frontier = seeds
    nodes = set(frontier.tolist())
    for _ in range(num_hops):
        nxt = []
        for v in frontier:
            neigh = index.in_neighbors(v)
            if len(neigh) > fanout:
                neigh = rng.choice(neigh, fanout, replace=False)
            if len(neigh):
                nxt.append(neigh)
        if not nxt:
            break
        frontier = np.unique(np.concatenate(nxt))
        frontier = frontier[~np.isin(frontier, list(nodes))]
        nodes.update(frontier.tolist())
    node_ids = np.sort(np.fromiter(nodes, dtype=np.int64))
    # relabel via binary search over the (small) sampled node set — no
    # O(max_node_id) table allocation
    sub_src_parts, sub_dst_parts = [], []
    for v in node_ids:
        neigh = index.in_neighbors(int(v))
        keep = np.isin(neigh, node_ids, assume_unique=False)
        kept = neigh[keep]
        sub_src_parts.append(np.searchsorted(node_ids, kept))
        sub_dst_parts.append(
            np.full(len(kept), np.searchsorted(node_ids, v), np.int64))
    if sub_src_parts:
        sub_edges = np.stack([np.concatenate(sub_src_parts),
                              np.concatenate(sub_dst_parts)])
    else:
        sub_edges = np.zeros((2, 0), np.int64)
    seed_pos = np.searchsorted(node_ids, np.asarray(seed_nodes))
    return node_ids, sub_edges.astype(np.int32), seed_pos.astype(np.int32)
