"""BERT for pretraining — the flagship benchmark model.

Capability parity with the reference BERT
(reference: examples/nlp/bert/hetu_bert.py — BertForPreTraining; training
scripts examples/nlp/bert/train_hetu_bert_dp.py), re-designed TPU-first:
post-LN encoder blocks matching BERT, bf16 compute policy with fp32
layernorm/softmax statistics, tied MLM decoder, logical sharding axes on all
weights so DP/TP/ZeRO placement is a strategy choice, and a pluggable
attention core (Pallas flash attention on TPU).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module, maybe_remat
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import normal, zeros
from hetu_tpu.layers import Embedding, LayerNorm, Linear, TransformerBlock
from hetu_tpu.ops import (
    dropout,
    gelu,
    softmax_cross_entropy_sparse,
)
from hetu_tpu.ops.losses import lm_head_cross_entropy

__all__ = [
    "BertConfig", "BertModel", "BertForPreTraining", "BertForMaskedLM",
    "BertForNextSentencePrediction", "BertForSequenceClassification",
    "BertMoEModel", "BertMoEForPreTraining",
    "bert_base", "bert_large",
]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_ratio: int = 4
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    initializer_range: float = 0.02
    # stream the MLM-head CE over vocab chunks of this size instead of
    # materializing (tokens, vocab) logits — a MEMORY knob for huge vocabs
    # / long sequences (ops.lm_head_cross_entropy; where the logits fit,
    # the materialized path is faster)
    streamed_head_chunk: int = 0
    # Pallas fused residual+dropout+LayerNorm at the post-LN sites (one
    # HBM pass per direction; see ops/pallas/fused_ln.py).  Off by
    # default: measured per-config on TPU before enabling in a bench
    fused_ln: bool = False
    # per-block rematerialization policy (hetu_tpu.mem.policy registry:
    # 'none', 'full', 'dots_saveable', 'offload_dots', ...): numerically
    # exact, the policy picks what the backward saves — the knob that
    # lifts the seq-512 batch cap (24 -> 48 on 16 GB with 'full'; bench
    # probes it).  Legacy booleans still work (True -> 'full'),
    # deprecation-warned.
    remat: object = "none"
    dtype: object = jnp.float32

    def __post_init__(self):
        from hetu_tpu.mem.policy import normalize_remat_field
        normalize_remat_field(self)


def bert_base(**kw) -> BertConfig:
    return BertConfig(**kw)


def bert_large(**kw) -> BertConfig:
    return BertConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


class BertEmbeddings(Module):
    def __init__(self, cfg: BertConfig):
        init = normal(stddev=cfg.initializer_range)
        self.word = Embedding(cfg.vocab_size, cfg.hidden_size, initializer=init,
                              dtype=cfg.dtype)
        self.position = Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                                  initializer=init, dtype=cfg.dtype,
                                  axes=(None, "embed"))
        self.token_type = Embedding(cfg.type_vocab_size, cfg.hidden_size,
                                    initializer=init, dtype=cfg.dtype,
                                    axes=(None, "embed"))
        self.ln = LayerNorm(cfg.hidden_size)

    def __call__(self, input_ids, token_type_ids=None):
        s = input_ids.shape[-1]
        x = self.word(input_ids)
        x = x + self.position(jnp.arange(s))
        if token_type_ids is not None:
            x = x + self.token_type(token_type_ids)
        return self.ln(x)


class BertModel(Module):
    def __init__(self, cfg: BertConfig, attn_fn=None):
        self.embeddings = BertEmbeddings(cfg)
        self.blocks = [
            TransformerBlock(
                cfg.hidden_size, cfg.num_heads, cfg.intermediate_ratio,
                post_ln=True, dropout_rate=cfg.dropout_rate, attn_fn=attn_fn,
                fused_ln=cfg.fused_ln, dtype=cfg.dtype,
            )
            for _ in range(cfg.num_layers)
        ]
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size, dtype=cfg.dtype,
                             axes=("embed", None))
        self.config = cfg

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, *,
                 key=None, training: bool = False, compute_dtype=None):
        x = self.embeddings(input_ids, token_type_ids)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        mask = None
        if attention_mask is not None:
            # (b, s) 1=valid -> (b, 1, 1, s) broadcast over heads and queries
            mask = attention_mask[:, None, None, :]
        keys = (
            jax.random.split(key, len(self.blocks)) if key is not None
            else [None] * len(self.blocks)
        )
        step = maybe_remat(
            lambda b, xx, kk: b(xx, mask, key=kk, training=training),
            self.config.remat)
        for blk, k in zip(self.blocks, keys):
            x = step(blk, x, k)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertPreTrainingHeads(Module):
    def __init__(self, cfg: BertConfig):
        init = normal(stddev=cfg.initializer_range)
        # MLM transform
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                initializer=init, dtype=cfg.dtype,
                                axes=("embed", None))
        self.transform_ln = LayerNorm(cfg.hidden_size)
        # decoder weight is tied to word embeddings; only a bias lives here
        self.decoder_bias = zeros(None, (cfg.vocab_size,), cfg.dtype)
        self.decoder_bias_axes = ("vocab",)
        self.nsp = Linear(cfg.hidden_size, 2, initializer=init, dtype=cfg.dtype,
                          axes=("embed", None))

    def __call__(self, hidden, pooled, word_embedding):
        h = self.transform_ln(gelu(self.transform(hidden)))
        mlm_logits = h @ word_embedding.T.astype(h.dtype) + self.decoder_bias.astype(h.dtype)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertForPreTraining(Module):
    """MLM + NSP pretraining model (reference hetu_bert.py BertForPreTraining)."""

    def __init__(self, cfg: BertConfig, attn_fn=None):
        self.bert = BertModel(cfg, attn_fn=attn_fn)
        self.heads = BertPreTrainingHeads(cfg)
        self.config = cfg

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, *,
                 key=None, training: bool = False, compute_dtype=None):
        hidden, pooled = self.bert(
            input_ids, token_type_ids, attention_mask, key=key,
            training=training, compute_dtype=compute_dtype,
        )
        return self.heads(hidden, pooled, self.bert.embeddings.word.weight)

    def loss(self, input_ids, token_type_ids, attention_mask, mlm_labels,
             nsp_labels, *, key=None, training: bool = True, compute_dtype=None):
        """Masked-LM + next-sentence loss; label -1 = unmasked position
        (reference train_hetu_bert_dp.py loss construction).  With
        ``streamed_head_chunk`` set, the MLM decoder never materializes the
        (tokens, vocab) logits (ops.lm_head_cross_entropy)."""
        chunk = self.config.streamed_head_chunk
        if chunk > 0:
            hidden, pooled = self.bert(
                input_ids, token_type_ids, attention_mask, key=key,
                training=training, compute_dtype=compute_dtype)
            h = self.heads.transform_ln(gelu(self.heads.transform(hidden)))
            b, sq = input_ids.shape
            word = self.bert.embeddings.word.weight
            mlm_nll = lm_head_cross_entropy(
                h.reshape(b * sq, -1), word.T.astype(h.dtype),
                mlm_labels.reshape(-1),
                bias=self.heads.decoder_bias.astype(h.dtype), chunk=chunk)
            m = (mlm_labels.reshape(-1) >= 0).astype(jnp.float32)
            mlm_loss = jnp.sum(mlm_nll) / jnp.maximum(jnp.sum(m), 1.0)
            nsp_logits = self.heads.nsp(pooled)
            nsp_loss = softmax_cross_entropy_sparse(
                nsp_logits, nsp_labels).mean()
            return mlm_loss + nsp_loss, {"mlm_loss": mlm_loss,
                                         "nsp_loss": nsp_loss}
        mlm_logits, nsp_logits = self(
            input_ids, token_type_ids, attention_mask, key=key,
            training=training, compute_dtype=compute_dtype,
        )
        mlm_loss, nsp_loss = _mlm_nsp_loss(
            mlm_logits, nsp_logits, mlm_labels, nsp_labels)
        return mlm_loss + nsp_loss, {"mlm_loss": mlm_loss, "nsp_loss": nsp_loss}


def _mlm_nsp_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels):
    """Masked-LM + next-sentence loss; label -1 = unmasked position
    (reference train_hetu_bert_dp.py loss construction).  Shared by the
    dense and MoE pretraining heads."""
    mlm_nll = softmax_cross_entropy_sparse(
        mlm_logits, jnp.maximum(mlm_labels, 0), ignore_index=None)
    m = (mlm_labels >= 0).astype(jnp.float32)
    mlm_loss = jnp.sum(mlm_nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    nsp_loss = softmax_cross_entropy_sparse(nsp_logits, nsp_labels).mean()
    return mlm_loss, nsp_loss


class BertMoEModel(Module):
    """BERT encoder with MoE FFN blocks (reference hetu_bert_moe.py
    BertModel; examples/nlp/bert/train_hetu_bert_moe.py): the standard
    post-LN TransformerBlock with its FFN swapped for a top-k MoE layer
    (AllToAll expert dispatch).  ``mesh`` routes the exchange over the 'ep'
    axis for expert parallelism."""

    def __init__(self, cfg: BertConfig, *, num_experts: int = 8,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 mesh=None, attn_fn=None):
        from hetu_tpu.layers.moe import moe_transformer_mlp

        self.embeddings = BertEmbeddings(cfg)
        self.blocks = [
            TransformerBlock(
                cfg.hidden_size, cfg.num_heads, post_ln=True,
                dropout_rate=cfg.dropout_rate, attn_fn=attn_fn,
                dtype=cfg.dtype,
                mlp=moe_transformer_mlp(
                    cfg.hidden_size, cfg.intermediate_ratio * cfg.hidden_size,
                    num_experts, k=top_k, capacity_factor=capacity_factor,
                    mesh=mesh, dtype=cfg.dtype),
            )
            for _ in range(cfg.num_layers)
        ]
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size, dtype=cfg.dtype,
                             axes=("embed", None))
        self.config = cfg

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, *,
                 key=None, training: bool = False, compute_dtype=None):
        x = self.embeddings(input_ids, token_type_ids)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        mask = attention_mask[:, None, None, :] if attention_mask is not None else None
        keys = (jax.random.split(key, len(self.blocks)) if key is not None
                else [None] * len(self.blocks))
        aux_total = jnp.float32(0.0)
        for blk, k in zip(self.blocks, keys):
            x, aux = blk(x, mask, key=k, training=training)
            aux_total = aux_total + aux
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled, aux_total / len(self.blocks)


class BertMoEForPreTraining(Module):
    """MLM + NSP on the MoE encoder; adds the gate load-balancing aux loss
    (reference hetu_bert_moe.py BertForPreTraining)."""

    def __init__(self, cfg: BertConfig, *, num_experts: int = 8,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 aux_weight: float = 1e-2, mesh=None, attn_fn=None):
        self.bert = BertMoEModel(cfg, num_experts=num_experts, top_k=top_k,
                                 capacity_factor=capacity_factor, mesh=mesh,
                                 attn_fn=attn_fn)
        self.heads = BertPreTrainingHeads(cfg)
        self.aux_weight = aux_weight
        self.config = cfg

    def loss(self, input_ids, token_type_ids, attention_mask, mlm_labels,
             nsp_labels, *, key=None, training: bool = True,
             compute_dtype=None):
        hidden, pooled, aux = self.bert(
            input_ids, token_type_ids, attention_mask, key=key,
            training=training, compute_dtype=compute_dtype)
        mlm_logits, nsp_logits = self.heads(
            hidden, pooled, self.bert.embeddings.word.weight)
        mlm_loss, nsp_loss = _mlm_nsp_loss(
            mlm_logits, nsp_logits, mlm_labels, nsp_labels)
        total = mlm_loss + nsp_loss + self.aux_weight * aux
        return total, {"mlm_loss": mlm_loss, "nsp_loss": nsp_loss,
                       "moe_aux": aux}


class BertForMaskedLM(Module):
    """MLM-only head (reference hetu_bert.py:656 BertForMaskedLM)."""

    def __init__(self, cfg: BertConfig, attn_fn=None):
        self.bert = BertModel(cfg, attn_fn=attn_fn)
        self.heads = BertPreTrainingHeads(cfg)
        self.config = cfg

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, *,
                 key=None, training: bool = False):
        hidden, pooled = self.bert(input_ids, token_type_ids, attention_mask,
                                   key=key, training=training)
        mlm_logits, _ = self.heads(hidden, pooled,
                                   self.bert.embeddings.word.weight)
        return mlm_logits

    def loss(self, input_ids, token_type_ids, attention_mask, mlm_labels, *,
             key=None, training: bool = True):
        logits = self(input_ids, token_type_ids, attention_mask, key=key,
                      training=training)
        nll = softmax_cross_entropy_sparse(logits, jnp.maximum(mlm_labels, 0))
        m = (mlm_labels >= 0).astype(jnp.float32)
        loss = jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
        return loss, {"mlm_loss": loss}


class BertForNextSentencePrediction(Module):
    """NSP-only head (reference hetu_bert.py:726)."""

    def __init__(self, cfg: BertConfig, attn_fn=None):
        self.bert = BertModel(cfg, attn_fn=attn_fn)
        init = normal(stddev=cfg.initializer_range)
        self.nsp = Linear(cfg.hidden_size, 2, initializer=init, dtype=cfg.dtype,
                          axes=("embed", None))
        self.config = cfg

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, *,
                 key=None, training: bool = False):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask,
                              key=key, training=training)
        return self.nsp(pooled)

    def loss(self, input_ids, token_type_ids, attention_mask, nsp_labels, *,
             key=None, training: bool = True):
        logits = self(input_ids, token_type_ids, attention_mask, key=key,
                      training=training)
        loss = softmax_cross_entropy_sparse(logits, nsp_labels).mean()
        return loss, {"nsp_loss": loss}


class BertForSequenceClassification(Module):
    """Pooled-output classifier for GLUE-style fine-tuning
    (reference hetu_bert.py:802 BertForSequenceClassification; GLUE scripts
    examples/nlp/bert/scripts/test_glue_*.sh)."""

    def __init__(self, cfg: BertConfig, num_labels: int, attn_fn=None):
        self.bert = BertModel(cfg, attn_fn=attn_fn)
        init = normal(stddev=cfg.initializer_range)
        self.classifier = Linear(cfg.hidden_size, num_labels,
                                 initializer=init, dtype=cfg.dtype,
                                 axes=("embed", None))
        self.num_labels = num_labels
        self.config = cfg

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, *,
                 key=None, training: bool = False):
        k_bert = k_drop = None
        if key is not None:
            k_bert, k_drop = jax.random.split(key)
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask,
                              key=k_bert, training=training)
        if training and k_drop is not None:
            pooled = dropout(pooled, self.config.dropout_rate, k_drop)
        return self.classifier(pooled)

    def loss(self, input_ids, token_type_ids, attention_mask, labels, *,
             key=None, training: bool = True):
        logits = self(input_ids, token_type_ids, attention_mask, key=key,
                      training=training)
        loss = softmax_cross_entropy_sparse(logits, labels).mean()
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, {"accuracy": acc}
