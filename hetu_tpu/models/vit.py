"""Vision Transformer.

Capability parity with the Galvatron ViT family (reference:
tools/Galvatron/vit/hybrid_parallel_model.py over HF ViT — SURVEY §2.5),
TPU-first: patch embedding as one reshaped matmul (MXU-friendly, no conv
im2col), pre-LN blocks reused from the shared transformer stack, learned
position embeddings, CLS-token classification head.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module, maybe_remat
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import normal, truncated_normal, zeros
from hetu_tpu.layers import LayerNorm, Linear, TransformerBlock
from hetu_tpu.ops import softmax_cross_entropy_sparse

__all__ = ["ViTConfig", "ViT", "vit_base", "vit_large", "vit_huge"]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    num_classes: int = 1000
    dropout_rate: float = 0.0
    # per-block rematerialization policy (hetu_tpu.mem.policy registry):
    # exact numerics, O(layers) activation memory under 'full'.  Legacy
    # booleans deprecation-warned.
    remat: object = "none"
    dtype: object = jnp.float32

    def __post_init__(self):
        from hetu_tpu.mem.policy import normalize_remat_field
        normalize_remat_field(self)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def vit_base(**kw) -> ViTConfig:
    return ViTConfig(**kw)


def vit_large(**kw) -> ViTConfig:
    return ViTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


def vit_huge(**kw) -> ViTConfig:
    return ViTConfig(hidden_size=1280, num_layers=32, num_heads=16, **kw)


class PatchEmbed(Module):
    """Non-overlapping patches -> linear projection.  Expressed as a
    reshape + one [P*P*C, D] matmul so XLA lands it on the MXU directly.

    Shared by ViT and Swin (``flatten=False`` keeps the [B, H/p, W/p, D]
    feature-map layout Swin's windowed stages consume).
    """

    def __init__(self, patch_size: int, num_channels: int, dim: int,
                 dtype=jnp.float32, flatten: bool = True):
        p, c = patch_size, num_channels
        self.proj = Linear(p * p * c, dim, initializer=truncated_normal(stddev=0.02),
                           dtype=dtype, axes=(None, "embed"))
        self.patch = p
        self.flatten = flatten

    @classmethod
    def from_config(cls, cfg: ViTConfig) -> "PatchEmbed":
        return cls(cfg.patch_size, cfg.num_channels, cfg.hidden_size,
                   dtype=cfg.dtype)

    def __call__(self, images):
        """images: [B, H, W, C] -> [B, (H/p)*(W/p), D] (or [B, H/p, W/p, D])."""
        b, h, w, c = images.shape
        p = self.patch
        if h % p or w % p:
            raise ValueError(
                f"image size {(h, w)} not divisible by patch size {p}")
        x = images.reshape(b, h // p, p, w // p, p, c)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
        if self.flatten:
            x = x.reshape(b, (h // p) * (w // p), p * p * c)
        else:
            x = x.reshape(b, h // p, w // p, p * p * c)
        return self.proj(x)


class ViT(Module):
    """ViT classifier (HF ViTForImageClassification capability)."""

    def __init__(self, cfg: ViTConfig, attn_fn=None):
        self.patch_embed = PatchEmbed.from_config(cfg)
        self.cls_token = zeros(None, (1, 1, cfg.hidden_size), cfg.dtype)
        self.cls_token_axes = (None, None, "embed")
        self.pos_embed = truncated_normal(stddev=0.02)(
            next_key(), (1, cfg.num_patches + 1, cfg.hidden_size), cfg.dtype)
        self.pos_embed_axes = (None, None, "embed")
        self.blocks = [
            TransformerBlock(cfg.hidden_size, cfg.num_heads, cfg.mlp_ratio,
                             dropout_rate=cfg.dropout_rate, attn_fn=attn_fn,
                             dtype=cfg.dtype)
            for _ in range(cfg.num_layers)
        ]
        self.ln = LayerNorm(cfg.hidden_size)
        self.head = Linear(cfg.hidden_size, cfg.num_classes,
                           initializer=normal(stddev=0.02), dtype=cfg.dtype,
                           axes=("embed", None))
        self.config = cfg

    def __call__(self, images, *, key=None, training=False):
        x = self.patch_embed(images)
        b = x.shape[0]
        cls = jnp.broadcast_to(self.cls_token.astype(x.dtype),
                               (b, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1) + self.pos_embed.astype(x.dtype)
        keys = (jax.random.split(key, len(self.blocks)) if key is not None
                else [None] * len(self.blocks))
        step = maybe_remat(
            lambda b_, xx, kk: b_(xx, key=kk, training=training),
            self.config.remat)
        for blk, k in zip(self.blocks, keys):
            x = step(blk, x, k)
        return self.head(self.ln(x[:, 0]))

    def loss(self, images, labels, *, key=None, training=True):
        logits = self(images, key=key, training=training)
        loss = softmax_cross_entropy_sparse(logits, labels).mean()
        return loss, {"cls_loss": loss}
