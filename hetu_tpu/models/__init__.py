from hetu_tpu.models.bert import (
    BertConfig,
    BertForMaskedLM,
    BertForNextSentencePrediction,
    BertForPreTraining,
    BertForSequenceClassification,
    BertModel,
    BertMoEForPreTraining,
    BertMoEModel,
    bert_base,
    bert_large,
)
from hetu_tpu.models.ctr import DCN, CTRConfig, DeepCrossing, DeepFM, WideDeep
from hetu_tpu.models.gpt import GPT, GPTConfig, gpt2_large, gpt2_medium, gpt2_small
from hetu_tpu.models.moe_lm import MoEBlock, MoELM, MoELMConfig
from hetu_tpu.models.ncf import GMF, MF, MLPRec, NeuMF
from hetu_tpu.models.resnet import BasicBlock, ResNet, resnet18, resnet34
from hetu_tpu.models.rnn import (
    GRUCell,
    LSTMCell,
    RNN,
    RNNCell,
    RNNClassifier,
)
from hetu_tpu.models.simple import MLP, LeNet, LogReg, alexnet, vgg16
from hetu_tpu.models.swin import Swin, SwinConfig, swin_base, swin_large, swin_tiny
from hetu_tpu.models.t5 import (
    T5Config,
    T5ForConditionalGeneration,
    T5Model,
    t5_base,
    t5_large,
    t5_small,
)
from hetu_tpu.models.vit import ViT, ViTConfig, vit_base, vit_huge, vit_large
