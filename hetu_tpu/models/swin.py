"""Swin Transformer.

Capability parity with the Galvatron Swin family (reference:
tools/Galvatron/swin/hybrid_parallel_model.py over HF Swin — SURVEY §2.5),
TPU-first: window partitioning is pure static reshape/transpose (XLA fuses
it into the attention einsums), shifted windows via ``jnp.roll`` with an
additive shift mask (no gather), relative-position bias indexed from a
static table, and patch merging as reshape + matmul.  All shapes static per
stage, so every stage jits to a fixed MXU-tiled program.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.core.module import Module, maybe_remat
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import truncated_normal, zeros
from hetu_tpu.layers import LayerNorm, Linear
from hetu_tpu.models.vit import PatchEmbed
from hetu_tpu.layers.transformer import TransformerMLP
from hetu_tpu.ops import softmax_cross_entropy_sparse

__all__ = ["SwinConfig", "Swin", "swin_tiny", "swin_base", "swin_large"]


@dataclasses.dataclass(frozen=True)
class SwinConfig:
    image_size: int = 224
    patch_size: int = 4
    num_channels: int = 3
    embed_dim: int = 96
    depths: Sequence[int] = (2, 2, 6, 2)
    num_heads: Sequence[int] = (3, 6, 12, 24)
    window_size: int = 7
    mlp_ratio: int = 4
    num_classes: int = 1000
    # per-block rematerialization policy (hetu_tpu.mem.policy registry;
    # legacy booleans deprecation-warned)
    remat: object = "none"
    dtype: object = jnp.float32

    def __post_init__(self):
        from hetu_tpu.mem.policy import normalize_remat_field
        normalize_remat_field(self)


def swin_tiny(**kw) -> SwinConfig:
    return SwinConfig(**kw)


def swin_base(**kw) -> SwinConfig:
    return SwinConfig(embed_dim=128, depths=(2, 2, 18, 2),
                      num_heads=(4, 8, 16, 32), **kw)


def swin_large(**kw) -> SwinConfig:
    return SwinConfig(embed_dim=192, depths=(2, 2, 18, 2),
                      num_heads=(6, 12, 24, 48), **kw)


def _window_partition(x, ws: int):
    """[B,H,W,C] -> [B*nW, ws*ws, C] (static reshapes only)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // ws, ws, w // ws, ws, c)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(-1, ws * ws, c)


def _window_reverse(wins, ws: int, h: int, w: int):
    b = wins.shape[0] // ((h // ws) * (w // ws))
    x = wins.reshape(b, h // ws, w // ws, ws, ws, -1)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, h, w, -1)


def _relative_index(ws: int) -> np.ndarray:
    """Static [ws*ws, ws*ws] index into the (2ws-1)^2 bias table."""
    coords = np.stack(np.meshgrid(np.arange(ws), np.arange(ws),
                                  indexing="ij")).reshape(2, -1)
    rel = coords[:, :, None] - coords[:, None, :]
    rel = rel.transpose(1, 2, 0) + (ws - 1)
    return (rel[..., 0] * (2 * ws - 1) + rel[..., 1]).astype(np.int32)


def _shift_mask(h: int, w: int, ws: int, shift: int) -> np.ndarray:
    """Additive attention mask for shifted windows: -inf between tokens from
    different pre-shift regions (computed statically at trace time)."""
    img = np.zeros((h, w))
    cnt = 0
    for hs in (slice(0, -ws), slice(-ws, -shift), slice(-shift, None)):
        for vs in (slice(0, -ws), slice(-ws, -shift), slice(-shift, None)):
            img[hs, vs] = cnt
            cnt += 1
    wins = img.reshape(h // ws, ws, w // ws, ws).transpose(0, 2, 1, 3)
    wins = wins.reshape(-1, ws * ws)
    diff = wins[:, None, :] - wins[:, :, None]
    return np.where(diff != 0, -1e9, 0.0).astype(np.float32)  # [nW,wsq,wsq]


class WindowAttention(Module):
    """MHA inside ws×ws windows with learned relative-position bias
    (HF SwinSelfAttention capability, static-shape formulation)."""

    def __init__(self, dim: int, num_heads: int, ws: int, dtype=jnp.float32):
        init = truncated_normal(stddev=0.02)
        self.wqkv = init(next_key(), (dim, 3 * dim), dtype)
        self.wqkv_axes = ("embed", "qkv_three_heads")
        self.bqkv = zeros(None, (3 * dim,), dtype)
        self.wo = init(next_key(), (dim, dim), dtype)
        self.wo_axes = ("heads_merged", "embed")
        self.bo = zeros(None, (dim,), dtype)
        self.bias_table = init(
            next_key(), ((2 * ws - 1) ** 2, num_heads), jnp.float32)
        self.bias_table_axes = (None, "heads")
        self.num_heads = num_heads
        self.ws = ws

    def __call__(self, wins, shift_mask=None):
        """wins: [nB, wsq, C]; shift_mask: [nW, wsq, wsq] additive or None."""
        nb, wsq, c = wins.shape
        H, Dh = self.num_heads, c // self.num_heads
        qkv = wins @ self.wqkv.astype(wins.dtype) + self.bqkv.astype(wins.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(nb, wsq, H, Dh)
        k = k.reshape(nb, wsq, H, Dh)
        v = v.reshape(nb, wsq, H, Dh)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits * (Dh ** -0.5)
        bias = self.bias_table[jnp.asarray(_relative_index(self.ws))]
        logits = logits + jnp.transpose(bias, (2, 0, 1))[None]
        if shift_mask is not None:
            nw = shift_mask.shape[0]
            logits = logits.reshape(nb // nw, nw, H, wsq, wsq)
            logits = logits + shift_mask[None, :, None]
            logits = logits.reshape(nb, H, wsq, wsq)
        probs = jax.nn.softmax(logits, axis=-1).astype(wins.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(nb, wsq, c)
        return out @ self.wo.astype(wins.dtype) + self.bo.astype(wins.dtype)


class SwinBlock(Module):
    def __init__(self, dim: int, num_heads: int, ws: int, shift: int,
                 mlp_ratio: int, resolution: int, dtype=jnp.float32):
        if resolution <= ws:
            # feature map no bigger than one window: whole-map attention,
            # shifting would only mask out in-window pairs (official Swin
            # sets shift_size=0 and window_size=resolution in this case)
            ws, shift = resolution, 0
        if resolution % ws:
            raise ValueError(
                f"stage resolution {resolution} is not divisible by "
                f"window_size {ws}; pick image_size/patch_size/window_size "
                f"so every stage's feature map tiles into whole windows")
        self.ln1 = LayerNorm(dim)
        self.attn = WindowAttention(dim, num_heads, ws, dtype=dtype)
        self.ln2 = LayerNorm(dim)
        self.mlp = TransformerMLP(dim, mlp_ratio * dim, dtype=dtype)
        self.ws = ws
        self.shift = shift

    def __call__(self, x):
        """x: [B, H, W, C] feature map."""
        b, h, w, c = x.shape
        ws, shift = self.ws, self.shift
        shortcut = x
        x = self.ln1(x)
        if shift:
            x = jnp.roll(x, (-shift, -shift), axis=(1, 2))
            mask = jnp.asarray(_shift_mask(h, w, ws, shift))
        else:
            mask = None
        wins = _window_partition(x, ws)
        wins = self.attn(wins, mask)
        x = _window_reverse(wins, ws, h, w)
        if shift:
            x = jnp.roll(x, (shift, shift), axis=(1, 2))
        x = shortcut + x
        return x + self.mlp(self.ln2(x))


class PatchMerging(Module):
    """2x2 neighborhood concat + linear 4C->2C downsample (Swin stage
    transition), as reshape + matmul."""

    def __init__(self, dim: int, dtype=jnp.float32):
        self.ln = LayerNorm(4 * dim)
        self.proj = Linear(4 * dim, 2 * dim, bias=False,
                           initializer=truncated_normal(stddev=0.02),
                           dtype=dtype, axes=(None, "embed"))

    def __call__(self, x):
        b, h, w, c = x.shape
        x = x.reshape(b, h // 2, 2, w // 2, 2, c)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
            b, h // 2, w // 2, 4 * c)
        return self.proj(self.ln(x))


class Swin(Module):
    """Swin classifier (HF SwinForImageClassification capability)."""

    def __init__(self, cfg: SwinConfig):
        if cfg.image_size % cfg.patch_size:
            raise ValueError(
                f"image_size {cfg.image_size} not divisible by "
                f"patch_size {cfg.patch_size}")
        self.patch_embed = PatchEmbed(cfg.patch_size, cfg.num_channels,
                                      cfg.embed_dim, dtype=cfg.dtype,
                                      flatten=False)
        self.patch_ln = LayerNorm(cfg.embed_dim)
        self.stages = []
        self.merges = []
        dim = cfg.embed_dim
        resolution = cfg.image_size // cfg.patch_size
        for si, (depth, heads) in enumerate(zip(cfg.depths, cfg.num_heads)):
            blocks = [
                SwinBlock(dim, heads, cfg.window_size,
                          shift=0 if i % 2 == 0 else cfg.window_size // 2,
                          mlp_ratio=cfg.mlp_ratio, resolution=resolution,
                          dtype=cfg.dtype)
                for i in range(depth)
            ]
            self.stages.append(blocks)
            if si < len(cfg.depths) - 1:
                self.merges.append(PatchMerging(dim, dtype=cfg.dtype))
                dim *= 2
                resolution //= 2
        self.final_ln = LayerNorm(dim)
        self.head = Linear(dim, cfg.num_classes,
                           initializer=truncated_normal(stddev=0.02),
                           dtype=cfg.dtype, axes=("embed", None))
        self.config = cfg

    def __call__(self, images, *, key=None, training=False):
        x = self.patch_ln(self.patch_embed(images))
        step = maybe_remat(lambda b, xx: b(xx), self.config.remat)
        for si, blocks in enumerate(self.stages):
            for blk in blocks:
                x = step(blk, x)
            if si < len(self.stages) - 1:
                x = self.merges[si](x)
        x = self.final_ln(x)
        return self.head(jnp.mean(x, axis=(1, 2)))

    def loss(self, images, labels, *, key=None, training=True):
        logits = self(images, key=key, training=training)
        loss = softmax_cross_entropy_sparse(logits, labels).mean()
        return loss, {"cls_loss": loss}
