"""GPT decoder-only LM (reference examples/auto_parallel GPT configs;
Galvatron's GPT target).  Pre-LN causal transformer with tied output head
option — the model family used by the auto-parallel searcher benchmarks.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module, maybe_remat
from hetu_tpu.init import normal
from hetu_tpu.core.rng import next_key
from hetu_tpu.layers import Embedding, LayerNorm, TransformerBlock
from hetu_tpu.ops import softmax_cross_entropy_sparse
from hetu_tpu.ops.losses import lm_head_cross_entropy

__all__ = ["GPTConfig", "GPT", "gpt2_small", "gpt2_medium", "gpt2_large"]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    dropout_rate: float = 0.0
    initializer_range: float = 0.02
    tie_embeddings: bool = True
    # stream the LM-head CE over vocab chunks of this size instead of
    # materializing (tokens, vocab) logits — a MEMORY knob for huge vocabs
    # / very long sequences (ops.lm_head_cross_entropy; where the logits
    # fit, the default materialized path is faster)
    streamed_head_chunk: int = 0
    # per-block rematerialization policy (hetu_tpu.mem.policy registry:
    # 'none', 'full', 'dots_saveable', 'offload_dots', ...): exact
    # numerics, the policy picks what the backward saves — the
    # long-context batch-cap knob (same as BertConfig.remat).  Legacy
    # booleans still work (True -> 'full'), deprecation-warned.
    remat: object = "none"
    dtype: object = jnp.float32

    def __post_init__(self):
        from hetu_tpu.mem.policy import normalize_remat_field
        normalize_remat_field(self)


def gpt2_small(**kw):
    return GPTConfig(**kw)


def gpt2_medium(**kw):
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


def gpt2_large(**kw):
    return GPTConfig(hidden_size=1280, num_layers=36, num_heads=20, **kw)


class GPT(Module):
    def __init__(self, cfg: GPTConfig, attn_fn=None):
        init = normal(stddev=cfg.initializer_range)
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size, initializer=init,
                             dtype=cfg.dtype)
        self.wpe = Embedding(cfg.max_seq_len, cfg.hidden_size, initializer=init,
                             dtype=cfg.dtype, axes=(None, "embed"))
        self.blocks = [
            TransformerBlock(cfg.hidden_size, cfg.num_heads, 4, causal=True,
                             dropout_rate=cfg.dropout_rate, attn_fn=attn_fn,
                             dtype=cfg.dtype)
            for _ in range(cfg.num_layers)
        ]
        self.ln_f = LayerNorm(cfg.hidden_size)
        self.lm_head = (
            None if cfg.tie_embeddings
            else init(next_key(), (cfg.hidden_size, cfg.vocab_size), cfg.dtype)
        )
        self.lm_head_axes = ("embed", "vocab")
        self.config = cfg

    def _head(self):
        """(hidden, vocab) projection — tied to the token embedding unless
        an untied lm_head exists."""
        return self.wte.weight.T if self.lm_head is None else self.lm_head

    def __call__(self, input_ids, *, key=None, training: bool = False,
                 compute_dtype=None, kv_cache=None, cache_index=None,
                 seq_lengths=None, paged_tables=None):
        """Logits.  Training/eval (``kv_cache=None``): full (batch, seq,
        vocab) logits, as before.

        Incremental decode (``kv_cache`` = per-block list of ``(k_cache,
        v_cache)`` pairs, ``cache_index`` = per-sequence history lengths):
        ``input_ids`` (batch, s) are s NEW tokens appended at each row's
        offset — s = the padded prompt bucket on prefill, 1 per decode
        step after.  Returns ``(last_logits, new_kv_cache)`` where
        ``last_logits`` (batch, vocab) is the next-token distribution at
        each row's LAST VALID new position (``seq_lengths``, default s —
        pass true prompt lengths when the prefill batch is right-padded
        to a bucket), so the (s, vocab) logits matrix is never
        materialized during serving.

        Paged decode (``paged_tables`` set, s == 1): ``kv_cache`` is ONE
        ``(k_pool, v_pool)`` pair of stacked ``(layers, pages, page_size,
        H, D)`` pool arrays and attention runs the in-place Pallas
        paged-decode kernel — no contiguous K/V view is ever built."""
        if kv_cache is None:
            x = self.hidden_states(input_ids, key=key, training=training,
                                   compute_dtype=compute_dtype)
            return x @ self._head().astype(x.dtype)
        x, new_kv = self.hidden_states(
            input_ids, training=False, compute_dtype=compute_dtype,
            kv_cache=kv_cache, cache_index=cache_index,
            paged_tables=paged_tables)
        if seq_lengths is None:
            last = x[:, -1]
        else:
            last = jnp.take_along_axis(
                x, (seq_lengths - 1)[:, None, None], axis=1)[:, 0]
        return last @ self._head().astype(last.dtype), new_kv

    def hidden_states(self, input_ids, *, key=None, training: bool = False,
                      compute_dtype=None, kv_cache=None, cache_index=None,
                      paged_tables=None):
        """Final-layer-norm hidden states (no LM-head projection).  With
        ``kv_cache``/``cache_index``, runs the incremental-decode path and
        returns ``(hidden, new_kv_cache)``; positions are each row's
        ``cache_index + arange(s)`` so ragged batches place the new
        tokens' position embeddings correctly.  With ``paged_tables``,
        ``kv_cache`` is the stacked pool pair and each block attends in
        place at its own layer index (see ``__call__``)."""
        s = input_ids.shape[-1]
        if kv_cache is not None and paged_tables is not None:
            from hetu_tpu.layers.attention import PagedDecode
            positions = cache_index[:, None] + jnp.arange(s)[None, :]
            x = self.wte(input_ids) + self.wpe(positions)
            if compute_dtype is not None:
                x = x.astype(compute_dtype)
            k, v = kv_cache
            for li, blk in enumerate(self.blocks):
                x, (k, v) = blk(x, kv_cache=(k, v), cache_index=cache_index,
                                paged=PagedDecode(paged_tables, layer=li))
            return self.ln_f(x), (k, v)
        if kv_cache is not None:
            positions = cache_index[:, None] + jnp.arange(s)[None, :]
            x = self.wte(input_ids) + self.wpe(positions)
            if compute_dtype is not None:
                x = x.astype(compute_dtype)
            new_kv = []
            for blk, kv in zip(self.blocks, kv_cache):
                x, kv = blk(x, kv_cache=kv, cache_index=cache_index)
                new_kv.append(kv)
            return self.ln_f(x), new_kv
        x = self.wte(input_ids) + self.wpe(jnp.arange(s))
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        keys = (
            jax.random.split(key, len(self.blocks)) if key is not None
            else [None] * len(self.blocks)
        )
        step = maybe_remat(
            lambda b, xx, kk: b(xx, key=kk, training=training),
            self.config.remat)
        for blk, k in zip(self.blocks, keys):
            x = step(blk, x, k)
        return self.ln_f(x)

    def loss(self, input_ids, *, key=None, training: bool = True,
             compute_dtype=None):
        """Next-token cross entropy.  With ``streamed_head_chunk`` set, the
        head never materializes the (tokens, vocab) logits."""
        chunk = self.config.streamed_head_chunk
        if chunk > 0:
            x = self.hidden_states(input_ids, key=key, training=training,
                                   compute_dtype=compute_dtype)
            b, sm1 = input_ids.shape[0], input_ids.shape[1] - 1
            nll = lm_head_cross_entropy(
                x[:, :-1].reshape(b * sm1, -1), self._head().astype(x.dtype),
                input_ids[:, 1:].reshape(-1), chunk=chunk)
            return nll.mean()
        logits = self(input_ids, key=key, training=training,
                      compute_dtype=compute_dtype)
        nll = softmax_cross_entropy_sparse(logits[:, :-1], input_ids[:, 1:])
        return nll.mean()
