"""Small reference-parity models: MLP, LeNet, VGG-style CNN, logistic
regression (reference examples/cnn/models/hetu/{mlp,lenet,vgg,logreg}.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.layers import (
    AvgPool2d,
    Conv2d,
    Flatten,
    Linear,
    MaxPool2d,
    Sequential,
)
from hetu_tpu.layers.base import Lambda
from hetu_tpu.ops import relu

__all__ = ["MLP", "LeNet", "VGGBlock", "vgg16", "LogReg", "alexnet"]


class MLP(Module):
    def __init__(self, sizes=(784, 256, 128, 10)):
        self.layers = [Linear(a, b) for a, b in zip(sizes[:-1], sizes[1:])]

    def __call__(self, x):
        for i, l in enumerate(self.layers):
            x = l(x)
            if i < len(self.layers) - 1:
                x = relu(x)
        return x


class LeNet(Module):
    """LeNet-5 over NHWC (reference examples/cnn/models/hetu/lenet.py)."""

    def __init__(self, num_classes: int = 10, in_ch: int = 1):
        self.c1 = Conv2d(in_ch, 6, 5, padding=2)
        self.p1 = AvgPool2d(2)
        self.c2 = Conv2d(6, 16, 5, padding=0)
        self.p2 = AvgPool2d(2)
        self.flat = Flatten()
        self.f1 = Linear(16 * 5 * 5, 120)
        self.f2 = Linear(120, 84)
        self.f3 = Linear(84, num_classes)

    def __call__(self, x):
        x = self.p1(relu(self.c1(x)))
        x = self.p2(relu(self.c2(x)))
        x = self.flat(x)
        x = relu(self.f1(x))
        x = relu(self.f2(x))
        return self.f3(x)


class VGGBlock(Module):
    def __init__(self, in_ch: int, out_ch: int, n: int):
        convs = []
        for i in range(n):
            convs.append(Conv2d(in_ch if i == 0 else out_ch, out_ch, 3, padding=1))
        self.convs = convs
        self.pool = MaxPool2d(2)

    def __call__(self, x):
        for c in self.convs:
            x = relu(c(x))
        return self.pool(x)


def vgg16(num_classes: int = 10) -> Sequential:
    """VGG-16 for 32x32 inputs (reference examples/cnn/models/hetu/vgg.py)."""
    return Sequential(
        VGGBlock(3, 64, 2),
        VGGBlock(64, 128, 2),
        VGGBlock(128, 256, 3),
        VGGBlock(256, 512, 3),
        VGGBlock(512, 512, 3),
        Flatten(),
        Linear(512, 4096), Lambda(relu),
        Linear(4096, 4096), Lambda(relu),
        Linear(4096, num_classes),
    )


class LogReg(Module):
    def __init__(self, in_dim: int = 784, num_classes: int = 10):
        self.fc = Linear(in_dim, num_classes)

    def __call__(self, x):
        return self.fc(x)


def alexnet(num_classes: int = 10, in_ch: int = 3) -> Sequential:
    """AlexNet sized for 32x32 inputs (reference
    examples/cnn/models/AlexNet.py uses the CIFAR-scale variant)."""
    return Sequential(
        Conv2d(in_ch, 64, 3, stride=1, padding=1), Lambda(relu),
        MaxPool2d(2),
        Conv2d(64, 192, 3, padding=1), Lambda(relu),
        MaxPool2d(2),
        Conv2d(192, 384, 3, padding=1), Lambda(relu),
        Conv2d(384, 256, 3, padding=1), Lambda(relu),
        Conv2d(256, 256, 3, padding=1), Lambda(relu),
        MaxPool2d(2),
        Flatten(),
        Linear(256 * 4 * 4, 1024), Lambda(relu),
        Linear(1024, 512), Lambda(relu),
        Linear(512, num_classes),
    )
