"""T5 encoder-decoder LM.

Capability parity with the Galvatron T5 family (reference:
tools/Galvatron/t5/hybrid_parallel_model.py and its vendored
huggingface/megatron T5 stack — SURVEY §2.5), re-designed TPU-first rather
than wrapping torch modules: RMSNorm pre-LN blocks, bias-free projections,
bucketed relative-position bias shared across layers, tied embedding/LM
head with the d_model**-0.5 rescale, fp32 softmax statistics, and logical
sharding axes on every weight so the strategy layer can place DP/TP/ZeRO
(Galvatron's dp/tp/sdp choices) without touching the model.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module, maybe_remat
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import normal
from hetu_tpu.layers import Embedding, RMSNorm
from hetu_tpu.ops import dropout as dropout_op
from hetu_tpu.ops import relu, softmax_cross_entropy_sparse

__all__ = ["T5Config", "T5Model", "T5ForConditionalGeneration", "t5_small",
           "t5_base", "t5_large"]


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6            # encoder layers (= decoder layers)
    num_heads: int = 8
    relative_buckets: int = 32
    relative_max_distance: int = 128
    dropout_rate: float = 0.1
    # per-block rematerialization policy (hetu_tpu.mem.policy registry;
    # same knob as BertConfig.remat).  Legacy booleans deprecation-warned.
    remat: object = "none"
    dtype: object = jnp.float32

    def __post_init__(self):
        from hetu_tpu.mem.policy import normalize_remat_field
        normalize_remat_field(self)


def t5_small(**kw) -> T5Config:
    return T5Config(**kw)


def t5_base(**kw) -> T5Config:
    return T5Config(d_model=768, d_ff=3072, num_layers=12, num_heads=12, **kw)


def t5_large(**kw) -> T5Config:
    return T5Config(d_model=1024, d_ff=4096, num_layers=24, num_heads=16, **kw)


def relative_position_bucket(relative_position, *, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """T5's log-spaced relative position bucketing: half the buckets are
    exact small offsets, the rest span up to ``max_distance``
    logarithmically (HF T5 `_relative_position_bucket` semantics)."""
    ret = 0
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    # max(n, 1) keeps the log defined where the large branch is DISCARDED
    # (n < max_exact selects is_small); for selected positions n >=
    # max_exact >= 1, so the values match the reference epsilon-free
    # formula exactly (an additive epsilon can flip a bucket at a
    # boundary)
    val_if_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5RelativeBias(Module):
    """Per-head learned bias over bucketed relative positions; lives on the
    first layer of each stack and is shared by all layers (T5 design)."""

    def __init__(self, cfg: T5Config, *, bidirectional: bool):
        self.table = normal(stddev=0.02)(
            next_key(), (cfg.relative_buckets, cfg.num_heads), jnp.float32)
        self.table_axes = (None, "heads")
        self.bidirectional = bidirectional
        self.num_buckets = cfg.relative_buckets
        self.max_distance = cfg.relative_max_distance

    def __call__(self, q_len: int, k_len: int):
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        bucket = relative_position_bucket(
            mem - ctx, bidirectional=self.bidirectional,
            num_buckets=self.num_buckets, max_distance=self.max_distance)
        bias = self.table[bucket]                    # [q, k, heads]
        return jnp.transpose(bias, (2, 0, 1))[None]  # [1, heads, q, k]


class T5Attention(Module):
    """Self- or cross-attention, bias-free, unscaled QK^T (T5 folds the
    scale into the init), with optional shared relative-position bias."""

    def __init__(self, cfg: T5Config, *, causal: bool = False):
        d_inner = cfg.num_heads * cfg.d_kv
        init = normal(stddev=cfg.d_model ** -0.5)
        self.wq = init(next_key(), (cfg.d_model, d_inner), cfg.dtype)
        self.wq_axes = ("embed", "heads_kv")
        self.wk = init(next_key(), (cfg.d_model, d_inner), cfg.dtype)
        self.wk_axes = ("embed", "heads_kv")
        self.wv = init(next_key(), (cfg.d_model, d_inner), cfg.dtype)
        self.wv_axes = ("embed", "heads_kv")
        self.wo = init(next_key(), (d_inner, cfg.d_model), cfg.dtype)
        self.wo_axes = ("heads_kv", "embed")
        self.num_heads = cfg.num_heads
        self.d_kv = cfg.d_kv
        self.causal = causal

    def __call__(self, x, kv=None, mask=None, pos_bias=None):
        b, qs, _ = x.shape
        kv = x if kv is None else kv
        ks = kv.shape[1]
        H, Dh = self.num_heads, self.d_kv
        q = (x @ self.wq.astype(x.dtype)).reshape(b, qs, H, Dh)
        k = (kv @ self.wk.astype(x.dtype)).reshape(b, ks, H, Dh)
        v = (kv @ self.wv.astype(x.dtype)).reshape(b, ks, H, Dh)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        if pos_bias is not None:
            logits = logits + pos_bias
        if self.causal:
            cmask = jnp.tril(jnp.ones((qs, ks), bool), k=ks - qs)
            logits = jnp.where(cmask, logits, -1e30)
        if mask is not None:
            logits = jnp.where(mask.astype(bool), logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, qs, H * Dh)
        return out @ self.wo.astype(x.dtype)


class T5MLP(Module):
    def __init__(self, cfg: T5Config):
        init = normal(stddev=cfg.d_model ** -0.5)
        self.w_in = init(next_key(), (cfg.d_model, cfg.d_ff), cfg.dtype)
        self.w_in_axes = ("embed", "mlp")
        self.w_out = init(next_key(), (cfg.d_ff, cfg.d_model), cfg.dtype)
        self.w_out_axes = ("mlp", "embed")

    def __call__(self, x):
        return relu(x @ self.w_in.astype(x.dtype)) @ self.w_out.astype(x.dtype)


class T5Block(Module):
    def __init__(self, cfg: T5Config, *, decoder: bool):
        self.ln1 = RMSNorm(cfg.d_model)
        self.attn = T5Attention(cfg, causal=decoder)
        self.cross_ln = RMSNorm(cfg.d_model) if decoder else None
        self.cross = T5Attention(cfg) if decoder else None
        self.ln2 = RMSNorm(cfg.d_model)
        self.mlp = T5MLP(cfg)
        self.dropout_rate = cfg.dropout_rate

    def __call__(self, x, *, enc=None, mask=None, enc_mask=None,
                 pos_bias=None, key=None, training=False):
        keys = jax.random.split(key, 3) if key is not None else (None,) * 3
        x = x + self._drop(
            self.attn(self.ln1(x), mask=mask, pos_bias=pos_bias),
            keys[0], training)
        if self.cross is not None and enc is not None:
            x = x + self._drop(
                self.cross(self.cross_ln(x), kv=enc, mask=enc_mask),
                keys[1], training)
        return x + self._drop(self.mlp(self.ln2(x)), keys[2], training)

    def _drop(self, x, key, training):
        if training and self.dropout_rate > 0.0 and key is not None:
            return dropout_op(x, self.dropout_rate, key, training=True)
        return x


class T5Stack(Module):
    def __init__(self, cfg: T5Config, *, decoder: bool):
        self.rel_bias = T5RelativeBias(cfg, bidirectional=not decoder)
        self.blocks = [T5Block(cfg, decoder=decoder)
                       for _ in range(cfg.num_layers)]
        self.final_ln = RMSNorm(cfg.d_model)
        self.decoder = decoder
        self.config = cfg

    def __call__(self, x, *, enc=None, mask=None, enc_mask=None, key=None,
                 training=False):
        s = x.shape[1]
        pos_bias = self.rel_bias(s, s)
        keys = (jax.random.split(key, len(self.blocks)) if key is not None
                else [None] * len(self.blocks))
        step = maybe_remat(
            lambda b, xx, kk: b(xx, enc=enc, mask=mask, enc_mask=enc_mask,
                                pos_bias=pos_bias, key=kk,
                                training=training),
            self.config.remat)
        for blk, k in zip(self.blocks, keys):
            x = step(blk, x, k)
        return self.final_ln(x)


class T5Model(Module):
    def __init__(self, cfg: T5Config):
        self.shared = Embedding(cfg.vocab_size, cfg.d_model,
                                initializer=normal(stddev=1.0),
                                dtype=cfg.dtype)
        self.encoder = T5Stack(cfg, decoder=False)
        self.decoder = T5Stack(cfg, decoder=True)
        self.config = cfg

    def __call__(self, input_ids, decoder_input_ids, *,
                 attention_mask=None, decoder_attention_mask=None,
                 key=None, training=False):
        ek = dk = None
        if key is not None:
            ek, dk = jax.random.split(key)
        mask = (attention_mask[:, None, None, :]
                if attention_mask is not None else None)
        enc = self.encoder(self.shared(input_ids), mask=mask, key=ek,
                           training=training)
        dmask = (decoder_attention_mask[:, None, None, :]
                 if decoder_attention_mask is not None else None)
        dec = self.decoder(self.shared(decoder_input_ids), enc=enc,
                           mask=dmask, enc_mask=mask, key=dk,
                           training=training)
        return enc, dec


class T5ForConditionalGeneration(Module):
    """Seq2seq LM head over T5Model; head tied to the shared embedding with
    the d_model**-0.5 output rescale (original T5 tie)."""

    def __init__(self, cfg: T5Config):
        self.t5 = T5Model(cfg)
        self.config = cfg

    def __call__(self, input_ids, decoder_input_ids, **kw):
        _, dec = self.t5(input_ids, decoder_input_ids, **kw)
        dec = dec * (self.config.d_model ** -0.5)
        return dec @ self.t5.shared.weight.T.astype(dec.dtype)

    def loss(self, input_ids, decoder_input_ids, labels, *,
             attention_mask=None, key=None, training=True):
        logits = self(input_ids, decoder_input_ids,
                      attention_mask=attention_mask, key=key,
                      training=training)
        nll = softmax_cross_entropy_sparse(logits, jnp.maximum(labels, 0))
        m = (labels >= 0).astype(jnp.float32)
        loss = jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
        return loss, {"lm_loss": loss}
