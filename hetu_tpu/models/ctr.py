"""CTR model family: Wide&Deep, DeepFM, DCN (+ cross network).

TPU-native re-designs of the reference CTR examples
(examples/ctr/models/{wdl_criteo.py,wdl_adult.py,deepfm_criteo.py,
dcn_criteo.py}): criteo layout of 13 dense + 26 categorical fields embedded
into a shared id space, a deep MLP tower, and the model-specific parts —
W&D's wide concat, DeepFM's factorization-machine second-order term, DCN's
cross layers.

The embedding is pluggable: ``embedding="device"`` keeps the table on-chip
(pure XLA gather); ``embedding="host"`` uses the HET engine
(hetu_tpu/embed — host table + cache + server-side optimizer), matching the
reference's Hybrid mode where embeddings always route through the PS
(executor.py:276-283) while dense params train on-chip.
"""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.embed import (HBMCachedEmbedding, HostEmbedding,
                            StagedHostEmbedding)
from hetu_tpu.init import normal
from hetu_tpu.layers import Embedding, Linear, MLPTower
from hetu_tpu.ops import binary_cross_entropy_with_logits, relu, sigmoid

__all__ = ["CTRConfig", "WideDeep", "DeepFM", "DCN", "DeepCrossing",
           "make_embedding"]


class CTRConfig:
    """Criteo-shaped feature layout (reference examples/ctr/load_data.py)."""

    def __init__(self, dense_dim: int = 13, sparse_fields: int = 26,
                 vocab: int = 26000, embed_dim: int = 16,
                 mlp_hidden: int = 256, embedding: str = "device",
                 host_optimizer: str = "sgd", host_lr: float = 0.01,
                 cache_capacity: int = 0, cache_policy: str = "lru",
                 pull_bound: int = 0, push_bound: int = 0,
                 host_bridge: str = "auto", host_async_push: bool = False,
                 servers=None, reconnect_attempts: int = 0,
                 restore_path: str | None = None, storage: str = "f32",
                 host_cache_capacity: int | None = None,
                 promote_touches: int = 2, demote_idle: int = 0):
        self.dense_dim = dense_dim
        self.sparse_fields = sparse_fields
        self.vocab = vocab
        self.embed_dim = embed_dim
        self.mlp_hidden = mlp_hidden
        self.embedding = embedding
        self.host_optimizer = host_optimizer
        self.host_lr = host_lr
        self.cache_capacity = cache_capacity
        self.cache_policy = cache_policy
        self.pull_bound = pull_bound
        self.push_bound = push_bound
        # PS storage form ("f32" | "int8" — the quantized PS tier) for the
        # host-engine embedding modes; tier policy knobs apply to
        # embedding="tiered" (cache_capacity = the HBM row budget there,
        # host_cache_capacity = the host HET-cache width, default 4x)
        if storage not in ("f32", "int8"):
            raise ValueError(f"unknown storage {storage!r}: 'f32' or 'int8'")
        if storage != "f32" and embedding in ("device", "remote"):
            raise ValueError(
                'storage="int8" is the host-PS storage knob: it needs a '
                'host-engine embedding ("host" | "hbm" | "tiered")')
        self.storage = storage
        self.host_cache_capacity = host_cache_capacity
        self.promote_touches = promote_touches
        self.demote_idle = demote_idle
        # "callback" = io_callback bridge inside jit; "staged" = pull/push
        # outside jit (works on backends without host callbacks, e.g. the
        # tunneled axon TPU); "auto" picks per backend.
        self.host_bridge = host_bridge
        # ASP-style pushes off the step's critical path (reference PS
        # default bsp=-1, executor.py:203); staged bridge only
        self.host_async_push = host_async_push
        self.servers = list(servers) if servers else []  # embedding="remote"
        # PS fault tolerance (embedding="remote", uncached): reconnect
        # with bounded backoff + checkpoint restore on server restart
        # (embed.net.RemoteEmbeddingTable)
        if restore_path is not None and reconnect_attempts <= 0:
            raise ValueError(
                "restore_path only takes effect during a reconnect — set "
                "reconnect_attempts > 0 or the checkpoint would silently "
                "never be restored after a PS restart")
        if reconnect_attempts > 0 and embedding != "remote":
            raise ValueError(
                'reconnect_attempts is the network-PS fault-tolerance '
                'knob: it needs embedding="remote"')
        self.reconnect_attempts = reconnect_attempts
        self.restore_path = restore_path


def make_embedding(cfg: CTRConfig, dim: int | None = None, seed: int = 0):
    dim = dim if dim is not None else cfg.embed_dim
    if cfg.embedding == "remote":
        # key-partitioned across network PS servers (reference multi-server
        # deployment; servers spawned by heturun or embed.net standalone)
        from hetu_tpu.embed.net import RemoteHostEmbedding
        if not cfg.servers:
            raise ValueError('embedding="remote" needs CTRConfig.servers')
        return RemoteHostEmbedding(
            cfg.vocab, dim, servers=cfg.servers,
            optimizer=cfg.host_optimizer, lr=cfg.host_lr, seed=seed,
            cache_capacity=cfg.cache_capacity, policy=cfg.cache_policy,
            pull_bound=cfg.pull_bound, push_bound=cfg.push_bound,
            reconnect_attempts=cfg.reconnect_attempts,
            restore_path=cfg.restore_path)
    if cfg.embedding == "tiered":
        # the full production hierarchy: HBM hot rows over the host HET
        # cache over the (optionally int8-quantized) PS table, with
        # touch-frequency promotion/demotion (embed.tier)
        from hetu_tpu.embed import TieredEmbedding, TierPolicy
        if cfg.cache_capacity <= 0:
            raise ValueError('embedding="tiered" needs cache_capacity > 0 '
                             "(the HBM-resident row budget)")
        return TieredEmbedding(
            cfg.vocab, dim, hbm_capacity=cfg.cache_capacity,
            host_capacity=cfg.host_cache_capacity,
            policy=TierPolicy(promote_touches=cfg.promote_touches,
                              demote_idle=cfg.demote_idle),
            hbm_pull_bound=cfg.pull_bound, host_pull_bound=cfg.pull_bound,
            storage=cfg.storage, cache_policy=cfg.cache_policy,
            push_bound=cfg.push_bound, optimizer=cfg.host_optimizer,
            lr=cfg.host_lr, seed=seed)
    if cfg.embedding == "hbm":
        # host store + hot rows staged into device HBM (the north-star
        # layout; warm steps transfer only refreshed rows).  The device
        # cache is LRU; cache_policy/push_bound apply to the host paths
        # only.
        if cfg.cache_capacity <= 0:
            raise ValueError('embedding="hbm" needs cache_capacity > 0 '
                             "(the HBM-resident row budget)")
        return HBMCachedEmbedding(
            cfg.vocab, dim, optimizer=cfg.host_optimizer, lr=cfg.host_lr,
            seed=seed, hbm_capacity=cfg.cache_capacity,
            hbm_pull_bound=cfg.pull_bound, storage=cfg.storage)
    if cfg.embedding == "host":
        bridge = cfg.host_bridge
        if bridge == "auto":
            from hetu_tpu.embed.bridge import host_callbacks_supported
            bridge = "callback" if host_callbacks_supported() else "staged"
        cls = StagedHostEmbedding if bridge == "staged" else HostEmbedding
        kw = dict(optimizer=cfg.host_optimizer, lr=cfg.host_lr, seed=seed,
                  cache_capacity=cfg.cache_capacity,
                  policy=cfg.cache_policy, pull_bound=cfg.pull_bound,
                  push_bound=cfg.push_bound, storage=cfg.storage)
        if cls is StagedHostEmbedding:
            kw["async_push"] = cfg.host_async_push
        elif cfg.host_async_push:
            # the callback bridge pushes inside the jitted step; silently
            # ignoring the ASP request would change staleness semantics
            # per backend
            raise ValueError(
                "host_async_push requires the staged bridge "
                '(host_bridge="staged"); the callback bridge resolved here '
                "pushes inside the step")
        return cls(cfg.vocab, dim, **kw)
    return Embedding(cfg.vocab, dim)


class _DeepTower(MLPTower):
    """relu MLP tower (the shared DNN of all three models) — the constant-
    hidden special case of layers.MLPTower, last layer unactivated."""

    def __init__(self, in_dim: int, hidden: int, out_dim: int, depth: int = 3):
        super().__init__([in_dim] + [hidden] * (depth - 1) + [out_dim],
                         final_relu=False)


class WideDeep(Module):
    """Wide&Deep (reference wdl_criteo.py:8): deep tower on dense features,
    concat with flattened embeddings, linear head."""

    def __init__(self, cfg: CTRConfig):
        self.cfg = cfg
        self.embed = make_embedding(cfg)
        self.deep = _DeepTower(cfg.dense_dim, cfg.mlp_hidden, cfg.mlp_hidden)
        self.head = Linear(
            cfg.mlp_hidden + cfg.sparse_fields * cfg.embed_dim, 1)

    def logits(self, dense, sparse):
        emb = self.embed(sparse).reshape(dense.shape[0], -1)
        deep = self.deep(dense)
        return self.head(jnp.concatenate([emb, deep], axis=1))[:, 0]

    def loss(self, dense, sparse, label):
        logits = self.logits(dense, sparse)
        loss = binary_cross_entropy_with_logits(logits, label).mean()
        return loss, {"pred": sigmoid(logits)}


class DeepFM(Module):
    """DeepFM (reference deepfm_criteo.py): first-order embedding +
    FM second-order interaction + deep tower over flattened embeddings."""

    def __init__(self, cfg: CTRConfig):
        self.cfg = cfg
        self.embed = make_embedding(cfg)                 # second-order (k-dim)
        self.embed1 = make_embedding(cfg, dim=1, seed=1)  # first-order
        self.deep = _DeepTower(
            cfg.sparse_fields * cfg.embed_dim, cfg.mlp_hidden, 1)
        self.bias = jnp.zeros((1,), jnp.float32)

    def logits(self, dense, sparse):
        v = self.embed(sparse)                       # (b, fields, k)
        first = self.embed1(sparse)[..., 0].sum(1)   # (b,)
        # FM: 0.5 * ((sum_f v)^2 - sum_f v^2), summed over k
        s = v.sum(axis=1)
        second = 0.5 * ((s * s).sum(-1) - (v * v).sum(axis=(1, 2)))
        deep = self.deep(v.reshape(v.shape[0], -1))[:, 0]
        return first + second + deep + self.bias[0]

    def loss(self, dense, sparse, label):
        logits = self.logits(dense, sparse)
        loss = binary_cross_entropy_with_logits(logits, label).mean()
        return loss, {"pred": sigmoid(logits)}


class _ResidualUnit(Module):
    """DeepCrossing residual unit (reference dc_criteo.py residual_layer):
    relu(x + W2 relu(W1 x + b1) + b2)."""

    def __init__(self, dim: int, hidden: int):
        self.fc1 = Linear(dim, hidden, initializer=normal(stddev=0.1))
        self.fc2 = Linear(hidden, dim, initializer=normal(stddev=0.1))

    def __call__(self, x):
        return relu(x + self.fc2(relu(self.fc1(x))))


class DeepCrossing(Module):
    """DeepCrossing (reference examples/ctr/models/dc_criteo.py): stacked
    residual units over [embeddings ++ dense], linear scoring head."""

    def __init__(self, cfg: CTRConfig, num_residual: int = 3,
                 residual_hidden: int | None = None):
        self.cfg = cfg
        self.embed = make_embedding(cfg)
        in_dim = cfg.sparse_fields * cfg.embed_dim + cfg.dense_dim
        hidden = residual_hidden if residual_hidden is not None else cfg.mlp_hidden
        self.residuals = [_ResidualUnit(in_dim, hidden)
                          for _ in range(num_residual)]
        self.head = Linear(in_dim, 1, initializer=normal(stddev=0.1))

    def logits(self, dense, sparse):
        emb = self.embed(sparse).reshape(dense.shape[0], -1)
        x = jnp.concatenate([emb, dense], axis=1)
        for unit in self.residuals:
            x = unit(x)
        return self.head(x)[:, 0]

    def loss(self, dense, sparse, label):
        logits = self.logits(dense, sparse)
        loss = binary_cross_entropy_with_logits(logits, label).mean()
        return loss, {"pred": sigmoid(logits)}


class CrossLayer(Module):
    """One DCN cross layer (reference dcn_criteo.py:8 cross_layer):
    y = x0 * (x1 @ w) + b + x1."""

    def __init__(self, dim: int):
        init = normal(stddev=0.01)
        self.w = init(next_key(), (dim, 1), jnp.float32)
        self.b = init(next_key(), (dim,), jnp.float32)

    def __call__(self, x0, x1):
        x1w = x1 @ self.w              # (b, 1)
        return x0 * x1w + self.b + x1


class DCN(Module):
    """Deep&Cross (reference dcn_criteo.py:28): cross network + deep tower
    over [embeddings ++ dense], concatenated into the head."""

    def __init__(self, cfg: CTRConfig, num_cross: int = 3):
        self.cfg = cfg
        self.embed = make_embedding(cfg)
        in_dim = cfg.sparse_fields * cfg.embed_dim + cfg.dense_dim
        self.cross = [CrossLayer(in_dim) for _ in range(num_cross)]
        self.deep = _DeepTower(in_dim, cfg.mlp_hidden, cfg.mlp_hidden)
        self.head = Linear(in_dim + cfg.mlp_hidden, 1)

    def logits(self, dense, sparse):
        emb = self.embed(sparse).reshape(dense.shape[0], -1)
        x0 = jnp.concatenate([emb, dense], axis=1)
        x1 = x0
        for layer in self.cross:
            x1 = layer(x0, x1)
        deep = self.deep(x0)
        return self.head(jnp.concatenate([x1, deep], axis=1))[:, 0]

    def loss(self, dense, sparse, label):
        logits = self.logits(dense, sparse)
        loss = binary_cross_entropy_with_logits(logits, label).mean()
        return loss, {"pred": sigmoid(logits)}
