"""Neural collaborative filtering family: MF, GMF, MLP, NeuMF.

TPU-native re-designs of the reference recommendation models
(reference: examples/rec/models/{mf,gmf,mlp,neumf}.py): user and item ids
embed into a shared table (two sparse fields), and the heads differ —
MF/GMF take the elementwise product of the two embeddings (MF scores its
sum, GMF learns a linear head over it), MLP feeds the concatenation
through a tower, NeuMF splits the embedding into a GMF factor slice and an
MLP slice and concatenates both branches before the prediction layer
(neumf.py:19-29).  Ratings train with logistic loss like the reference's
``RatingModel_Head.output``.

The embedding is pluggable exactly like the CTR family — pass any module
with the ``emb(ids) -> [batch, 2, dim]`` contract (on-device ``Embedding``,
``HostEmbedding``/``StagedHostEmbedding``, ``ShardedHostEmbedding``, or a
compressed variant from ``embed/compress`` — the reference drives these
models through its compression suite, examples/rec/run_compressed.py).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.layers import Embedding, Linear, MLPTower
from hetu_tpu.ops import binary_cross_entropy_with_logits, sigmoid

__all__ = ["MF", "GMF", "MLPRec", "NeuMF"]


class _RatingModel(Module):
    """Shared skeleton: embedding over [user_id, item_id] + logistic loss."""

    def __init__(self, num_embeddings: int, dim: int,
                 embedding: Optional[Module] = None):
        self.embed = embedding or Embedding(num_embeddings, dim)
        self.dim = dim

    def _pair(self, ids):
        """ids [batch, 2] -> embeddings [batch, 2, dim]."""
        return self.embed(ids).astype(jnp.float32)

    def logits(self, ids):
        raise NotImplementedError

    def loss(self, ids, label):
        logits = self.logits(ids)
        loss = binary_cross_entropy_with_logits(logits, label).mean()
        return loss, {"pred": sigmoid(logits)}


class MF(_RatingModel):
    """Plain matrix factorization: score = <user, item> (mf.py)."""

    def logits(self, ids):
        e = self._pair(ids)
        return jnp.sum(e[:, 0] * e[:, 1], axis=-1)


class GMF(_RatingModel):
    """Generalized MF: learned linear head over the elementwise product
    (gmf.py:15-17)."""

    def __init__(self, num_embeddings: int, dim: int,
                 embedding: Optional[Module] = None):
        super().__init__(num_embeddings, dim, embedding)
        self.predict = Linear(dim, 1)

    def logits(self, ids):
        e = self._pair(ids)
        return self.predict(e[:, 0] * e[:, 1])[:, 0]


class MLPRec(_RatingModel):
    """MLP head over the concatenated pair (mlp.py): tower halves the
    width each layer down to one factor."""

    def __init__(self, num_embeddings: int, dim: int,
                 embedding: Optional[Module] = None, depth: int = 3):
        super().__init__(num_embeddings, dim, embedding)
        dims = [2 * dim] + [max(2 * dim // (2 ** (i + 1)), 8)
                            for i in range(depth)]
        self.tower = MLPTower(dims)
        self.predict = Linear(dims[-1], 1)

    def logits(self, ids):
        e = self._pair(ids)
        h = self.tower(e.reshape(e.shape[0], -1))
        return self.predict(h)[:, 0]


class NeuMF(_RatingModel):
    """Neural MF (neumf.py): the embedding splits into a GMF factor slice
    (dim//5, neumf.py:9-12) and an MLP slice; the GMF product and the MLP
    tower output concatenate into the prediction layer."""

    def __init__(self, num_embeddings: int, dim: int,
                 embedding: Optional[Module] = None):
        if dim % 5:
            raise ValueError("NeuMF needs embed dim divisible by 5 "
                             "(reference neumf.py:9)")
        super().__init__(num_embeddings, dim, embedding)
        self.factor = dim // 5
        # fixed 2-pair MLP: [8f, 4f, 2f, f] like neumf.py:13-14
        self.tower = MLPTower([8 * self.factor, 4 * self.factor,
                               2 * self.factor, self.factor])
        self.predict = Linear(2 * self.factor, 1)

    def logits(self, ids):
        e = self._pair(ids)
        gmf = e[:, :, :self.factor]
        mlp = e[:, :, self.factor:]
        out_gmf = gmf[:, 0] * gmf[:, 1]                     # [b, f]
        h = self.tower(mlp.reshape(mlp.shape[0], -1))       # [b, f]
        return self.predict(jnp.concatenate([out_gmf, h], axis=-1))[:, 0]
