"""Recurrent models: vanilla RNN, LSTM, GRU cells + scan-based runners.

Reference: examples/cnn/models/RNN.py and LSTM.py build recurrences by
unrolling Python loops of matmul ops over the sequence (one graph node per
timestep).  TPU-native design: the carry-independent input projection
``x @ W_x`` is hoisted OUT of the loop as one big [B*T, F]x[F, kH] MXU
matmul over the whole sequence, and the recurrence is a single ``lax.scan``
whose body does only the [B, H]x[H, kH] recurrent matmul per tick (all gates
stacked on the output dim — 4H for LSTM, 3H for GRU), so XLA compiles one
tight loop instead of a thousand-node unrolled graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import xavier_uniform, zeros
from hetu_tpu.layers import Linear

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "RNN", "RNNClassifier"]


class RNNCell(Module):
    """h' = tanh(x W_x + h W_h + b)."""

    def __init__(self, input_size: int, hidden_size: int,
                 dtype=jnp.float32):
        init = xavier_uniform()
        self.wx = init(next_key(), (input_size, hidden_size), dtype)
        self.wx_axes = ("in", "hidden")
        self.wh = init(next_key(), (hidden_size, hidden_size), dtype)
        self.wh_axes = ("hidden", "hidden2")
        self.b = zeros(None, (hidden_size,), dtype)
        self.b_axes = ("hidden",)
        self.hidden_size = hidden_size

    def init_state(self, batch: int, dtype=None):
        return jnp.zeros((batch, self.hidden_size), dtype or self.b.dtype)

    def input_proj(self, x):
        """Carry-independent projection — applied to the whole sequence at
        once by ``RNN``, outside the scan."""
        return x @ self.wx.astype(x.dtype) + self.b.astype(x.dtype)

    def step(self, state, xg):
        h = jnp.tanh(xg + state @ self.wh.astype(xg.dtype))
        return h, h

    def __call__(self, state, x):
        return self.step(state, self.input_proj(x))


class LSTMCell(Module):
    """Fused-gate LSTM: gates stacked [in, 4H] (i, f, g, o)."""

    def __init__(self, input_size: int, hidden_size: int,
                 dtype=jnp.float32, forget_bias: float = 1.0):
        init = xavier_uniform()
        self.wx = init(next_key(), (input_size, 4 * hidden_size), dtype)
        self.wx_axes = ("in", "gates")
        self.wh = init(next_key(), (hidden_size, 4 * hidden_size), dtype)
        self.wh_axes = ("hidden", "gates")
        self.b = zeros(None, (4 * hidden_size,), dtype)
        self.b_axes = ("gates",)
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias

    def init_state(self, batch: int, dtype=None):
        dt = dtype or self.b.dtype
        return (jnp.zeros((batch, self.hidden_size), dt),
                jnp.zeros((batch, self.hidden_size), dt))

    def input_proj(self, x):
        return x @ self.wx.astype(x.dtype) + self.b.astype(x.dtype)

    def step(self, state, xg):
        h, c = state
        gates = xg + h @ self.wh.astype(xg.dtype)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + self.forget_bias) * c + \
            jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    def __call__(self, state, x):
        return self.step(state, self.input_proj(x))


class GRUCell(Module):
    """Fused-gate GRU: gates stacked [in, 3H] (r, z, n)."""

    def __init__(self, input_size: int, hidden_size: int,
                 dtype=jnp.float32):
        init = xavier_uniform()
        self.wx = init(next_key(), (input_size, 3 * hidden_size), dtype)
        self.wx_axes = ("in", "gates")
        self.wh = init(next_key(), (hidden_size, 3 * hidden_size), dtype)
        self.wh_axes = ("hidden", "gates")
        self.b = zeros(None, (3 * hidden_size,), dtype)
        self.b_axes = ("gates",)
        self.hidden_size = hidden_size

    def init_state(self, batch: int, dtype=None):
        return jnp.zeros((batch, self.hidden_size), dtype or self.b.dtype)

    def input_proj(self, x):
        return x @ self.wx.astype(x.dtype) + self.b.astype(x.dtype)

    def step(self, state, xg):
        hg = state @ self.wh.astype(xg.dtype)
        xr, xz, xn = jnp.split(xg, 3, axis=-1)
        hr, hz, hn = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h = (1 - z) * n + z * state
        return h, h

    def __call__(self, state, x):
        return self.step(state, self.input_proj(x))


class RNN(Module):
    """Run a cell over a [batch, time, features] sequence with ``lax.scan``.

    The input projection runs once over the whole sequence (one large MXU
    matmul); only the recurrent matmul lives in the scan body.  Returns
    (outputs [batch, time, hidden], final_state).
    """

    def __init__(self, cell, reverse: bool = False):
        self.cell = cell
        self.reverse = reverse

    def __call__(self, x, state=None):
        if state is None:
            state = self.cell.init_state(x.shape[0], x.dtype)
        xg = self.cell.input_proj(x)     # [B, T, kH] in one matmul
        xgs = jnp.swapaxes(xg, 0, 1)     # [T, B, kH] for the scan

        def body(carry, xg_t):
            return self.cell.step(carry, xg_t)

        state, ys = lax.scan(body, state, xgs, reverse=self.reverse)
        return jnp.swapaxes(ys, 0, 1), state


class RNNClassifier(Module):
    """Sequence classifier over the last hidden state (the reference's
    RNN/LSTM MNIST examples classify rows-as-timesteps the same way)."""

    def __init__(self, input_size: int, hidden_size: int, num_classes: int,
                 cell: str = "lstm", dtype=jnp.float32):
        cells = {"rnn": RNNCell, "lstm": LSTMCell, "gru": GRUCell}
        self.rnn = RNN(cells[cell](input_size, hidden_size, dtype=dtype))
        self.head = Linear(hidden_size, num_classes, dtype=dtype)

    def __call__(self, x):
        ys, _ = self.rnn(x)
        return self.head(ys[:, -1])
