"""ResNet for CIFAR/ImageNet — BASELINE config 1 (ResNet-18/CIFAR-10).

Reference: examples/cnn/models/hetu/resnet.py (ResNet-18/34 via its op graph).
TPU-native design: NHWC layout, functional BatchNorm threading (training
forward returns (logits, updated_model) carrying new running stats — XLA
keeps everything fused; there is no in-place state).
"""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.layers import AvgPool2d, BatchNorm2d, Conv2d, Flatten, Linear, MaxPool2d
from hetu_tpu.ops import relu

__all__ = ["ResNet", "resnet18", "resnet34", "BasicBlock"]


class BasicBlock(Module):
    expansion = 1

    def __init__(self, in_ch: int, out_ch: int, stride: int = 1):
        self.conv1 = Conv2d(in_ch, out_ch, 3, stride=stride, padding=1, bias=False)
        self.bn1 = BatchNorm2d(out_ch)
        self.conv2 = Conv2d(out_ch, out_ch, 3, stride=1, padding=1, bias=False)
        self.bn2 = BatchNorm2d(out_ch)
        if stride != 1 or in_ch != out_ch:
            self.shortcut_conv = Conv2d(in_ch, out_ch, 1, stride=stride,
                                        padding=0, bias=False)
            self.shortcut_bn = BatchNorm2d(out_ch)
        else:
            self.shortcut_conv = None
            self.shortcut_bn = None

    def __call__(self, x, *, training: bool = False):
        y, bn1 = self.bn1(self.conv1(x), training=training)
        y = relu(y)
        y, bn2 = self.bn2(self.conv2(y), training=training)
        if self.shortcut_conv is not None:
            sc, sbn = self.shortcut_bn(self.shortcut_conv(x), training=training)
        else:
            sc, sbn = x, self.shortcut_bn
        new = self.replace(bn1=bn1, bn2=bn2, shortcut_bn=sbn)
        return relu(y + sc), new


class ResNet(Module):
    def __init__(self, layers_per_stage, num_classes: int = 10,
                 cifar_stem: bool = True):
        self.stem_conv = Conv2d(3, 64, 3 if cifar_stem else 7,
                                stride=1 if cifar_stem else 2,
                                padding=1 if cifar_stem else 3, bias=False)
        self.stem_bn = BatchNorm2d(64)
        self.stem_pool = None if cifar_stem else MaxPool2d(3, 2, padding=1)
        stages = []
        in_ch = 64
        for i, n in enumerate(layers_per_stage):
            out_ch = 64 * (2**i)
            blocks = []
            for j in range(n):
                stride = 2 if (j == 0 and i > 0) else 1
                blocks.append(BasicBlock(in_ch, out_ch, stride))
                in_ch = out_ch
            stages.append(blocks)
        self.stages = stages
        self.flatten = Flatten()
        self.fc = Linear(in_ch, num_classes)

    def __call__(self, x, *, training: bool = False):
        y, stem_bn = self.stem_bn(self.stem_conv(x), training=training)
        y = relu(y)
        if self.stem_pool is not None:
            y = self.stem_pool(y)
        new_stages = []
        for blocks in self.stages:
            new_blocks = []
            for blk in blocks:
                y, nb = blk(y, training=training)
                new_blocks.append(nb)
            new_stages.append(new_blocks)
        y = jnp.mean(y, axis=(1, 2))  # global average pool
        logits = self.fc(y)
        return logits, self.replace(stem_bn=stem_bn, stages=new_stages)


def resnet18(num_classes: int = 10, cifar_stem: bool = True) -> ResNet:
    return ResNet([2, 2, 2, 2], num_classes, cifar_stem)


def resnet34(num_classes: int = 10, cifar_stem: bool = True) -> ResNet:
    return ResNet([3, 4, 6, 3], num_classes, cifar_stem)
