"""Block-allocated KV-cache pool with per-sequence page tables.

The serving memory manager (the vLLM/Orca idea restated TPU-first): the
KV cache for all concurrent sequences lives in ONE pair of device arrays

    k, v : (num_layers, num_pages, page_size, num_heads, head_dim)

and each sequence owns an ordered list of physical pages (its *page
table*).  Sequences grow a page at a time, free their pages the moment
they finish, and never copy — admission capacity is bounded by free
pages, not by worst-case padded sequences.

XLA, however, wants static shapes.  The bridge is the *bucketed view*:
``gather_indices(seq_ids)`` pads every page table to the same
``pages_per_seq`` with the reserved scratch page 0, so the jitted decode
step always sees

    page_idx : (batch, pages_per_seq)                       — int32
    view     : k[:, page_idx] -> (L, batch, max_len, H, D)  — one gather

and writes back with one scatter.  Shapes depend only on (batch bucket,
length bucket), so XLA compiles ONE decode program and one prefill
program per bucket, ever.  Page 0 is never allocated to a sequence:
padded table entries read (masked) garbage from it and scatter their
dead rows back into it, keeping both directions legal without per-row
conditionals.

Host-side management (alloc/grow/free/defrag) is plain Python over a
sorted free list — deterministic: the same request schedule produces the
same physical placement, which the bitwise-replay acceptance tests rely
on.  ``defrag()`` compacts live pages toward low indices (the long-lived
server shape: after hours of ragged arrivals, a fresh long request needs
contiguous-ish headroom only the compactor can guarantee).

**Copy-on-write prefix sharing** (the fleet tier, serve/fleet/prefix.py):
pages are REFCOUNTED.  ``alloc(..., shared_pages=)`` returns a table
whose leading entries alias already-written pages of an identical prompt
prefix (each alias is a refcount, not a copy — the fleet stops re-storing
the same system prompt per request); :meth:`~KVCachePool.retain` lets the
prefix trie keep a page alive after its publishing sequence retires;
``free`` only returns a page to the free list when its last reference
drops, and a second ``free`` of the same sequence raises the NAMED
:class:`DoubleFree` instead of silently corrupting the free list.
:meth:`~KVCachePool.copy_on_write` un-shares a page the moment a
sequence needs to WRITE into it, and ``defrag`` treats every shared or
trie-cached page as pinned-by-refcount (moving a page another table or
the trie also points at would corrupt them all).  :meth:`stats` is the
supported introspection surface — pages by class, the refcount
histogram, and an alloc/free balance invariant asserted on every call.

**KV-page migration** (the disaggregated tier, serve/fleet/disagg.py):
:meth:`~KVCachePool.export_pages` snapshots one sequence's pages into a
self-describing, CRC- and fingerprint-verified record
(serve/fleet/migrate.py) and places an EXPORT HOLD (one extra refcount
per page) so that ``free()`` of the exporting sequence cannot recycle
the pages until :meth:`~KVCachePool.ack_export` /
:meth:`~KVCachePool.cancel_export` settles the handoff;
:meth:`~KVCachePool.import_pages` re-verifies the record (torn / CRC /
fingerprint / geometry, each a named diagnosis) before a single byte is
admitted into the destination pool.  ``stats()`` carries the
``exported_pages`` / ``imported_pages`` / ``pages_export_held``
counters and asserts the hold-backed-by-refcount invariant.
"""

from __future__ import annotations

import bisect
import dataclasses

import jax.numpy as jnp
import numpy as np

from hetu_tpu.obs import memledger as _memledger

__all__ = ["KVCachePool", "PageTable", "OutOfPages", "DoubleFree",
           "SCRATCH_PAGE", "gather_view_count", "reset_gather_view_count",
           "pages_written_count", "reset_pages_written_count",
           "note_pages_written"]

# Counting seam for the no-materialization acceptance test: gather_views
# is THE place a contiguous (L, batch, max_len, H, D) view of the pool is
# built, and it runs at trace time (inside jit), so counting its calls
# proves which jitted programs gather.  The paged decode step must trace
# to zero gathers; prefill (bucketed, once per request) still gathers.
_gather_view_calls = 0


def gather_view_count() -> int:
    """How many times :func:`gather_views` has traced a contiguous view."""
    return _gather_view_calls


def reset_gather_view_count() -> None:
    global _gather_view_calls
    _gather_view_calls = 0


# Second counting seam, same style: how many KV pages were freshly
# COMPUTED-AND-WRITTEN by prefill (the engine notes them after each
# prefill step).  A shared-prefix prefill aliases its prefix pages
# instead of recomputing them, so the acceptance test can prove that an
# identical-prefix request writes ZERO duplicate prefix pages — the
# whole point of copy-on-write sharing.
_pages_written = 0


def pages_written_count() -> int:
    """Pages freshly written by prefill since the last reset (aliased
    shared-prefix pages are never counted — they were not recomputed)."""
    return _pages_written


def note_pages_written(n: int) -> None:
    global _pages_written
    _pages_written += int(n)


def reset_pages_written_count() -> None:
    global _pages_written
    _pages_written = 0

# Physical page 0 is reserved: page-table padding points at it, and the
# scatter of a padded decode batch dumps dead rows into it.  Never
# allocated, never trusted.
SCRATCH_PAGE = 0


class OutOfPages(RuntimeError):
    """The pool cannot satisfy an allocation — admission control should
    hold the request in the queue until sequences retire."""


class DoubleFree(RuntimeError):
    """A sequence (or page) was freed twice.  Raised by ``free`` for an
    unknown sequence id and by ``release`` for a page already on the
    free list — NAMED, so the bug surfaces at the second free instead of
    corrupting the free list and handing one physical page to two
    sequences steps later."""


@dataclasses.dataclass
class PageTable:
    """One sequence's allocation: ordered physical pages + token length."""

    seq_id: int
    pages: list
    length: int = 0  # valid tokens written so far

    def capacity(self, page_size: int) -> int:
        return len(self.pages) * page_size


class KVCachePool:
    """Paged KV storage for all layers of one model + its allocator.

    The jitted serving step treats ``k``/``v`` as inputs and returns the
    updated arrays; the engine stores them back via :meth:`commit` — the
    pool itself stays a plain host-side object (no tracers).
    """

    def __init__(self, *, num_layers: int, num_heads: int, head_dim: int,
                 num_pages: int, page_size: int, max_seq_len: int,
                 dtype=jnp.float32):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the reserved "
                             "scratch page)")
        if max_seq_len % page_size:
            raise ValueError(f"max_seq_len {max_seq_len} must be a "
                             f"multiple of page_size {page_size}")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_seq_len = max_seq_len
        self.pages_per_seq = max_seq_len // page_size
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # ascending free list => lowest-index-first placement, deterministic
        self._free: list = list(range(1, num_pages))
        self._tables: dict = {}
        # page -> reference count (tables aliasing it + trie retains +
        # export holds); absent == on the free list.  A page leaves the
        # free list with rc 1 and returns only when its LAST reference
        # drops.
        self._refcount: dict = {}
        # alloc/free balance for the stats() invariant
        self._allocs = 0
        self._frees = 0
        # outstanding KV-page exports (disaggregated serving): seq_id ->
        # the pages snapshotted into a MigrationRecord, each holding one
        # extra reference until the import acks or the export is
        # cancelled — free() of an exporting sequence must never recycle
        # a page an in-flight migration may still need
        self._exports: dict = {}
        self._exported_pages = 0   # cumulative pages exported
        self._imported_pages = 0   # cumulative pages imported
        # seq_id -> owner (tenant id) for the per-tenant ledger view;
        # absent == unowned (stats report it under "-")
        self._owners: dict = {}

    # -- allocator ----------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def live_sequences(self) -> int:
        return len(self._tables)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.page_size)

    def can_admit(self, n_tokens: int) -> bool:
        return self.pages_needed(n_tokens) <= len(self._free)

    def alloc(self, seq_id: int, n_tokens: int,
              shared_pages=(), owner=None) -> PageTable:
        """Reserve capacity for ``n_tokens`` (>=1 page).  Raises
        :exc:`OutOfPages` without side effects when the pool is short.

        ``shared_pages`` are already-allocated pages holding an identical
        prompt prefix (the prefix trie's match): the returned table's
        leading entries ALIAS them — each gains a refcount, no K/V bytes
        move — and only the remainder is freshly allocated.  ``owner``
        (a tenant id) tags the sequence for the per-tenant ledger/stats
        view; it never affects placement."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        need = self.pages_needed(n_tokens)
        if n_tokens > self.max_seq_len:
            raise ValueError(f"sequence of {n_tokens} tokens exceeds "
                             f"max_seq_len {self.max_seq_len}")
        shared = list(shared_pages)
        if len(shared) > need:
            raise ValueError(f"{len(shared)} shared prefix pages exceed "
                             f"the {need} pages {n_tokens} tokens need")
        for p in shared:
            if self._refcount.get(p, 0) < 1:
                raise ValueError(f"shared page {p} is not allocated")
        fresh = need - len(shared)
        if fresh > len(self._free):
            raise OutOfPages(f"need {fresh} pages, {len(self._free)} free")
        for p in shared:
            self._refcount[p] += 1
        pages = shared + [self._free.pop(0) for _ in range(fresh)]
        for p in pages[len(shared):]:
            self._refcount[p] = 1
        pt = PageTable(seq_id, pages)
        self._tables[seq_id] = pt
        self._allocs += 1
        if owner is not None:
            self._owners[seq_id] = str(owner)
        _memledger.note_kv(self, alloc=1)
        return pt

    def ensure(self, seq_id: int, n_tokens: int) -> PageTable:
        """Grow ``seq_id``'s allocation to cover ``n_tokens`` (the
        one-page-at-a-time growth of a decoding sequence)."""
        pt = self._tables[seq_id]
        if n_tokens > self.max_seq_len:
            raise ValueError(f"sequence {seq_id} would exceed max_seq_len "
                             f"{self.max_seq_len}")
        while pt.capacity(self.page_size) < n_tokens:
            if not self._free:
                raise OutOfPages(f"growing sequence {seq_id}: no free pages")
            p = self._free.pop(0)
            self._refcount[p] = 1
            pt.pages.append(p)
        _memledger.note_kv(self)
        return pt

    def retain(self, page: int) -> None:
        """Add one reference to an allocated page (the prefix trie's hold:
        a published prefix outlives the sequence that computed it)."""
        if self._refcount.get(page, 0) < 1:
            raise ValueError(f"retain of unallocated page {page}")
        self._refcount[page] += 1
        _memledger.note_kv(self)

    def release(self, page: int) -> None:
        """Drop one reference; the page returns to the free list only at
        zero (sorted insert keeps placement deterministic)."""
        rc = self._refcount.get(page)
        if rc is None:
            raise DoubleFree(f"page {page} is already on the free list")
        if rc == 1:
            del self._refcount[page]
            bisect.insort(self._free, page)
        else:
            self._refcount[page] = rc - 1
        _memledger.note_kv(self)

    def free(self, seq_id: int) -> None:
        """Drop the sequence's reference on each of its pages; pages whose
        last reference this was return to the pool.  A second ``free`` of
        the same sequence raises :exc:`DoubleFree`."""
        pt = self._tables.pop(seq_id, None)
        if pt is None:
            raise DoubleFree(f"sequence {seq_id} already freed (or never "
                             f"allocated)")
        for p in pt.pages:
            self.release(p)
        self._frees += 1
        self._owners.pop(seq_id, None)
        _memledger.note_kv(self, free=1)

    def copy_on_write(self, seq_id: int, token_index: int) -> bool:
        """Un-share before a write: if the page holding ``token_index``
        is aliased (refcount > 1), copy its K/V rows into a fresh private
        page, point this sequence's table at the copy, and drop the
        reference on the original — the other aliases keep the original
        bytes.  Returns True when a copy happened (refcount-1 pages are
        already private: no copy, False)."""
        pt = self._tables[seq_id]
        i = token_index // self.page_size
        old = pt.pages[i]
        if self._refcount[old] == 1:
            return False
        if not self._free:
            raise OutOfPages(f"copy-on-write for sequence {seq_id}: "
                             f"no free page for the private copy")
        new = self._free.pop(0)
        self.k = self.k.at[:, new].set(self.k[:, old])
        self.v = self.v.at[:, new].set(self.v[:, old])
        self._refcount[new] = 1
        pt.pages[i] = new
        self.release(old)
        _memledger.note_kv(self)
        return True

    # -- KV-page migration (disaggregated serving) --------------------------

    def export_pages(self, seq_id: int):
        """Snapshot ``seq_id``'s pages into a self-describing, verifiable
        :class:`~hetu_tpu.serve.fleet.migrate.MigrationRecord` (payload +
        page order + length + per-page CRC32 + the PR 10 content
        fingerprint) and place an EXPORT HOLD on every page: a
        subsequent ``free()`` of the sequence keeps the pages off the
        free list until :meth:`ack_export` (the import landed) or
        :meth:`cancel_export` (the handoff was abandoned) settles the
        hold — closing the export/free race that would otherwise hand an
        in-flight migration's physical pages to a new sequence."""
        from hetu_tpu.serve.fleet.migrate import build_record
        pt = self._tables[seq_id]
        if seq_id in self._exports:
            raise ValueError(f"sequence {seq_id} already has an "
                             f"outstanding export")
        pages = list(pt.pages)
        idx = jnp.asarray(pages, jnp.int32)
        k = np.asarray(self.k[:, idx])   # (L, n_pages, page, H, D) copies
        v = np.asarray(self.v[:, idx])
        for p in pages:
            self._refcount[p] += 1       # the export hold
        self._exports[seq_id] = pages
        self._exported_pages += len(pages)
        _memledger.note_kv(self)
        return build_record(seq_id=seq_id, length=pt.length,
                            page_size=self.page_size, k_pages=k, v_pages=v)

    def _settle_export(self, seq_id: int) -> None:
        pages = self._exports.pop(seq_id, None)
        if pages is None:
            raise DoubleFree(f"export of sequence {seq_id} already "
                             f"settled (or never exported)")
        for p in pages:
            self.release(p)
        _memledger.note_kv(self)

    def ack_export(self, seq_id: int) -> None:
        """The importer admitted (or terminally resolved) the migrated
        sequence: drop the export hold; pages whose last reference this
        was return to the free list.  A second settle of the same export
        raises :exc:`DoubleFree` — the same named-at-the-bug contract as
        a double ``free``."""
        self._settle_export(seq_id)

    def cancel_export(self, seq_id: int) -> None:
        """The handoff was abandoned (every decode worker shed, or the
        exporter is shutting down): identical mechanics to
        :meth:`ack_export`, kept as its own name so call sites read as
        what happened."""
        self._settle_export(seq_id)

    def import_pages(self, record, *, seq_id=None, owner=None) -> PageTable:
        """Verify and admit a migrated sequence: re-check the record
        (``verify_record`` — torn payloads, per-page CRCs, the content
        fingerprint) and the pool geometry BEFORE allocating, then write
        the page payloads into freshly allocated private pages and set
        the table's ``length`` to the record's decode cursor.  Raises the
        named :exc:`~hetu_tpu.serve.fleet.migrate.MigrationIntegrityError`
        without side effects when anything disagrees — corrupt KV is
        never admitted."""
        from hetu_tpu.serve.fleet.migrate import (MigrationIntegrityError,
                                                  verify_record)
        verify_record(record)
        L, n, page, H, D = record.k_pages.shape
        mine = (self.num_layers, self.page_size, self.num_heads,
                self.head_dim)
        theirs = (L, page, H, D)
        if mine != theirs:
            raise MigrationIntegrityError(
                "geometry", f"record pages are (layers, page, heads, "
                            f"head_dim)={theirs}, this pool is {mine}")
        if str(record.dtype) != str(self.k.dtype):
            raise MigrationIntegrityError(
                "geometry", f"record dtype {record.dtype} != pool dtype "
                            f"{self.k.dtype}")
        if n * self.page_size > self.max_seq_len:
            raise MigrationIntegrityError(
                "geometry", f"{n} pages exceed this pool's max_seq_len "
                            f"{self.max_seq_len}")
        sid = record.seq_id if seq_id is None else seq_id
        pt = self.alloc(sid, n * self.page_size, owner=owner)
        idx = jnp.asarray(pt.pages, jnp.int32)
        self.k = self.k.at[:, idx].set(jnp.asarray(record.k_pages))
        self.v = self.v.at[:, idx].set(jnp.asarray(record.v_pages))
        pt.length = record.length
        self._imported_pages += n
        return pt

    def table(self, seq_id: int) -> PageTable:
        return self._tables[seq_id]

    def refcount(self, page: int) -> int:
        """Current reference count (0 == on the free list)."""
        return self._refcount.get(page, 0)

    def shared_pages_count(self) -> int:
        """Pages with more than one reference — the hot-path form of
        ``stats()['pages_shared']`` (no invariant sweep)."""
        return sum(1 for rc in self._refcount.values() if rc > 1)

    def owner(self, seq_id: int):
        """The tenant id ``alloc(owner=)`` tagged this sequence with
        (None when untagged)."""
        return self._owners.get(seq_id)

    def page_classes(self) -> dict:
        """The EXACT page partition the memory ledger attributes bytes
        by: every physical page lands in exactly one class —

        - ``scratch``: the reserved page 0;
        - ``export_hold``: under an unsettled export hold (an in-flight
          migration may still need the bytes);
        - ``shared_prefix``: aliased by several tables (refcount > 1) or
          held only by the prefix trie / a hold with no table (allocated
          but in no table);
        - ``active``: privately held by exactly one live sequence;
        - ``free``: on the free list.

        Counts sum to ``num_pages`` (asserted by ``_check_invariants``
        on every ``stats()`` call and by every ledger snapshot)."""
        held_by_table = set()
        for pt in self._tables.values():
            held_by_table.update(pt.pages)
        export_held = set()
        for pages in self._exports.values():
            export_held.update(pages)
        classes = {"active": 0, "shared_prefix": 0, "export_hold": 0,
                   "scratch": 1, "free": len(self._free)}
        for p, rc in self._refcount.items():
            if p in export_held:
                classes["export_hold"] += 1
            elif rc > 1 or p not in held_by_table:
                classes["shared_prefix"] += 1
            else:
                classes["active"] += 1
        return classes

    def pages_by_tenant(self) -> dict:
        """Table-page holds per owner (untagged sequences under ``"-"``),
        sorted by tenant.  A page aliased by two tenants' tables counts
        once per holder — this is the billing-shaped view, NOT the exact
        physical partition (that is :meth:`page_classes`)."""
        out: dict = {}
        for sid, pt in self._tables.items():
            t = self._owners.get(sid, "-")
            out[t] = out.get(t, 0) + len(pt.pages)
        return {t: out[t] for t in sorted(out)}

    def stats(self) -> dict:
        """The supported introspection surface: page classes, the
        refcount histogram, and the alloc/free balance — with the pool's
        accounting invariants ASSERTED on every call (a violation here is
        a double-free/leak caught at the scrape, not at the much-later
        wrong-answer)."""
        self._check_invariants()
        hist: dict = {}
        for rc in self._refcount.values():
            hist[rc] = hist.get(rc, 0) + 1
        shared = sum(1 for rc in self._refcount.values() if rc > 1)
        classes = self.page_classes()
        return {
            "pages_total": self.num_pages - 1,
            "pages_free": len(self._free),
            "pages_private": len(self._refcount) - shared,
            "pages_shared": shared,
            # the ledger's exact partition (classes sum to num_pages)
            # and the per-tenant table-page holds (PR 16 identity)
            "pages_by_class": classes,
            "pages_by_tenant": self.pages_by_tenant(),
            "refcount_histogram": {str(k): hist[k] for k in sorted(hist)},
            "sequences": len(self._tables),
            "allocs": self._allocs,
            "frees": self._frees,
            "page_size": self.page_size,
            # KV-page migration accounting (disaggregated serving):
            # cumulative export/import totals plus the pages currently
            # pinned by an unsettled export hold
            "exported_pages": self._exported_pages,
            "imported_pages": self._imported_pages,
            "pages_export_held": sum(len(p)
                                     for p in self._exports.values()),
            "exports_outstanding": len(self._exports),
        }

    def _check_invariants(self) -> None:
        free = set(self._free)
        assert len(free) == len(self._free), \
            f"free list holds duplicates: {sorted(self._free)}"
        assert SCRATCH_PAGE not in free and \
            SCRATCH_PAGE not in self._refcount, "scratch page was allocated"
        overlap = free & set(self._refcount)
        assert not overlap, \
            f"pages {sorted(overlap)} are both free and refcounted"
        assert len(free) + len(self._refcount) == self.num_pages - 1, \
            (f"page accounting leak: {len(free)} free + "
             f"{len(self._refcount)} allocated != {self.num_pages - 1}")
        # every table reference AND export hold must be backed by at
        # least that many refs — the export/free-race invariant: a page
        # under an unsettled export hold can never be on the free list
        held: dict = {}
        for pt in self._tables.values():
            for p in pt.pages:
                held[p] = held.get(p, 0) + 1
        for pages in self._exports.values():
            for p in pages:
                held[p] = held.get(p, 0) + 1
        for p, n in held.items():
            assert self._refcount.get(p, 0) >= n, \
                (f"page {p} referenced by {n} table entries / export "
                 f"holds but refcount is {self._refcount.get(p, 0)}")
        assert self._allocs - self._frees == len(self._tables), \
            (f"alloc/free imbalance: {self._allocs} allocs - "
             f"{self._frees} frees != {len(self._tables)} live sequences")
        # the ledger partition must be exact: every physical page in
        # exactly one class (a page double-classed or dropped here would
        # make the memory ledger mis-attribute bytes silently)
        classes = self.page_classes()
        assert sum(classes.values()) == self.num_pages, \
            (f"page classes {classes} sum to {sum(classes.values())}, "
             f"not num_pages {self.num_pages}")
        assert not (set(self._owners) - set(self._tables)), \
            (f"owner tags for dead sequences: "
             f"{sorted(set(self._owners) - set(self._tables))}")

    def defrag(self) -> int:
        """Compact movable live pages into the lowest physical indices,
        moving the K/V rows along (one permutation gather per array) and
        rewriting the page tables.  Returns the number of pages moved.
        Call between steps — the arrays are replaced, so in-flight views
        are stale.

        Pages are PINNED-BY-REFCOUNT: a page aliased by several tables
        (refcount > 1) or held only by the prefix trie or an unsettled
        export hold (allocated but in no table) stays at its physical
        index — moving it would require rewriting every alias
        atomically, and the trie's/export's references are not table
        entries this compactor can see.  Only single-reference,
        single-table pages move; the compaction target slots skip the
        pinned indices."""
        held_by_table = set()
        for pt in self._tables.values():
            held_by_table.update(pt.pages)
        pinned = {p for p, rc in self._refcount.items()
                  if rc > 1 or p not in held_by_table}
        movable = [p for pt in sorted(self._tables.values(),
                                      key=lambda t: t.seq_id)
                   for p in pt.pages if p not in pinned]
        # target layout: scratch, then (skipping pinned slots) movable
        # pages packed in (seq, pos) order, then the free pages
        slots = [s for s in range(1, self.num_pages) if s not in pinned]
        mapping = dict(zip(movable, slots))
        moved = sum(1 for old, new in mapping.items() if old != new)
        if moved == 0:
            return 0
        perm = list(range(self.num_pages))  # perm[new] = old
        for old, new in mapping.items():
            perm[new] = old
        moved_from = set(mapping)  # old indices already placed
        spare = iter(p for p in slots if p not in moved_from)
        for new in slots[len(movable):]:
            perm[new] = next(spare)
        perm_arr = jnp.asarray(perm, jnp.int32)
        self.k = jnp.take(self.k, perm_arr, axis=1)
        self.v = jnp.take(self.v, perm_arr, axis=1)
        for pt in self._tables.values():
            pt.pages = [mapping.get(p, p) for p in pt.pages]
        self._refcount = {mapping.get(p, p): rc
                          for p, rc in self._refcount.items()}
        self._free = sorted(slots[len(movable):])
        _memledger.note_kv(self)
        return moved

    # -- the static-shape bridge -------------------------------------------

    def gather_indices(self, seq_ids) -> jnp.ndarray:
        """(batch, pages_per_seq) int32 page-table matrix for the jitted
        step, padded with the scratch page.  ``None`` entries (idle slots)
        become all-scratch rows."""
        rows = []
        for sid in seq_ids:
            pages = [] if sid is None else self._tables[sid].pages
            rows.append(pages + [SCRATCH_PAGE] *
                        (self.pages_per_seq - len(pages)))
        return jnp.asarray(rows, jnp.int32)

    def commit(self, k, v) -> None:
        """Adopt the updated arrays a jitted step returned."""
        self.k = k
        self.v = v

    def utilization(self) -> dict:
        used = self.num_pages - 1 - len(self._free)
        return {"pages_total": self.num_pages - 1, "pages_used": used,
                "sequences": len(self._tables),
                "page_size": self.page_size}


def gather_views(k, v, page_idx):
    """Inside-jit helper: materialize the bucket-padded contiguous views
    ``(L, batch, max_len, H, D)`` from the page arrays — one gather each.
    Counted (at trace time) so the paged-decode acceptance test can prove
    the decode program never builds a view."""
    global _gather_view_calls
    _gather_view_calls += 1
    L, _, page, H, D = k.shape
    b, P = page_idx.shape
    kv_shape = (L, b, P * page, H, D)
    return (k[:, page_idx].reshape(kv_shape),
            v[:, page_idx].reshape(kv_shape))


def scatter_views(k, v, page_idx, k_view, v_view):
    """Inside-jit helper: write updated contiguous views back into the
    page arrays.  Every live page belongs to exactly one (sequence, slot),
    so the scatter is conflict-free except for the scratch page, whose
    content is never read unmasked."""
    L, _, page, H, D = k.shape
    b, P = page_idx.shape
    pg_shape = (L, b, P, page, H, D)
    return (k.at[:, page_idx].set(k_view.reshape(pg_shape)),
            v.at[:, page_idx].set(v_view.reshape(pg_shape)))
