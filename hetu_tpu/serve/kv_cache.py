"""Block-allocated KV-cache pool with per-sequence page tables.

The serving memory manager (the vLLM/Orca idea restated TPU-first): the
KV cache for all concurrent sequences lives in ONE pair of device arrays

    k, v : (num_layers, num_pages, page_size, num_heads, head_dim)

and each sequence owns an ordered list of physical pages (its *page
table*).  Sequences grow a page at a time, free their pages the moment
they finish, and never copy — admission capacity is bounded by free
pages, not by worst-case padded sequences.

XLA, however, wants static shapes.  The bridge is the *bucketed view*:
``gather_indices(seq_ids)`` pads every page table to the same
``pages_per_seq`` with the reserved scratch page 0, so the jitted decode
step always sees

    page_idx : (batch, pages_per_seq)                       — int32
    view     : k[:, page_idx] -> (L, batch, max_len, H, D)  — one gather

and writes back with one scatter.  Shapes depend only on (batch bucket,
length bucket), so XLA compiles ONE decode program and one prefill
program per bucket, ever.  Page 0 is never allocated to a sequence:
padded table entries read (masked) garbage from it and scatter their
dead rows back into it, keeping both directions legal without per-row
conditionals.

Host-side management (alloc/grow/free/defrag) is plain Python over a
sorted free list — deterministic: the same request schedule produces the
same physical placement, which the bitwise-replay acceptance tests rely
on.  ``defrag()`` compacts live pages toward low indices (the long-lived
server shape: after hours of ragged arrivals, a fresh long request needs
contiguous-ish headroom only the compactor can guarantee).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

__all__ = ["KVCachePool", "PageTable", "OutOfPages", "SCRATCH_PAGE",
           "gather_view_count", "reset_gather_view_count"]

# Counting seam for the no-materialization acceptance test: gather_views
# is THE place a contiguous (L, batch, max_len, H, D) view of the pool is
# built, and it runs at trace time (inside jit), so counting its calls
# proves which jitted programs gather.  The paged decode step must trace
# to zero gathers; prefill (bucketed, once per request) still gathers.
_gather_view_calls = 0


def gather_view_count() -> int:
    """How many times :func:`gather_views` has traced a contiguous view."""
    return _gather_view_calls


def reset_gather_view_count() -> None:
    global _gather_view_calls
    _gather_view_calls = 0

# Physical page 0 is reserved: page-table padding points at it, and the
# scatter of a padded decode batch dumps dead rows into it.  Never
# allocated, never trusted.
SCRATCH_PAGE = 0


class OutOfPages(RuntimeError):
    """The pool cannot satisfy an allocation — admission control should
    hold the request in the queue until sequences retire."""


@dataclasses.dataclass
class PageTable:
    """One sequence's allocation: ordered physical pages + token length."""

    seq_id: int
    pages: list
    length: int = 0  # valid tokens written so far

    def capacity(self, page_size: int) -> int:
        return len(self.pages) * page_size


class KVCachePool:
    """Paged KV storage for all layers of one model + its allocator.

    The jitted serving step treats ``k``/``v`` as inputs and returns the
    updated arrays; the engine stores them back via :meth:`commit` — the
    pool itself stays a plain host-side object (no tracers).
    """

    def __init__(self, *, num_layers: int, num_heads: int, head_dim: int,
                 num_pages: int, page_size: int, max_seq_len: int,
                 dtype=jnp.float32):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the reserved "
                             "scratch page)")
        if max_seq_len % page_size:
            raise ValueError(f"max_seq_len {max_seq_len} must be a "
                             f"multiple of page_size {page_size}")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_seq_len = max_seq_len
        self.pages_per_seq = max_seq_len // page_size
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # ascending free list => lowest-index-first placement, deterministic
        self._free: list = list(range(1, num_pages))
        self._tables: dict = {}

    # -- allocator ----------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def live_sequences(self) -> int:
        return len(self._tables)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.page_size)

    def can_admit(self, n_tokens: int) -> bool:
        return self.pages_needed(n_tokens) <= len(self._free)

    def alloc(self, seq_id: int, n_tokens: int) -> PageTable:
        """Reserve capacity for ``n_tokens`` (>=1 page).  Raises
        :exc:`OutOfPages` without side effects when the pool is short."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        need = self.pages_needed(n_tokens)
        if n_tokens > self.max_seq_len:
            raise ValueError(f"sequence of {n_tokens} tokens exceeds "
                             f"max_seq_len {self.max_seq_len}")
        if need > len(self._free):
            raise OutOfPages(f"need {need} pages, {len(self._free)} free")
        pt = PageTable(seq_id, [self._free.pop(0) for _ in range(need)])
        self._tables[seq_id] = pt
        return pt

    def ensure(self, seq_id: int, n_tokens: int) -> PageTable:
        """Grow ``seq_id``'s allocation to cover ``n_tokens`` (the
        one-page-at-a-time growth of a decoding sequence)."""
        pt = self._tables[seq_id]
        if n_tokens > self.max_seq_len:
            raise ValueError(f"sequence {seq_id} would exceed max_seq_len "
                             f"{self.max_seq_len}")
        while pt.capacity(self.page_size) < n_tokens:
            if not self._free:
                raise OutOfPages(f"growing sequence {seq_id}: no free pages")
            pt.pages.append(self._free.pop(0))
        return pt

    def free(self, seq_id: int) -> None:
        """Return the sequence's pages to the pool (sorted re-insert keeps
        placement deterministic)."""
        pt = self._tables.pop(seq_id)
        self._free = sorted(self._free + pt.pages)

    def table(self, seq_id: int) -> PageTable:
        return self._tables[seq_id]

    def defrag(self) -> int:
        """Compact live pages into the lowest physical indices, moving the
        K/V rows along (one permutation gather per array) and rewriting the
        page tables.  Returns the number of pages moved.  Call between
        steps — the arrays are replaced, so in-flight views are stale."""
        live = [(pt.seq_id, i, p)
                for pt in sorted(self._tables.values(),
                                 key=lambda t: t.seq_id)
                for i, p in enumerate(pt.pages)]
        # target layout: scratch, then live pages packed in (seq, pos) order
        mapping = {old: new for new, (_, _, old) in enumerate(live, start=1)}
        moved = sum(1 for old, new in mapping.items() if old != new)
        if moved == 0:
            return 0
        perm = list(range(self.num_pages))  # perm[new] = old
        for old, new in mapping.items():
            perm[new] = old
        moved_from = set(mapping)  # old indices already placed
        spare = iter(p for p in range(1, self.num_pages)
                     if p not in moved_from)
        for new in range(1 + len(live), self.num_pages):
            perm[new] = next(spare)
        perm_arr = jnp.asarray(perm, jnp.int32)
        self.k = jnp.take(self.k, perm_arr, axis=1)
        self.v = jnp.take(self.v, perm_arr, axis=1)
        for pt in self._tables.values():
            pt.pages = [mapping[p] for p in pt.pages]
        self._free = list(range(1 + len(live), self.num_pages))
        return moved

    # -- the static-shape bridge -------------------------------------------

    def gather_indices(self, seq_ids) -> jnp.ndarray:
        """(batch, pages_per_seq) int32 page-table matrix for the jitted
        step, padded with the scratch page.  ``None`` entries (idle slots)
        become all-scratch rows."""
        rows = []
        for sid in seq_ids:
            pages = [] if sid is None else self._tables[sid].pages
            rows.append(pages + [SCRATCH_PAGE] *
                        (self.pages_per_seq - len(pages)))
        return jnp.asarray(rows, jnp.int32)

    def commit(self, k, v) -> None:
        """Adopt the updated arrays a jitted step returned."""
        self.k = k
        self.v = v

    def utilization(self) -> dict:
        used = self.num_pages - 1 - len(self._free)
        return {"pages_total": self.num_pages - 1, "pages_used": used,
                "sequences": len(self._tables),
                "page_size": self.page_size}


def gather_views(k, v, page_idx):
    """Inside-jit helper: materialize the bucket-padded contiguous views
    ``(L, batch, max_len, H, D)`` from the page arrays — one gather each.
    Counted (at trace time) so the paged-decode acceptance test can prove
    the decode program never builds a view."""
    global _gather_view_calls
    _gather_view_calls += 1
    L, _, page, H, D = k.shape
    b, P = page_idx.shape
    kv_shape = (L, b, P * page, H, D)
    return (k[:, page_idx].reshape(kv_shape),
            v[:, page_idx].reshape(kv_shape))


def scatter_views(k, v, page_idx, k_view, v_view):
    """Inside-jit helper: write updated contiguous views back into the
    page arrays.  Every live page belongs to exactly one (sequence, slot),
    so the scatter is conflict-free except for the scratch page, whose
    content is never read unmasked."""
    L, _, page, H, D = k.shape
    b, P = page_idx.shape
    pg_shape = (L, b, P, page, H, D)
    return (k.at[:, page_idx].set(k_view.reshape(pg_shape)),
            v.at[:, page_idx].set(v_view.reshape(pg_shape)))
