"""Speculative decoding: draft proposes, target verifies in one step.

Decode is memory-bound — each (num_slots, 1) paged step streams the
whole KV history and model weights to emit ONE token per slot.  A small
draft GPT can propose ``k`` likely continuations per slot for a fraction
of that traffic, and the target then scores all of them in a SINGLE
batched paged-decode step: slot ``s`` expands into ``k + 1`` rows that
share its page table at consecutive cache indices, feeding the chain
``[last_emitted, d_1, ..., d_k]``.  Row ``j``'s K/V lands at position
``L + j`` BEFORE attention runs (``MultiHeadAttention._call_paged``
scatters every row's K/V into the pool first), so row ``j`` attends over
the history *including* rows ``< j`` of its own chain — the chain
composes inside one program.

**The bitwise guarantee.**  Sampling keys derive from ``(seed, request,
position)`` — not from a shared stream — so the token the engine emits
at position ``p`` is a pure function of the logits at ``p`` and the key.
Verification regenerates exactly those draws: row ``j`` samples with the
key at position ``L + j + 1``, and its context is valid iff the draft's
fed tokens match what the engine actually emitted (``d_i == t_{i-1}``
cumulatively).  Accepted tokens are therefore not merely from the right
*distribution* (the vLLM-style rejection-sampling bar) — they are the
IDENTICAL tokens the non-speculative engine would have produced, bit for
bit, which the acceptance tests assert across greedy, temperature, and
top-k sampling.  A mispredicted draft costs nothing but the wasted rows:
the page-table cursor (``PageTable.length``) simply does not advance
past the last accepted token — rejected rows' K/V stays as dead bytes
beyond ``length``, masked by every future step and overwritten as the
sequence grows, the same contract prefill-bucket padding already relies
on.  Pages allocated for the chain are NOT freed on rejection (the next
steps will fill them).

Speculation requires the paged decode path: paged K/V writes are
element-scattered per (page, slot), so chained rows compose; the gather
fallback scatters whole per-row page COPIES back and chained rows would
clobber each other (``serve.engine`` enforces this at construction).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.obs import compile as _compile
from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import registry as _obs
from hetu_tpu.serve.kv_cache import OutOfPages

__all__ = ["DraftProposer", "SpeculativeDecoder"]

_spec_metrics = None


def _spec_m() -> dict:
    global _spec_metrics
    if _spec_metrics is None:
        reg = _obs.get_registry()
        _spec_metrics = {
            "proposed": reg.counter(
                "hetu_spec_proposed_tokens_total",
                "draft tokens proposed for target verification"),
            "accepted": reg.counter(
                "hetu_spec_accepted_tokens_total",
                "draft tokens accepted (bitwise equal to what the "
                "non-speculative engine would have emitted)"),
        }
    return _spec_metrics


class DraftProposer:
    """Greedy draft proposals at a fixed (num_slots, max_len) shape.

    The draft runs a full-context forward per proposed token (k jitted
    calls per scheduler tick) — simple and exactly deterministic.  Padding
    beyond each row's length is harmless under causal attention: the
    logits at ``length - 1`` never see it.  Greedy argmax keeps the draft
    itself seed-free; draft quality only moves the acceptance RATE, never
    the emitted stream.  (A KV-cached draft is the obvious next
    optimization once the fleet tier carries real traffic — the proposer
    is the seam it slots into.)"""

    def __init__(self, model, num_slots: int, max_len: int):
        self.model = model
        self.num_slots = num_slots
        self.max_len = max_len
        self._fn = _compile.instrument(jax.jit(self._impl),
                                       site="serve.spec_draft")

    def _impl(self, model, tokens, lengths):
        logits = model(tokens)  # (S, max_len, vocab), causal
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return jnp.argmax(last, axis=-1).astype(jnp.int32)

    def propose(self, contexts, k: int) -> np.ndarray:
        """``contexts[slot]`` is the full token context (prompt +
        generated) or None for slots not speculating; returns (num_slots,
        k) proposals (zeros on non-speculating rows)."""
        S = self.num_slots
        toks = np.zeros((S, self.max_len), np.int32)
        lens = np.ones((S,), np.int32)
        for s, ctx in enumerate(contexts):
            if ctx is None:
                continue
            n = min(len(ctx), self.max_len)
            toks[s, :n] = ctx[-n:]
            lens[s] = n
        out = np.zeros((S, k), np.int32)
        for j in range(k):
            nxt = np.asarray(self._fn(self.model, jnp.asarray(toks),
                                      jnp.asarray(lens)))
            out[:, j] = nxt
            for s in range(S):
                if contexts[s] is not None and lens[s] < self.max_len:
                    toks[s, lens[s]] = nxt[s]
                    lens[s] += 1
        return out


class SpeculativeDecoder:
    """Replaces the engine's per-token decode step with propose-and-
    verify; constructed by ``ServingEngine(draft_model=..., spec_k=...)``
    and driven from the scheduler tick."""

    def __init__(self, draft_model, k: int, *, num_slots: int,
                 max_len: int):
        if k < 1:
            raise ValueError(f"spec_k must be >= 1 draft tokens, got {k}")
        if draft_model.config.max_seq_len < max_len:
            raise ValueError(
                f"draft max_seq_len {draft_model.config.max_seq_len} is "
                f"shorter than the serving window {max_len}")
        self.k = k
        self.width = k + 1  # chain rows per slot: base token + k drafts
        self.draft = DraftProposer(draft_model, num_slots, max_len)

    def stats(self) -> dict:
        return {"k": self.k, "width": self.width}

    def decode_step(self, eng) -> int:
        """One speculative scheduler decode: propose, verify every slot's
        chain in ONE (num_slots * (k+1), 1) paged step, emit the accepted
        prefix of each chain, roll the cursor back over the rest."""
        active = eng.batcher.active()
        if not active:
            return 0
        t0 = eng.clock()
        S, W = eng.batcher.num_slots, self.width
        rows = S * W
        seq_ids = [None] * rows
        tokens = np.zeros((rows, 1), np.int32)
        index = np.zeros(rows, np.int32)
        rids = np.zeros(rows, np.int32)
        positions = np.zeros(rows, np.int32)
        chain_len: dict = {}
        contexts = [None] * S
        evicted = []
        ps = eng.pool.page_size
        for slot, req in active:
            pt = eng.pool.table(req.id)
            L = pt.length
            remaining = req.max_new_tokens - len(req.tokens)
            cl = max(1, min(W, remaining, eng.max_seq_len - L))
            try:
                eng._ensure_pages(req.id, L + cl)
            except OutOfPages:
                cl = 1
                try:
                    eng._ensure_pages(req.id, L + 1)
                except OutOfPages:
                    evicted.append((slot, req))
                    continue
            # copy-on-write guard over every page the chain writes into
            # (prefix sharing keeps write targets private by construction;
            # this is the enforced invariant, not an expected copy) — a
            # CoW needing a free page on a full pool evicts, the same
            # answer the non-speculative decode gives
            try:
                if eng.sharer is not None:
                    for pi in range(L // ps, (L + cl - 1) // ps + 1):
                        eng.pool.copy_on_write(req.id, pi * ps)
            except OutOfPages:
                evicted.append((slot, req))
                continue
            chain_len[slot] = cl
            if cl > 1:
                contexts[slot] = req.prompt + req.tokens
        for slot, req in evicted:
            eng._retire(req, "evicted", eng.clock())
        active = [(s, r) for s, r in active if r.slot is not None]
        if not active:
            return 0
        if any(c is not None for c in contexts):
            proposals = self.draft.propose(contexts, self.k)
        else:
            proposals = np.zeros((S, self.k), np.int32)
        chains: dict = {}
        proposed_total = 0
        for slot, req in active:
            L = eng.pool.table(req.id).length
            cl = chain_len[slot]
            chain = [req.tokens[-1]] + [int(t)
                                        for t in proposals[slot][:cl - 1]]
            chains[slot] = chain
            proposed_total += cl - 1
            for j in range(cl):
                r = slot * W + j
                seq_ids[r] = req.id
                tokens[r, 0] = chain[j]
                index[r] = L + j
                rids[r] = req.id
                positions[r] = L + j + 1
        toks_dev, k_arr, v_arr = eng._paged_step_fn(
            eng.model, eng.pool.k, eng.pool.v,
            eng.pool.gather_indices(seq_ids),
            jnp.asarray(index), jnp.asarray(tokens),
            jnp.asarray(rids), jnp.asarray(positions))
        eng.pool.commit(k_arr, v_arr)
        toks = np.asarray(toks_dev)
        now = eng.clock()
        nactive = len(active)
        produced = 0
        accepted_total = 0
        for slot, req in active:
            cl, chain = chain_len[slot], chains[slot]
            base = slot * W
            # t_0 is the ordinary next token; t_j is exact iff the fed
            # chain matches the emitted stream so far
            emit = [int(toks[base])]
            j = 1
            while j < cl and chain[j] == emit[j - 1]:
                emit.append(int(toks[base + j]))
                j += 1
            emitted = 0
            for tok in emit:
                eng.pool.table(req.id).length += 1
                produced += 1
                emitted += 1
                eng._append_token(req, tok, now, batch=nactive)
                if req.slot is None:
                    break  # retired (EOS / budget / context): the rest
                    # of the accepted chain is past the stream's end
            # count only draft tokens that actually ENTERED the stream
            # (a mid-chain EOS retire discards the accepted tail, and the
            # acceptance-rate telemetry must not flatter the draft)
            accepted_total += max(emitted - 1, 0)
        m = _spec_m()
        if proposed_total:
            m["proposed"].inc(proposed_total)
            m["accepted"].inc(accepted_total)
            _journal.record("spec_verify", proposed=proposed_total,
                            accepted=accepted_total)
        dt = now - t0
        from hetu_tpu.serve.engine import _serve_m
        sm = _serve_m()
        sm["tok_latency"].observe(dt / max(produced, 1))
        sm["tps"].set(produced / dt if dt > 0 else 0.0)
        return produced
