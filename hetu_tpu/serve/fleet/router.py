"""Cache-affinity request routing across ServingEngine replicas.

A fleet of N replicas each carries its own KV pool and prefix trie, so
WHERE a request lands decides whether its system prompt is a page alias
or a full recompute.  :class:`FleetRouter` places each request by
ranking replicas on:

1. **prefix affinity** — the longest token-verified trie match for the
   prompt (``PrefixSharer.match_tokens``, a read-only probe: ranking N
   replicas must not perturb any trie's LRU state between replays);
2. **shed pressure** — the replica's published SLO burn gauge
   (``SLOEngine.shed_pressure``), the same signal the runtime controller
   sheds on, so routing and remediation agree about who is drowning;
3. **load factor** — queue + slot occupancy, the cold-start tie-breaker
   before any SLO burn exists;
4. replica index — the deterministic final tie-break.

A placement that comes back as LOAD SHEDDING (controller shed latch,
admission-queue depth, compile-storm bucket freeze —
``RequestHandle.shed_reason``) is re-routed to the next-ranked replica
with bounded retries; validation rejections (empty prompt, over-budget)
return immediately — every replica would say the same thing.  Placements
are counted (``hetu_router_placements_total{reason=affinity|pressure|
retry}``), journaled (kind ``router_place``), and recorded on
``router.placements`` — the replay acceptance test asserts the whole
placement sequence is identical across same-seed runs.

The router is in-process and synchronous (the replicas' scheduler
threads or a deterministic ``step()`` driver do the work) — the
disaggregated prefill/decode tier (ROADMAP item 2) will swap the
in-process list for gang-dir transport without changing this policy.

**Mid-flight membership** (hetu_tpu/broker): the replica set is no
longer fixed at construction.  Each replica carries a membership state
— ``serving`` (rankable), ``warming`` (just granted by the capacity
broker, catching up on the latest gated snapshot: stepped but never
ranked, so no request ever lands on stale weights), ``reclaiming``
(lease being called back: never ranked, still stepped, so its in-flight
requests DRAIN rather than drop), ``failed`` (the heartbeat monitor —
serve/fleet/failover.py — declared it dead or silent: never ranked,
still stepped so a merely-hung engine can recover, its in-flight
requests evacuated and re-homed by the monitor), ``retired`` (lease
returned: the entry stays in ``engines`` forever so replica indices in
the placement log and journal stay stable across the whole episode).
``add_replica`` / ``mark_serving`` / ``begin_reclaim`` /
``mark_failed`` / ``retire_replica`` walk a replica through those
states; ``retire_replica`` refuses while the engine still holds work —
the drain guarantee is structural, not a broker courtesy (a ``failed``
replica retires only after the monitor evacuated it).

**Fault tolerance** (hetu_tpu/serve/fleet/failover.py): the router
keeps an in-flight LEDGER — request id, tenant, prompt, and the tokens
emitted so far — so a replica failure never loses the information
needed to re-home its requests, and a client retry of an in-flight
request id re-attaches to the live handle instead of double-executing.
To make ledger keys (and the idempotent-resubmit contract) meaningful,
the router assigns GLOBAL request ids in submission order when the
caller does not pin one — the DisaggRouter discipline, now fleet-wide —
which also makes token streams comparable across same-seed runs with
and without injected replica faults.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import registry as _obs

__all__ = ["FleetRouter", "MEMBERSHIP_STATES"]

# the replica-membership lifecycle (see module docstring): only
# "serving" is rankable; "retired" entries persist for index stability
MEMBERSHIP_STATES = ("serving", "warming", "reclaiming", "failed",
                     "retired")

_router_metrics = None


def _router_m() -> dict:
    global _router_metrics
    if _router_metrics is None:
        reg = _obs.get_registry()
        _router_metrics = {
            "placements": reg.counter(
                "hetu_router_placements_total",
                "fleet placements by deciding signal (affinity: a prefix-"
                "trie match won; pressure: no affinity anywhere, lowest "
                "shed-pressure/load won; retry: re-routed after a load-"
                "shedding rejection)",
                ("reason",)),
        }
    return _router_metrics


class FleetRouter:
    """Front end over N in-process ``ServingEngine`` replicas."""

    def __init__(self, engines, *, max_retries: int | None = None):
        engines = list(engines)
        if not engines:
            raise ValueError("need at least one replica")
        self.engines = engines
        if max_retries is None:
            env = os.environ.get("HETU_TPU_FLEET_MAX_RETRIES")
            # default: a retry budget of N-1 visits every other replica once
            max_retries = len(engines) - 1 if env is None else int(env)
        self.max_retries = int(max_retries)
        self.placements: list = []  # the deterministic placement log
        # membership state per replica, parallel to ``engines`` — the
        # construction-time set starts serving (the pre-broker fleet,
        # bit for bit); broker-granted replicas enter warming
        self._membership = ["serving"] * len(self.engines)
        # global request ids in submission order (when the caller does
        # not pin one): ledger keys and the idempotent-resubmit contract
        # need fleet-unique ids, and the draw must be atomic — the HTTP
        # front end submits from concurrent handler threads
        self._next_rid = 0
        self._rid_lock = threading.Lock()
        # the in-flight ledger: rid -> {handle, replica, tenant, prompt,
        # max_new_tokens, deadline_s, tokens}.  Entries live from
        # placement to handle resolution (the engines' on_finish hook
        # prunes); the failover monitor re-homes from it.
        self._ledger: dict = {}
        self._ledger_lock = threading.Lock()
        # a FailoverMonitor attaches itself here; step() ticks it
        self.monitor = None
        for i in range(len(self.engines)):
            self._wire(i)

    def _wire(self, idx: int) -> None:
        """Install the ledger hooks on one engine: every emitted token
        lands in the in-flight ledger entry, and handle resolution
        prunes it (both called under the engine's own lock — keep them
        tiny)."""
        e = self.engines[idx]
        e.on_token = self._note_token
        e.on_finish = self._note_finish

    def _note_token(self, rid: int, tok: int) -> None:
        with self._ledger_lock:
            ent = self._ledger.get(rid)
            if ent is not None:
                ent["tokens"].append(int(tok))

    def _note_finish(self, rid: int) -> None:
        with self._ledger_lock:
            self._ledger.pop(rid, None)

    def inflight(self, rid: int):
        """The in-flight ledger entry for ``rid`` (a shallow copy with
        the tokens snapshotted), or None — the idempotency window."""
        with self._ledger_lock:
            ent = self._ledger.get(rid)
            if ent is None:
                return None
            out = dict(ent)
            out["tokens"] = list(ent["tokens"])
            return out

    # -- mid-flight membership ----------------------------------------------

    @property
    def membership(self) -> list:
        """Per-replica membership states (a copy), parallel to
        ``engines``."""
        return list(self._membership)

    def serving_indices(self) -> list:
        """The rankable replica set, in index order."""
        return [i for i, s in enumerate(self._membership)
                if s == "serving"]

    def add_replica(self, engine, *, warming: bool = True) -> int:
        """Append a replica mid-flight; returns its (stable) index.
        ``warming`` (the default) keeps it out of ranking until
        :meth:`mark_serving` — a lent chip must finish catching up on
        the latest gated snapshot before any request can land on it."""
        self.engines.append(engine)
        self._membership.append("warming" if warming else "serving")
        self._wire(len(self.engines) - 1)
        return len(self.engines) - 1

    def mark_serving(self, replica: int) -> None:
        """Warm-up complete (or a hung replica recovered: the failover
        monitor restores ``failed`` members whose heartbeat resumed):
        the replica joins the rankable set."""
        if self._membership[replica] not in ("warming", "serving",
                                             "failed"):
            raise ValueError(
                f"replica {replica} is {self._membership[replica]!r}, "
                f"not warming or failed — cannot mark serving")
        self._membership[replica] = "serving"

    def mark_failed(self, replica: int) -> None:
        """The failover monitor declared this replica dead or silent: it
        leaves the rankable set immediately but keeps being stepped
        (a merely-hung engine counts down to recovery; a crashed one
        no-ops).  Only the monitor calls this — detection, evacuation
        and journaling are one atomic decision there."""
        if self._membership[replica] in ("failed", "retired"):
            raise ValueError(
                f"replica {replica} is {self._membership[replica]!r} — "
                f"cannot mark failed")
        self._membership[replica] = "failed"

    def begin_reclaim(self, replica: int) -> None:
        """Start draining a replica: it leaves the rankable set
        immediately (no new placements) but keeps stepping, so its
        in-flight requests finish rather than drop."""
        if self._membership[replica] not in ("serving", "warming"):
            raise ValueError(
                f"replica {replica} is {self._membership[replica]!r} — "
                f"cannot begin reclaim")
        self._membership[replica] = "reclaiming"

    def retire_replica(self, replica: int) -> None:
        """Finish a reclaim.  Refuses while the engine still holds
        queued or active work — retirement must never drop an in-flight
        request (the broker polls idleness and retries next tick).  The
        entry stays in ``engines`` so every later replica index, and the
        whole placement log, is unaffected.  A ``failed`` replica may
        retire directly — its lease is written off, not drained — but
        only after the monitor's evacuation emptied it."""
        if self._membership[replica] not in ("reclaiming", "failed"):
            raise ValueError(
                f"replica {replica} is {self._membership[replica]!r}, "
                f"not reclaiming — cannot retire")
        if not self.engines[replica].batcher.idle:
            raise RuntimeError(
                f"replica {replica} is still draining "
                f"(queue_len={self.engines[replica].batcher.queue_len}, "
                f"active={self.engines[replica].batcher.active_slots}) — "
                f"retiring now would drop in-flight requests")
        self._membership[replica] = "retired"

    # -- placement ----------------------------------------------------------

    def _rank(self, prompt) -> list:
        """SERVING replicas best-first: (-affinity, shed_pressure,
        load_factor, index) ascending — all four components
        deterministic under the engines' injected clocks.  Warming /
        reclaiming / retired replicas are never candidates."""
        ranked = sorted(
            (-(e.sharer.match_tokens(prompt) if e.sharer is not None
               else 0),
             e.slo.shed_pressure(), e.batcher.load_factor(), i)
            for i, e in enumerate(self.engines)
            if self._membership[i] == "serving")
        if not ranked:
            raise RuntimeError("no serving replica in the fleet — every "
                               "member is warming, reclaiming or retired")
        return ranked

    def submit(self, prompt, max_new_tokens: int = 16, *,
               deadline_s: float | None = None,
               request_id: int | None = None, tenant=None):
        """Place one request; returns the chosen replica's handle.  On a
        load-shedding rejection the request re-routes to the next-ranked
        replica (bounded by ``max_retries``); the last handle is returned
        when every candidate shed.  ``tenant`` passes through to the
        chosen engine's multi-tenant front door; a QUOTA rejection is
        never re-routed — the tenant's token bucket is its fleet-wide
        contract, and walking the replica list with a drained bucket
        would be quota evasion, not load balancing.  ``request_id`` pins
        the engine-side id across every retry; None draws a GLOBAL id in
        submission order.  A ``request_id`` that is still in the
        in-flight ledger is an idempotent RESUBMIT: the live handle is
        returned (no double execution) — the contract a client retrying
        a dropped ``/infer`` response relies on.  When no replica is
        rankable AND some replica has failed, the request is rejected
        with a ``replica_failed`` handle (outcome ``evicted`` → HTTP
        503) carrying ``retry_after_s`` instead of raising — a degraded
        fleet asks the client to come back, it does not traceback."""
        if request_id is not None:
            live = self.inflight(int(request_id))
            if live is not None:
                return live["handle"]
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        if request_id is None:
            with self._rid_lock:
                request_id = self._next_rid
                self._next_rid += 1
        try:
            ranked = self._rank(prompt)
        except RuntimeError:
            if "failed" not in self._membership:
                raise  # the pre-failover contract, bit for bit
            return self._reject_failed(int(request_id), tenant)
        tries = min(len(ranked), self.max_retries + 1)
        for a, (neg_aff, _pressure, _load, idx) in enumerate(ranked[:tries]):
            handle = self.engines[idx].submit(prompt, max_new_tokens,
                                              deadline_s=deadline_s,
                                              request_id=request_id,
                                              tenant=tenant)
            if handle.status == "rejected" \
                    and handle.shed_reason in (None, "quota"):
                # a validation rejection is identical on every replica;
                # a quota rejection is the tenant's own contract
                return handle
            shed = (handle.status == "rejected")
            if shed and a + 1 < tries:
                continue  # re-route around the shedding replica
            if shed and "failed" in self._membership:
                # the retry budget is exhausted AND the fleet is
                # degraded: name the failure so the client's error is
                # distinguishable from ordinary load shedding
                down = [i for i, s in enumerate(self._membership)
                        if s == "failed"]
                handle.error = (
                    f"{handle.error}; fleet degraded: replica(s) "
                    f"{','.join(str(i) for i in down)} failed "
                    f"(replica_failed)")
            reason = ("retry" if a > 0
                      else "affinity" if neg_aff < 0 else "pressure")
            if handle.status is None:
                with self._ledger_lock:
                    self._ledger[handle.request_id] = {
                        "handle": handle, "replica": idx,
                        "tenant": tenant, "prompt": list(prompt),
                        "max_new_tokens": int(max_new_tokens),
                        "deadline_s": deadline_s, "tokens": []}
            self._place(handle, idx, reason)
            return handle
        raise AssertionError("unreachable: the loop always returns")

    def _reject_failed(self, rid: int, tenant):
        """The degraded-fleet rejection: no replica is rankable and at
        least one has FAILED — reject with a named ``replica_failed``
        reason and a retry hint (outcome ``evicted`` maps to HTTP 503
        in serve/server.py) instead of the no-serving RuntimeError."""
        from hetu_tpu.serve.engine import RequestHandle
        down = [i for i, s in enumerate(self._membership)
                if s == "failed"]
        handle = RequestHandle(rid)
        handle.tenant = tenant
        # machine-readable like the shed reasons: serve/server.py gates
        # the body's reason/retry_after_s pair on shed_reason
        handle.shed_reason = "replica_failed"
        handle.retry_after_s = (self.monitor.retry_after_s
                                if self.monitor is not None else 1.0)
        handle._finish(
            "evicted",
            error=(f"replica_failed: replica(s) "
                   f"{','.join(str(i) for i in down)} failed and no "
                   f"serving replica remains — retry after "
                   f"{handle.retry_after_s}s"))
        return handle

    def _place(self, handle, replica: int, reason: str) -> None:
        _router_m()["placements"].labels(reason=reason).inc()
        # tenant extra only on non-default traffic: pre-tenant fleet
        # journals stay bit-identical
        tenant = getattr(handle, "tenant", None)
        extra = {} if tenant in (None, "default") else {"tenant": tenant}
        _journal.record("router_place", request_id=handle.request_id,
                        replica=replica, reason=reason, **extra)
        self.placements.append({"request_id": handle.request_id,
                                "replica": replica, "reason": reason,
                                **extra})

    # -- fleet drivers ------------------------------------------------------

    def step(self) -> int:
        """One deterministic fleet tick: tick the failover monitor
        (heartbeat scan + chaos-fault consumption + re-homing decisions
        happen BEFORE the engines move, so detection latency is an exact
        tick count), then step every non-retired replica in index order
        (reclaiming replicas keep stepping — that IS the drain; failed
        replicas keep stepping so a hung engine counts down to
        recovery while a crashed one no-ops); returns tokens produced
        fleet-wide."""
        if self.monitor is not None:
            self.monitor.tick()
        return sum(e.step() for e, s in zip(self.engines, self._membership)
                   if s != "retired")

    @property
    def idle(self) -> bool:
        return all(e.batcher.idle
                   for e, s in zip(self.engines, self._membership)
                   if s != "retired")

    def run_until_idle(self, max_steps: int = 100000) -> None:
        for _ in range(max_steps):
            self.step()
            if self.idle:
                return
        raise RuntimeError(f"fleet not idle after {max_steps} ticks")

    def start(self, poll_interval: float = 0.001) -> "FleetRouter":
        for e in self.engines:
            e.start(poll_interval)
        return self

    def stop(self) -> None:
        for e in self.engines:
            e.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        """The ``/fleet/serve`` payload: per-replica occupancy/pressure/
        cache state plus fleet aggregates and the placement tally."""
        reasons: dict = {}
        for p in self.placements:
            reasons[p["reason"]] = reasons.get(p["reason"], 0) + 1
        replicas = []
        for i, e in enumerate(self.engines):
            pool = e.pool.stats()
            replicas.append({
                "replica": i,
                "membership": self._membership[i],
                "queue_len": e.batcher.queue_len,
                "active_slots": e.batcher.active_slots,
                "num_slots": e.batcher.num_slots,
                "shed_pressure": e.slo.shed_pressure(),
                "load_factor": round(e.batcher.load_factor(), 6),
                "shedding": e.batcher.shed_reason,
                "tenant_shedding": e.batcher.tenant_sheds,
                "tenant_queue_lens": e.batcher.queue_lens(),
                "pages_free": pool["pages_free"],
                "pages_shared": pool["pages_shared"],
                "prefix": (None if e.sharer is None else e.sharer.stats()),
                "speculative": (None if e.spec is None else e.spec.stats()),
            })
        member_counts: dict = {}
        for s in self._membership:
            member_counts[s] = member_counts.get(s, 0) + 1
        return {
            "replicas": replicas,
            "num_replicas": len(self.engines),
            "membership": member_counts,
            "placements": len(self.placements),
            "placements_by_reason": reasons,
            "max_retries": self.max_retries,
            "queue_len": sum(r["queue_len"] for r in replicas),
            "active_slots": sum(r["active_slots"] for r in replicas),
            "pages_shared": sum(r["pages_shared"] for r in replicas),
            "inflight": len(self._ledger),
            "failover": (None if self.monitor is None
                         else self.monitor.summary()),
        }
