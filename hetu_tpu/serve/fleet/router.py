"""Cache-affinity request routing across ServingEngine replicas.

A fleet of N replicas each carries its own KV pool and prefix trie, so
WHERE a request lands decides whether its system prompt is a page alias
or a full recompute.  :class:`FleetRouter` places each request by
ranking replicas on:

1. **prefix affinity** — the longest token-verified trie match for the
   prompt (``PrefixSharer.match_tokens``, a read-only probe: ranking N
   replicas must not perturb any trie's LRU state between replays);
2. **shed pressure** — the replica's published SLO burn gauge
   (``SLOEngine.shed_pressure``), the same signal the runtime controller
   sheds on, so routing and remediation agree about who is drowning;
3. **load factor** — queue + slot occupancy, the cold-start tie-breaker
   before any SLO burn exists;
4. replica index — the deterministic final tie-break.

A placement that comes back as LOAD SHEDDING (controller shed latch,
admission-queue depth, compile-storm bucket freeze —
``RequestHandle.shed_reason``) is re-routed to the next-ranked replica
with bounded retries; validation rejections (empty prompt, over-budget)
return immediately — every replica would say the same thing.  Placements
are counted (``hetu_router_placements_total{reason=affinity|pressure|
retry}``), journaled (kind ``router_place``), and recorded on
``router.placements`` — the replay acceptance test asserts the whole
placement sequence is identical across same-seed runs.

The router is in-process and synchronous (the replicas' scheduler
threads or a deterministic ``step()`` driver do the work) — the
disaggregated prefill/decode tier (ROADMAP item 2) will swap the
in-process list for gang-dir transport without changing this policy.

**Mid-flight membership** (hetu_tpu/broker): the replica set is no
longer fixed at construction.  Each replica carries a membership state
— ``serving`` (rankable), ``warming`` (just granted by the capacity
broker, catching up on the latest gated snapshot: stepped but never
ranked, so no request ever lands on stale weights), ``reclaiming``
(lease being called back: never ranked, still stepped, so its in-flight
requests DRAIN rather than drop), ``retired`` (lease returned: the
entry stays in ``engines`` forever so replica indices in the placement
log and journal stay stable across the whole episode).  ``add_replica``
/ ``mark_serving`` / ``begin_reclaim`` / ``retire_replica`` walk a
replica through those states; ``retire_replica`` refuses while the
engine still holds work — the drain guarantee is structural, not a
broker courtesy.
"""

from __future__ import annotations

import os

import numpy as np

from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import registry as _obs

__all__ = ["FleetRouter", "MEMBERSHIP_STATES"]

# the replica-membership lifecycle (see module docstring): only
# "serving" is rankable; "retired" entries persist for index stability
MEMBERSHIP_STATES = ("serving", "warming", "reclaiming", "retired")

_router_metrics = None


def _router_m() -> dict:
    global _router_metrics
    if _router_metrics is None:
        reg = _obs.get_registry()
        _router_metrics = {
            "placements": reg.counter(
                "hetu_router_placements_total",
                "fleet placements by deciding signal (affinity: a prefix-"
                "trie match won; pressure: no affinity anywhere, lowest "
                "shed-pressure/load won; retry: re-routed after a load-"
                "shedding rejection)",
                ("reason",)),
        }
    return _router_metrics


class FleetRouter:
    """Front end over N in-process ``ServingEngine`` replicas."""

    def __init__(self, engines, *, max_retries: int | None = None):
        engines = list(engines)
        if not engines:
            raise ValueError("need at least one replica")
        self.engines = engines
        if max_retries is None:
            env = os.environ.get("HETU_TPU_FLEET_MAX_RETRIES")
            # default: a retry budget of N-1 visits every other replica once
            max_retries = len(engines) - 1 if env is None else int(env)
        self.max_retries = int(max_retries)
        self.placements: list = []  # the deterministic placement log
        # membership state per replica, parallel to ``engines`` — the
        # construction-time set starts serving (the pre-broker fleet,
        # bit for bit); broker-granted replicas enter warming
        self._membership = ["serving"] * len(self.engines)

    # -- mid-flight membership ----------------------------------------------

    @property
    def membership(self) -> list:
        """Per-replica membership states (a copy), parallel to
        ``engines``."""
        return list(self._membership)

    def serving_indices(self) -> list:
        """The rankable replica set, in index order."""
        return [i for i, s in enumerate(self._membership)
                if s == "serving"]

    def add_replica(self, engine, *, warming: bool = True) -> int:
        """Append a replica mid-flight; returns its (stable) index.
        ``warming`` (the default) keeps it out of ranking until
        :meth:`mark_serving` — a lent chip must finish catching up on
        the latest gated snapshot before any request can land on it."""
        self.engines.append(engine)
        self._membership.append("warming" if warming else "serving")
        return len(self.engines) - 1

    def mark_serving(self, replica: int) -> None:
        """Warm-up complete: the replica joins the rankable set."""
        if self._membership[replica] not in ("warming", "serving"):
            raise ValueError(
                f"replica {replica} is {self._membership[replica]!r}, "
                f"not warming — cannot mark serving")
        self._membership[replica] = "serving"

    def begin_reclaim(self, replica: int) -> None:
        """Start draining a replica: it leaves the rankable set
        immediately (no new placements) but keeps stepping, so its
        in-flight requests finish rather than drop."""
        if self._membership[replica] not in ("serving", "warming"):
            raise ValueError(
                f"replica {replica} is {self._membership[replica]!r} — "
                f"cannot begin reclaim")
        self._membership[replica] = "reclaiming"

    def retire_replica(self, replica: int) -> None:
        """Finish a reclaim.  Refuses while the engine still holds
        queued or active work — retirement must never drop an in-flight
        request (the broker polls idleness and retries next tick).  The
        entry stays in ``engines`` so every later replica index, and the
        whole placement log, is unaffected."""
        if self._membership[replica] != "reclaiming":
            raise ValueError(
                f"replica {replica} is {self._membership[replica]!r}, "
                f"not reclaiming — cannot retire")
        if not self.engines[replica].batcher.idle:
            raise RuntimeError(
                f"replica {replica} is still draining "
                f"(queue_len={self.engines[replica].batcher.queue_len}, "
                f"active={self.engines[replica].batcher.active_slots}) — "
                f"retiring now would drop in-flight requests")
        self._membership[replica] = "retired"

    # -- placement ----------------------------------------------------------

    def _rank(self, prompt) -> list:
        """SERVING replicas best-first: (-affinity, shed_pressure,
        load_factor, index) ascending — all four components
        deterministic under the engines' injected clocks.  Warming /
        reclaiming / retired replicas are never candidates."""
        ranked = sorted(
            (-(e.sharer.match_tokens(prompt) if e.sharer is not None
               else 0),
             e.slo.shed_pressure(), e.batcher.load_factor(), i)
            for i, e in enumerate(self.engines)
            if self._membership[i] == "serving")
        if not ranked:
            raise RuntimeError("no serving replica in the fleet — every "
                               "member is warming, reclaiming or retired")
        return ranked

    def submit(self, prompt, max_new_tokens: int = 16, *,
               deadline_s: float | None = None,
               request_id: int | None = None, tenant=None):
        """Place one request; returns the chosen replica's handle.  On a
        load-shedding rejection the request re-routes to the next-ranked
        replica (bounded by ``max_retries``); the last handle is returned
        when every candidate shed.  ``tenant`` passes through to the
        chosen engine's multi-tenant front door; a QUOTA rejection is
        never re-routed — the tenant's token bucket is its fleet-wide
        contract, and walking the replica list with a drained bucket
        would be quota evasion, not load balancing.  ``request_id`` pins
        the engine-side id across every retry (the DisaggRouter's
        global-id seam); None lets the chosen engine draw its own."""
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        ranked = self._rank(prompt)
        tries = min(len(ranked), self.max_retries + 1)
        for a, (neg_aff, _pressure, _load, idx) in enumerate(ranked[:tries]):
            handle = self.engines[idx].submit(prompt, max_new_tokens,
                                              deadline_s=deadline_s,
                                              request_id=request_id,
                                              tenant=tenant)
            if handle.status == "rejected" \
                    and handle.shed_reason in (None, "quota"):
                # a validation rejection is identical on every replica;
                # a quota rejection is the tenant's own contract
                return handle
            shed = (handle.status == "rejected")
            if shed and a + 1 < tries:
                continue  # re-route around the shedding replica
            reason = ("retry" if a > 0
                      else "affinity" if neg_aff < 0 else "pressure")
            self._place(handle, idx, reason)
            return handle
        raise AssertionError("unreachable: the loop always returns")

    def _place(self, handle, replica: int, reason: str) -> None:
        _router_m()["placements"].labels(reason=reason).inc()
        # tenant extra only on non-default traffic: pre-tenant fleet
        # journals stay bit-identical
        tenant = getattr(handle, "tenant", None)
        extra = {} if tenant in (None, "default") else {"tenant": tenant}
        _journal.record("router_place", request_id=handle.request_id,
                        replica=replica, reason=reason, **extra)
        self.placements.append({"request_id": handle.request_id,
                                "replica": replica, "reason": reason,
                                **extra})

    # -- fleet drivers ------------------------------------------------------

    def step(self) -> int:
        """One deterministic fleet tick: step every non-retired replica
        in index order (reclaiming replicas keep stepping — that IS the
        drain); returns tokens produced fleet-wide."""
        return sum(e.step() for e, s in zip(self.engines, self._membership)
                   if s != "retired")

    @property
    def idle(self) -> bool:
        return all(e.batcher.idle
                   for e, s in zip(self.engines, self._membership)
                   if s != "retired")

    def run_until_idle(self, max_steps: int = 100000) -> None:
        for _ in range(max_steps):
            self.step()
            if self.idle:
                return
        raise RuntimeError(f"fleet not idle after {max_steps} ticks")

    def start(self, poll_interval: float = 0.001) -> "FleetRouter":
        for e in self.engines:
            e.start(poll_interval)
        return self

    def stop(self) -> None:
        for e in self.engines:
            e.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        """The ``/fleet/serve`` payload: per-replica occupancy/pressure/
        cache state plus fleet aggregates and the placement tally."""
        reasons: dict = {}
        for p in self.placements:
            reasons[p["reason"]] = reasons.get(p["reason"], 0) + 1
        replicas = []
        for i, e in enumerate(self.engines):
            pool = e.pool.stats()
            replicas.append({
                "replica": i,
                "membership": self._membership[i],
                "queue_len": e.batcher.queue_len,
                "active_slots": e.batcher.active_slots,
                "num_slots": e.batcher.num_slots,
                "shed_pressure": e.slo.shed_pressure(),
                "load_factor": round(e.batcher.load_factor(), 6),
                "shedding": e.batcher.shed_reason,
                "tenant_shedding": e.batcher.tenant_sheds,
                "tenant_queue_lens": e.batcher.queue_lens(),
                "pages_free": pool["pages_free"],
                "pages_shared": pool["pages_shared"],
                "prefix": (None if e.sharer is None else e.sharer.stats()),
                "speculative": (None if e.spec is None else e.spec.stats()),
            })
        member_counts: dict = {}
        for s in self._membership:
            member_counts[s] = member_counts.get(s, 0) + 1
        return {
            "replicas": replicas,
            "num_replicas": len(self.engines),
            "membership": member_counts,
            "placements": len(self.placements),
            "placements_by_reason": reasons,
            "max_retries": self.max_retries,
            "queue_len": sum(r["queue_len"] for r in replicas),
            "active_slots": sum(r["active_slots"] for r in replicas),
            "pages_shared": sum(r["pages_shared"] for r in replicas),
        }
