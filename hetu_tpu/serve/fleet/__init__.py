"""Serving fleet tier: prefix sharing, speculative decoding, routing.

The per-chip serve/ stack (paged Pallas decode + fused sampling, request
tracing, SLO burn rates, controller actuators) stalls the ROADMAP's
million-user north star at one replica's token rate.  This package is
the multi-replica tier on top — the paper's hybrid-communication
philosophy (cache-enabled parameter tier for hot state + topology-aware
placement) applied to inference:

- :mod:`~hetu_tpu.serve.fleet.prefix` — copy-on-write prefix sharing:
  a trie keyed on token-block hashes maps identical prompt prefixes
  (system prompts, few-shot templates) to shared refcounted KV pages in
  the :class:`~hetu_tpu.serve.kv_cache.KVCachePool`, so the fleet stops
  recomputing and re-storing the same prefill;
- :mod:`~hetu_tpu.serve.fleet.spec` — speculative decoding: a small
  draft GPT proposes k tokens per slot and the target verifies all of
  them in ONE batched paged-decode step; the per-(request, position)
  seeded sampler regenerates the same draws, so every accepted stream is
  bitwise identical to its non-speculative replay — a stronger guarantee
  than distribution-preserving rejection samplers offer;
- :mod:`~hetu_tpu.serve.fleet.router` — :class:`FleetRouter` placing
  requests across N in-process ``ServingEngine`` replicas by
  prefix-cache affinity, shedding by each replica's published
  shed-pressure gauge, with bounded re-routes on shed/freeze rejections;
- :mod:`~hetu_tpu.serve.fleet.migrate` — self-describing, CRC- and
  fingerprint-verified KV-page migration records plus the atomic-file
  fabric (``<dir>/kv/``) for the multi-process form;
- :mod:`~hetu_tpu.serve.fleet.disagg` — :class:`DisaggRouter` splitting
  the fleet into prefill and decode worker pools: a finished prefill
  migrates its KV pages to a decode worker, streams stay bitwise
  identical to colocated same-seed runs, and a long-prompt burst never
  stalls an in-flight decode stream again.

Everything stays deterministic under a fixed seed: placements, streams,
and journal replay bitwise — the fleet inherits the single-replica
guarantee.
"""

from hetu_tpu.serve.fleet.disagg import DisaggRouter, MigrationTicket
from hetu_tpu.serve.fleet.migrate import (MigrationFileFabric,
                                          MigrationIntegrityError,
                                          MigrationRecord)
from hetu_tpu.serve.fleet.prefix import PrefixSharer, PrefixTrie
from hetu_tpu.serve.fleet.router import FleetRouter
from hetu_tpu.serve.fleet.spec import SpeculativeDecoder

__all__ = ["PrefixTrie", "PrefixSharer", "SpeculativeDecoder",
           "FleetRouter", "DisaggRouter", "MigrationTicket",
           "MigrationRecord", "MigrationIntegrityError",
           "MigrationFileFabric"]
