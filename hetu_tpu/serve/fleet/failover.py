"""Serving fault tolerance: replica failure detection + deterministic
request failover.

The training half of the repo survives kills, stalls and shard loss
with bitwise-replayable recovery (resilience.py, gang.py, faults.py) —
but a serving replica that died mid-decode used to strand every
in-flight request silently.  This module closes that gap with the same
discipline the training side uses: an injected-clock lease, journaled
decisions, and streams that stay bitwise identical across the failure.

:class:`FailoverMonitor` attaches to a
:class:`~hetu_tpu.serve.fleet.router.FleetRouter` and runs once per
fleet tick (``router.step()`` ticks it BEFORE the engines move, so
detection latency is an exact tick count):

1. **Chaos intake** — consumes the serving fault kinds from the active
   :class:`~hetu_tpu.exec.faults.FaultPlan`: ``replica_crash``
   (``worker=`` names the replica; permanent death) and ``decode_hang``
   (silent for ``arg`` ticks, then recovers).  ``migrate_drop`` is
   consumed at the KV-salvage transit seam below (and at the
   disaggregated hand-off in disagg.py).

2. **Heartbeat lease** — every engine beats once per healthy scheduler
   tick (``ServingEngine._beat``); a beat frozen for more than
   ``lease_ticks`` monitor ticks moves the replica into the router's
   ``failed`` membership state (the ``GangMembership`` lease idiom on
   the fleet's own tick clock — no wall time anywhere) and journals
   ``replica_lost``.  A failed replica whose beat RESUMES (a hang that
   ended) is restored to ``serving`` — unless the controller
   quarantined it for flapping (:meth:`quarantine`, driven by
   ``RuntimeController.on_replica_lost`` with the controller's usual
   hysteresis + dry-run parity).

3. **Request failover** — the failed engine is evacuated
   (:meth:`~hetu_tpu.serve.engine.ServingEngine.evacuate`): every
   in-flight request re-homes to a surviving replica and CONTINUES
   deterministically.  When the engine merely hung, its KV pages export
   as a verified :class:`~hetu_tpu.serve.fleet.migrate.MigrationRecord`
   and the survivor imports them (salvage: decode resumes exactly where
   the lost engine stopped).  A crashed engine's pages — or a record
   that fails verification or is dropped in transit — fall back to
   re-prefill: the request re-enters empty and regenerates its stream,
   bitwise identical because sampling keys derive from ``(seed, request
   id, position)`` alone.  Degraded is never dropped: a re-home that
   finds no survivor (everything shedding or failed) parks in
   ``pending`` and retries every tick.  Export HOLDs on the dead
   replica are settled either way — the salvage ticket acks at import,
   a refused record cancels here — so the pool never leaks pages.

Every decision journals (``replica_lost`` / ``request_rehome`` /
``failover``), counts (``hetu_serve_failover_*``), and lands on
``self.decisions`` — the ``/fleet/failover`` payload and the replay
acceptance surface: two same-seed chaos runs must produce identical
decision sequences, and every rehomed stream (fingerprint included)
must match the crash-free same-seed run bitwise.

This file is covered by the plan-determinism AST lint (tests/
test_obs.py): no clock or entropy imports, and every dict walk pinned
by ``sorted(...)`` at the call site — a failover decision that cannot
replay bitwise is a failover decision that cannot be audited.
"""

from __future__ import annotations

from typing import Optional

from hetu_tpu.exec import controller as _controller
from hetu_tpu.exec import faults as _faults
from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import registry as _obs
from hetu_tpu.serve.fleet.disagg import MigrationTicket
from hetu_tpu.serve.fleet.migrate import (MigrationIntegrityError,
                                          migrate_metrics, verify_record)

__all__ = ["FailoverMonitor"]

_failover_metrics = None


def _failover_m() -> dict:
    global _failover_metrics
    if _failover_metrics is None:
        reg = _obs.get_registry()
        _failover_metrics = {
            "replicas": reg.counter(
                "hetu_serve_failover_replicas_total",
                "replica failure-plane transitions by reason (crashed: "
                "permanent death; lease_expired: heartbeat silent past "
                "the lease; recovered: a hung replica's beat resumed "
                "and it was restored to serving)",
                ("reason",)),
            "rehomed": reg.counter(
                "hetu_serve_failover_requests_total",
                "in-flight requests re-homed off a failed replica, by "
                "KV disposition (salvaged: verified pages imported on "
                "the survivor; reprefill: re-entered empty and "
                "regenerated — same stream either way)",
                ("kv",)),
            "pending": reg.gauge(
                "hetu_serve_failover_pending",
                "re-homes waiting for a survivor (every candidate shed "
                "or failed) — retried every fleet tick, never dropped"),
        }
    return _failover_metrics


class FailoverMonitor:
    """Heartbeat-lease failure detection + deterministic re-homing over
    one fleet router.  Driven entirely by the fleet's tick counter (the
    router ticks it at the top of :meth:`~hetu_tpu.serve.fleet.router.
    FleetRouter.step`), so a same-seed replay reproduces every decision
    bitwise."""

    def __init__(self, router, *, lease_ticks: int = 3):
        if lease_ticks < 1:
            raise ValueError(f"lease_ticks must be >= 1, got "
                             f"{lease_ticks}")
        self.router = router
        self.lease_ticks = int(lease_ticks)
        self._tick = 0
        # replica -> [last observed beat, tick it last changed]
        self._beats: dict = {}
        # replica -> how many times it has been declared lost (the
        # controller's flap signal)
        self.lost_counts: dict = {}
        # replicas the controller quarantined: never restored on
        # recovery (the flapping-replica remedy)
        self.quarantined: set = set()
        self._quarantine_announced: set = set()
        # re-homes that found no survivor yet: retried every tick
        self._pending: list = []
        # the deterministic decision log (the replay surface)
        self.decisions: list = []
        router.monitor = self

    # -- derived hints ------------------------------------------------------

    @property
    def retry_after_s(self) -> float:
        """The deterministic backoff hint a degraded-fleet 503 carries:
        one scheduler wave per lease tick — by then the monitor has
        either re-homed onto a survivor or the fleet is still down and
        the client should keep backing off."""
        return round(0.05 * (self.lease_ticks + 1), 6)

    # -- the per-tick loop --------------------------------------------------

    def tick(self) -> None:
        """One monitor tick: retry parked re-homes, consume scheduled
        serving faults, scan heartbeats, fail/restore replicas."""
        self._tick += 1
        self._retry_pending()
        self._consume_faults()
        self._scan()
        if _obs.enabled():
            _failover_m()["pending"].set(float(len(self._pending)))

    def _consume_faults(self) -> None:
        plan = _faults.active_plan()
        if plan is None:
            return
        while True:
            f = plan.take("replica_crash", "decode_hang", late_ok=True,
                          now=self._tick, require_worker=True)
            if f is None:
                return
            engine = self.router.engines[int(f.worker)]
            if f.kind == "replica_crash":
                engine.crash()
            else:
                engine.hang(int(f.arg) if f.arg
                            else self.lease_ticks + 2)

    def _scan(self) -> None:
        membership = self.router.membership
        for i, state in enumerate(membership):
            if state == "retired":
                continue
            beat = int(self.router.engines[i]._beat)
            rec = self._beats.get(i)
            if rec is None or beat != rec[0]:
                self._beats[i] = [beat, self._tick]
                stalled = 0
            else:
                stalled = self._tick - rec[1]
            if state == "failed":
                if stalled == 0:
                    self._maybe_restore(i)
                continue
            if stalled > self.lease_ticks:
                self._fail(i)

    # -- failure ------------------------------------------------------------

    def _fail(self, replica: int) -> None:
        engine = self.router.engines[replica]
        reason = "crashed" if engine.crashed else "lease_expired"
        self.router.mark_failed(replica)
        self.lost_counts[replica] = self.lost_counts.get(replica, 0) + 1
        _journal.record("replica_lost", replica=replica, reason=reason)
        if _obs.enabled():
            _failover_m()["replicas"].labels(reason=reason).inc()
        ctrl = _controller.get_controller()
        if ctrl is not None:
            ctrl.on_replica_lost(self, replica,
                                 self.lost_counts[replica])
        rehomed = self._evacuate(replica)
        _journal.record("failover", replica=replica, rehomed=len(rehomed),
                        reason=reason)
        self.decisions.append({"tick": self._tick, "replica": replica,
                               "reason": reason, "rehomed": rehomed})

    def _maybe_restore(self, replica: int) -> None:
        """A failed replica's heartbeat resumed (the hang ended): restore
        it to serving — empty, consistent, rankable again — unless the
        controller quarantined it for flapping."""
        if replica in self.quarantined:
            if replica not in self._quarantine_announced:
                self._quarantine_announced.add(replica)
                _journal.record("failover", replica=replica, rehomed=0,
                                reason="quarantined")
                self.decisions.append({"tick": self._tick,
                                       "replica": replica,
                                       "reason": "quarantined",
                                       "rehomed": []})
            return
        self.router.mark_serving(replica)
        _journal.record("failover", replica=replica, rehomed=0,
                        reason="recovered")
        if _obs.enabled():
            _failover_m()["replicas"].labels(reason="recovered").inc()
        self.decisions.append({"tick": self._tick, "replica": replica,
                               "reason": "recovered", "rehomed": []})

    def quarantine(self, replica: int) -> None:
        """Controller actuator: never restore this replica on recovery
        (it flapped past the controller's hysteresis threshold).  The
        broker may still reclaim and replace it."""
        self.quarantined.add(int(replica))

    # -- evacuation + re-homing ---------------------------------------------

    def _evacuate(self, replica: int) -> list:
        """Drain the failed engine and re-home every in-flight request:
        verified KV salvage when the pages survived, re-prefill
        otherwise.  Returns the decision rows ``(request_id,
        to_replica_or_None, kv)`` in admission order."""
        dead = self.router.engines[replica]
        plan = _faults.active_plan()
        rehomed = []
        for req, record, handle, tl in dead.evacuate():
            ticket = None
            kv = "reprefill"
            if record is not None:
                dropped = (plan is not None and plan.take(
                    "migrate_drop", late_ok=True,
                    now=self._tick) is not None)
                if dropped:
                    migrate_metrics()["failures"].labels(
                        reason="dropped").inc()
                    _journal.record("migrate_verify_failed",
                                    request_id=req.id, reason="dropped")
                    dead.pool.cancel_export(req.id)
                else:
                    try:
                        verify_record(record)
                        ticket = MigrationTicket(record, dead)
                        kv = "salvaged"
                    except MigrationIntegrityError as e:
                        migrate_metrics()["failures"].labels(
                            reason=e.reason).inc()
                        _journal.record("migrate_verify_failed",
                                        request_id=req.id,
                                        reason=e.reason)
                        dead.pool.cancel_export(req.id)
            item = {"from": replica, "req": req, "ticket": ticket,
                    "handle": handle, "tl": tl, "kv": kv}
            to = self._place(item)
            if to is None:
                self._pending.append(item)
            rehomed.append((req.id, to, kv))
        return rehomed

    def _place(self, item: dict) -> Optional[int]:
        """Try every ranked survivor within the router's retry budget;
        returns the accepting replica index or None (parked)."""
        req = item["req"]
        order = self._survivors(req.prompt)
        tries = min(len(order), self.router.max_retries + 1)
        for _aff, _pressure, _load, idx in order[:tries]:
            shed = self.router.engines[idx].accept_failover(
                req, item["handle"], item["tl"], ticket=item["ticket"])
            if shed is not None:
                continue
            _journal.record("request_rehome", request_id=req.id,
                            from_replica=item["from"], to_replica=idx,
                            kv=item["kv"])
            if _obs.enabled():
                _failover_m()["rehomed"].labels(kv=item["kv"]).inc()
            with self.router._ledger_lock:
                ent = self.router._ledger.get(req.id)
                if ent is not None:
                    ent["replica"] = idx
                    if item["kv"] == "reprefill":
                        # the stream restarts from a fresh first token;
                        # the regenerated tokens re-accrue via on_token
                        ent["tokens"] = []
            return idx
        return None

    def _survivors(self, prompt) -> list:
        """Re-home ranking: the router's placement ordering (-affinity,
        shed pressure, load, index) over SERVING members that can decode
        (a prefill-role worker holds KV for one prefill only — it is not
        a re-home target)."""
        r = self.router
        membership = r.membership
        return sorted(
            (-(r.engines[i].sharer.match_tokens(prompt)
               if r.engines[i].sharer is not None else 0),
             r.engines[i].slo.shed_pressure(),
             r.engines[i].batcher.load_factor(), i)
            for i in range(len(r.engines))
            if membership[i] == "serving"
            and r.engines[i].role != "prefill")

    def _retry_pending(self) -> None:
        if not self._pending:
            return
        still = []
        for item in self._pending:
            to = self._place(item)
            if to is None:
                still.append(item)
            else:
                for row in self.decisions:
                    for j, (rid, dst, kv) in enumerate(row["rehomed"]):
                        if rid == item["req"].id and dst is None:
                            row["rehomed"][j] = (rid, to, kv)
        self._pending = still

    # -- read side ----------------------------------------------------------

    def summary(self) -> dict:
        """The ``/fleet/failover`` payload: lease policy, per-replica
        loss counts, quarantine set, parked re-homes, and the decision
        log (the replay surface)."""
        return {
            "lease_ticks": self.lease_ticks,
            "tick": self._tick,
            "retry_after_s": self.retry_after_s,
            "membership": self.router.membership,
            "lost_counts": {str(i): self.lost_counts[i]
                            for i in sorted(self.lost_counts)},
            "quarantined": sorted(self.quarantined),
            "pending": len(self._pending),
            "decisions": [dict(d) for d in self.decisions],
        }
