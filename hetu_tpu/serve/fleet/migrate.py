"""KV-page migration records: verifiable transport for disaggregation.

Disaggregated serving (serve/fleet/disagg.py) moves a finished prefill's
KV pages from the prefill worker's pool to a decode worker's pool.  The
unit of transport is the :class:`MigrationRecord` — a SELF-DESCRIBING
snapshot of one sequence's pages that carries everything the importer
needs to re-verify it before a single byte is admitted:

- the page payloads (K and V, per page, in page-table order) and the
  geometry they were cut from (page size, layers, heads, head dim,
  dtype) — an importer with a different pool shape refuses with a
  ``geometry`` diagnosis instead of silently reinterpreting bytes;
- the sequence's token ``length`` (the decode cursor: migration must
  preserve ``cache_index`` exactly for the bitwise-stream guarantee);
- a **per-page CRC32** over each page's K||V bytes, so a single torn or
  bit-rotted page is named by index;
- the **PR 10 deterministic fingerprint**
  (:func:`~hetu_tpu.obs.numerics.host_fingerprint`) folded over the full
  payload *and* the record's metadata — a tampered ``length`` or a
  CRC-colliding payload rewrite fails this cross-check even when every
  per-page CRC still matches.

:func:`verify_record` runs the checks in diagnosis order (``torn`` →
``page_crc`` → ``fingerprint``; :meth:`KVCachePool.import_pages` adds
``geometry``) and raises the NAMED :class:`MigrationIntegrityError` —
the decode engine journals the reason and falls back to re-prefill, so a
corrupt record can never become corrupt served KV.

Transport has two forms, matching the gang fabric's conventions:

- **in-process handoff** — the fleet simulation passes the record object
  directly (the router's ``migrate_out`` hook);
- **atomic files** — :class:`MigrationFileFabric` writes
  ``<dir>/kv/seq_NNNNNN.kvmig`` via the checkpoint layer's
  tmp+fsync+replace (``exec/checkpoint._atomic_write_bytes``), so a
  reader never observes a torn file from a crashed writer; acks are
  marker files the exporting process polls to settle its export holds.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib

import numpy as np

from hetu_tpu.obs import registry as _obs
from hetu_tpu.obs.numerics import (host_combine, host_fingerprint,
                                   host_fingerprint_ints)

__all__ = ["MigrationRecord", "MigrationIntegrityError", "build_record",
           "verify_record", "MigrationFileFabric"]

FORMAT = "hetu-kv-migration-v1"

_migrate_metrics = None


def migrate_metrics() -> dict:
    global _migrate_metrics
    if _migrate_metrics is None:
        reg = _obs.get_registry()
        _migrate_metrics = {
            "pages": reg.counter(
                "hetu_migrate_pages_total",
                "KV pages migrated from a prefill worker to a decode "
                "worker (counted at successful handoff)"),
            "bytes": reg.counter(
                "hetu_migrate_bytes_total",
                "KV payload bytes migrated prefill -> decode"),
            "failures": reg.counter(
                "hetu_migrate_failures_total",
                "migration records refused at import verification, by "
                "diagnosis (torn: payload shorter than the header "
                "declares; page_crc: a page's K||V bytes fail their "
                "CRC32; fingerprint: the whole-record content "
                "fingerprint disagrees — metadata tamper or a CRC-"
                "colliding rewrite; geometry: the importing pool's "
                "shape/dtype differs from the exporter's)",
                ("reason",)),
        }
    return _migrate_metrics


class MigrationIntegrityError(RuntimeError):
    """A migration record failed verification.  ``reason`` is the named
    diagnosis (``torn`` | ``page_crc`` | ``fingerprint`` | ``geometry``)
    the decode engine journals before falling back to re-prefill."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"migration record rejected ({reason}): {detail}")
        self.reason = reason


@dataclasses.dataclass
class MigrationRecord:
    """One sequence's KV pages, self-describing and verifiable."""

    seq_id: int
    length: int            # valid tokens written (the decode cursor)
    page_size: int
    dtype: str             # numpy/ml_dtypes name, e.g. "float32"
    k_pages: np.ndarray    # (num_layers, num_pages, page_size, H, D)
    v_pages: np.ndarray
    page_crcs: list        # crc32 over page i's K||V bytes
    fingerprint: int       # host_combine over payload + metadata words

    @property
    def num_pages(self) -> int:
        return int(self.k_pages.shape[1])

    @property
    def nbytes(self) -> int:
        return int(self.k_pages.nbytes + self.v_pages.nbytes)

    # -- file form ----------------------------------------------------------

    def header_bytes(self) -> bytes:
        """One header line of JSON: geometry, lengths, CRCs, fingerprint,
        declared payload size — everything needed to re-verify."""
        header = {
            "format": FORMAT,
            "seq_id": self.seq_id, "length": self.length,
            "page_size": self.page_size, "dtype": self.dtype,
            "k_shape": list(self.k_pages.shape),
            "v_shape": list(self.v_pages.shape),
            "page_crcs": [int(c) for c in self.page_crcs],
            "fingerprint": int(self.fingerprint),
            "payload_bytes": self.nbytes,
        }
        return json.dumps(header).encode() + b"\n"

    def to_bytes(self) -> bytes:
        """The header line followed by the raw K then V page bytes (the
        in-memory form; the file fabric writes the same three pieces as
        separate chunks to skip this concatenation copy)."""
        return (self.header_bytes()
                + self.k_pages.tobytes() + self.v_pages.tobytes())

    @staticmethod
    def from_bytes(data: bytes) -> "MigrationRecord":
        """Parse the file form; a truncated header or a payload shorter
        than the header declares is diagnosed ``torn``."""
        nl = data.find(b"\n")
        if nl < 0:
            raise MigrationIntegrityError(
                "torn", "no header line (truncated before the newline)")
        try:
            h = json.loads(data[:nl])
        except ValueError as e:
            raise MigrationIntegrityError("torn", f"unparseable header: {e}")
        if h.get("format") != FORMAT:
            raise MigrationIntegrityError(
                "torn", f"unknown format {h.get('format')!r} "
                        f"(expected {FORMAT})")
        payload = data[nl + 1:]
        # a bit-rotted header can still be valid JSON: every field it
        # feeds into parsing arithmetic below must diagnose as "torn",
        # never escape as a bare ValueError/AttributeError — the
        # importer's contract is named diagnosis + re-prefill fallback
        try:
            if len(payload) != h["payload_bytes"]:
                raise MigrationIntegrityError(
                    "torn", f"payload is {len(payload)} bytes, header "
                            f"declares {h['payload_bytes']}")
            dt = _resolve_dtype(h["dtype"])
            k_shape, v_shape = tuple(h["k_shape"]), tuple(h["v_shape"])
            k_bytes = int(np.prod(k_shape)) * dt.itemsize
            k = np.frombuffer(payload[:k_bytes], dt).reshape(k_shape)
            v = np.frombuffer(payload[k_bytes:], dt).reshape(v_shape)
            return MigrationRecord(
                seq_id=int(h["seq_id"]), length=int(h["length"]),
                page_size=int(h["page_size"]), dtype=h["dtype"],
                k_pages=k, v_pages=v, page_crcs=list(h["page_crcs"]),
                fingerprint=int(h["fingerprint"]))
        except MigrationIntegrityError:
            raise
        except (KeyError, ValueError, TypeError, AttributeError,
                OverflowError) as e:
            raise MigrationIntegrityError(
                "torn", f"corrupt header: {type(e).__name__}: {e}")


def _resolve_dtype(name: str) -> np.dtype:
    """Numpy dtype by name, falling back to ml_dtypes for the TPU types
    numpy does not know natively (bfloat16 et al.)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _page_crc(k_pages: np.ndarray, v_pages: np.ndarray, i: int) -> int:
    return zlib.crc32(np.ascontiguousarray(k_pages[:, i]).tobytes()
                      + np.ascontiguousarray(v_pages[:, i]).tobytes())


def _content_fingerprint(seq_id: int, length: int, page_size: int,
                         k_pages: np.ndarray, v_pages: np.ndarray) -> int:
    """The record-level cross-check: payload fingerprints folded with the
    metadata words, so tampering with ``length`` (the decode cursor the
    bitwise guarantee hangs on) is as detectable as flipping a payload
    bit."""
    return host_combine([
        host_fingerprint(k_pages), host_fingerprint(v_pages),
        host_fingerprint_ints(
            [seq_id, length, page_size, k_pages.shape[1]]),
    ])


def build_record(*, seq_id: int, length: int, page_size: int,
                 k_pages: np.ndarray, v_pages: np.ndarray
                 ) -> MigrationRecord:
    """Assemble a verified-by-construction record from page payload
    snapshots (``KVCachePool.export_pages`` is the caller)."""
    k_pages = np.asarray(k_pages)
    v_pages = np.asarray(v_pages)
    crcs = [_page_crc(k_pages, v_pages, i)
            for i in range(k_pages.shape[1])]
    return MigrationRecord(
        seq_id=int(seq_id), length=int(length), page_size=int(page_size),
        dtype=str(k_pages.dtype), k_pages=k_pages, v_pages=v_pages,
        page_crcs=crcs,
        fingerprint=_content_fingerprint(seq_id, length, page_size,
                                         k_pages, v_pages))


def verify_record(record: MigrationRecord) -> None:
    """Re-verify before admitting: structural completeness (``torn``),
    each page's CRC32 (``page_crc``, naming the page), then the whole-
    record content fingerprint (``fingerprint``).  Raises the named
    :class:`MigrationIntegrityError`; returning means every byte and
    every metadata word matches what the exporter recorded."""
    k, v = np.asarray(record.k_pages), np.asarray(record.v_pages)
    if k.ndim != 5 or v.shape != k.shape:
        raise MigrationIntegrityError(
            "torn", f"payload shapes {k.shape} / {v.shape} are not a "
                    f"matched (L, pages, page, H, D) pair")
    if record.page_size < 1 or record.length < 0:
        raise MigrationIntegrityError(
            "torn", f"nonsensical geometry: page_size "
                    f"{record.page_size}, length {record.length}")
    n = k.shape[1]
    if len(record.page_crcs) != n:
        raise MigrationIntegrityError(
            "torn", f"{len(record.page_crcs)} page CRCs for {n} pages")
    if k.shape[2] != record.page_size:
        raise MigrationIntegrityError(
            "torn", f"payload page dimension {k.shape[2]} != declared "
                    f"page_size {record.page_size}")
    need = -(-max(record.length, 1) // record.page_size)
    if n < need:
        raise MigrationIntegrityError(
            "torn", f"{n} pages cannot hold the declared length "
                    f"{record.length}")
    for i in range(n):
        crc = _page_crc(k, v, i)
        if crc != (int(record.page_crcs[i]) & 0xFFFFFFFF):
            raise MigrationIntegrityError(
                "page_crc", f"page {i}: payload CRC32 {crc:#010x} != "
                            f"recorded {int(record.page_crcs[i]):#010x}")
    fp = _content_fingerprint(record.seq_id, record.length,
                              record.page_size, k, v)
    if fp != int(record.fingerprint):
        raise MigrationIntegrityError(
            "fingerprint", f"content fingerprint {fp:#010x} != recorded "
                           f"{int(record.fingerprint):#010x} (metadata "
                           f"tamper or CRC-colliding payload rewrite)")


class MigrationFileFabric:
    """The multi-process transport: records as atomic files under
    ``<dir>/kv/``, acks as marker files.

    The exporter calls :meth:`export` (tmp+fsync+replace through the
    checkpoint writer — a reader never sees a torn file from a crashed
    writer; torn can only mean on-disk corruption after the fact, which
    verification catches).  The importer polls :meth:`pending`, reads
    with :meth:`read` and acks with :meth:`ack`; the exporter polls
    :meth:`acked` to settle its pools' export holds
    (``KVCachePool.ack_export``) and :meth:`clear` to retire the pair of
    files."""

    def __init__(self, root: str):
        self.dir = os.path.join(root, "kv")
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, seq_id: int) -> str:
        return os.path.join(self.dir, f"seq_{int(seq_id):06d}.kvmig")

    def _ack_path(self, seq_id: int) -> str:
        return self._path(seq_id) + ".ack"

    def export(self, record: MigrationRecord) -> str:
        from hetu_tpu.exec.checkpoint import _atomic_write_bytes
        path = self._path(record.seq_id)
        # three chunks written back to back: no concatenation copy of
        # the KV payload (the checkpoint writer's own discipline)
        _atomic_write_bytes(path, record.header_bytes(),
                            record.k_pages.tobytes(),
                            record.v_pages.tobytes())
        return path

    def pending(self) -> list:
        """Unacked sequence ids with a record file, ascending."""
        out = []
        for name in os.listdir(self.dir):
            if name.endswith(".kvmig"):
                sid = int(name[len("seq_"):-len(".kvmig")])
                if not os.path.exists(self._ack_path(sid)):
                    out.append(sid)
        return sorted(out)

    def read(self, seq_id: int) -> MigrationRecord:
        with open(self._path(seq_id), "rb") as f:
            return MigrationRecord.from_bytes(f.read())

    def ack(self, seq_id: int) -> None:
        from hetu_tpu.exec.checkpoint import _atomic_write_bytes
        _atomic_write_bytes(self._ack_path(seq_id), b"ok\n")

    def acked(self) -> list:
        return sorted(int(n[len("seq_"):-len(".kvmig.ack")])
                      for n in os.listdir(self.dir)
                      if n.endswith(".kvmig.ack"))

    def clear(self, seq_id: int) -> None:
        """Retire a settled migration's record + ack files."""
        for p in (self._path(seq_id), self._ack_path(seq_id)):
            if os.path.exists(p):
                os.remove(p)
