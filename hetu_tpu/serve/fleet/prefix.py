"""Copy-on-write prefix sharing over the paged KV pool.

Production prompt traffic is template-heavy: the same system prompt /
few-shot preamble arrives thousands of times with different suffixes,
and a single-replica engine recomputes and re-stores the identical
prefill every time.  The page tables are exactly the right substrate to
stop that: a prefix of ``n`` full KV pages is suffix-independent state
(K/V at positions < n depend only on the tokens at positions < n under
causal attention), so two prompts that agree on their leading blocks can
ALIAS the same physical pages.

:class:`PrefixTrie` indexes published prefixes by token-block hash, one
node per full ``page_size`` block.  Hashes only route: every match and
every insert re-checks TOKEN EQUALITY against the stored block, so a
hash collision degrades to a miss — two prompts differing anywhere
inside a block can never alias (property-tested).

:class:`PrefixSharer` is the engine-facing policy:

- :meth:`~PrefixSharer.lookup` returns the longest trie match as a list
  of shared pages (capped one token short of the prompt, so prefill
  always has at least one suffix token to compute the first sample
  from), counting ``hetu_serve_prefix_{hits,misses}_total``;
- :meth:`~PrefixSharer.publish` inserts a prefilled prompt's full blocks
  into the trie, RETAINING each newly published page
  (:meth:`~hetu_tpu.serve.kv_cache.KVCachePool.retain`) so the prefix
  outlives the sequence that computed it — that is what makes the cache
  useful across requests, not just across concurrent ones;
- :meth:`~PrefixSharer.reclaim` evicts trie-only pages (refcount 1,
  held by no table) leaves-first in least-recently-matched order when
  the allocator runs short — cached prefixes are a performance loan the
  admission gate can call in.

Sharing never changes what a write sees: prefill computes only the
suffix at ``cache_index = shared_tokens`` (page-aligned by
construction, so the suffix always starts in a private page), and the
engine runs :meth:`KVCachePool.copy_on_write` before every decode write
as the guard rail for any path that would touch a shared page.
"""

from __future__ import annotations

import zlib

import numpy as np

from hetu_tpu.obs import registry as _obs
from hetu_tpu.serve.kv_cache import KVCachePool, PageTable

__all__ = ["PrefixTrie", "PrefixSharer", "block_key"]

_prefix_metrics = None


def _prefix_m() -> dict:
    global _prefix_metrics
    if _prefix_metrics is None:
        reg = _obs.get_registry()
        _prefix_metrics = {
            "hits": reg.counter(
                "hetu_serve_prefix_hits_total",
                "prompt-prefix KV pages served by aliasing a shared page "
                "instead of recomputing the prefill block"),
            "misses": reg.counter(
                "hetu_serve_prefix_misses_total",
                "shareable full prompt blocks that had no trie match and "
                "were computed (and published) fresh"),
            "shared": reg.gauge(
                "hetu_serve_pages_shared",
                "KV pages currently aliased by more than one reference "
                "(tables and/or the prefix trie)"),
        }
    return _prefix_metrics


def block_key(block) -> int:
    """Deterministic hash of one token block (crc32 of the little-endian
    u32 token ids — stable across processes, unlike ``hash()``).  Keys
    only ROUTE; aliasing always re-checks token equality."""
    return zlib.crc32(np.asarray(block, "<u4").tobytes())


class _Node:
    __slots__ = ("tokens", "page", "children", "last_used")

    def __init__(self, tokens: tuple, page: int, last_used: int):
        self.tokens = tokens
        self.page = page
        self.children: dict = {}
        self.last_used = last_used


class PrefixTrie:
    """Token-block-hash trie: one node per published full block, each
    holding the block's tokens (the collision guard) and the physical
    page its K/V lives in."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.children: dict = {}   # root level: block key -> _Node
        self._clock = 0            # monotonic use counter (LRU, no wall time)
        self.nodes = 0

    def _blocks(self, prompt):
        ps = self.page_size
        for i in range(len(prompt) // ps):
            yield tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])

    def match(self, prompt, max_blocks: int | None = None, *,
              peek: bool = False) -> list:
        """Pages of the longest published prefix of ``prompt`` (full
        blocks only, token-verified per block).  Bumps recency unless
        ``peek`` (the router's affinity probe must not perturb LRU
        eviction order between replays)."""
        pages = []
        level = self.children
        for bi, block in enumerate(self._blocks(prompt)):
            if max_blocks is not None and bi >= max_blocks:
                break
            node = level.get(block_key(block))
            if node is None or node.tokens != block:
                break  # miss — or a hash collision, which must be a miss
            if not peek:
                self._clock += 1
                node.last_used = self._clock
            pages.append(node.page)
            level = node.children
        return pages

    def insert(self, prompt, table: PageTable, pool: KVCachePool,
               max_blocks: int | None = None) -> int:
        """Publish ``prompt``'s full blocks, pointing new nodes at the
        sequence's own pages and RETAINING each (the trie's reference).
        Existing nodes keep their page (first publisher wins — later
        identical prefills computed a duplicate only for themselves); a
        colliding node (same hash, different tokens) stops publication
        at that depth.  Returns the number of newly published blocks."""
        level = self.children
        new = 0
        for bi, block in enumerate(self._blocks(prompt)):
            if max_blocks is not None and bi >= max_blocks:
                break
            key = block_key(block)
            node = level.get(key)
            if node is None:
                page = table.pages[bi]
                pool.retain(page)
                self._clock += 1
                node = _Node(block, page, self._clock)
                level[key] = node
                self.nodes += 1
                new += 1
            elif node.tokens != block:
                break  # hash collision: never alias, never overwrite
            level = node.children
        return new

    def evict_reclaimable(self, pool: KVCachePool, n_pages: int) -> int:
        """Drop trie leaves whose page the trie alone keeps alive
        (refcount 1), least-recently-matched first, until ``n_pages``
        pages returned to the free list or nothing is evictable.
        Deterministic: recency is the use counter, ties broken by page
        index."""
        freed = 0
        while freed < n_pages:
            leaves = []  # (last_used, page, parent_level, key)
            stack = [(self.children, k, n) for k, n in self.children.items()]
            while stack:
                level, key, node = stack.pop()
                if not node.children:
                    if pool.refcount(node.page) == 1:
                        leaves.append((node.last_used, node.page,
                                       level, key))
                else:
                    stack.extend((node.children, k, c)
                                 for k, c in node.children.items())
            if not leaves:
                break
            _, page, level, key = min(leaves)
            del level[key]
            self.nodes -= 1
            pool.release(page)
            freed += 1
        return freed


class PrefixSharer:
    """The engine-facing prefix-sharing policy over one pool + one trie
    (per replica — the router compares tries across replicas for
    affinity placement)."""

    def __init__(self, pool: KVCachePool):
        self.pool = pool
        self.trie = PrefixTrie(pool.page_size)

    def _max_share_blocks(self, prompt_len: int) -> int:
        # never share the whole prompt: prefill must keep >= 1 suffix
        # token to compute the first sampled token's logits from
        return max(0, (prompt_len - 1) // self.pool.page_size)

    def lookup(self, prompt, max_tokens: int | None = None) -> tuple:
        """``(shared_pages, shared_tokens)`` for a prompt about to be
        allocated; counts block hits and (shareable) misses.
        ``max_tokens`` further caps the share (the engine trims so that
        ``shared + suffix_bucket`` always fits the serving window, and
        drops sharing entirely under a bucket-growth freeze when the
        suffix bucket would be a cold compile)."""
        cap = self._max_share_blocks(len(prompt))
        if max_tokens is not None:
            cap = min(cap, max_tokens // self.pool.page_size)
        pages = self.trie.match(prompt, cap)
        m = _prefix_m()
        if pages:
            m["hits"].inc(len(pages))
        if cap > len(pages):
            m["misses"].inc(cap - len(pages))
        return pages, len(pages) * self.pool.page_size

    def match_tokens(self, prompt) -> int:
        """Affinity probe: how many leading tokens of ``prompt`` this
        replica's trie already holds.  Read-only (no recency bump, no
        hit/miss counting) so routing probes across N replicas leave
        every trie bitwise unchanged."""
        return len(self.trie.match(
            prompt, self._max_share_blocks(len(prompt)), peek=True)) \
            * self.pool.page_size

    def publish(self, prompt, table: PageTable) -> int:
        """Publish a prefilled prompt's fully-written blocks; updates the
        shared-pages gauge.  Returns newly published block count."""
        new = self.trie.insert(prompt, table, self.pool,
                               max_blocks=len(prompt) // self.pool.page_size)
        # one cheap refcount pass — publish is on the per-request prefill
        # path, so no stats() invariant sweep here
        _prefix_m()["shared"].set(self.pool.shared_pages_count())
        return new

    def reclaim(self, n_pages: int) -> int:
        """Evict trie-only pages to unblock an allocation; returns pages
        actually freed."""
        return self.trie.evict_reclaimable(self.pool, n_pages)

    def stats(self) -> dict:
        return {"trie_nodes": self.trie.nodes,
                "page_size": self.pool.page_size}
