"""Disaggregated prefill/decode serving over the page fabric.

Prefill is compute-bound and decode is memory-bound, yet a colocated
replica timeslices both on the same chip — so a burst of long prompts
stalls every in-flight decode stream behind it (ROADMAP item 2's named
failure mode).  This module splits the PR 13 fleet into ROLE pools:

- **prefill workers** (``ServingEngine(role="prefill")``) admit, prefill
  at high slot turnover (a slot is held for ONE prefill, then recycled),
  sample the first token, and migrate the request's KV pages out;
- **decode workers** (``role="decode"``) ingest verified migration
  records (serve/fleet/migrate.py) and decode continuously — no prefill
  ever preempts their token loop;
- **colocated** engines do both (the baseline, and the degraded mode a
  one-chip deployment falls back to).

:class:`DisaggRouter` fronts both pools with the PR 13 placement
discipline applied per side: submissions rank the PREFILL pool by
prefix-trie affinity (then shed pressure, load, index — exactly
``FleetRouter``'s ordering), migrations rank the DECODE pool by shed
pressure then load, and both sides re-route around load-shedding
rejections with the same bounded retry budget.  The router assigns
GLOBAL request ids in submission order, so a request's token stream —
a pure function of ``(seed, request id, prompt)`` — is bitwise
identical whether it was served colocated or migrated across workers:
the stronger-than-vLLM guarantee PR 13's speculative decoding proved,
now across a worker boundary.

Migration is a first-class, journaled artifact: every successful
handoff emits ``kv_migrate`` (+ ``hetu_migrate_{pages,bytes}_total``),
every refused record emits ``migrate_verify_failed`` with its named
diagnosis, and role assignment itself is journaled (``role_assign``) —
a same-seed replay reproduces the whole migration journal bitwise.
Transport is the in-process hook below for the fleet simulation and
:class:`~hetu_tpu.serve.fleet.migrate.MigrationFileFabric` (atomic
files under ``<dir>/kv/``) for the multi-process form.
"""

from __future__ import annotations

from hetu_tpu.exec import faults as _faults
from hetu_tpu.obs import journal as _journal
from hetu_tpu.serve.fleet.migrate import migrate_metrics
from hetu_tpu.serve.fleet.router import FleetRouter

__all__ = ["DisaggRouter", "MigrationTicket"]


class MigrationTicket:
    """The settle side of one in-process migration: the record plus the
    obligation to release the SOURCE pool's export hold exactly once —
    at import, at re-prefill fallback, or at queue expiry, whichever
    resolves the migrated request's intake."""

    def __init__(self, record, src_engine):
        self.record = record
        self._src = src_engine
        self._settled = False

    def settle(self) -> None:
        """Release the source's export hold (idempotent; the caller runs
        this OUTSIDE its own engine lock — see ``ServingEngine.step``)."""
        if self._settled:
            return
        self._settled = True
        src = self._src
        with src._lock:
            src.pool.ack_export(self.record.seq_id)


class DisaggRouter(FleetRouter):
    """Role-aware front end over prefill / decode / colocated engines."""

    def __init__(self, engines, *, max_retries=None):
        super().__init__(engines, max_retries=max_retries)
        self._prefill_idx = [i for i, e in enumerate(self.engines)
                             if e.role in ("prefill", "colocated")]
        self._decode_idx = [i for i, e in enumerate(self.engines)
                            if e.role in ("decode", "colocated")]
        if not self._prefill_idx:
            raise ValueError("no prefill-capable engine (role 'prefill' "
                             "or 'colocated') in the fleet")
        if not self._decode_idx:
            raise ValueError("no decode-capable engine (role 'decode' "
                             "or 'colocated') in the fleet")
        self.migrations: list = []   # the deterministic migration log
        for i, e in enumerate(self.engines):
            _journal.record("role_assign", replica=i, role=e.role)
            if e.role == "prefill":
                e.migrate_out = self._migrate_out

    # -- mid-flight membership ----------------------------------------------

    def add_replica(self, engine, *, warming: bool = True) -> int:
        """A broker-granted worker joins the role pools too: the index
        lands in ``_prefill_idx``/``_decode_idx`` by role (ranking still
        skips it until :meth:`mark_serving`), the assignment is
        journaled like a construction-time worker's, and a prefill
        worker gets the migration hook installed."""
        i = super().add_replica(engine, warming=warming)
        _journal.record("role_assign", replica=i, role=engine.role)
        if engine.role in ("prefill", "colocated"):
            self._prefill_idx.append(i)
        if engine.role in ("decode", "colocated"):
            self._decode_idx.append(i)
        if engine.role == "prefill":
            engine.migrate_out = self._migrate_out
        return i

    # -- placement ----------------------------------------------------------

    def _rank(self, prompt) -> list:
        """Prefill-side ranking: the FleetRouter ordering (-affinity,
        shed pressure, load factor, index) restricted to the SERVING
        members of the prefill-capable pool."""
        ranked = sorted(
            (-(self.engines[i].sharer.match_tokens(prompt)
               if self.engines[i].sharer is not None else 0),
             self.engines[i].slo.shed_pressure(),
             self.engines[i].batcher.load_factor(), i)
            for i in self._prefill_idx
            if self._membership[i] == "serving")
        if not ranked:
            raise RuntimeError("no serving prefill-capable replica — "
                               "every one is warming, reclaiming or "
                               "retired")
        return ranked

    def _rank_decode(self) -> list:
        """Decode-side ranking: shed pressure, then load factor, then
        index — migrations have no prompt affinity (their KV travels
        with them), so who is drowning is the whole signal.  Restricted
        to SERVING members: a reclaiming decode worker finishes the
        streams it has but takes no new migrations."""
        return sorted(
            (self.engines[i].slo.shed_pressure(),
             self.engines[i].batcher.load_factor(), i)
            for i in self._decode_idx
            if self._membership[i] == "serving")

    def submit(self, prompt, max_new_tokens: int = 16, *,
               deadline_s=None, request_id=None, tenant=None):
        """Place one request on the prefill side (``_rank`` restricts
        the base placement loop to the prefill-capable pool).  The
        router assigns a GLOBAL request id in submission order (re-route
        retries reuse it — since PR 20 the base ``FleetRouter`` owns
        that discipline, idempotent resubmission included), so streams
        are bitwise comparable to a colocated same-seed run of the same
        trace.  ``tenant`` rides the request end to end: the prefill
        worker's front door charges the quota and WFQ-schedules it, and
        the migrated request carries the id to the decode worker (whose
        intake never re-charges it)."""
        return super().submit(prompt, max_new_tokens,
                              deadline_s=deadline_s,
                              request_id=request_id, tenant=tenant)

    # -- the migration hook -------------------------------------------------

    def _migrate_out(self, src, req, record) -> bool:
        """Installed as every prefill engine's ``migrate_out``: place the
        exported record on the best decode worker, re-routing around
        shed rejections with the submission-side retry budget.  Returns
        False when every candidate shed — or when a scheduled
        ``migrate_drop`` fault eats the record in transit — the source
        cancels the export and decodes the request itself (degraded,
        never dropped)."""
        src_idx = self.engines.index(src)
        plan = _faults.active_plan()
        if plan is not None and plan.take(
                "migrate_drop", late_ok=True, now=src._tick) is not None:
            migrate_metrics()["failures"].labels(reason="dropped").inc()
            _journal.record("migrate_verify_failed", request_id=req.id,
                            reason="dropped")
            return False
        handle = src._handles[req.id]
        timeline = src._timelines[req.id]
        ticket = MigrationTicket(record, src)
        order = self._rank_decode()
        tries = min(len(order), self.max_retries + 1)
        for _pressure, _load, j in order[:tries]:
            shed = self.engines[j].accept_migration(
                req, record, ticket, handle, timeline)
            if shed is not None:
                continue
            mm = migrate_metrics()
            mm["pages"].inc(record.num_pages)
            mm["bytes"].inc(record.nbytes)
            _journal.record("kv_migrate", request_id=req.id,
                            pages=record.num_pages, bytes=record.nbytes,
                            src=src_idx, dst=j)
            self.migrations.append({"request_id": req.id, "src": src_idx,
                                    "dst": j, "pages": record.num_pages})
            return True
        return False

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        """``/fleet/serve`` with role columns + migration tallies on top
        of the FleetRouter payload."""
        out = super().stats()
        for row, e in zip(out["replicas"], self.engines):
            row["role"] = e.role
            row["migrations"] = dict(e._migrations)
            pool = e.pool.stats()
            row["pages_export_held"] = pool["pages_export_held"]
        out["roles"] = {r: sum(1 for e in self.engines if e.role == r)
                        for r in ("prefill", "decode", "colocated")}
        out["migrations"] = {
            "count": len(self.migrations),
            "pages": sum(m["pages"] for m in self.migrations),
            "reprefills": sum(e._migrations["reprefill"]
                              for e in self.engines),
        }
        return out
