"""Online inference subsystem: continuous batching over a paged KV cache.

The ROADMAP's north star is a system that *serves* heavy traffic, and the
paper's headline capability — the cache-enabled parameter server for huge
embedding tables (HET, VLDB'22) — is as much a serving story as a training
one.  This package is the inference path the training stack feeds:

- :mod:`~hetu_tpu.serve.kv_cache` — block-allocated KV-cache pool with
  per-sequence page tables (alloc/grow/free/defrag) behind fixed padded
  shapes, so XLA compiles one decode program and one prefill program per
  prompt bucket;
- :mod:`~hetu_tpu.serve.batcher` — Orca-style continuous batching
  (OSDI'22): admission queue with depth limit and per-request deadlines,
  prefill/decode interleave, slot recycling the moment a sequence
  finishes;
- :mod:`~hetu_tpu.serve.engine` — ``ServingEngine`` driving seeded GPT
  generation through the decode seams in ``layers/attention.py`` /
  ``models/gpt.py``, plus a CTR inference path that pulls embeddings
  READ-ONLY through the HET caches (no gradient push; PS faults from
  ``exec/faults.py`` remain injectable);
- :mod:`~hetu_tpu.serve.server` — stdlib-HTTP ``/infer`` + ``/stats``
  endpoint registered on the ``obs.server`` route table, sharing a port
  with ``/metrics``;
- :mod:`~hetu_tpu.serve.tenant` — the multi-tenant front door: priority
  classes (``latency`` / ``batch``), deterministic token-bucket quotas,
  and the per-tenant metering artifact the ``/tenants`` endpoint serves;
  the batcher schedules admission weighted-fair across tenants and the
  controller sheds one tenant without touching the others;
- :mod:`~hetu_tpu.serve.loadgen` — seeded deterministic load generator
  (the acceptance tests replay identical request schedules), including
  template-heavy shared-prefix traces and adversarial multi-tenant
  mixes;
- :mod:`~hetu_tpu.serve.fleet` — the multi-replica tier: copy-on-write
  prefix sharing over the paged pool, speculative decoding with a draft
  GPT (accepted streams bitwise identical to non-speculative runs), and
  :class:`~hetu_tpu.serve.fleet.FleetRouter` placing requests across N
  replicas by prefix-cache affinity and shed pressure, and the
  disaggregated prefill/decode tier
  (:class:`~hetu_tpu.serve.fleet.DisaggRouter`): finished prefills
  migrate their KV pages to decode workers as verified records, streams
  staying bitwise identical to colocated same-seed runs.

Everything is deterministic under a fixed seed: same schedule, same
tokens, bit-for-bit — the serving counterpart of the training stack's
chaos-lineage guarantee.
"""

from hetu_tpu.serve.batcher import (AdmissionQueueFull, AdmissionShed,
                                    ContinuousBatcher, Request,
                                    TenantQuotaExceeded)
from hetu_tpu.serve.engine import RequestHandle, ServingEngine
from hetu_tpu.serve.kv_cache import (DoubleFree, KVCachePool, OutOfPages,
                                     PageTable)
from hetu_tpu.serve.loadgen import (LoadItem, generate_diurnal_load,
                                    generate_load,
                                    generate_multitenant_load,
                                    generate_prefill_burst_load,
                                    generate_shared_prefix_load)
from hetu_tpu.serve.server import (FleetServingServer, ServingServer,
                                   serve_engine, serve_fleet_router)
from hetu_tpu.serve.tenant import (DEFAULT_TENANT, Tenant, TenantPolicy,
                                   TokenBucket)
from hetu_tpu.serve.fleet import (DisaggRouter, FleetRouter,
                                  MigrationFileFabric,
                                  MigrationIntegrityError, MigrationRecord,
                                  PrefixSharer, PrefixTrie,
                                  SpeculativeDecoder)

__all__ = [
    "KVCachePool", "PageTable", "OutOfPages", "DoubleFree",
    "ContinuousBatcher", "Request", "AdmissionQueueFull", "AdmissionShed",
    "TenantQuotaExceeded",
    "Tenant", "TenantPolicy", "TokenBucket", "DEFAULT_TENANT",
    "ServingEngine", "RequestHandle",
    "ServingServer", "serve_engine",
    "FleetServingServer", "serve_fleet_router",
    "generate_load", "generate_shared_prefix_load",
    "generate_prefill_burst_load", "generate_multitenant_load",
    "generate_diurnal_load", "LoadItem",
    "PrefixTrie", "PrefixSharer", "SpeculativeDecoder", "FleetRouter",
    "DisaggRouter", "MigrationRecord", "MigrationIntegrityError",
    "MigrationFileFabric",
]
