"""Multi-tenant front door: tenant identity, quotas, and metering.

"Millions of users" (ROADMAP north star) means tenants with different
contracts sharing one fleet — not one anonymous FIFO queue where a
single abusive client starves everyone and the SLO-burn shed punishes
victims and attackers alike.  This module carries the identity and
policy side of that story; the scheduling side (deterministic
weighted-fair admission over per-tenant sub-queues) lives in
:mod:`~hetu_tpu.serve.batcher`.

- :class:`Tenant` — one tenant's identity: id, **priority class**
  (``latency`` — interactive traffic graded against the tight SLO — or
  ``batch`` — throughput traffic the controller sheds FIRST under
  sustained burn), and WFQ **weight** (its fair share of admission).
- :class:`TokenBucket` — a deterministic per-tenant admission quota in
  *work tokens* (``prompt + max_new_tokens``, the same cost unit WFQ
  schedules on).  Refill is computed from the injected clock's
  timestamps, never wall time, so same-seed replays exhaust and refill
  the bucket at identical instants.  Exhaustion raises
  :class:`~hetu_tpu.serve.batcher.TenantQuotaExceeded` upstream, whose
  ``retry_after_s`` is this bucket's refill arithmetic — the client is
  told exactly how long to back off.
- :class:`TenantPolicy` — the registry mapping tenant ids to their
  contract (class, weight, quota).  Unknown tenants resolve to a
  default-contract :class:`Tenant` (latency class, weight 1, no quota)
  so the front door never 500s on a new customer; ``tenant=None``
  resolves to :data:`DEFAULT_TENANT`, which keeps every pre-tenant
  call site bitwise on its old path.  Share ONE policy across a fleet's
  replicas and the token buckets become fleet-wide quotas (the bucket
  state is the shared object).
- :class:`TenantMeter` — the per-tenant billing artifact: requests by
  outcome, prompt/generated tokens, KV pages held, and compile-seconds
  attributed to the tenant whose prefill warmed the bucket.  Mirrors
  onto the ``hetu_tenant_*`` metric family and serves as the
  ``/tenants`` payload.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterable, Optional

from hetu_tpu.obs import registry as _obs

__all__ = ["Tenant", "TokenBucket", "TenantPolicy", "TenantMeter",
           "DEFAULT_TENANT", "PRIORITY_CLASSES"]

#: the two contract tiers: ``latency`` (interactive; shed LAST) and
#: ``batch`` (throughput; the controller's first shed target)
PRIORITY_CLASSES = ("latency", "batch")


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One tenant's identity + contract as the scheduler sees it."""

    id: str
    klass: str = "latency"     # priority class: "latency" | "batch"
    weight: float = 1.0        # WFQ share; admission cost is divided by it

    def __post_init__(self):
        if not self.id or not isinstance(self.id, str):
            raise ValueError(f"tenant id must be a non-empty string, "
                             f"got {self.id!r}")
        if self.klass not in PRIORITY_CLASSES:
            raise ValueError(f"unknown priority class {self.klass!r}; "
                             f"one of {PRIORITY_CLASSES}")
        if not self.weight > 0:
            raise ValueError(f"tenant weight must be positive, "
                             f"got {self.weight}")


#: the anonymous pre-tenant caller: every request that names no tenant
#: is this one, so single-tenant deployments keep their exact old
#: admission order, journal, and metric series semantics
DEFAULT_TENANT = Tenant(id="default", klass="latency", weight=1.0)


class TokenBucket:
    """Deterministic token-bucket quota in work tokens.

    State advances only on the timestamps the caller passes (the
    engine's injectable clock), so a replayed trace drains and refills
    the bucket bitwise.  A request costing more than ``capacity`` is
    charged ``capacity`` (it admits from a full bucket) — clamping, not
    permanently starving, oversized-but-legal work.  Thread-safe so one
    bucket can back a whole fleet's replicas as a shared quota.
    """

    def __init__(self, capacity: float, refill_per_s: float, *,
                 tokens: Optional[float] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if refill_per_s < 0:
            raise ValueError(f"refill_per_s must be >= 0, "
                             f"got {refill_per_s}")
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self.tokens = self.capacity if tokens is None else float(tokens)
        self._updated: Optional[float] = None   # clock of last refill
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        if self._updated is None:
            self._updated = now
            return
        dt = max(now - self._updated, 0.0)
        self._updated = now
        if dt and self.refill_per_s:
            self.tokens = min(self.capacity,
                              self.tokens + dt * self.refill_per_s)

    def _cost(self, cost: float) -> float:
        return min(max(float(cost), 0.0), self.capacity)

    def try_take(self, cost: float, now: float) -> bool:
        """Refill to ``now`` and take ``cost`` tokens if available."""
        with self._lock:
            self._refill(now)
            c = self._cost(cost)
            if self.tokens + 1e-12 < c:
                return False
            self.tokens -= c
            return True

    def retry_after(self, cost: float, now: float) -> float:
        """Seconds until ``cost`` tokens will be available (0.0 when
        affordable right now; ``capacity / refill`` bounds it).  With a
        zero refill rate the bucket never recovers — one full capacity
        drain's worth of seconds is reported as the honest 'a while'."""
        with self._lock:
            self._refill(now)
            c = self._cost(cost)
            short = c - self.tokens
            if short <= 0:
                return 0.0
            if self.refill_per_s <= 0:
                return float(self.capacity)
            return short / self.refill_per_s

    def stats(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity,
                    "refill_per_s": self.refill_per_s,
                    "tokens": round(self.tokens, 6)}


class TenantPolicy:
    """Tenant id -> contract (class, weight, quota bucket).

    ``resolve`` accepts a :class:`Tenant`, a bare id string, or ``None``
    and always returns a :class:`Tenant`: unknown ids are auto-
    registered with the default contract (a new customer is traffic,
    not an error), ``None`` is :data:`DEFAULT_TENANT`.  Thread-safe;
    share one instance across a fleet's engines for fleet-wide quota
    semantics.
    """

    def __init__(self, tenants: Iterable[Tenant] = (),
                 quotas: Optional[Dict[str, TokenBucket]] = None):
        self._tenants: Dict[str, Tenant] = {DEFAULT_TENANT.id:
                                            DEFAULT_TENANT}
        self._quotas: Dict[str, TokenBucket] = dict(quotas or {})
        self._lock = threading.Lock()
        for t in tenants:
            self.register(t)
        for tid in self._quotas:
            self.resolve(tid)   # a quota names a tenant into existence

    def register(self, tenant: Tenant,
                 quota: Optional[TokenBucket] = None) -> Tenant:
        """Install (or replace) one tenant's contract."""
        with self._lock:
            self._tenants[tenant.id] = tenant
            if quota is not None:
                self._quotas[tenant.id] = quota
        return tenant

    def resolve(self, tenant) -> Tenant:
        """``None`` | id-string | :class:`Tenant` -> :class:`Tenant`."""
        if tenant is None:
            return DEFAULT_TENANT
        if isinstance(tenant, Tenant):
            with self._lock:
                known = self._tenants.get(tenant.id)
                if known is None or known != tenant:
                    self._tenants[tenant.id] = tenant
            return tenant
        tid = str(tenant)
        with self._lock:
            known = self._tenants.get(tid)
            if known is None:
                known = Tenant(id=tid)
                self._tenants[tid] = known
            return known

    def bucket(self, tenant_id: str) -> Optional[TokenBucket]:
        with self._lock:
            return self._quotas.get(tenant_id)

    def known(self) -> Dict[str, Tenant]:
        with self._lock:
            return dict(self._tenants)

    def stats(self) -> dict:
        with self._lock:
            return {tid: {"class": t.klass, "weight": t.weight,
                          "quota": (self._quotas[tid].stats()
                                    if tid in self._quotas else None)}
                    for tid, t in sorted(self._tenants.items())}


_tenant_metrics = None


def _tenant_m() -> dict:
    global _tenant_metrics
    if _tenant_metrics is None:
        reg = _obs.get_registry()
        _tenant_metrics = {
            "requests": reg.counter(
                "hetu_tenant_requests_total",
                "per-tenant serving requests by outcome (the tenant-"
                "scoped twin of hetu_serve_requests_total)",
                ("tenant", "outcome")),
            "tokens": reg.counter(
                "hetu_tenant_tokens_total",
                "per-tenant token metering by kind (prompt: tokens "
                "admitted for prefill; generated: tokens decoded) — "
                "the billing artifact",
                ("tenant", "kind")),
            "pages": reg.counter(
                "hetu_tenant_kv_pages_total",
                "per-tenant KV pages held at request retirement "
                "(cumulative page-holds, the pool-occupancy billing "
                "unit)", ("tenant",)),
            "compile": reg.counter(
                "hetu_tenant_compile_seconds_total",
                "per-tenant XLA compile wall seconds, attributed to "
                "the tenant whose prefill warmed the program",
                ("tenant",)),
            "queue": reg.gauge(
                "hetu_tenant_queue_depth",
                "per-tenant admission sub-queue depth", ("tenant",)),
        }
    return _tenant_metrics


class TenantMeter:
    """Per-tenant usage accumulators — the billing artifact.

    All mutators take the tenant id; unknown ids materialize a zeroed
    row.  Mirrors onto the ``hetu_tenant_*`` families when telemetry is
    enabled; :meth:`summary` is the ``/tenants`` payload.  The recorded
    quantities are schedule-deterministic (token counts, page counts)
    except ``compile_s``, which is measured wall time — billing data,
    deliberately excluded from the bitwise-replay surfaces.
    """

    def __init__(self):
        self._rows: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def _row(self, tenant_id: str) -> dict:
        row = self._rows.get(tenant_id)
        if row is None:
            row = {"requests": {}, "prompt_tokens": 0,
                   "generated_tokens": 0, "kv_pages": 0,
                   "compile_s": 0.0, "shed": {}}
            self._rows[tenant_id] = row
        return row

    def note_outcome(self, tenant_id: str, outcome: str) -> None:
        with self._lock:
            req = self._row(tenant_id)["requests"]
            req[outcome] = req.get(outcome, 0) + 1
        if _obs.enabled():
            _tenant_m()["requests"].labels(tenant=tenant_id,
                                           outcome=outcome).inc()

    def note_shed(self, tenant_id: str, reason: str) -> None:
        with self._lock:
            shed = self._row(tenant_id)["shed"]
            shed[reason] = shed.get(reason, 0) + 1

    def note_tokens(self, tenant_id: str, *, prompt: int = 0,
                    generated: int = 0) -> None:
        with self._lock:
            row = self._row(tenant_id)
            row["prompt_tokens"] += int(prompt)
            row["generated_tokens"] += int(generated)
        if _obs.enabled():
            m = _tenant_m()
            if prompt:
                m["tokens"].labels(tenant=tenant_id, kind="prompt").inc(
                    int(prompt))
            if generated:
                m["tokens"].labels(tenant=tenant_id,
                                   kind="generated").inc(int(generated))

    def note_pages(self, tenant_id: str, pages: int) -> None:
        with self._lock:
            self._row(tenant_id)["kv_pages"] += int(pages)
        if _obs.enabled() and pages:
            _tenant_m()["pages"].labels(tenant=tenant_id).inc(int(pages))

    def note_compile(self, tenant_id: str, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._lock:
            self._row(tenant_id)["compile_s"] += float(seconds)
        if _obs.enabled():
            _tenant_m()["compile"].labels(tenant=tenant_id).inc(
                float(seconds))

    def shed_counts(self, tenant_id: str) -> dict:
        with self._lock:
            return dict(self._rows.get(tenant_id, {}).get("shed", {}))

    def summary(self) -> dict:
        with self._lock:
            return {tid: {"requests": dict(row["requests"]),
                          "prompt_tokens": row["prompt_tokens"],
                          "generated_tokens": row["generated_tokens"],
                          "kv_pages": row["kv_pages"],
                          "compile_s": round(row["compile_s"], 6),
                          "shed": dict(row["shed"])}
                    for tid, row in sorted(self._rows.items())}
