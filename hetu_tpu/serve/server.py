"""Serving endpoint: ``/infer`` + ``/stats`` on the obs route table.

The satellite payoff of the ``obs/server.py`` refactor: this module
registers handlers on a :class:`~hetu_tpu.obs.server.Routes` table and
inherits every line of HTTP plumbing — plus the full telemetry surface
(``/metrics``, ``/metrics.json``, ``/healthz``, ``/journal``), so one
ephemeral port scrapes the serving SLO metrics next to the endpoints
they describe.

- ``POST /infer`` with ``{"prompt": [ids...], "max_new_tokens": n,
  "deadline_s": s?, "timeout_s": s?, "tenant": "id"?}`` blocks until
  the request resolves and returns ``{"request_id", "trace_id",
  "status", "tokens", "ttft_s", "latency_s"}`` — 200 on completion, 429
  on admission rejection, 504 on deadline expiry.  ``tenant`` names the
  submitting tenant (omitted = the default tenant): admission is
  weighted-fair across tenants, quota buckets gate the front door, and
  the controller can shed one tenant without the others.  A malformed
  body is a **400 with a named diagnosis** (``"diagnosis": "bad_json" |
  "missing_field" | "too_large"`` plus a human-readable ``error``),
  never a traceback.  Non-completed
  responses carry a human-readable ``error`` naming what happened
  (rejection reason; deadline stage and age), and ``trace_id`` keys the
  request's full timeline at ``/trace/<request_id>``.  Rejections that
  are *load shedding* additionally carry a machine-readable ``reason``
  (``controller`` | ``queue_full`` | ``bucket_freeze`` | ``quota``) and
  a deterministic ``retry_after_s`` backoff hint (the token bucket's
  exact refill time on quota; pressure/queue-derived otherwise) — each
  also counted under ``hetu_serve_shed_total{reason=,tenant=}`` and
  journaled (kind ``shed``; quota rejections add ``tenant_quota``).
- ``GET /tenants`` returns the per-tenant metering artifact: the tenant
  policy (class, weight, quota bucket state), usage accumulators
  (requests by outcome, prompt/generated tokens, KV pages held,
  compile-seconds), per-tenant queue depths, and live scoped-shed
  latches — the billing surface.
- ``GET /controller`` (via the telemetry routes) reports the installed
  runtime controller's policy, latches, and decision list — README
  "Self-driving runtime".
- ``POST /infer`` with ``{"dense": [[...]], "sparse": [[...]]}`` runs
  the read-only CTR path and returns ``{"pred": [...]}``.
- ``GET /stats`` returns the engine's scheduler/pool/counter snapshot.
- ``GET /slo`` returns the SLO engine's summary: targets, per-stage
  request-seconds, burn rates (short+long window per target), and the
  shed-pressure gauge.
- ``GET /trace`` lists the trace buffer (ring ids + exemplar ids);
  ``GET /trace/<request_id>`` returns one request's timeline — outcome,
  exact stage decomposition, span list (Chrome-stitchable schema).
"""

from __future__ import annotations

import json

from hetu_tpu.obs.server import Routes, RoutedHTTPServer, telemetry_routes

__all__ = ["ServingServer", "serve_engine", "FleetServingServer",
           "serve_fleet_router"]

# an /infer body past this is refused up front (diagnosis "too_large"):
# the serving front door must never json-parse an unbounded upload on a
# handler thread
MAX_INFER_BODY_BYTES = 1 << 20


def _infer_400(diagnosis: str, detail: str):
    """One named /infer diagnosis: machine-readable ``diagnosis``
    (``bad_json`` | ``missing_field`` | ``too_large``) + human-readable
    ``error`` — the malformed-request counterpart of the shed
    ``reason`` contract."""
    return (json.dumps({"diagnosis": diagnosis, "error": detail}
                       ).encode(), "application/json", 400)


def _parse_infer(body):
    """Validate one /infer body.  Returns ``(request_dict, None)`` or
    ``(None, <400 response triple>)`` — the handler returns the triple
    verbatim, so a malformed body can never reach ``submit`` (or a
    traceback reach the client)."""
    if body is not None and len(body) > MAX_INFER_BODY_BYTES:
        return None, _infer_400(
            "too_large",
            f"request body is {len(body)} bytes; /infer accepts at "
            f"most {MAX_INFER_BODY_BYTES}")
    try:
        req = json.loads(body or b"{}")
    except (ValueError, UnicodeDecodeError) as e:
        return None, _infer_400(
            "bad_json", f"request body is not valid JSON: {e}")
    if not isinstance(req, dict):
        return None, _infer_400(
            "bad_json", f"request body must be a JSON object, got "
            f"{type(req).__name__}")
    return req, None


def _handle_body(handle) -> dict:
    """The shared /infer response body for a resolved handle."""
    body = {
        "request_id": handle.request_id,
        "trace_id": handle.trace_id,
        "status": handle.status,
        "tokens": handle.tokens,
        # deterministic token-stream fingerprint: same seed + same
        # prompt must return the same value however the batch was
        # composed — compare across replicas/replays to catch
        # sampler nondeterminism in prod (null until a token lands)
        "stream_fingerprint": handle.stream_fingerprint,
        "ttft_s": handle.ttft_s,
        "latency_s": handle.latency_s,
    }
    if handle.error is not None:
        # the distinguishable-error contract: a shed/expired request
        # says WHY, not just a status code
        body["error"] = handle.error
    if handle.shed_reason is not None:
        # machine-readable backoff contract: WHICH door closed
        # (controller | queue_full | bucket_freeze | quota) and how
        # long to back off — the quota hint is the token bucket's
        # exact refill arithmetic
        body["reason"] = handle.shed_reason
        if handle.retry_after_s is not None:
            body["retry_after_s"] = handle.retry_after_s
    if getattr(handle, "tenant", None) not in (None, "default"):
        body["tenant"] = handle.tenant
    return body


def serving_routes(engine) -> Routes:
    """Telemetry routes + the serving endpoints over ``engine``.  Always
    scrapes the process-wide registry — that is where the engine's
    ``hetu_serve_*`` metrics live, so accepting a custom registry here
    would serve a /metrics with none of the serving SLO series."""
    routes = telemetry_routes()

    def infer(query, body):
        req, err = _parse_infer(body)
        if err is not None:
            return err
        if "dense" in req or "sparse" in req:
            if "dense" not in req or "sparse" not in req:
                return _infer_400(
                    "missing_field", "the CTR path needs BOTH 'dense' "
                    "and 'sparse' feature arrays")
            pred = engine.infer_ctr(req["dense"], req["sparse"])
            return json.dumps({"pred": [float(p) for p in pred]}).encode()
        if "prompt" not in req:
            return _infer_400(
                "missing_field", "/infer requires a 'prompt' field (a "
                "list of token ids) — or 'dense'+'sparse' for the CTR "
                "path")
        handle = engine.submit(
            req["prompt"], int(req.get("max_new_tokens", 16)),
            deadline_s=req.get("deadline_s"),
            tenant=req.get("tenant"))
        # `or`: a JSON null (or 0) timeout_s must not disable the timeout
        # and hang this handler thread forever
        if not handle.wait(timeout=float(req.get("timeout_s") or 60.0)):
            return (json.dumps({"request_id": handle.request_id,
                                "trace_id": handle.trace_id,
                                "status": "pending"}).encode(),
                    "application/json", 504)
        status = {"completed": 200, "rejected": 429,
                  "expired": 504, "evicted": 503}[handle.status]
        return (json.dumps(_handle_body(handle)).encode(),
                "application/json", status)

    def tenants(query, body):
        return json.dumps({
            "policy": engine.batcher.policy.stats(),
            "meter": engine.tenant_meter.summary(),
            "queue_lens": engine.batcher.queue_lens(),
            "shedding": engine.batcher.tenant_sheds,
        }).encode()

    def trace_index(query, body):
        buf = engine.trace_buffer
        return json.dumps({
            "completed": buf.completed,
            "ring": buf.request_ids(),
            "exemplars": [t.request_id for t in buf.exemplars()],
        }).encode()

    def trace_one(rest, query, body):
        try:
            rid = int(rest)
        except ValueError:
            return (json.dumps({"error": f"bad request id {rest!r}"}
                               ).encode(), "application/json", 400)
        tl = engine.trace_buffer.get(rid)
        if tl is None:
            return (json.dumps({"error": f"no timeline for request {rid} "
                                "(evicted from the ring and not an "
                                "exemplar, or never submitted)"}).encode(),
                    "application/json", 404)
        return json.dumps(tl.summary()).encode()

    routes.add("POST", "/infer", infer)
    routes.add("GET", "/tenants", tenants)
    routes.add("GET", "/stats",
               lambda q, b: json.dumps(engine.stats()).encode())
    routes.add("GET", "/slo",
               lambda q, b: json.dumps(engine.slo.summary()).encode())
    routes.add("GET", "/trace", trace_index)
    routes.add_prefix("GET", "/trace/", trace_one)
    return routes


class ServingServer(RoutedHTTPServer):
    """HTTP front end over a :class:`~hetu_tpu.serve.engine.ServingEngine`
    (which should be :meth:`~hetu_tpu.serve.engine.ServingEngine.start`-ed
    so its scheduler loop drains the queue)."""

    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1"):
        super().__init__(serving_routes(engine), port, host,
                         thread_name="hetu-serve-http")
        self.engine = engine


def serve_engine(engine, port: int = 0,
                 host: str = "127.0.0.1") -> ServingServer:
    """Start the engine's scheduler thread and an HTTP front end for it;
    returns the started server (``.port`` has the bound port; ``stop()``
    stops the HTTP thread — stop the engine separately)."""
    engine.start()
    srv = ServingServer(engine, port, host)
    srv.start()
    return srv


def fleet_serving_routes(router) -> Routes:
    """Telemetry routes + the FLEET serving endpoints: ``POST /infer``
    places each request through the router's affinity policy (same
    request/response contract as the single-engine handler — callers
    cannot tell one replica from N, which is the point), and ``GET
    /fleet/serve`` reports the router's aggregated stats (per-replica
    occupancy/pressure/cache state, placement tally by reason).  A
    :class:`~hetu_tpu.serve.fleet.DisaggRouter` adds role columns
    (``role`` + per-replica ``migrations``/``pages_export_held``) and
    the fleet-wide migration tally to the same payload — the
    disaggregated tier serves through this front end unchanged."""
    routes = telemetry_routes()

    def infer(query, body):
        req, err = _parse_infer(body)
        if err is not None:
            return err
        if "prompt" not in req:
            return _infer_400(
                "missing_field", "/infer requires a 'prompt' field (a "
                "list of token ids)")
        kwargs = {"deadline_s": req.get("deadline_s"),
                  "tenant": req.get("tenant")}
        if req.get("request_id") is not None:
            # the idempotent-resubmit contract: a client retrying after
            # a dropped connection names its request id — an id still in
            # flight re-attaches to the LIVE handle (surviving failover,
            # since re-homes keep the id), never double-submits
            kwargs["request_id"] = int(req["request_id"])
        handle = router.submit(
            req["prompt"], int(req.get("max_new_tokens", 16)), **kwargs)
        if not handle.wait(timeout=float(req.get("timeout_s") or 60.0)):
            return (json.dumps({"request_id": handle.request_id,
                                "trace_id": handle.trace_id,
                                "status": "pending"}).encode(),
                    "application/json", 504)
        status = {"completed": 200, "rejected": 429,
                  "expired": 504, "evicted": 503}[handle.status]
        return (json.dumps(_handle_body(handle)).encode(),
                "application/json", status)

    def tenants(query, body):
        return json.dumps({
            "replicas": [{
                "replica": i,
                "meter": e.tenant_meter.summary(),
                "queue_lens": e.batcher.queue_lens(),
                "shedding": e.batcher.tenant_sheds,
            } for i, e in enumerate(router.engines)],
            # replicas may share one TenantPolicy (fleet-wide quotas);
            # report the first engine's view as the fleet policy
            "policy": router.engines[0].batcher.policy.stats(),
        }).encode()

    routes.add("POST", "/infer", infer)
    routes.add("GET", "/tenants", tenants)
    routes.add("GET", "/fleet/serve",
               lambda q, b: json.dumps(router.stats()).encode())
    routes.add("GET", "/fleet/failover",
               lambda q, b: json.dumps(
                   {"installed": False} if router.monitor is None
                   else router.monitor.summary()).encode())
    return routes


class FleetServingServer(RoutedHTTPServer):
    """HTTP front end over a :class:`~hetu_tpu.serve.fleet.FleetRouter`
    (whose replicas should be ``start()``-ed so their scheduler loops
    drain the queues)."""

    def __init__(self, router, port: int = 0, host: str = "127.0.0.1"):
        super().__init__(fleet_serving_routes(router), port, host,
                         thread_name="hetu-fleet-http")
        self.router = router


def serve_fleet_router(router, port: int = 0,
                       host: str = "127.0.0.1") -> FleetServingServer:
    """Start every replica's scheduler thread and one fleet HTTP front
    end; returns the started server.  Accepts a ``FleetRouter`` or a
    role-aware ``DisaggRouter`` — the endpoint contract is identical."""
    router.start()
    srv = FleetServingServer(router, port, host)
    srv.start()
    return srv
