"""Seeded deterministic load generator.

Serving acceptance needs *replayable* traffic: the same seed must yield
the same prompts, the same token budgets, and the same arrival times, so
two runs of the engine produce bitwise-identical token streams and the
obs counters can be asserted exactly.  Arrivals are expressed in
*virtual seconds* — the tests drive the engine with a virtual clock and
submit each item when the clock passes ``submit_at`` (Poisson-ish via
seeded exponential gaps, the standard open-loop load model).
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["LoadItem", "generate_load", "generate_shared_prefix_load",
           "generate_prefill_burst_load", "generate_multitenant_load",
           "generate_diurnal_load", "DEFAULT_DIURNAL_PHASES"]


@dataclasses.dataclass(frozen=True)
class LoadItem:
    """One scheduled request of a load trace."""

    submit_at: float       # virtual seconds from trace start
    prompt: tuple          # token ids
    max_new_tokens: int
    deadline_s: float | None = None
    # shared-prefix traces: which template pool entry this prompt leads
    # with (None = unique-prompt traffic) — lets tests assert affinity
    # placement without re-deriving the prefix from tokens
    template: int | None = None
    # prefill-burst traces: True on the bursty long-prompt arrivals —
    # lets the disaggregation A/B attribute tail latency to the burst
    # without re-deriving it from prompt lengths
    burst: bool = False
    # multi-tenant traces: which tenant submits this request (None =
    # the default tenant) — drives the WFQ front door and lets the
    # flood A/B attribute sheds per tenant from the trace spec alone
    tenant: str | None = None
    # diurnal traces: which named phase (off_peak/ramp/peak/decay) this
    # arrival belongs to — lets the broker acceptance attribute grants
    # and reclaims to the traffic shape from the trace spec alone
    phase: str | None = None


def generate_load(seed: int, n_requests: int, *, vocab: int,
                  prompt_len=(2, 24), max_new=(1, 12),
                  mean_gap_s: float = 0.002,
                  deadline_s: float | None = None) -> list:
    """A seeded open-loop trace of ``n_requests`` ragged requests.

    ``prompt_len``/``max_new`` are inclusive (lo, hi) ranges sampled
    uniformly; arrivals accumulate seeded exponential gaps with mean
    ``mean_gap_s``.  Same seed, same trace — bit for bit.
    """
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for _ in range(n_requests):
        t += float(rng.exponential(mean_gap_s))
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        out.append(LoadItem(
            submit_at=t,
            prompt=tuple(int(x) for x in rng.integers(0, vocab, plen)),
            max_new_tokens=int(rng.integers(max_new[0], max_new[1] + 1)),
            deadline_s=deadline_s))
    return out


def generate_shared_prefix_load(seed: int, n_requests: int, *, vocab: int,
                                n_templates: int = 4,
                                prefix_len: int = 16,
                                suffix_len=(2, 8), max_new=(1, 8),
                                shared_fraction: float = 0.7,
                                unique_len=(4, 24),
                                mean_gap_s: float = 0.002,
                                deadline_s: float | None = None) -> list:
    """Template-heavy production traffic, seeded: a pool of
    ``n_templates`` fixed ``prefix_len``-token system prompts, each
    request drawing (with probability ``shared_fraction``) one template
    plus a fresh uniform suffix of ``suffix_len`` tokens — the remainder
    is unique-prompt traffic of ``unique_len`` tokens.  ``template`` on
    each item names the drawn template (None for unique traffic), so the
    prefix-sharing win and the router's affinity placements are
    assertable from the trace spec alone.  Same seed, same trace — bit
    for bit (unit-tested)."""
    rng = np.random.default_rng(seed)
    templates = [tuple(int(x) for x in rng.integers(0, vocab, prefix_len))
                 for _ in range(n_templates)]
    out, t = [], 0.0
    for _ in range(n_requests):
        t += float(rng.exponential(mean_gap_s))
        if float(rng.random()) < shared_fraction:
            tid = int(rng.integers(0, n_templates))
            slen = int(rng.integers(suffix_len[0], suffix_len[1] + 1))
            prompt = templates[tid] + tuple(
                int(x) for x in rng.integers(0, vocab, slen))
        else:
            tid = None
            ulen = int(rng.integers(unique_len[0], unique_len[1] + 1))
            prompt = tuple(int(x) for x in rng.integers(0, vocab, ulen))
        out.append(LoadItem(
            submit_at=t, prompt=prompt,
            max_new_tokens=int(rng.integers(max_new[0], max_new[1] + 1)),
            deadline_s=deadline_s, template=tid))
    return out


def generate_prefill_burst_load(seed: int, n_requests: int, *, vocab: int,
                                short_len=(2, 8), short_new=(8, 16),
                                long_len=(40, 60), long_new=(1, 4),
                                burst_every: int = 8, burst_size: int = 4,
                                mean_gap_s: float = 0.002,
                                burst_gap_s: float | None = None,
                                deadline_s: float | None = None) -> list:
    """The workload where colocation loses: steady SHORT-prompt traffic
    with real decode budgets (the memory-bound stream a production fleet
    must keep flowing), punctuated by clumped BURSTS of long prompts
    with tiny decode budgets (the compute-bound prefill wall that stalls
    a timeslicing chip).  Every ``burst_every`` steady arrivals, a burst
    of ``burst_size`` long items lands nearly at once (``burst_gap_s``,
    default ``mean_gap_s / 50``).  ``burst`` on each item marks the
    bursty arrivals, so the disaggregation A/B can attribute the TTFT
    tail from the trace spec alone.  Same seed, same trace — bit for bit
    (unit-tested)."""
    if burst_every < 1:
        raise ValueError(f"burst_every must be >= 1, got {burst_every}")
    if burst_gap_s is None:
        burst_gap_s = mean_gap_s / 50.0
    rng = np.random.default_rng(seed)
    period = burst_every + max(burst_size, 0)
    out, t = [], 0.0
    for i in range(n_requests):
        in_burst = burst_size > 0 and (i % period) >= burst_every
        if in_burst:
            t += float(rng.exponential(burst_gap_s))
            plen = int(rng.integers(long_len[0], long_len[1] + 1))
            mnt = int(rng.integers(long_new[0], long_new[1] + 1))
        else:
            t += float(rng.exponential(mean_gap_s))
            plen = int(rng.integers(short_len[0], short_len[1] + 1))
            mnt = int(rng.integers(short_new[0], short_new[1] + 1))
        out.append(LoadItem(
            submit_at=t,
            prompt=tuple(int(x) for x in rng.integers(0, vocab, plen)),
            max_new_tokens=mnt, deadline_s=deadline_s, burst=in_burst))
    return out


def generate_multitenant_load(seed: int, n_requests: int, *, vocab: int,
                              tenants,
                              mean_gap_s: float = 0.002,
                              deadline_s: float | None = None) -> list:
    """Seeded adversarial multi-tenant traffic: each arrival draws its
    submitting tenant from ``tenants`` — a sequence of spec dicts ::

        {"id": "acme", "share": 0.8,          # arrival-mix weight
         "prompt_len": (2, 24), "max_new": (1, 12),   # optional ranges
         "deadline_s": 0.5}                            # optional override

    ``share`` weights are normalised over the pool, so a flooding mix is
    one line: ``[{"id": "flood", "share": 0.9, "max_new": (16, 32)},
    {"id": "victim", "share": 0.1}]``.  Per-tenant shape ranges let the
    flood carry heavy decode budgets while the victim stays latency-
    shaped; a per-tenant ``deadline_s`` overrides the trace default.
    Arrivals accumulate one shared exponential-gap stream (the open-loop
    model above), and the tenant choice is a seeded weighted draw per
    arrival — same seed, same trace, bit for bit (unit-tested)."""
    specs = [dict(s) for s in tenants]
    if not specs:
        raise ValueError("need at least one tenant spec")
    shares = np.array([float(s.get("share", 1.0)) for s in specs])
    if (shares < 0).any() or shares.sum() <= 0:
        raise ValueError(f"tenant shares must be >= 0 with a positive "
                         f"sum, got {shares.tolist()}")
    shares = shares / shares.sum()
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for _ in range(n_requests):
        t += float(rng.exponential(mean_gap_s))
        spec = specs[int(rng.choice(len(specs), p=shares))]
        lo, hi = spec.get("prompt_len", (2, 24))
        nlo, nhi = spec.get("max_new", (1, 12))
        plen = int(rng.integers(lo, hi + 1))
        out.append(LoadItem(
            submit_at=t,
            prompt=tuple(int(x) for x in rng.integers(0, vocab, plen)),
            max_new_tokens=int(rng.integers(nlo, nhi + 1)),
            deadline_s=spec.get("deadline_s", deadline_s),
            tenant=str(spec["id"])))
    return out


# one synthetic day in four phases: name, arrival-rate multiplier over
# ``peak_gap_s`` (1.0 = the peak gap itself), and share of the request
# budget spent in the phase.  The 5x off-peak:peak rate swing is the
# diurnal shape the capacity broker (hetu_tpu/broker) follows.
DEFAULT_DIURNAL_PHASES = (
    {"name": "off_peak", "rate": 0.2, "share": 0.2},
    {"name": "ramp", "rate": 0.6, "share": 0.2},
    {"name": "peak", "rate": 1.0, "share": 0.4},
    {"name": "decay", "rate": 0.35, "share": 0.2},
)


def generate_diurnal_load(seed: int, n_requests: int, *, vocab: int,
                          phases=None, peak_gap_s: float = 0.002,
                          tenants=None,
                          prompt_len=(2, 24), max_new=(1, 12),
                          deadline_s: float | None = None) -> list:
    """One seeded synthetic day: the trace walks ``phases`` in order
    (default :data:`DEFAULT_DIURNAL_PHASES` — off-peak → ramp → peak →
    decay), each phase spending its ``share`` of the request budget at
    exponential-gap arrivals of mean ``peak_gap_s / rate`` (``rate`` is
    the multiplier over the peak arrival rate, so ``rate=1.0`` is peak
    traffic and ``rate=0.2`` is a 5x-quieter night).  A phase may carry
    its own ``tenants`` mix (the :func:`generate_multitenant_load` spec
    dicts) overriding the trace-wide ``tenants`` — a real day shifts
    WHO is submitting, not just how fast; ``None`` leaves the phase
    untenanted.  Every item is stamped with its phase name.  One shared
    RNG stream drives gaps, tenant draws, and shapes across all phases
    — same seed, same trace, bit for bit (unit-tested)."""
    phases = [dict(p) for p in (DEFAULT_DIURNAL_PHASES
                                if phases is None else phases)]
    if not phases:
        raise ValueError("need at least one phase")
    shares = np.array([float(p.get("share", 1.0)) for p in phases])
    if (shares < 0).any() or shares.sum() <= 0:
        raise ValueError(f"phase shares must be >= 0 with a positive "
                         f"sum, got {shares.tolist()}")
    for p in phases:
        if float(p.get("rate", 1.0)) <= 0:
            raise ValueError(f"phase {p.get('name')!r} needs a positive "
                             f"rate, got {p.get('rate')}")
    shares = shares / shares.sum()
    # deterministic integer budget split: floors first, the remainder to
    # the earliest phases (largest-remainder would need a tie-break;
    # index order IS the tie-break)
    counts = [int(n_requests * s) for s in shares]
    for i in range(n_requests - sum(counts)):
        counts[i % len(counts)] += 1
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for p, count in zip(phases, counts):
        name = str(p.get("name", "phase"))
        gap = peak_gap_s / float(p.get("rate", 1.0))
        specs = p.get("tenants", tenants)
        if specs is not None:
            specs = [dict(s) for s in specs]
            t_shares = np.array([float(s.get("share", 1.0))
                                 for s in specs])
            if not specs or (t_shares < 0).any() or t_shares.sum() <= 0:
                raise ValueError(
                    f"phase {name!r}: tenant shares must be >= 0 with "
                    f"a positive sum")
            t_shares = t_shares / t_shares.sum()
        for _ in range(count):
            t += float(rng.exponential(gap))
            spec = (specs[int(rng.choice(len(specs), p=t_shares))]
                    if specs is not None else {})
            lo, hi = spec.get("prompt_len", prompt_len)
            nlo, nhi = spec.get("max_new", max_new)
            plen = int(rng.integers(lo, hi + 1))
            out.append(LoadItem(
                submit_at=t,
                prompt=tuple(int(x)
                             for x in rng.integers(0, vocab, plen)),
                max_new_tokens=int(rng.integers(nlo, nhi + 1)),
                deadline_s=spec.get("deadline_s", deadline_s),
                tenant=(str(spec["id"]) if "id" in spec else None),
                phase=name))
    return out
