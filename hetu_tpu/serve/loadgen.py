"""Seeded deterministic load generator.

Serving acceptance needs *replayable* traffic: the same seed must yield
the same prompts, the same token budgets, and the same arrival times, so
two runs of the engine produce bitwise-identical token streams and the
obs counters can be asserted exactly.  Arrivals are expressed in
*virtual seconds* — the tests drive the engine with a virtual clock and
submit each item when the clock passes ``submit_at`` (Poisson-ish via
seeded exponential gaps, the standard open-loop load model).
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["LoadItem", "generate_load"]


@dataclasses.dataclass(frozen=True)
class LoadItem:
    """One scheduled request of a load trace."""

    submit_at: float       # virtual seconds from trace start
    prompt: tuple          # token ids
    max_new_tokens: int
    deadline_s: float | None = None


def generate_load(seed: int, n_requests: int, *, vocab: int,
                  prompt_len=(2, 24), max_new=(1, 12),
                  mean_gap_s: float = 0.002,
                  deadline_s: float | None = None) -> list:
    """A seeded open-loop trace of ``n_requests`` ragged requests.

    ``prompt_len``/``max_new`` are inclusive (lo, hi) ranges sampled
    uniformly; arrivals accumulate seeded exponential gaps with mean
    ``mean_gap_s``.  Same seed, same trace — bit for bit.
    """
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for _ in range(n_requests):
        t += float(rng.exponential(mean_gap_s))
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        out.append(LoadItem(
            submit_at=t,
            prompt=tuple(int(x) for x in rng.integers(0, vocab, plen)),
            max_new_tokens=int(rng.integers(max_new[0], max_new[1] + 1)),
            deadline_s=deadline_s))
    return out
