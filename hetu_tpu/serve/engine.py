"""``ServingEngine``: the online-inference driver.

Marries the decode seams (``models/gpt.py`` ``kv_cache=``/``cache_index=``)
to the paged pool and the continuous batcher, and carries the two serving
workloads the stack trains:

- **Generation** — seeded greedy/top-k sampling over a GPT.  Prefill
  runs the bucketed gather step at ``(1, bucket_len)`` (one compile per
  prompt bucket, once per request).  Decode — the per-token hot path —
  defaults to the PAGED step at the fixed ``(num_slots, 1)`` shape: the
  Pallas paged-decode kernel (ops/pallas/paged_decode.py) attends in
  place over the pool's page tables, so the per-step contiguous
  ``(L, batch, max_len, H, D)`` gather/scatter of the whole KV history —
  the dominant decode HBM traffic at long context — never happens
  (``kv_cache.gather_view_count`` proves the decode program traces zero
  views).  Sampling fuses into the LM head
  (ops/pallas/lm_head.py ``lm_head_sample_pallas``): the decode logits
  never materialize in HBM for greedy/top-k (temperature mode streams
  its bitwise-exact gumbel field instead — a wash, not a win).
  ``paged_decode=False`` restores the gather path.  Sampling keys derive from ``(seed, request.id,
  position)`` in both paths, so a request's token stream is a pure
  function of the seed and its own prompt — independent of which
  neighbors shared its batch.  Two same-seed runs of the same schedule
  produce bitwise-identical streams; the acceptance test asserts it.

- **CTR inference** — :meth:`infer_ctr` pulls embedding rows READ-ONLY
  through the model's existing HET stores (``CacheTable`` /
  ``RemoteEmbeddingTable``): stage-then-forward, never a gradient push.
  Local ``CacheTable`` stores are flipped to ``read_only`` at engine
  construction so a miswired training step raises instead of silently
  updating the table.  Remote pulls keep riding ``embed.net._rpc`` — the
  ``exec/faults.py`` PS seams stay injectable, so a socket kill under
  load must surface as a counted redial, not a wrong answer.

Telemetry (lazily registered, all no-ops when obs is disabled): queue
depth and active-slot gauges, TTFT and per-token latency histograms,
token/request counters by outcome, tokens/s gauge; admission rejections
are journaled (``serve_reject``) and deadline expiries are counted by
stage (``hetu_serve_deadline_expired_total{stage=queued|running}``) and
journaled (``request_expired``).  **Request-scope observability**: every
request carries a :class:`~hetu_tpu.obs.reqtrace.RequestTimeline` —
spans for queue wait, admission, prefill, each decode iteration (batch
composition in the attrs), and emit, with the stage decomposition
summing to wall time exactly — finished timelines land in
``self.trace_buffer`` (ring + slowest-N exemplars, ``/trace/<id>``) and
are graded by ``self.slo`` (:class:`~hetu_tpu.obs.slo.SLOEngine`:
TTFT/TPOT/queue-age targets, burn rates, shed pressure on ``/slo``).
The three jitted step functions are compile-counting seams
(:func:`obs.compile.instrument`, AOT): ``serve.prefill_step`` /
``serve.paged_decode`` / ``serve.sample`` own their program caches, so
``hetu_compile_total`` is exact and a recompile storm is a gauge.  The
clock is injectable — the deterministic tests drive a virtual clock,
production defaults to ``time.monotonic``.

**Fleet tier** (serve/fleet): ``prefix_sharing=True`` attaches a
per-engine :class:`~hetu_tpu.serve.fleet.prefix.PrefixSharer` — prompt
prefixes alias shared refcounted KV pages and prefill computes only the
unshared suffix; ``draft_model=`` swaps the decode step for
propose-and-verify speculation
(:class:`~hetu_tpu.serve.fleet.spec.SpeculativeDecoder`, paged path
only) with accepted streams bitwise identical to the non-speculative
run; a :class:`~hetu_tpu.serve.fleet.router.FleetRouter` places
requests across N engines by trie affinity and shed pressure
(``RequestHandle.shed_reason`` marks re-routable rejections).

**Disaggregated serving** (serve/fleet/disagg.py): ``role=`` splits the
fleet into prefill workers (compute-bound: prefill, sample the first
token, then MIGRATE the KV pages to a decode worker and recycle the
slot immediately) and decode workers (memory-bound: ingest verified
migration records — or re-prefill on a corrupt one — and decode without
ever being stalled by a long-prompt burst); ``colocated`` (the default)
is the classic timeslicing engine.  Because sampling keys derive from
``(seed, request id, position)`` and migration preserves
``cache_index``/lengths exactly, a migrated stream is bitwise identical
to its colocated same-seed twin — the PR 13 guarantee carried across a
worker boundary.  ``prefill_tick_cost`` enables the virtual-time
timeslice model the deterministic A/B tests and benches drive
(``HETU_TPU_DISAGG_ROLE`` / ``HETU_TPU_DISAGG_PREFILL_COST`` back the
kwargs).

Deadlines: ``deadline_s`` bounds a request's total age.  A request past
its deadline while still *queued* is dropped before admission (stage
``queued``); one that exceeds it while *running* is retired at the next
scheduler tick with the tokens generated so far (stage ``running``) —
serving it further would be serving it late.  Both resolve the handle
with status ``expired`` and a human-readable ``error``.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.exec import controller as _controller
from hetu_tpu.exec import faults as _faults
from hetu_tpu.obs import compile as _compile
from hetu_tpu.obs import journal as _journal
from hetu_tpu.obs import numerics as _numerics
from hetu_tpu.obs import registry as _obs
from hetu_tpu.obs import tracing as _tracing
from hetu_tpu.obs.reqtrace import ReqTraceBuffer, RequestTimeline
from hetu_tpu.obs.slo import SLOEngine
from hetu_tpu.ops.pallas.lm_head import lm_head_sample_pallas
from hetu_tpu.ops.random import (greedy_sample, temperature_sample,
                                 top_k_sample)
from hetu_tpu.serve.batcher import (AdmissionQueueFull, AdmissionShed,
                                    ContinuousBatcher, Request,
                                    TenantQuotaExceeded)
from hetu_tpu.serve.tenant import (DEFAULT_TENANT, TenantMeter,
                                   TenantPolicy, _tenant_m)
from hetu_tpu.serve import kv_cache as _kv
from hetu_tpu.serve.kv_cache import (KVCachePool, OutOfPages, gather_views,
                                     scatter_views)

__all__ = ["ServingEngine", "RequestHandle"]

_serve_metrics = None


def _serve_m() -> dict:
    global _serve_metrics
    if _serve_metrics is None:
        reg = _obs.get_registry()
        _serve_metrics = {
            "requests": reg.counter(
                "hetu_serve_requests_total",
                "serving requests by outcome (admitted at slot placement; "
                "every submitted request ends completed, rejected, "
                "expired, or — under an overcommitted pool — evicted)",
                ("outcome",)),
            "tokens": reg.counter(
                "hetu_serve_tokens_total", "generated tokens"),
            "queue": reg.gauge(
                "hetu_serve_queue_depth", "requests waiting for a slot"),
            "slots": reg.gauge(
                "hetu_serve_active_slots", "slots currently decoding"),
            "ttft": reg.histogram(
                "hetu_serve_ttft_seconds",
                "time to first token (arrival -> prefill sample)"),
            "tok_latency": reg.histogram(
                "hetu_serve_token_latency_seconds",
                "per-token decode latency (one batched step amortized "
                "over its active slots)"),
            "tps": reg.gauge(
                "hetu_serve_tokens_per_second",
                "decode throughput over the last step"),
            "ctr": reg.counter(
                "hetu_serve_ctr_requests_total", "CTR inference batches"),
            "deadline": reg.counter(
                "hetu_serve_deadline_expired_total",
                "requests dropped at their deadline, by the stage they "
                "were in (queued: expired waiting for a slot; running: "
                "cut off mid-decode, keeping the tokens generated)",
                ("stage",)),
            "shed": reg.counter(
                "hetu_serve_shed_total",
                "admission rejections that were load shedding, by cause "
                "(controller: the runtime controller's sustained-SLO-"
                "burn latch — global or tenant-scoped; queue_full: the "
                "per-tenant depth limit; bucket_freeze: prompt-bucket "
                "growth frozen during a compile storm; quota: the "
                "tenant's token bucket) and by submitting tenant "
                "(single-tenant deployments only ever emit "
                "tenant=\"default\")", ("reason", "tenant")),
        }
    return _serve_metrics


class RequestHandle:
    """Caller-side future for one generation request."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.trace_id = f"req-{request_id}"   # reqtrace derivation: the
        # handle can name its /trace/<id> timeline before resolving
        self._done = threading.Event()
        # completed | rejected | expired | evicted (overcommitted pool only)
        self.status: Optional[str] = None
        self.tokens: list = []
        self.ttft_s: Optional[float] = None
        self.latency_s: Optional[float] = None
        self.error: Optional[str] = None   # human-readable failure reason
        # set on LOAD-SHEDDING rejections only ("controller" |
        # "queue_full" | "bucket_freeze" | "quota"): the fleet router
        # re-routes the first three to another replica; validation
        # rejections (None) would fail identically everywhere and quota
        # rejections are the tenant's own contract (re-routing would be
        # quota evasion) — both are returned as-is
        self.shed_reason: Optional[str] = None
        # multi-tenant front door: the resolved submitting tenant's id,
        # and — on shed/quota rejections — the deterministic backoff
        # hint /infer surfaces as retry_after_s
        self.tenant: Optional[str] = None
        self.retry_after_s: Optional[float] = None
        # deterministic uint32 fingerprint of the token stream
        # (obs.numerics.host_fingerprint_ints): two same-seed runs of the
        # same schedule must agree — a mismatch in prod IS sampler
        # nondeterminism, detectable from the /infer response alone
        self.stream_fingerprint: Optional[int] = None

    def _finish(self, status: str, tokens=(), ttft_s=None, latency_s=None,
                error=None, stream_fingerprint=None):
        self.status = status
        self.tokens = list(tokens)
        self.ttft_s = ttft_s
        self.latency_s = latency_s
        self.error = error
        self.stream_fingerprint = stream_fingerprint
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


class ServingEngine:
    """Continuous-batching inference over one GPT (and optionally one CTR
    model sharing the process' HET stores)."""

    def __init__(self, model, *, num_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None, queue_depth: int = 64,
                 prompt_buckets=None,
                 sampling: str = "greedy", top_k: int = 5,
                 temperature: float = 1.0, eos_id: Optional[int] = None,
                 seed: int = 0, clock=time.monotonic,
                 defrag_every: int = 0, ctr_model=None,
                 paged_decode: bool = True,
                 fused_sampling: Optional[bool] = None,
                 slo_targets=None, trace_capacity: int = 256,
                 trace_slow_n: int = 8, trace_window: int = 128,
                 controller=None, prefix_sharing: Optional[bool] = None,
                 draft_model=None, spec_k: Optional[int] = None,
                 role: Optional[str] = None,
                 prefill_tick_cost: Optional[float] = None,
                 ctr_follower=None, tenants: Optional[TenantPolicy] = None,
                 plan=None):
        cfg = model.config
        self.model = model
        self.eos_id = eos_id
        # Plan-bearing construction (hetu_tpu/plan): the plan's serving
        # axes fill every knob the caller left unset — explicit kwargs
        # always win, so a plan composes with manual overrides.  spec_k
        # applies only when a draft model exists to speculate with.
        self.plan = plan
        if plan is not None:
            if num_slots is None:
                num_slots = plan.slots_per_replica
            if page_size is None and plan.page_size > 0:
                page_size = plan.page_size
            if prompt_buckets is None and plan.bucket_ladder:
                prompt_buckets = plan.bucket_ladder
            if num_pages is None and plan.kv_pool_pages > 0:
                num_pages = plan.kv_pool_pages
            if spec_k is None and plan.spec_k > 0 \
                    and draft_model is not None:
                spec_k = plan.spec_k
        # the historical defaults, applied after the plan merge
        num_slots = 8 if num_slots is None else int(num_slots)
        page_size = 16 if page_size is None else int(page_size)
        if prompt_buckets is None:
            prompt_buckets = (8, 16, 32, 64, 128)
        # disaggregated serving (serve/fleet/disagg.py): the worker ROLE.
        # "colocated" (default) timeslices prefill and decode on this
        # engine; "prefill" hands every freshly prefilled request's KV
        # pages to a decode worker through the router-installed
        # ``migrate_out`` hook; "decode" only ever decodes (migrated
        # requests arrive via accept_migration; re-prefill is the
        # verify-failure fallback).  HETU_TPU_DISAGG_ROLE backs the kwarg
        # — one env block configures every worker, the fleet convention.
        if role is None:
            role = os.environ.get("HETU_TPU_DISAGG_ROLE", "colocated")
        if role not in ("prefill", "decode", "colocated"):
            raise ValueError(f"unknown role {role!r}; one of 'prefill', "
                             f"'decode', 'colocated'")
        self.role = role
        # virtual-time cost model for the deterministic fleet ticks: a
        # prefill of bucket B makes this engine BUSY for
        # ceil(B * prefill_tick_cost) scheduler ticks (admission and
        # decode both skip — the chip is crunching the prefill), so the
        # simulation reproduces the timeslice stall a colocated chip
        # pays and a disaggregated decode worker never does.  0 (the
        # default) disables the model entirely: production engines on a
        # real clock measure real compute instead.
        if prefill_tick_cost is None:
            prefill_tick_cost = float(os.environ.get(
                "HETU_TPU_DISAGG_PREFILL_COST", "0") or 0)
        self.prefill_tick_cost = float(prefill_tick_cost)
        self._busy_ticks = 0
        self._tick_prefill_charge = 0
        # router-installed migration hook (role "prefill" only):
        # called as migrate_out(engine, request, record) -> bool
        self.migrate_out = None
        # migration settle callbacks (export-hold acks against the
        # SOURCE pool) deferred to run outside this engine's lock — a
        # decode worker settling while a prefill worker migrates to it
        # must not deadlock on crossed engine locks
        self._pending_settles: list = []
        self._migrations = {"out": 0, "in": 0, "reprefill": 0}
        if sampling not in ("greedy", "top_k", "temperature"):
            raise ValueError(f"unknown sampling mode {sampling!r}; one of "
                             f"'greedy', 'top_k', 'temperature'")
        if sampling == "top_k" and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        self.sampling = sampling
        self.top_k = top_k
        self.temperature = temperature
        self.clock = clock
        self.defrag_every = defrag_every
        self.max_seq_len = min(max_seq_len or cfg.max_seq_len,
                               cfg.max_seq_len)
        if self.max_seq_len % page_size:
            self.max_seq_len -= self.max_seq_len % page_size
        pages_per_seq = self.max_seq_len // page_size
        self.pool = KVCachePool(
            num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            num_pages=(num_pages if num_pages is not None
                       else 1 + num_slots * pages_per_seq),
            page_size=page_size, max_seq_len=self.max_seq_len,
            dtype=cfg.dtype)
        buckets = tuple(b for b in sorted(prompt_buckets)
                        if b <= self.max_seq_len) or (self.max_seq_len,)
        # multi-tenant front door: the tenant policy (priority classes,
        # WFQ weights, quota buckets) feeds the batcher's weighted-fair
        # admission; share ONE TenantPolicy across a fleet's replicas
        # and the token buckets become fleet-wide quotas.  None = every
        # caller is the default tenant (the exact pre-tenant FIFO).
        self.batcher = ContinuousBatcher(num_slots, queue_depth=queue_depth,
                                         prompt_buckets=buckets,
                                         policy=tenants)
        # per-tenant usage metering (tokens, KV pages, compile-seconds,
        # outcomes) — the billing artifact behind /tenants
        self.tenant_meter = TenantMeter()
        # tenant ids whose queue-depth gauge has been published at least
        # once (so drained tenants can be zeroed on later steps)
        self._tenant_depth_published: set = set()
        self._base_key = jax.random.PRNGKey(seed)
        self._lock = threading.RLock()
        self._handles: dict = {}
        self._next_id = 0
        self._recycled = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # request-scope observability: one timeline per in-flight request,
        # finished timelines into the ring/exemplar buffer and the SLO
        # engine (both driven by the engine's own injectable clock, so
        # same-seed runs produce bitwise-identical timelines)
        self.trace_buffer = ReqTraceBuffer(capacity=trace_capacity,
                                           slow_n=trace_slow_n,
                                           window=trace_window)
        self.slo = SLOEngine(slo_targets, clock=clock)
        self._timelines: dict = {}
        # the jit seams are compile-counting (obs.compile AOT: the
        # instrumented cache IS the program cache, so hetu_compile_total
        # is exact and a recompile storm is a gauge, not a bench round)
        self._step_fn = _compile.instrument(jax.jit(self._step_impl),
                                            site="serve.prefill_step")
        self._sample_fn = _compile.instrument(jax.jit(self._sample_impl),
                                              site="serve.sample")
        self.paged_decode = bool(paged_decode)
        if fused_sampling is None:
            # the fused sampler's streamed top-k holds at most 128
            # candidates in its lane-wide scratch; wider top-k falls back
            # to XLA logits + the row sampler (still paged attention)
            fused_sampling = (sampling != "top_k"
                              or min(top_k, cfg.vocab_size) <= 128)
        self._fused_sampling = bool(fused_sampling)
        self._paged_step_fn = _compile.instrument(
            jax.jit(self._paged_decode_impl), site="serve.paged_decode")
        self.ctr_model = ctr_model
        if ctr_model is not None:
            _mark_stores_read_only(ctr_model)
        # streaming freshness (embed.stream): a SnapshotFollower over the
        # CTR model's stores — infer_ctr gates on it, so training pushes
        # reach this read-only replica within the staleness bound without
        # the stores ever training in place
        if ctr_follower is not None and ctr_model is None:
            raise ValueError("ctr_follower needs a ctr_model to install "
                             "snapshots into")
        self.ctr_follower = ctr_follower
        # closed-loop remediation (exec.controller): the attached (or
        # process-wide installed) RuntimeController runs once per
        # scheduler tick — shed latch on sustained SLO burn, bucket
        # freeze under a compile storm.  With neither, the tick seam is
        # one attribute + one global load and a branch.
        self.controller = controller
        # while frozen, prompts needing a prefill bucket that has not
        # compiled yet are rejected instead of feeding the storm
        self.freeze_bucket_growth = False
        self._prefill_buckets: set = set()
        self._tick = 0
        # serving fault tolerance (serve/fleet/failover.py): the
        # heartbeat the monitor leases against, and the injected failure
        # modes.  _beat advances once per HEALTHY scheduler tick (a
        # crashed or hung engine's beat freezes — that IS the failure
        # signal); crash() is permanent, hang(n) is silence for n ticks.
        self._beat = 0
        self._crashed = False
        self._hang_ticks = 0
        # router-installed ledger hooks: on_token(rid, tok) after every
        # emitted token, on_finish(rid) when a handle resolves — both
        # called under this engine's lock, so they must stay tiny
        self.on_token = None
        self.on_finish = None
        # fleet tier (serve/fleet): copy-on-write prefix sharing maps
        # identical prompt prefixes to shared refcounted KV pages, and a
        # draft model turns decode into propose-and-verify (bitwise
        # identical streams).  Lazy imports: serve.fleet imports this
        # module's types back.
        # HETU_TPU_FLEET_* env knobs back the explicit kwargs (the fleet
        # deployment story: one env block configures every replica)
        if prefix_sharing is None:
            prefix_sharing = os.environ.get(
                "HETU_TPU_FLEET_PREFIX_SHARE", "0") not in ("0", "", "false")
        if spec_k is None:
            spec_k = int(os.environ.get("HETU_TPU_FLEET_SPEC_K", "4"))
        self.sharer = None
        if prefix_sharing:
            from hetu_tpu.serve.fleet.prefix import PrefixSharer
            self.sharer = PrefixSharer(self.pool)
        self.spec = None
        if draft_model is not None:
            if not self.paged_decode:
                raise ValueError(
                    "speculative decoding requires paged_decode=True: "
                    "chained verify rows share one page table, which "
                    "only element-scattered paged K/V writes compose "
                    "(the gather path scatters whole per-row page "
                    "copies back — chained rows would clobber each "
                    "other)")
            from hetu_tpu.serve.fleet.spec import SpeculativeDecoder
            self.spec = SpeculativeDecoder(
                draft_model, spec_k, num_slots=num_slots,
                max_len=self.max_seq_len)

    # -- jitted compute -----------------------------------------------------

    def _step_impl(self, model, k, v, page_idx, cache_index, tokens,
                   seq_lengths):
        """One serving step at any bucket shape: gather the paged views,
        run the model's incremental path, scatter the updated KV back.
        Prefill and decode differ only in the shapes they call this at."""
        k_view, v_view = gather_views(k, v, page_idx)
        kv = [(k_view[i], v_view[i]) for i in range(self.pool.num_layers)]
        logits, new_kv = model(tokens, kv_cache=kv, cache_index=cache_index,
                               seq_lengths=seq_lengths)
        k_upd = jnp.stack([kv_l[0] for kv_l in new_kv])
        v_upd = jnp.stack([kv_l[1] for kv_l in new_kv])
        k, v = scatter_views(k, v, page_idx, k_upd, v_upd)
        return logits, k, v

    def _paged_decode_impl(self, model, k, v, page_tables, lengths, tokens,
                           request_ids, positions):
        """The paged decode step: attention reads K/V pages IN PLACE via
        the page tables (Pallas paged-decode kernel), each layer's new
        K/V lands with one small scatter, and sampling fuses into the
        LM-head kernel — neither the contiguous KV views nor the (slots,
        vocab) logits ever materialize.  Same key derivation as
        :meth:`_sample_impl`, so streams stay bitwise-reproducible."""
        x, (k, v) = model.hidden_states(
            tokens, kv_cache=(k, v), cache_index=lengths,
            paged_tables=page_tables)
        last = x[:, -1]
        head = model._head().astype(last.dtype)
        if self._fused_sampling:
            keys = None
            if self.sampling != "greedy":
                keys = jax.vmap(lambda r, p: jax.random.fold_in(
                    jax.random.fold_in(self._base_key, r), p))(
                    request_ids, positions)
            toks = lm_head_sample_pallas(
                last, head, mode=self.sampling, top_k=self.top_k,
                temperature=self.temperature, keys=keys)
        else:
            toks = self._sample_impl(last @ head, request_ids, positions)
        return toks, k, v

    def _sample_impl(self, logits, request_ids, positions):
        """Per-row seeded sampling (vmapped: one dispatch per step).  Keys
        derive INSIDE the jitted program from ``(seed, request id, token
        position)``, so batch composition cannot perturb any request's
        stream and the host loop ships two int32 vectors, not keys."""
        if self.sampling == "greedy":
            return greedy_sample(logits)

        def row(lg, rid, pos):
            key = jax.random.fold_in(
                jax.random.fold_in(self._base_key, rid), pos)
            if self.sampling == "temperature":
                return temperature_sample(lg, self.temperature, key=key)
            return top_k_sample(lg, self.top_k, self.temperature, key=key)

        return jax.vmap(row)(logits, request_ids, positions)

    # -- submission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 16, *,
               deadline_s: Optional[float] = None,
               request_id: Optional[int] = None,
               tenant=None) -> RequestHandle:
        """Queue one generation request; never blocks.  Returns a handle
        that resolves when the request completes, is rejected (queue
        depth / quota / too long), or expires at its deadline.

        ``tenant`` names the submitting tenant (an id string or a
        :class:`~hetu_tpu.serve.tenant.Tenant`; ``None`` = the default
        tenant, the exact pre-tenant path): admission runs weighted-fair
        over per-tenant sub-queues, quota buckets gate the front door
        (:class:`TenantQuotaExceeded` -> status ``rejected`` with
        ``shed_reason="quota"`` and a ``retry_after_s`` backoff hint),
        and the controller's scoped shed latch can close ONE tenant's
        door.

        ``request_id`` pins the id instead of drawing from this engine's
        counter — the disaggregated router's seam: token streams are a
        pure function of ``(seed, request id, prompt)``, so a router that
        assigns GLOBAL ids in submission order makes a migrated stream
        bitwise comparable to its colocated same-seed twin."""
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        with self._lock:
            if request_id is None:
                rid = self._next_id
            else:
                rid = int(request_id)
                if rid in self._handles:
                    raise ValueError(f"request id {rid} is already in "
                                     f"flight on this engine")
            self._next_id = max(self._next_id, rid + 1)
            handle = RequestHandle(rid)
            ten = self.batcher.policy.resolve(tenant)
            handle.tenant = ten.id
            is_default = ten.id == DEFAULT_TENANT.id
            req = Request(id=rid, prompt=prompt,
                          max_new_tokens=int(max_new_tokens),
                          arrival=self.clock(), deadline_s=deadline_s,
                          tenant=None if is_default else ten.id)
            # tenant attrs only on non-default traffic, so a pre-tenant
            # deployment's timelines/spans stay bit-identical
            tattrs = {} if is_default else {"tenant": ten.id,
                                            "tenant_class": ten.klass}
            tl = RequestTimeline(rid, req.arrival, prompt_len=len(prompt),
                                 max_new_tokens=req.max_new_tokens,
                                 **tattrs)
            reason = None
            shed_reason = None  # set when the rejection is LOAD SHEDDING
            retry_after = None  # the /infer backoff hint, shed only
            max_bucket = self.batcher.prompt_buckets[-1]
            if not prompt:
                reason = "empty prompt"
            elif req.max_new_tokens < 1:
                reason = (f"max_new_tokens must be >= 1, got "
                          f"{req.max_new_tokens}")
            elif req.total_budget > self.max_seq_len:
                reason = (f"prompt+budget {req.total_budget} exceeds "
                          f"max_seq_len {self.max_seq_len}")
            elif len(prompt) > max_bucket:
                reason = (f"prompt of {len(prompt)} tokens exceeds the "
                          f"largest prefill bucket {max_bucket}")
            elif self.freeze_bucket_growth and \
                    self.batcher.bucket_for(len(prompt)) \
                    not in self._prefill_buckets:
                reason = (
                    f"prompt bucket "
                    f"{self.batcher.bucket_for(len(prompt))} not yet "
                    f"compiled and bucket growth is frozen (compile "
                    f"storm); warm buckets: "
                    f"{sorted(self._prefill_buckets)}")
                shed_reason = "bucket_freeze"
            if reason is None:
                try:
                    self.batcher.submit(req)
                except TenantQuotaExceeded as e:
                    # before AdmissionShed/QueueFull: it subclasses them
                    reason, shed_reason = str(e), "quota"
                    retry_after = round(e.retry_after_s, 6)
                except AdmissionShed as e:
                    reason, shed_reason = str(e), "controller"
                except AdmissionQueueFull as e:
                    reason, shed_reason = str(e), "queue_full"
            if reason is not None:
                _serve_m()["requests"].labels(outcome="rejected").inc()
                self.tenant_meter.note_outcome(ten.id, "rejected")
                if shed_reason is not None:
                    if retry_after is None:
                        retry_after = self._retry_hint(shed_reason)
                    self.tenant_meter.note_shed(ten.id, shed_reason)
                    _serve_m()["shed"].labels(reason=shed_reason,
                                              tenant=ten.id).inc()
                    _journal.record("shed", request_id=rid,
                                    reason=shed_reason,
                                    queue_depth=self.batcher.queue_len,
                                    **({} if is_default
                                       else {"tenant": ten.id}))
                    if shed_reason == "quota":
                        _journal.record("tenant_quota", request_id=rid,
                                        tenant=ten.id,
                                        retry_after_s=retry_after)
                _journal.record("serve_reject", request_id=rid,
                                reason=reason,
                                queue_depth=self.batcher.queue_len,
                                **({} if is_default
                                   else {"tenant": ten.id}))
                # a zero-length timeline still lands in the trace buffer
                # (a rejection is queryable forensics too), but it is NOT
                # graded: it never entered the serving pipeline, so it
                # must not consume SLO error budget
                tl.close("rejected", req.arrival, reason=reason)
                self._finalize_timeline(tl, grade=False)
                handle.shed_reason = shed_reason
                handle.retry_after_s = retry_after
                handle._finish("rejected", error=reason)
                return handle
            self._handles[rid] = handle
            self._timelines[rid] = tl
            _serve_m()["queue"].set(self.batcher.queue_len)
        return handle

    def _retry_hint(self, shed_reason: str) -> float:
        """The deterministic ``retry_after_s`` backoff hint for non-quota
        sheds (quota rejections carry the bucket's exact refill time
        instead).  ``controller``: scale with how far past the engage
        threshold the burn is (pressure 1.0 -> back off a long window's
        worth of tenths); ``queue_full``: one scheduler wave per queued
        batch ahead; ``bucket_freeze``: the storm detector's cool-down
        order of magnitude.  All pure functions of current deterministic
        state — same trace, same hints."""
        if shed_reason == "controller":
            return round(0.1 + self.slo.shed_pressure() *
                         self.slo.short_window_s / 10.0, 6)
        if shed_reason == "queue_full":
            waves = -(-self.batcher.queue_len
                      // max(self.batcher.num_slots, 1))
            return round(0.05 * max(waves, 1), 6)
        return 1.0  # bucket_freeze: wait out the compile storm

    # -- the scheduler loop -------------------------------------------------

    def step(self) -> int:
        """One scheduler tick: expire, admit+prefill (or ingest a
        migrated request's KV pages), one decode step.  Returns the
        number of tokens produced (0 when idle, or while the virtual
        prefill-cost model holds the engine busy)."""
        with self._lock:
            produced = self._step_locked()
        # settle migration export holds OUTSIDE this engine's lock: the
        # settle acquires the SOURCE engine's lock, and a prefill worker
        # migrating into this engine holds its own lock while taking
        # ours — nesting the other direction too would deadlock
        while True:
            try:
                settle = self._pending_settles.pop(0)
            except IndexError:
                break
            settle()
        return produced

    def _step_locked(self) -> int:
        self._tick += 1
        if self._crashed:
            # a crashed replica does nothing and — critically — does not
            # beat: the failover monitor reads the frozen heartbeat and
            # declares it lost after its lease expires
            return 0
        if self._hang_ticks > 0:
            # a hung replica is silent (no beat, no work) for the
            # injected span, then recovers on its own — the flap the
            # controller's quarantine hysteresis exists for
            self._hang_ticks -= 1
            return 0
        self._beat += 1
        plan = _faults.active_plan()
        if plan is not None:
            # chaos seam: a scheduled compile_storm fault notes `arg`
            # synthetic distinct-shape compiles (default: enough to
            # cross the threshold) into the process storm detector —
            # the deterministic stand-in for an unbucketed-shape
            # flood.  Only this kind is consumed here; the training
            # harnesses keep their own conventions.
            f = plan.take("compile_storm", late_ok=True, now=self._tick)
            if f is not None:
                storm = _compile.get_storm()
                for _ in range(int(f.arg or storm.threshold + 1)):
                    storm.note("fault_injection")
        _controller.maybe_serve_tick(self)
        m = _serve_m()
        if self._busy_ticks > 0:
            # the virtual prefill-cost model: the chip is still crunching
            # an earlier prefill — no admission, no decode this tick.
            # This is the timeslice stall a colocated worker pays under a
            # long-prompt burst and a disaggregated decode worker never
            # sees (its role never prefills).
            self._busy_ticks -= 1
            return 0
        now = self.clock()
        # reserving gate: poll admits several requests before any of
        # them allocates, so the budget must be decremented as each
        # one passes — gating on live pool state alone would overcommit
        budget = self.pool.free_pages

        def gate(r):
            nonlocal budget
            need = self.pool.pages_needed(len(r.prompt))
            if need > budget and self.sharer is not None:
                # cached prefixes are a loan: evict trie-only pages
                # (least-recently-matched first) to admit real work
                budget += self.sharer.reclaim(need - budget)
            if need > budget:
                return False
            budget -= need
            return True

        tick = self.batcher.poll(now, can_admit=gate)
        for req in tick.expired:
            waited = now - req.arrival
            if req.migration is not None:
                # a migrated request expired waiting for a decode slot:
                # its KV never imported — settle the source's export hold
                self._pending_settles.append(req.migration.settle)
            _journal.record("request_expired", request_id=req.id,
                            stage="queued", waited_s=round(waited, 6))
            m["requests"].labels(outcome="expired").inc()
            m["deadline"].labels(stage="queued").inc()
            self.tenant_meter.note_outcome(req.tenant_id, "expired")
            tl = self._timelines.pop(req.id)
            tl.close("expired", now, stage="queued")
            self._finalize_timeline(tl)
            self._handles.pop(req.id)._finish(
                "expired",
                error=f"deadline of {req.deadline_s}s expired after "
                      f"{waited:.6g}s in the admission queue")
            if self.on_finish is not None:
                self.on_finish(req.id)
        for req in tick.admitted:
            if req.migration is not None:
                # a migrated request enters a decode slot: import its KV
                # (or re-prefill on a corrupt record) — it was already
                # counted admitted by the prefill worker
                self._ingest_migration(req, now)
                continue
            m["requests"].labels(outcome="admitted").inc()
            self.tenant_meter.note_outcome(req.tenant_id, "admitted")
            self._timelines[req.id].admit(
                now, slot=req.slot, queue_depth=self.batcher.queue_len)
            self._prefill(req, now)
            if (self.role == "prefill" and self.migrate_out is not None
                    and req.id in self._handles):
                self._migrate_after_prefill(req)
        # a running request past its deadline is cut off here, with
        # the tokens it has — serving it further is serving it late
        for _slot, req in self.batcher.active():
            if req.expired(now):
                self._retire(req, "expired", now)
        charge = self._tick_prefill_charge
        self._tick_prefill_charge = 0
        if charge > 0:
            # this tick was spent prefilling (the first busy tick);
            # decode resumes when the remaining charge drains
            self._busy_ticks += charge - 1
            produced = 0
        else:
            produced = self._decode()
        m["queue"].set(self.batcher.queue_len)
        m["slots"].set(self.batcher.active_slots)
        # per-tenant depth gauges only once real multi-tenant traffic
        # exists (a pre-tenant deployment's metric surface is unchanged);
        # drained tenants are zeroed, not dropped, so dashboards see the
        # flood subside rather than a vanishing series
        lens = self.batcher.queue_lens()
        if any(tid != DEFAULT_TENANT.id for tid in lens) \
                or self._tenant_depth_published:
            tq = _tenant_m()["queue"]
            for tid in self._tenant_depth_published - set(lens):
                tq.labels(tenant=tid).set(0)
            for tid, n in lens.items():
                tq.labels(tenant=tid).set(n)
            self._tenant_depth_published |= set(lens)
        return produced

    def run_until_idle(self, max_steps: int = 100000) -> None:
        for _ in range(max_steps):
            self.step()
            if self.batcher.idle:
                return
        raise RuntimeError(f"not idle after {max_steps} scheduler steps")

    def start(self, poll_interval: float = 0.001) -> "ServingEngine":
        """Run the scheduler on a daemon thread (the HTTP-serving mode)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                with self._lock:
                    idle = self.batcher.idle
                if idle:
                    time.sleep(poll_interval)
                else:
                    self.step()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hetu-serve-engine")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(10)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- phases -------------------------------------------------------------

    def _prefill(self, req: Request, now: float) -> None:
        """Right-pad the prompt (or, under prefix sharing, just its
        unshared suffix) to its bucket, run one (1, bucket) step at
        ``cache_index = shared_tokens``, sample the first token at the
        prompt's true last position.

        With a trie hit, the table's leading entries alias the shared
        pages — their K/V is already written, so the step computes and
        writes ONLY the suffix pages (the ``pages_written`` seam counts
        them: an identical-prefix request writes zero duplicate prefix
        pages).  The sampled position and its key are the same either
        way, shared or not."""
        plen = len(req.prompt)
        shared_pages, shared_len = (), 0
        if self.sharer is not None:
            # trim the share so shared + suffix-bucket FITS the serving
            # window: the gathered view is max_seq_len tokens, and a
            # ragged write past it would be clamp-shifted back INTO the
            # shared prefix pages (dynamic_update_slice clamps), then
            # scattered back — corrupting the cached K/V for every alias
            m = self.sharer.match_tokens(req.prompt)
            while m and m + self.batcher.bucket_for(plen - m) \
                    > self.max_seq_len:
                m -= self.pool.page_size
            # under a compile-storm freeze, a COLD suffix bucket must not
            # slip past the admission gate (which checked the full-prompt
            # bucket): drop sharing, the full-prompt bucket is warm
            if m and self.freeze_bucket_growth and \
                    self.batcher.bucket_for(plen - m) \
                    not in self._prefill_buckets:
                m = 0
            shared_pages, shared_len = self.sharer.lookup(req.prompt, m)
        suffix = req.prompt[shared_len:]
        bucket = self.batcher.bucket_for(len(suffix))
        self._prefill_buckets.add(bucket)  # warm: survives a freeze
        # compile-seconds metering: whatever XLA compiles during THIS
        # prefill (a cold bucket, typically) is billed to the tenant
        # whose request warmed it — measured wall time, billing data
        # only, never part of the replay surfaces
        compile_before = self._compile_seconds()
        if self.prefill_tick_cost > 0:
            # virtual-time cost model: this prefill occupies the chip for
            # ceil(bucket * cost) scheduler ticks (consumed in step())
            self._tick_prefill_charge += max(
                1, math.ceil(bucket * self.prefill_tick_cost))
        self.pool.alloc(req.id, plen, shared_pages=shared_pages,
                        owner=req.tenant_id)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(suffix)] = suffix
        logits, k, v = self._step_fn(
            self.model, self.pool.k, self.pool.v,
            self.pool.gather_indices([req.id]),
            jnp.asarray([shared_len], jnp.int32), jnp.asarray(tokens),
            jnp.asarray([len(suffix)], jnp.int32))
        self.pool.commit(k, v)
        # the bucket's pad positions wrote garbage K/V beyond plen; the
        # table's length stays plen, so decode overwrites them in turn
        self.pool.table(req.id).length = plen
        _kv.note_pages_written(
            self.pool.pages_needed(plen) - len(shared_pages))
        if self.sharer is not None:
            if shared_len:
                _journal.record("prefix_share", request_id=req.id,
                                shared_tokens=shared_len, prompt_len=plen)
            self.sharer.publish(req.prompt, self.pool.table(req.id))
        tok = int(self._sample_fn(
            logits, jnp.asarray([req.id], jnp.int32),
            jnp.asarray([plen], jnp.int32))[0])
        # re-read the clock so the prefill stage absorbs the prefill
        # compute on the real clock (the virtual test clock returns the
        # same instant, keeping the decomposition deterministic) — the
        # same convention _decode uses for its post-compute timestamp
        done_at = self.clock()
        req.prefill_at = done_at
        self.tenant_meter.note_tokens(req.tenant_id, prompt=plen)
        self.tenant_meter.note_compile(
            req.tenant_id, self._compile_seconds() - compile_before)
        tl = self._timelines[req.id]
        tl.prefill(tl.admitted_at, done_at, bucket=bucket, prompt_len=plen,
                   **({"shared_tokens": shared_len} if shared_len else {}))
        self._append_token(req, tok, done_at, ttft=done_at - req.arrival,
                           batch=1)

    def _compile_seconds(self) -> float:
        """Total XLA compile wall seconds across the three instrumented
        step caches — the before/after delta attributes a prefill's cold
        compiles to its tenant."""
        return sum(p.compile_s
                   for fn in (self._step_fn, self._paged_step_fn,
                              self._sample_fn)
                   for p in fn.programs.values())

    # -- KV-page migration (disaggregated serving) --------------------------

    def _migrate_after_prefill(self, req: Request) -> None:
        """Role ``prefill``: hand the freshly prefilled request's KV
        pages to a decode worker through the router-installed
        ``migrate_out`` hook.  The export places a HOLD on the pages (the
        export/free race fix in kv_cache.py); a successful handoff
        recycles this engine's slot and pages immediately — prefill
        workers hold KV only for the duration of one prefill, which is
        what keeps their admission capacity high under a burst.  A failed
        placement (every decode worker shed) cancels the export and the
        request simply decodes here — degraded, never dropped."""
        record = self.pool.export_pages(req.id)
        placed = False
        try:
            placed = bool(self.migrate_out(self, req, record))
        finally:
            if not placed:
                self.pool.cancel_export(req.id)
        if placed:
            self._migrations["out"] += 1
            self.batcher.finish(req.slot)
            self.pool.free(req.id)
            self._recycled += 1
            if self.defrag_every and self._recycled % self.defrag_every == 0:
                self.pool.defrag()
            self._handles.pop(req.id)
            self._timelines.pop(req.id)

    def accept_migration(self, req: Request, record, ticket, handle,
                         timeline) -> Optional[str]:
        """Decode-side intake: queue a migrated request for a decode
        slot.  The KV import is DEFERRED to slot admission (so the
        ordinary page-budget admission gate covers it); the handle and
        timeline transfer so the request resolves here exactly as it
        would have colocated.  Returns ``None`` on acceptance, or the
        shed reason (``controller`` | ``queue_full``) so the router can
        re-route to the next-ranked decode worker."""
        if self.role == "prefill":
            raise ValueError("a prefill-role engine cannot accept "
                             "migrations")
        with self._lock:
            if req.id in self._handles:
                # a direct submission on this engine drew the same id
                # (mixing router-pinned and engine-local ids): refuse so
                # the router re-routes instead of stranding the in-flight
                # request by overwriting its handle
                return "id_collision"
            mreq = Request(
                id=req.id, prompt=list(req.prompt),
                max_new_tokens=req.max_new_tokens, arrival=req.arrival,
                deadline_s=req.deadline_s, tenant=req.tenant,
                tokens=list(req.tokens),
                prefill_at=req.prefill_at, migration=ticket)
            try:
                self.batcher.submit(mreq)
            except AdmissionShed:
                return "controller"
            except AdmissionQueueFull:
                return "queue_full"
            self._handles[req.id] = handle
            self._timelines[req.id] = timeline
            self._next_id = max(self._next_id, req.id + 1)
            _serve_m()["queue"].set(self.batcher.queue_len)
            return None

    def _ingest_migration(self, req: Request, now: float) -> None:
        """A migrated request enters a decode slot: verify + import its
        KV pages.  A torn or tampered record is journaled by named
        reason (``migrate_verify_failed``) and the request falls back to
        a local re-prefill — corrupt KV is never served, and the stream
        stays bitwise what the colocated engine would have produced
        because sampling keys derive from ``(seed, request id,
        position)`` alone."""
        from hetu_tpu.serve.fleet.migrate import (MigrationIntegrityError,
                                                  migrate_metrics)
        ticket = req.migration
        tl = self._timelines[req.id]
        verified = True
        try:
            self.pool.import_pages(ticket.record, seq_id=req.id,
                                   owner=req.tenant_id)
            self._migrations["in"] += 1
        except MigrationIntegrityError as e:
            verified = False
            migrate_metrics()["failures"].labels(reason=e.reason).inc()
            _journal.record("migrate_verify_failed", request_id=req.id,
                            reason=e.reason)
            self._reprefill(req)
            self._migrations["reprefill"] += 1
        finally:
            # settle the source pool's export hold outside our lock
            self._pending_settles.append(ticket.settle)
        tl.span("serve.migrate", now, self.clock(), slot=req.slot,
                pages=ticket.record.num_pages, verified=verified)

    def _reprefill(self, req: Request) -> None:
        """Recompute a migrated request's prompt KV locally (the
        corrupt-record fallback): one bucketed prefill step, no sharing.
        The first token was already sampled by the prefill worker from
        the same ``(seed, request id, position)`` key — recomputing it
        here must agree bitwise, and the locally recomputed draw is the
        one trusted (a record corrupt enough to fail verification is a
        record whose producer's outputs are not to be taken on faith)."""
        plen = len(req.prompt)
        bucket = self.batcher.bucket_for(plen)
        self._prefill_buckets.add(bucket)
        if self.prefill_tick_cost > 0:
            self._tick_prefill_charge += max(
                1, math.ceil(bucket * self.prefill_tick_cost))
        self.pool.alloc(req.id, plen, owner=req.tenant_id)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :plen] = req.prompt
        logits, k, v = self._step_fn(
            self.model, self.pool.k, self.pool.v,
            self.pool.gather_indices([req.id]),
            jnp.asarray([0], jnp.int32), jnp.asarray(tokens),
            jnp.asarray([plen], jnp.int32))
        self.pool.commit(k, v)
        self.pool.table(req.id).length = plen
        _kv.note_pages_written(self.pool.pages_needed(plen))
        tok = int(self._sample_fn(
            logits, jnp.asarray([req.id], jnp.int32),
            jnp.asarray([plen], jnp.int32))[0])
        # only prompt KV was recomputed: any tokens beyond the first have
        # no K/V here, so the stream restarts from the re-drawn first
        # token — decode regenerates the rest from the same (seed, rid,
        # position) keys, bitwise what the lost engine would have emitted
        req.tokens[:] = [tok]

    # -- failure & failover (serve/fleet/failover.py drives these) ----------

    @property
    def crashed(self) -> bool:
        return self._crashed

    def crash(self) -> None:
        """Inject a permanent replica death: the engine stops beating and
        stops doing work; its KV pages are treated as unexportable (a
        dead chip's HBM is gone), so every in-flight request re-homes by
        re-prefill."""
        with self._lock:
            self._crashed = True

    def hang(self, ticks: int) -> None:
        """Inject a silent hang: no heartbeat and no work for ``ticks``
        scheduler ticks, then the engine resumes on its own.  A hang
        longer than the monitor's lease triggers failover (the pages are
        still intact, so KV salvage applies); a recovered replica is
        restored to serving — and a flapping one is quarantined by the
        controller."""
        with self._lock:
            self._hang_ticks = max(self._hang_ticks, int(ticks))

    def evacuate(self) -> list:
        """Drain every in-flight request off this (failed) engine:
        returns ``[(request, record_or_None, handle, timeline)]`` in
        deterministic admission order and leaves the batcher empty and
        the pool holding nothing but export HOLDs.

        Active requests' KV pages are EXPORTED when the engine is merely
        hung (``record`` carries them; the monitor verifies and either
        salvages them on a survivor or cancels the hold) and ``None``
        when it crashed — a dead chip's HBM is not salvageable.  Queued
        requests never had pages; a queued MIGRATED request's inbound
        ticket is settled here (the source's export hold must not leak
        just because the destination died).  Pages are freed either way:
        the exporter's hold keeps exported bytes alive until the monitor
        settles or cancels, so the pool's alloc/free balance survives
        the failure."""
        with self._lock:
            active_ids = {r.id for _slot, r in self.batcher.active()}
            out = []
            for req in self.batcher.evacuate():
                handle = self._handles.pop(req.id, None)
                tl = self._timelines.pop(req.id, None)
                record = None
                if req.id in active_ids:
                    if not self._crashed:
                        try:
                            record = self.pool.export_pages(req.id)
                        except ValueError:
                            # an outstanding export already holds these
                            # pages (e.g. a prefill worker mid-migration):
                            # that ticket owns the hold; re-prefill here
                            record = None
                    self.pool.free(req.id)
                if req.migration is not None:
                    # inbound migrated request that never imported: the
                    # settle runs outside engine locks via step()'s drain
                    self._pending_settles.append(req.migration.settle)
                    req.migration = None
                if handle is not None:
                    out.append((req, record, handle, tl))
            return out

    def accept_failover(self, req: Request, handle, timeline,
                        ticket=None) -> Optional[str]:
        """Survivor-side intake for one request re-homed off a failed
        replica.  With a ``ticket`` (a verified KV salvage), the request
        keeps its emitted tokens and its pages import at slot admission
        — decode continues exactly where the lost engine stopped.
        Without one, the request re-enters EMPTY (no tokens): prefill
        re-samples the first token and decode regenerates the stream,
        bitwise identical because sampling keys derive from ``(seed,
        request id, position)`` alone.  Either way the handle and
        timeline transfer, so the request resolves here as if nothing
        happened.  Returns ``None`` on acceptance or a shed reason the
        monitor uses to try the next survivor; admission bypasses shed
        latches and quota (``requeue``) — the request already passed the
        fleet's front door once."""
        if self.role == "prefill":
            raise ValueError("a prefill-role engine cannot accept "
                             "failover re-homes")
        with self._lock:
            if req.id in self._handles:
                return "id_collision"
            if ticket is not None:
                mreq = Request(
                    id=req.id, prompt=list(req.prompt),
                    max_new_tokens=req.max_new_tokens,
                    arrival=req.arrival, deadline_s=req.deadline_s,
                    tenant=req.tenant, tokens=list(req.tokens),
                    prefill_at=req.prefill_at, migration=ticket)
            else:
                mreq = Request(
                    id=req.id, prompt=list(req.prompt),
                    max_new_tokens=req.max_new_tokens,
                    arrival=req.arrival, deadline_s=req.deadline_s,
                    tenant=req.tenant)
            try:
                self.batcher.submit(mreq, requeue=True)
            except AdmissionQueueFull:
                return "queue_full"
            self._handles[req.id] = handle
            self._timelines[req.id] = timeline
            self._next_id = max(self._next_id, req.id + 1)
            _serve_m()["queue"].set(self.batcher.queue_len)
            return None

    def _ensure_pages(self, req_id: int, n_tokens: int) -> None:
        """Grow a sequence's allocation, evicting trie-only cached
        prefixes first when the free list is short — cached prefixes are
        a loan, never a reason to evict live work.  Raises
        :exc:`OutOfPages` only when the pool is genuinely full."""
        need = self.pool.pages_needed(n_tokens) - \
            len(self.pool.table(req_id).pages)
        if need > self.pool.free_pages and self.sharer is not None:
            self.sharer.reclaim(need - self.pool.free_pages)
        self.pool.ensure(req_id, n_tokens)

    def _decode(self) -> int:
        """One fixed-shape (num_slots, 1) decode step over every active
        slot; idle slots ride along masked into the scratch page.  With
        a draft model attached, the step is propose-and-verify instead
        (serve/fleet/spec.py) — up to ``spec_k + 1`` tokens per slot per
        tick, bitwise the same streams."""
        if self.spec is not None:
            return self.spec.decode_step(self)
        active = self.batcher.active()
        if not active:
            return 0
        t0 = self.clock()
        seq_ids = [None] * self.batcher.num_slots
        tokens = np.zeros((self.batcher.num_slots, 1), np.int32)
        index = np.zeros(self.batcher.num_slots, np.int32)
        rids = np.zeros(self.batcher.num_slots, np.int32)
        positions = np.zeros(self.batcher.num_slots, np.int32)
        evicted = []
        for slot, req in active:
            # the fed token's K/V lands at index ``length``; its successor
            # is sampled at global position ``length + 1``
            try:
                self._ensure_pages(req.id,
                                   self.pool.table(req.id).length + 1)
                if self.sharer is not None:
                    # copy-on-write guard: never write into a page another
                    # table or the trie also references (sharing keeps the
                    # write target private by construction; this enforces
                    # the invariant rather than expecting it)
                    self.pool.copy_on_write(
                        req.id, self.pool.table(req.id).length)
            except OutOfPages:
                # only reachable under an explicitly overcommitted pool
                # (custom num_pages below full per-slot capacity); growth
                # takes ANY free page, so a full pool is really full —
                # retire the request with the tokens it has rather than
                # wedging the scheduler loop
                evicted.append((slot, req))
                continue
            seq_ids[slot] = req.id
            tokens[slot, 0] = req.tokens[-1]
            index[slot] = self.pool.table(req.id).length
            rids[slot] = req.id
            positions[slot] = self.pool.table(req.id).length + 1
        for slot, req in evicted:
            self._retire(req, "evicted", self.clock())
        active = [(s, r) for s, r in active
                  if r.slot is not None]  # drop the evicted
        if not active:
            return 0
        if self.paged_decode:
            toks_dev, k, v = self._paged_step_fn(
                self.model, self.pool.k, self.pool.v,
                self.pool.gather_indices(seq_ids),
                jnp.asarray(index), jnp.asarray(tokens),
                jnp.asarray(rids), jnp.asarray(positions))
            self.pool.commit(k, v)
            toks = np.asarray(toks_dev)
        else:
            logits, k, v = self._step_fn(
                self.model, self.pool.k, self.pool.v,
                self.pool.gather_indices(seq_ids),
                jnp.asarray(index), jnp.asarray(tokens), None)
            self.pool.commit(k, v)
            toks = np.asarray(self._sample_fn(logits, jnp.asarray(rids),
                                              jnp.asarray(positions)))
        now = self.clock()
        nactive = len(active)
        for slot, req in active:
            self.pool.table(req.id).length += 1  # fed token's K/V written
            self._append_token(req, int(toks[slot]), now, batch=nactive)
        # the injected clock times the step (production: time.monotonic
        # measures the real compute; the virtual test clock keeps the
        # latency histogram deterministic — the _prefill convention)
        dt = now - t0
        m = _serve_m()
        m["tok_latency"].observe(dt / max(len(active), 1))
        m["tps"].set(len(active) / dt if dt > 0 else 0.0)
        return len(active)

    def _append_token(self, req: Request, tok: int, now: float,
                      ttft: Optional[float] = None, batch: int = 1) -> None:
        """Account one generated token (its own K/V is written by the NEXT
        decode step, at index ``pool.table(id).length``); retire the
        request on EOS, budget exhaustion, or context exhaustion.
        ``batch`` is the decode step's batch composition (active slots),
        recorded on the token's ``serve.decode`` span — one span per
        generated token, the prefill-sampled first token included."""
        pt = self.pool.table(req.id)
        req.tokens.append(tok)
        if self.on_token is not None:
            # the router's in-flight ledger tracks tokens-emitted-so-far
            # (the failover monitor journals them at re-home time)
            self.on_token(req.id, tok)
        self._timelines[req.id].decode(now, batch=batch, slot=req.slot)
        m = _serve_m()
        m["tokens"].inc()
        if ttft is not None:
            m["ttft"].observe(max(ttft, 0.0))
        done = (tok == self.eos_id if self.eos_id is not None else False) \
            or len(req.tokens) >= req.max_new_tokens \
            or pt.length >= self.max_seq_len
        if done:
            self._retire(req, "completed", now)

    def _retire(self, req: Request, outcome: str, now: float) -> None:
        """Recycle the slot and pages, close the handle and timeline.
        ``outcome`` is ``completed``, ``expired`` (running deadline cut),
        or — only under an overcommitted pool — ``evicted``; the last two
        keep the tokens generated so far."""
        self.batcher.finish(req.slot)
        pages_held = len(self.pool.table(req.id).pages)
        self.pool.free(req.id)
        self._recycled += 1
        if self.defrag_every and self._recycled % self.defrag_every == 0:
            self.pool.defrag()
        m = _serve_m()
        error = None
        if outcome == "evicted":
            _journal.record("serve_evict", request_id=req.id,
                            tokens_generated=len(req.tokens))
            error = "evicted: KV pool exhausted (overcommitted num_pages)"
        elif outcome == "expired":
            age = now - req.arrival
            _journal.record("request_expired", request_id=req.id,
                            stage="running", age_s=round(age, 6),
                            tokens_generated=len(req.tokens))
            m["deadline"].labels(stage="running").inc()
            error = (f"deadline of {req.deadline_s}s expired after "
                     f"{age:.6g}s while decoding "
                     f"({len(req.tokens)} tokens generated)")
        m["requests"].labels(outcome=outcome).inc()
        self.tenant_meter.note_outcome(req.tenant_id, outcome)
        self.tenant_meter.note_tokens(req.tenant_id,
                                      generated=len(req.tokens))
        self.tenant_meter.note_pages(req.tenant_id, pages_held)
        # per-request token-stream fingerprint: O(tokens) host numpy, so
        # sampler nondeterminism is a field comparison in prod, not a
        # token-by-token diff (rides the handle, the /infer response, and
        # the request timeline)
        sfp = (_numerics.host_fingerprint_ints(req.tokens)
               if req.tokens else None)
        tl = self._timelines.pop(req.id)
        tl.close(outcome, now, tokens=len(req.tokens),
                 **({"stream_fp": sfp} if sfp is not None else {}))
        self._finalize_timeline(tl)
        self._handles.pop(req.id)._finish(
            outcome, req.tokens,
            ttft_s=(None if req.prefill_at is None
                    else req.prefill_at - req.arrival),
            latency_s=now - req.arrival, error=error,
            stream_fingerprint=sfp)
        if self.on_finish is not None:
            self.on_finish(req.id)  # prune the router's in-flight ledger

    def _finalize_timeline(self, tl: RequestTimeline,
                           grade: bool = True) -> None:
        """Resolved timeline -> trace buffer (+ SLO grading, + the process
        tracer when it is recording, so request traces stitch into the
        fleet timeline like any runtime span)."""
        self.trace_buffer.add(tl)
        if grade:
            self.slo.observe(tl)
        tracer = _tracing.get_tracer()
        if tracer.recording:
            tracer.record_external(tl.spans)

    # -- CTR inference ------------------------------------------------------

    def infer_ctr(self, dense, sparse) -> np.ndarray:
        """Read-only CTR scoring: stage the batch's embedding rows (host/
        remote pull through the HET caches — the fault-injectable PS path)
        and run the dense forward.  No gradients exist, so nothing can
        push; the stores are additionally flipped read-only at engine
        construction."""
        if self.ctr_model is None:
            raise RuntimeError("engine was built without a ctr_model")
        dense = jnp.asarray(np.asarray(dense, np.float32))
        sparse_np = np.asarray(sparse, np.int64)
        # stage-then-forward mutates the shared modules' staged rows, and
        # the HTTP front end is one-thread-per-request: serialize against
        # both concurrent CTR calls and the generation scheduler
        with self._lock:
            if self.ctr_follower is not None:
                # bounded staleness: install pending snapshot versions
                # BEFORE staging, so this batch never serves older than
                # the bound
                self.ctr_follower.gate()
            for mod in _staged_modules(self.ctr_model):
                mod.stage(sparse_np)
            logits = self.ctr_model.logits(dense, jnp.asarray(sparse_np))
        _serve_m()["ctr"].inc()
        return np.asarray(jax.nn.sigmoid(logits))

    # -- introspection ------------------------------------------------------

    def _embedding_stats(self) -> dict:
        """Embedding hit rates for ``/stats`` — tier stats for tiered
        layers, HBM hit stats otherwise, aggregated shard-cache stats as
        the fallback — beside the snapshot follower's freshness, so the
        CTR replica's cache efficiency scrapes next to the prefix-cache
        rates.  Reading the stats also refreshes the registry mirror
        (publish_cache_stats / the hetu_embed_* families), so
        ``/fleet/metrics`` carries the same numbers."""
        tables = []
        for mod in _staged_modules(self.ctr_model):
            fn = None
            for attr in ("tier_stats", "hit_stats", "stats"):
                fn = getattr(mod, attr, None)
                if fn is not None:
                    break
            if fn is None:
                # plain staged layer: the stats live on its HET cache
                fn = getattr(getattr(mod, "store", None), "stats", None)
            if fn is not None:
                tables.append(fn())
        return {"tables": tables,
                "snapshot": (None if self.ctr_follower is None
                             else self.ctr_follower.stats())}

    def stats(self) -> dict:
        """The ``/stats`` payload: scheduler + pool occupancy, the
        serving counters' current values, and an SLO quantile summary
        (TTFT / per-token latency p50+p99 from the serving histograms,
        via ``Histogram.quantile`` — the same quantile implementation
        ``bench.py --mode serve`` reports)."""
        with self._lock:
            reg = _obs.get_registry()
            snap = {k: v for k, v in reg.snapshot().items()
                    if k.startswith("hetu_serve_") and "_bucket" not in k}
            m = _serve_m()
            slo = {}
            for short, hist in (("ttft", m["ttft"]),
                                ("token_latency", m["tok_latency"])):
                h = hist.labels()
                for q, tag in ((0.5, "p50"), (0.99, "p99")):
                    v = h.quantile(q)
                    # empty histogram -> nan (deterministic); JSON has no
                    # NaN, so the payload carries null
                    slo[f"{short}_{tag}_s"] = (None if v is None or v != v
                                               else round(v, 6))
            return {
                "slo": slo,
                "shed_pressure": self.slo.shed_pressure(),
                "controller": {
                    "shedding": self.batcher.shed_reason,
                    "tenant_shedding": self.batcher.tenant_sheds,
                    "freeze_bucket_growth": self.freeze_bucket_growth,
                    "warm_buckets": sorted(self._prefill_buckets),
                },
                "tenants": {
                    "policy": self.batcher.policy.stats(),
                    "meter": self.tenant_meter.summary(),
                    "queue_lens": self.batcher.queue_lens(),
                },
                "queue_len": self.batcher.queue_len,
                "active_slots": self.batcher.active_slots,
                "num_slots": self.batcher.num_slots,
                "role": self.role,
                "migrations": dict(self._migrations),
                "prefix": (None if self.sharer is None
                           else self.sharer.stats()),
                "embedding": (None if self.ctr_model is None
                              else self._embedding_stats()),
                "speculative": (None if self.spec is None
                                else self.spec.stats()),
                "pool": self.pool.utilization(),
                "max_seq_len": self.max_seq_len,
                "sampling": self.sampling,
                "paged_decode": self.paged_decode,
                "fused_sampling": self._fused_sampling,
                "compile": _compile.compile_report(
                    self._step_fn, self._paged_step_fn, self._sample_fn),
                "metrics": snap,
            }


def _staged_modules(model) -> list:
    """Every staged host-embedding submodule of ``model`` (the Trainer's
    own discovery rule, reused)."""
    from hetu_tpu.exec.executor import _find_staged
    return _find_staged(model)


def _mark_stores_read_only(model) -> None:
    """Flip every local ``CacheTable`` store under ``model`` to read-only
    (serving must not train; see embed/engine.py).  A model that trained
    before being handed to the engine may hold buffered gradient pushes
    (``push_bound > 0``) and queued async pushes — drain them FIRST, so
    flipping the flag freezes the table instead of silently dropping the
    tail of training."""
    for mod in _staged_modules(model):
        flush_pushes = getattr(mod, "flush_pushes", None)
        if flush_pushes is not None:
            flush_pushes()
        stores = getattr(mod, "stores", None) or [getattr(mod, "store", None)]
        for st in stores:
            # engine CacheTable or PythonCacheTable (int8 tables) — the
            # shared is_het_cache duck tag
            if getattr(st, "is_het_cache", False) \
                    and hasattr(st, "read_only"):
                st.flush()  # apply buffered grads before freezing
                st.read_only = True
