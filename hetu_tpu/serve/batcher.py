"""Continuous-batching scheduler (Orca, OSDI'22 — iteration-level
scheduling restated for the paged pool) with a multi-tenant front door.

The batcher owns the *decisions*; the engine owns the *compute*.  Each
scheduler tick (:meth:`ContinuousBatcher.poll`):

1. expire — waiting requests past their admission deadline are dropped
   (they never held a slot; serving them late is serving them wrong);
2. admit — free slots are filled from the per-tenant sub-queues by
   deterministic virtual-time weighted-fair queueing (below), but only
   when the KV pool can actually hold the request's worst case *prompt*
   (its decode growth is page-at-a-time, backstopped by per-slot
   headroom);
3. the engine prefill-then-decodes whatever :meth:`active` returns, and
   recycles slots via :meth:`finish` the moment a sequence hits EOS or
   its token budget — the next tick's admissions take over mid-flight,
   which is the whole point of continuous batching.

Admission is **virtual-time WFQ** (self-clocked fair queueing,
Golestani '94, restated for request admission): each tenant has a FIFO
sub-queue; at *submit* a request is stamped with its finish tag
``max(V, tenant_last_tag) + cost / weight`` where cost =
``prompt + max_new_tokens`` work tokens, and each admission picks the
sub-queue head with the smallest ``(tag, seq)`` then advances the
global virtual clock ``V`` to the admitted tag.  Heavier weights accrue
tag mass slower and therefore admit more work per unit of virtual time,
yet a backlogged tenant's tags grow without bound while a queued
request's tag is frozen at enqueue — every nonzero-weight tenant's head
eventually becomes the minimum.  Weighted sharing with starvation
freedom, completely deterministic: no wall clock, no randomness, ties
broken by global submit order.  **With a single tenant this reduces
exactly to the old FIFO** (one sub-queue's tags are monotone in submit
order), so pre-tenant traces replay bitwise.

Queue depth is enforced *per tenant sub-queue*: a flooding tenant
exhausts its own depth while everyone else's front door stays open —
with one tenant this is the same global limit as before.  Per-tenant
token buckets (:class:`~hetu_tpu.serve.tenant.TokenBucket` via
:class:`~hetu_tpu.serve.tenant.TenantPolicy`) gate submit *before*
enqueue, raising :class:`TenantQuotaExceeded` with the bucket's exact
refill time as the retry hint.  The controller's shed actuator comes in
two scopes: the original global latch (:meth:`set_shed`) and per-tenant
latches (:meth:`set_tenant_shed`) so sustained burn can shed the tenant
*causing* it without closing the door on victims.

Prompt length buckets quantize prefill shapes (``bucket_for``), so XLA
compiles one prefill program per bucket instead of one per prompt
length; decode always runs at the fixed (num_slots, 1) shape.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from hetu_tpu.serve.tenant import DEFAULT_TENANT, TenantPolicy

__all__ = ["Request", "ContinuousBatcher", "AdmissionQueueFull",
           "AdmissionShed", "TenantQuotaExceeded", "SchedulerTick"]


class AdmissionQueueFull(RuntimeError):
    """The waiting queue is at its depth limit — shed load upstream."""


class AdmissionShed(AdmissionQueueFull):
    """Admission shedding engaged upstream (the runtime controller,
    under sustained SLO burn): the queue may have room, but admitting
    more work means serving it late.  Subclasses
    :class:`AdmissionQueueFull` so existing catch sites keep working,
    while the engine can tell a controller shed from a full queue —
    they are counted (``hetu_serve_shed_total{reason=}``), journaled
    (kind ``shed``), and surfaced on ``/infer`` distinguishably."""


class TenantQuotaExceeded(AdmissionQueueFull):
    """The submitting tenant's token-bucket quota is exhausted — the
    request was rejected by the tenant's *contract*, not by engine
    congestion.  Subclasses :class:`AdmissionQueueFull` so existing
    catch sites keep working; carries the bucket's deterministic refill
    arithmetic as ``retry_after_s`` so ``/infer`` can tell the client
    exactly how long to back off."""

    def __init__(self, message: str, *, tenant: str,
                 retry_after_s: float):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass
class Request:
    """One generation request as the scheduler sees it."""

    id: int
    prompt: list
    max_new_tokens: int
    arrival: float
    deadline_s: Optional[float] = None  # waiting-time budget; None = never
    # multi-tenant front door: the submitting tenant's id (None = the
    # default tenant — the anonymous pre-tenant caller)
    tenant: Optional[str] = None
    # engine-owned running state
    tokens: list = dataclasses.field(default_factory=list)  # generated
    prefill_at: Optional[float] = None
    slot: Optional[int] = None
    # batcher-owned: global submit sequence number (WFQ tie-breaker;
    # equals FIFO arrival order) and the WFQ virtual finish tag stamped
    # at enqueue
    seq: Optional[int] = None
    vft: Optional[float] = None
    # disaggregated serving: the inbound migration ticket (record +
    # settle callback) a decode worker ingests at slot admission instead
    # of running prefill; None for ordinary requests
    migration: Optional[object] = None

    @property
    def total_budget(self) -> int:
        return len(self.prompt) + self.max_new_tokens

    @property
    def tenant_id(self) -> str:
        return self.tenant if self.tenant is not None else DEFAULT_TENANT.id

    def expired(self, now: float) -> bool:
        return (self.deadline_s is not None
                and now - self.arrival > self.deadline_s)


@dataclasses.dataclass
class SchedulerTick:
    """What one :meth:`ContinuousBatcher.poll` decided."""

    expired: list          # Requests dropped at their deadline
    admitted: list         # Requests placed into slots this tick


class ContinuousBatcher:
    """Admission queues + slot map.  Pure scheduling — no jax, no model —
    so its behavior is unit-testable and deterministic by construction.

    ``policy`` is the tenant registry (class, WFQ weight, quota bucket);
    omitted, every caller is the default tenant and the scheduler
    behaves exactly like the pre-tenant FIFO."""

    def __init__(self, num_slots: int, *, queue_depth: int = 64,
                 prompt_buckets=(16, 32, 64, 128, 256, 512, 1024),
                 policy: Optional[TenantPolicy] = None):
        if num_slots <= 0:
            raise ValueError("need at least one slot")
        self.num_slots = num_slots
        self.queue_depth = queue_depth
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.policy = policy if policy is not None else TenantPolicy()
        # per-tenant FIFO sub-queues, keyed by tenant id
        self._queues: Dict[str, list] = {}
        self._slots: list = [None] * num_slots
        # WFQ state: global virtual time (the tag of the last admitted
        # request) + each tenant's last *enqueued* finish tag
        self._vtime: float = 0.0
        self._last_tag: Dict[str, float] = {}
        self._seq: int = 0
        # controller shed latch: while set, submit rejects with
        # AdmissionShed naming the reason (released by clear_shed)
        self.shed_reason: Optional[str] = None
        # tenant-scoped shed latches (the controller's surgical
        # actuator: shed the burning tenant, keep the door open)
        self._tenant_shed: Dict[str, str] = {}

    # -- admission ----------------------------------------------------------

    def set_shed(self, reason: str) -> None:
        """Engage admission shedding: every :meth:`submit` until
        :meth:`clear_shed` raises :exc:`AdmissionShed` carrying
        ``reason`` — the controller's sustained-SLO-burn actuator."""
        self.shed_reason = str(reason)

    def clear_shed(self) -> None:
        self.shed_reason = None

    @property
    def shedding(self) -> bool:
        return self.shed_reason is not None

    def set_tenant_shed(self, tenant_id: str, reason: str) -> None:
        """Engage admission shedding for ONE tenant: its submits raise
        :exc:`AdmissionShed` while everyone else's keep flowing — how
        the controller sheds the tenant burning the SLO without
        punishing the victims."""
        self._tenant_shed[str(tenant_id)] = str(reason)

    def clear_tenant_shed(self, tenant_id: Optional[str] = None) -> None:
        """Release one tenant's shed latch (all of them when ``None``)."""
        if tenant_id is None:
            self._tenant_shed.clear()
        else:
            self._tenant_shed.pop(str(tenant_id), None)

    def tenant_shed_reason(self, tenant_id: str) -> Optional[str]:
        return self._tenant_shed.get(str(tenant_id))

    @property
    def tenant_sheds(self) -> Dict[str, str]:
        """Engaged tenant-scoped shed latches (id -> reason), a copy."""
        return dict(self._tenant_shed)

    def submit(self, request: Request, *, requeue: bool = False) -> None:
        """Queue a request; raises :exc:`AdmissionShed` while the
        controller's global or tenant-scoped shed latch is engaged,
        :exc:`AdmissionQueueFull` at the tenant sub-queue's depth limit,
        and :exc:`TenantQuotaExceeded` when the tenant's token bucket
        cannot cover the request's work cost (the engine counts and
        journals all three, distinguishably).  The bucket is charged
        only for requests actually enqueued.

        ``requeue=True`` is the failover re-home path (the request
        already passed the fleet's front door once): shed latches and
        the quota bucket are bypassed — re-billing or re-shedding an
        admitted request on its survivor would turn one replica's death
        into a client-visible drop — leaving only the structural depth
        limit."""
        tid = request.tenant_id
        if not requeue:
            if self.shed_reason is not None:
                raise AdmissionShed(self.shed_reason)
            scoped = self._tenant_shed.get(tid)
            if scoped is not None:
                raise AdmissionShed(scoped)
        q = self._queues.get(tid)
        if q is not None and len(q) >= self.queue_depth:
            raise AdmissionQueueFull(
                f"admission queue at depth limit {self.queue_depth}"
                + (f" for tenant {tid}" if tid != DEFAULT_TENANT.id
                   else ""))
        bucket = self.policy.bucket(tid)
        # migrated requests already paid their quota at the front-door
        # engine's submit — charging the shared fleet bucket again at
        # the decode worker would double-bill the tenant (a failover
        # requeue likewise already paid at original admission)
        if bucket is not None and request.migration is None and not requeue:
            cost = float(request.total_budget)
            if not bucket.try_take(cost, request.arrival):
                raise TenantQuotaExceeded(
                    f"tenant {tid} quota exhausted "
                    f"(cost {cost:g} work tokens)",
                    tenant=tid,
                    retry_after_s=bucket.retry_after(cost,
                                                     request.arrival))
        request.seq = self._seq
        self._seq += 1
        # stamp the WFQ finish tag NOW (self-clocked fair queueing): the
        # tag is frozen at enqueue while a backlogged tenant's future
        # tags keep growing, which is exactly what guarantees every
        # nonzero-weight tenant's head eventually wins the admission
        weight = self.policy.resolve(tid).weight
        start = max(self._vtime, self._last_tag.get(tid, 0.0))
        request.vft = start + float(request.total_budget) / weight
        self._last_tag[tid] = request.vft
        if q is None:
            q = self._queues.setdefault(tid, [])
        q.append(request)

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest configured bucket holding ``prompt_len`` (prefill pads
        right up to it)."""
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(f"prompt of {prompt_len} tokens exceeds the "
                         f"largest bucket {self.prompt_buckets[-1]}")

    # -- the scheduler tick -------------------------------------------------

    def poll(self, now: float, can_admit=None) -> SchedulerTick:
        """Expire + admit.  ``can_admit(request) -> bool`` is the engine's
        capacity gate (KV pages); admission stops at the first refusal to
        preserve schedule order — skipping ahead would starve long
        prompts.  Admission picks the sub-queue head with the minimum
        WFQ ``(finish tag, submit seq)``; one tenant => plain FIFO."""
        expired: list = []
        for q in self._queues.values():
            dead = [r for r in q if r.expired(now)]
            if dead:
                expired.extend(dead)
                q[:] = [r for r in q if not r.expired(now)]
        if len(self._queues) > 1:
            expired.sort(key=lambda r: r.seq)
        admitted = []
        while None in self._slots:
            head = None
            for q in self._queues.values():
                if not q:
                    continue
                if head is None or (q[0].vft, q[0].seq) < (head.vft,
                                                           head.seq):
                    head = q[0]
            if head is None:
                break
            if can_admit is not None and not can_admit(head):
                break
            self._queues[head.tenant_id].pop(0)
            self._vtime = max(self._vtime, head.vft)
            slot = self._slots.index(None)  # lowest free slot: deterministic
            head.slot = slot
            self._slots[slot] = head
            admitted.append(head)
        return SchedulerTick(expired=expired, admitted=admitted)

    # -- running state ------------------------------------------------------

    def active(self) -> list:
        """[(slot, Request)] currently decoding, slot-ordered."""
        return [(i, r) for i, r in enumerate(self._slots) if r is not None]

    def finish(self, slot: int) -> Request:
        """Recycle a slot (EOS / budget exhausted / engine abort)."""
        r = self._slots[slot]
        if r is None:
            raise ValueError(f"slot {slot} is already free")
        self._slots[slot] = None
        r.slot = None
        return r

    def evacuate(self) -> list:
        """Drain EVERYTHING — every queued request and every occupied
        slot — in global submit order (``seq``), leaving the scheduler
        empty.  The replica-failure path: the failover monitor re-homes
        what this returns onto surviving replicas.  WFQ virtual time and
        the shed latches are left as they are; a recovered replica
        resumes with an empty, consistent scheduler."""
        out = []
        for tid in sorted(self._queues):
            out.extend(self._queues[tid])
            self._queues[tid] = []
        for i, r in enumerate(self._slots):
            if r is not None:
                self._slots[i] = None
                r.slot = None
                out.append(r)
        out.sort(key=lambda r: (r.seq if r.seq is not None else -1, r.id))
        return out

    def load_factor(self) -> float:
        """Occupancy in [0, 1]: (waiting + decoding) over total capacity
        (queue depth + slots), clamped — with several tenants the
        aggregate backlog can exceed one sub-queue's depth.  The fleet
        router's cold-start tie-breaker: before any SLO burn exists,
        shed-pressure gauges tie at 0.0 on every replica, and occupancy
        is the honest load signal."""
        return min(1.0, (self.queue_len + self.active_slots)
                   / max(self.queue_depth + self.num_slots, 1))

    def queue_lens(self) -> Dict[str, int]:
        """Per-tenant waiting depth (only tenants with queued work)."""
        return {tid: len(q) for tid, q in self._queues.items() if q}

    @property
    def queue_len(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def active_slots(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def idle(self) -> bool:
        return self.queue_len == 0 and self.active_slots == 0
