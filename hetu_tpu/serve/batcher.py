"""Continuous-batching scheduler (Orca, OSDI'22 — iteration-level
scheduling restated for the paged pool).

The batcher owns the *decisions*; the engine owns the *compute*.  Each
scheduler tick (:meth:`ContinuousBatcher.poll`):

1. expire — waiting requests past their admission deadline are dropped
   (they never held a slot; serving them late is serving them wrong);
2. admit — free slots are filled FIFO from the queue, but only when the
   KV pool can actually hold the request's worst case *prompt* (its
   decode growth is page-at-a-time, backstopped by per-slot headroom);
3. the engine prefill-then-decodes whatever :meth:`active` returns, and
   recycles slots via :meth:`finish` the moment a sequence hits EOS or
   its token budget — the next tick's admissions take over mid-flight,
   which is the whole point of continuous batching.

Everything is deterministic given the same submit/poll sequence and an
injected clock: FIFO admission, lowest-free-slot placement, sorted
expiry.  The engine exploits this for bitwise-replayable serving runs.

Prompt length buckets quantize prefill shapes (``bucket_for``), so XLA
compiles one prefill program per bucket instead of one per prompt
length; decode always runs at the fixed (num_slots, 1) shape.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = ["Request", "ContinuousBatcher", "AdmissionQueueFull",
           "AdmissionShed", "SchedulerTick"]


class AdmissionQueueFull(RuntimeError):
    """The waiting queue is at its depth limit — shed load upstream."""


class AdmissionShed(AdmissionQueueFull):
    """Admission shedding engaged upstream (the runtime controller,
    under sustained SLO burn): the queue may have room, but admitting
    more work means serving it late.  Subclasses
    :class:`AdmissionQueueFull` so existing catch sites keep working,
    while the engine can tell a controller shed from a full queue —
    they are counted (``hetu_serve_shed_total{reason=}``), journaled
    (kind ``shed``), and surfaced on ``/infer`` distinguishably."""


@dataclasses.dataclass
class Request:
    """One generation request as the scheduler sees it."""

    id: int
    prompt: list
    max_new_tokens: int
    arrival: float
    deadline_s: Optional[float] = None  # waiting-time budget; None = never
    # engine-owned running state
    tokens: list = dataclasses.field(default_factory=list)  # generated
    prefill_at: Optional[float] = None
    slot: Optional[int] = None
    # disaggregated serving: the inbound migration ticket (record +
    # settle callback) a decode worker ingests at slot admission instead
    # of running prefill; None for ordinary requests
    migration: Optional[object] = None

    @property
    def total_budget(self) -> int:
        return len(self.prompt) + self.max_new_tokens

    def expired(self, now: float) -> bool:
        return (self.deadline_s is not None
                and now - self.arrival > self.deadline_s)


@dataclasses.dataclass
class SchedulerTick:
    """What one :meth:`ContinuousBatcher.poll` decided."""

    expired: list          # Requests dropped at their deadline
    admitted: list         # Requests placed into slots this tick


class ContinuousBatcher:
    """Admission queue + slot map.  Pure scheduling — no jax, no model —
    so its behavior is unit-testable and deterministic by construction."""

    def __init__(self, num_slots: int, *, queue_depth: int = 64,
                 prompt_buckets=(16, 32, 64, 128, 256, 512, 1024)):
        if num_slots <= 0:
            raise ValueError("need at least one slot")
        self.num_slots = num_slots
        self.queue_depth = queue_depth
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self._waiting: list = []
        self._slots: list = [None] * num_slots
        # controller shed latch: while set, submit rejects with
        # AdmissionShed naming the reason (released by clear_shed)
        self.shed_reason: Optional[str] = None

    # -- admission ----------------------------------------------------------

    def set_shed(self, reason: str) -> None:
        """Engage admission shedding: every :meth:`submit` until
        :meth:`clear_shed` raises :exc:`AdmissionShed` carrying
        ``reason`` — the controller's sustained-SLO-burn actuator."""
        self.shed_reason = str(reason)

    def clear_shed(self) -> None:
        self.shed_reason = None

    @property
    def shedding(self) -> bool:
        return self.shed_reason is not None

    def submit(self, request: Request) -> None:
        """Queue a request; raises :exc:`AdmissionShed` while the
        controller's shed latch is engaged, :exc:`AdmissionQueueFull` at
        the depth limit (the engine counts and journals both,
        distinguishably)."""
        if self.shed_reason is not None:
            raise AdmissionShed(self.shed_reason)
        if len(self._waiting) >= self.queue_depth:
            raise AdmissionQueueFull(
                f"admission queue at depth limit {self.queue_depth}")
        self._waiting.append(request)

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest configured bucket holding ``prompt_len`` (prefill pads
        right up to it)."""
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(f"prompt of {prompt_len} tokens exceeds the "
                         f"largest bucket {self.prompt_buckets[-1]}")

    # -- the scheduler tick -------------------------------------------------

    def poll(self, now: float, can_admit=None) -> SchedulerTick:
        """Expire + admit.  ``can_admit(request) -> bool`` is the engine's
        capacity gate (KV pages); admission stops at the first refusal to
        preserve FIFO order — skipping ahead would starve long prompts."""
        expired = [r for r in self._waiting if r.expired(now)]
        if expired:
            self._waiting = [r for r in self._waiting
                             if not r.expired(now)]
        admitted = []
        while self._waiting and None in self._slots:
            head = self._waiting[0]
            if can_admit is not None and not can_admit(head):
                break
            self._waiting.pop(0)
            slot = self._slots.index(None)  # lowest free slot: deterministic
            head.slot = slot
            self._slots[slot] = head
            admitted.append(head)
        return SchedulerTick(expired=expired, admitted=admitted)

    # -- running state ------------------------------------------------------

    def active(self) -> list:
        """[(slot, Request)] currently decoding, slot-ordered."""
        return [(i, r) for i, r in enumerate(self._slots) if r is not None]

    def finish(self, slot: int) -> Request:
        """Recycle a slot (EOS / budget exhausted / engine abort)."""
        r = self._slots[slot]
        if r is None:
            raise ValueError(f"slot {slot} is already free")
        self._slots[slot] = None
        r.slot = None
        return r

    def load_factor(self) -> float:
        """Occupancy in [0, 1]: (waiting + decoding) over total capacity
        (queue depth + slots).  The fleet router's cold-start tie-breaker:
        before any SLO burn exists, shed-pressure gauges tie at 0.0 on
        every replica, and occupancy is the honest load signal."""
        return ((len(self._waiting) + self.active_slots)
                / max(self.queue_depth + self.num_slots, 1))

    @property
    def queue_len(self) -> int:
        return len(self._waiting)

    @property
    def active_slots(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def idle(self) -> bool:
        return not self._waiting and self.active_slots == 0
