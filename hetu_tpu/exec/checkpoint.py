"""Checkpoint save/load.

Reference: Executor.save/load (reference: gpu_ops/executor.py:568-670) —
rank-0 pickles a ``{name: array}`` state dict plus RNG ``(seed, seqnum)``
(executor.py:596-599; random.py:31); ``load_dict(consider_splits=True)``
reshapes entries for re-sharded placeholders (executor.py:619-636).

TPU-native version: pytrees of Modules round-trip directly (arrays are
device_get'd to numpy); the state dict form keyed by dotted path supports
loading into a differently-sharded/resized model (``consider_splits``).
Optimizer state is saved too (the reference leaves it on PS servers; here
there is no server — it is part of the functional state).
"""

from __future__ import annotations

import copy
import os
import pickle
import re
import struct
import sys
import threading
import time
import zlib
from typing import Any, Optional

import jax
import jax.tree_util as jtu
import numpy as np

from hetu_tpu.core import get_seed_status, reset_seed_seqnum
from hetu_tpu.core.module import named_parameters
from hetu_tpu.obs import journal as _obs_journal
from hetu_tpu.obs import registry as _obs

__all__ = ["save_checkpoint", "load_checkpoint", "state_dict",
           "load_state_dict", "AsyncCheckpointer", "CheckpointError",
           "CheckpointCorrupt", "read_footer_crc"]


class CheckpointError(Exception):
    """A checkpoint file could not be loaded (torn write, wrong file, ...)."""


class CheckpointCorrupt(CheckpointError):
    """The integrity footer is present but the CRC32 does not match: the
    bytes were damaged on disk AFTER a complete write (bit rot, a concurrent
    writer, or deliberate fault injection) — as opposed to a torn write,
    which loses the footer entirely."""


# Integrity footer appended after the pickle payload: 8-byte magic +
# CRC32 of the payload.  A torn write truncates the footer away (the
# legacy-load path then diagnoses it); in-place corruption keeps the
# footer but fails the CRC.
_FOOTER_MAGIC = b"HTCKPT1\x00"
_FOOTER = struct.Struct("<8sI")

# Fault-injection seam (exec.faults.install wires this up; None in
# production, so the hot path costs one global load).  Called with
# ("ckpt_write", final_path) after every durable write.
_fault_hook = None

# Step number baked into resilience checkpoint names (ckpt.step_NNN,
# written by resilience.checkpoint_path); journaled when present so events
# correlate with the driver's counter.  Canonical search pattern — the
# fault harness keys checkpoint events on it too, so a rename of the
# checkpoint scheme must change them together.
_STEP_IN_NAME = re.compile(r"ckpt\.step_(\d+)$")

_ckpt_metrics = None


def _ckpt_m() -> dict:
    global _ckpt_metrics
    if _ckpt_metrics is None:
        reg = _obs.get_registry()
        _ckpt_metrics = {
            "seconds": reg.histogram(
                "hetu_checkpoint_write_seconds",
                "durable checkpoint write time (pickle+fsync+rename, on "
                "whichever thread ran it)"),
            "bytes": reg.counter(
                "hetu_checkpoint_bytes_total",
                "bytes durably written as checkpoints"),
            "writes": reg.counter(
                "hetu_checkpoint_writes_total",
                "checkpoints durably written"),
        }
    return _ckpt_metrics


def _snap(x):
    """Host snapshot of one leaf; always a fresh buffer (device_get is a
    no-op for numpy arrays, so force the copy)."""
    if isinstance(x, np.ndarray):
        return x.copy()
    return np.asarray(jax.device_get(x))


def _to_host(tree):
    return jtu.tree_map(_snap, tree)


def _make_payload(state: Any, extra: Optional[dict]) -> dict:
    """Host snapshot of state + RNG + a defensive copy of extra, built on
    the caller's thread so later mutations cannot race a background write."""
    return {
        "state": _to_host(state),
        "rng": get_seed_status(),
        "extra": copy.deepcopy(extra) if extra else {},
    }


def _atomic_write_bytes(path: str, *chunks: bytes) -> None:
    """tmp-write + fsync + rename + directory fsync: a crash at any point
    leaves either the old or the new file, never a torn one.  Shared by
    the pickle checkpoint writer and the gang manifest writer (chunks are
    written back to back — no concatenation copy of a multi-GB payload)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        for chunk in chunks:
            f.write(chunk)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)  # make the rename itself durable
    finally:
        os.close(dfd)


def read_footer_crc(path: str) -> Optional[int]:
    """The CRC32 recorded in a checkpoint file's integrity footer, or None
    when the file is missing, too short, or carries no footer (legacy file
    or torn write).  Reads 12 bytes — cheap enough for a gang manifest to
    collect every shard's CRC without re-reading the payloads."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size < _FOOTER.size:
                return None
            f.seek(size - _FOOTER.size)
            magic, crc = _FOOTER.unpack(f.read(_FOOTER.size))
    except OSError:
        return None
    return crc if magic == _FOOTER_MAGIC else None


def _atomic_write(path: str, payload: dict) -> None:
    """Durable pickle write with a CRC32 integrity footer so silent
    on-disk corruption is detected at load time."""
    t0 = time.perf_counter() if _obs.enabled() else None
    buf = pickle.dumps(payload)
    crc = zlib.crc32(buf) & 0xFFFFFFFF
    footer = _FOOTER.pack(_FOOTER_MAGIC, crc)
    _atomic_write_bytes(path, buf, footer)
    if t0 is not None:
        dt = time.perf_counter() - t0
        nbytes = len(buf) + _FOOTER.size
        m = _ckpt_m()
        m["seconds"].observe(dt)
        m["bytes"].inc(nbytes)
        m["writes"].inc()
        step = _STEP_IN_NAME.search(path)
        _obs_journal.record(
            "checkpoint_saved", path=path,
            step=int(step.group(1)) if step else None,
            bytes=nbytes, crc32=crc, duration_s=round(dt, 6))
    if _fault_hook is not None:
        _fault_hook("ckpt_write", path)


def save_checkpoint(path: str, state: Any, extra: Optional[dict] = None) -> None:
    """Pickle a host copy of ``state`` plus the global RNG (seed, seqnum);
    atomic against crashes mid-write."""
    _atomic_write(path, _make_payload(state, extra))


class AsyncCheckpointer:
    """Non-blocking checkpointing: the device→host snapshot happens on the
    caller's thread (cheap, and consistent — arrays are immutable), the
    pickle+fsync happens on a background thread so the train loop never
    waits on disk.  Writes to ``path.tmp`` then atomically renames, so a
    crash mid-write never corrupts the previous checkpoint.

    (The reference blocks the worker for the whole save, executor.py:568;
    async snapshots are beyond it — this is a rebuild extra.)
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, path: str, state: Any, extra: Optional[dict] = None):
        """Snapshot ``state`` (and copy ``extra``) now; persist in the
        background.  A previous in-flight save is waited on first (ordered
        checkpoints)."""
        self.wait()
        payload = _make_payload(state, extra)  # caller-thread snapshot

        def write():
            try:
                _atomic_write(path, payload)
            except BaseException as e:
                # stored for the next wait()/save(); ALSO printed so a
                # failed final save of an exiting process is not silent
                print(f"AsyncCheckpointer: write to {path} failed: {e!r}",
                      file=sys.stderr)
                self._error = e

        # non-daemon: interpreter exit joins the writer, so the final save
        # of a script that forgets wait() still lands on disk
        self._thread = threading.Thread(target=write, daemon=False)
        self._thread.start()

    def wait(self):
        """Block until the in-flight save (if any) is durable; re-raise any
        background write error."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def _parse_payload(raw: bytes, path: str) -> dict:
    """Decode checkpoint bytes, verifying the CRC32 footer when present.

    Raises ``CheckpointCorrupt`` on a CRC mismatch and ``CheckpointError``
    (naming the path and the likely cause) when the bytes do not decode at
    all — instead of the raw ``EOFError``/``UnpicklingError`` pickle emits
    on a truncated file."""
    if len(raw) >= _FOOTER.size:
        magic, crc = _FOOTER.unpack_from(raw, len(raw) - _FOOTER.size)
        if magic == _FOOTER_MAGIC:
            # memoryview: no second multi-GB copy of the payload
            body = memoryview(raw)[:len(raw) - _FOOTER.size]
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: CRC32 mismatch — the file was "
                    f"corrupted on disk after a complete write (bit rot or "
                    f"an interfering writer); pick an older checkpoint")
            try:
                return pickle.loads(body)
            except Exception as e:  # CRC passed yet unpickle failed: not
                raise CheckpointError(  # our bytes at all
                    f"checkpoint {path}: integrity footer valid but payload "
                    f"does not unpickle ({e!r}) — is this really a "
                    f"checkpoint file?") from e
    # No footer: a legacy (pre-footer) checkpoint or a torn write that
    # truncated the footer away.  Let pickle decide, but translate its
    # stream errors into a diagnosis.
    try:
        return pickle.loads(raw)
    except Exception as e:
        raise CheckpointError(
            f"cannot load checkpoint {path}: {e!r} — most likely a "
            f"torn/truncated write (the file lacks the integrity footer "
            f"current saves append), or the path is not a checkpoint file "
            f"at all") from e


def load_checkpoint(path: str, restore_rng: bool = True):
    """Returns (state, extra).  Restores the RNG stream by default so resumed
    training replays the identical randomness (reference executor.py:653).

    Raises ``CheckpointCorrupt`` when the CRC32 footer does not match the
    bytes and ``CheckpointError`` for torn/alien files — both carry the path
    and a likely cause, so resume loops can skip bad files with a clear
    diagnosis instead of dying on a raw pickle error."""
    import mmap
    with open(path, "rb") as f:
        try:
            # OS-paged view: no private heap copy of a multi-GB file on
            # top of the unpickled arrays
            raw = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (ValueError, OSError):  # empty file / mmap-less fs
            raw = f.read()
        try:
            payload = _parse_payload(raw, path)
        finally:
            if isinstance(raw, mmap.mmap):
                try:
                    raw.close()
                except BufferError:
                    pass  # a memoryview pinned by an in-flight traceback
                    #       still references it; GC closes it later
    if not isinstance(payload, dict) or "state" not in payload:
        raise CheckpointError(
            f"checkpoint {path} decoded to {type(payload).__name__} without "
            f"a 'state' entry — wrong file?")
    if restore_rng and "rng" in payload:
        reset_seed_seqnum(*payload["rng"])
    return payload["state"], payload.get("extra", {})


def state_dict(tree: Any) -> dict:
    """Flat {dotted.path: numpy array} — the reference's state_dict form."""
    return {name: _snap(x) for name, x in named_parameters(tree)}


def load_state_dict(tree: Any, sd: dict, *, consider_splits: bool = False):
    """Load a flat state dict into a congruent pytree.

    With ``consider_splits`` a saved entry larger than the model parameter is
    sliced down to fit — the reference's re-sharded placeholder reload
    (executor.py:619-636 PlaceholderOp.reshape_tensor).  A smaller entry is
    an error either way.
    """
    leaves, treedef = jtu.tree_flatten_with_path(tree)
    new_leaves = []
    for path, leaf in leaves:
        name = ".".join(
            str(getattr(k, "name", getattr(k, "idx", getattr(k, "key", k))))
            for k in path
        )
        if name not in sd:
            new_leaves.append(leaf)
            continue
        val = sd[name]
        if hasattr(leaf, "shape") and tuple(val.shape) != tuple(leaf.shape):
            if not consider_splits or any(
                v < s for v, s in zip(val.shape, leaf.shape)
            ) or len(val.shape) != len(leaf.shape):
                raise ValueError(
                    f"shape mismatch for {name}: ckpt {val.shape} vs model {leaf.shape}"
                )
            val = val[tuple(slice(0, s) for s in leaf.shape)]
        new_leaves.append(
            val.astype(leaf.dtype) if hasattr(leaf, "dtype") else val
        )
    return jtu.tree_unflatten(jtu.tree_structure(tree), new_leaves)
