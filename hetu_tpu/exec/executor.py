"""Executor / Trainer — the user-facing run loop.

The reference's ``Executor`` (reference: python/hetu/gpu_ops/executor.py:430)
owns named subgraphs ({'train': ..., 'validate': ...}), a ``run(feed_dict)``
loop that walks a topo order calling kernels, manual stream/event overlap, a
memory planner, and checkpoint save/load.  Under XLA the topo walk, memory
plan, and stream overlap are the compiler's job, so the TPU-native executor
is thin: it jits step functions, carries a functional ``TrainState``, applies
the sharding strategy (hetu_tpu/parallel), and keeps API parity with
``run('train', feed_dict)``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module, trainable_mask
from hetu_tpu.core.rng import next_key
from hetu_tpu.obs import compile as _obs_compile
from hetu_tpu.obs import goodput as _obs_goodput
from hetu_tpu.obs import memledger as _obs_memledger
from hetu_tpu.obs import numerics as _obs_numerics
from hetu_tpu.obs import registry as _obs
from hetu_tpu.obs import tracing as _obs_tracing
from hetu_tpu.optim.optimizers import Optimizer

__all__ = ["TrainState", "Trainer", "Executor"]

# Fault-injection seam (exec.faults.install wires this up; None in
# production).  Called with ("grad", batch) before each train step; a
# non-None return replaces the batch — the deterministic NaN-poisoning
# path of the chaos harness (a NaN input poisons every gradient).
_fault_hook = None

# Train-loop metric families, built on first instrumented step (never
# while telemetry is disabled — the disabled path must register nothing).
_step_metrics = None


def _step_m() -> dict:
    global _step_metrics
    if _step_metrics is None:
        reg = _obs.get_registry()
        _step_metrics = {
            "latency": reg.histogram(
                "hetu_step_latency_seconds",
                "Trainer.step wall latency (host-side, dispatch-"
                "inclusive; device time is exec.profiler's job)"),
            "steps": reg.counter(
                "hetu_train_steps_total",
                "train steps by outcome (ok, or skipped by the anomaly "
                "guard)", ("outcome",)),
            "examples": reg.counter(
                "hetu_train_examples_total",
                "examples consumed by committed train steps"),
            "eps": reg.gauge(
                "hetu_examples_per_second",
                "throughput of the most recent committed step"),
            "grad_norm": reg.gauge(
                "hetu_grad_norm",
                "global gradient L2 norm of the last committed step "
                "(guarded trainers only — the plain program carries no "
                "grad_norm)"),
        }
    return _step_metrics


def _batch_examples(batch) -> int:
    """Leading dim of the first array-ish leaf — the batch size for
    throughput accounting (0 when the batch carries no arrays)."""
    for leaf in jax.tree_util.tree_leaves(batch):
        shape = getattr(leaf, "shape", None)
        if shape:
            return int(shape[0])
    return 0


def _global_grad_norm(grads):
    """Global L2 norm over every floating grad leaf — the anomaly signal
    the resilience layer watches (a single NaN/Inf anywhere in the grads
    makes it non-finite).  float32 accumulation so bf16 models do not
    overflow the sum of squares."""
    total = jnp.zeros((), jnp.float32)
    for g in jax.tree_util.tree_leaves(grads):
        if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating):
            total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return jnp.sqrt(total)


def _apply_refreshes(model):
    """Fold HBM-cached embeddings' pending refresh leaves into their cache
    (embed.HBMCachedEmbedding.apply_refresh) — inside jit, so the scatter
    rides the step's dispatch and the merged cache persists in the new
    state."""
    is_hbm = lambda x: getattr(x, "is_hbm_cached_embedding", False)  # noqa
    return jax.tree_util.tree_map(
        lambda m: m.apply_refresh() if is_hbm(m) else m, model,
        is_leaf=is_hbm)


def _find_staged(tree) -> list:
    """Collect StagedHostEmbedding modules (duck-typed via the
    ``is_staged_host_embedding`` class marker, avoiding an import of
    hetu_tpu.embed).  Uses jax's own flatten order so the list pairs up with
    the same walk over the traced grads tree."""
    def is_staged(x):
        return getattr(x, "is_staged_host_embedding", False)

    return [x for x in jax.tree_util.tree_leaves(tree, is_leaf=is_staged)
            if is_staged(x)]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    model: Any
    opt_state: Any

    @property
    def step(self):
        return self.opt_state["step"]


class Trainer:
    """Builds and jits the train/eval step.

    ``loss_fn(model, batch, key) -> (loss, aux)`` where ``aux`` is a dict of
    scalars; if the model carries functional state (BatchNorm), ``aux`` may
    include the updated model under the reserved key ``"model"`` (it is
    extracted, not treated as a metric).
    """

    def __init__(self, model: Module, optimizer: Optimizer,
                 loss_fn: Callable, *, strategy=None, donate: bool = True,
                 memory_plan=None):
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.strategy = strategy
        # mem.planner.MemoryPlan (or None): the planner's (policy,
        # microbatch) decision this trainer is expected to run under.
        # Stored for audit and published to the metrics registry so
        # /metrics shows planned-vs-actual peak bytes side by side; the
        # policy itself lives in the model config (maybe_remat reads it).
        self.memory_plan = memory_plan
        if memory_plan is not None and _obs.enabled():
            from hetu_tpu.mem.estimator import record_memory_gauges
            record_memory_gauges(
                predicted=memory_plan.predicted_peak_bytes)
        # Recorded so wrappers (exec.resilience) can tell whether the
        # pre-step state survives the jitted call; strategies always jit
        # with donation (strategies.py install).
        self.donate = bool(donate) or strategy is not None
        # Optional commit gate: ``grad_guard(metrics) -> bool`` runs after
        # the jitted step but BEFORE the new state is committed and staged
        # embedding grads are pushed; returning False discards the update
        # (metrics come back with ``skipped=True``).  The resilience
        # layer's NaN/Inf anomaly policy hangs here — rejecting before the
        # staged push matters, because a NaN pushed to a parameter server
        # cannot be rolled back.  Attach BEFORE the first step: the guard's
        # ``grad_norm`` metric is added at trace time.
        self.grad_guard: Optional[Callable[[dict], bool]] = None
        # (batch, key) of the last step that carried numerics stats —
        # the NaN-provenance post-mortem replays these exact inputs
        self._last_step_inputs: Optional[tuple] = None
        self._state = TrainState(model, optimizer.init(model))
        # Non-trainable state (BatchNorm statistics) must not see weight decay
        # or moment updates; the mask is static model structure, closed over.
        param_mask = trainable_mask(model)
        # Staged host embeddings (embed.StagedHostEmbedding): the step must
        # hand their rows-gradients back to the host engine (SparsePush).
        self._has_staged = bool(_find_staged(model))
        if self._has_staged and strategy is not None:
            raise ValueError(
                "StagedHostEmbedding is incompatible with sharding "
                "strategies that repartition the model (each worker owns "
                "its own host store, like the reference's PS workers); "
                "drop the strategy or use the io_callback HostEmbedding")

        def train_step(state: TrainState, batch, key):
            def wrapped(model):
                loss, aux = loss_fn(model, batch, key)
                new_model = aux.pop("model", None)
                return loss, (aux, new_model)

            model0 = (_apply_refreshes(state.model) if self._has_staged
                      else state.model)
            (loss, (aux, new_model)), grads = jax.value_and_grad(
                wrapped, has_aux=True
            )(model0)
            base = new_model if new_model is not None else model0
            params, opt_state = optimizer.update(
                grads, state.opt_state, base, mask=param_mask
            )
            metrics = {"loss": loss, **aux}
            # trace-time check: only guarded trainers (exec.resilience
            # attaches grad_guard before the first step) pay for the
            # all-gradients reduction; a plain Trainer's program — and the
            # benchmarked scan_steps path — is unchanged
            if self.grad_guard is not None:
                metrics["grad_norm"] = _global_grad_norm(grads)
            # trace-time check, same rule as grad_guard: only trainers
            # built while a flight recorder is installed
            # (obs.numerics.install) trace the tensor stats — per-group
            # grad norms/max-abs/nonfinite/zero-fraction plus the
            # deterministic bitcast-uint32 fingerprints of the UPDATED
            # params — into the step program.  They ride the step's
            # outputs as device scalars, so recording adds no host sync;
            # a plain Trainer's program is unchanged.
            if _obs_numerics.recording():
                metrics["_numerics"] = {
                    "grad": _obs_numerics.group_stats(grads),
                    "param_fp": _obs_numerics.tree_fingerprints(params),
                }
            if self._has_staged:
                metrics["_staged_rows_grads"] = [
                    m.rows for m in _find_staged(grads)]
            return TrainState(params, opt_state), metrics

        def eval_step(state: TrainState, batch):
            loss, aux = loss_fn(state.model, batch, None)
            aux.pop("model", None)
            return {"loss": loss, **aux}

        if strategy is not None:
            train_step, eval_step, self._state = strategy.install(
                train_step, eval_step, self._state
            )
        else:
            # staged host embeddings: NEVER donate the state.  stage()
            # re-installs leaf objects from the previous state (the reused
            # zeros ``rows`` buffer, the HBM cache between refreshes), so
            # donating hands XLA buffers the host-side staging protocol
            # still references — observed as a use-after-free when the
            # persistent compile cache serves the step executable (the
            # deserialized aliasing config bypasses the compile-time
            # "donated buffer not usable" rejection that masked this).
            donate_args = (0,) if donate and not self._has_staged else ()
            train_step = jax.jit(train_step, donate_argnums=donate_args)
            eval_step = jax.jit(eval_step)
        # compile-counting seams (obs.compile watch mode: the wrapped jit
        # keeps dispatching — donation/sharding strategies unchanged — and
        # the disabled path stays one global load + branch).  A recompile
        # here is a shape-signature change the journal names.
        self._train_step = _obs_compile.watch(train_step, site="train.step")
        self._eval_step = _obs_compile.watch(eval_step, site="train.eval")
        # memory-ledger seam: weights/optimizer bytes of the initial
        # state (re-posted whenever the state is rebound — the setter)
        _obs_memledger.note_train_state(self._state)

    @property
    def state(self) -> TrainState:
        return self._state

    @state.setter
    def state(self, s: TrainState):
        self._state = s
        # a rebind (checkpoint restore, rescale) may change leaf shapes/
        # dtypes: re-post the ledger's train-state bytes
        _obs_memledger.note_train_state(s)

    @property
    def model(self):
        return self._state.model

    def staged_modules(self) -> list:
        """StagedHostEmbedding modules of the CURRENT model (re-walk every
        step: optimizer updates replace the module objects).  Call
        ``m.stage(ids)`` on each before ``step``; the gradient push back to
        the host engine happens automatically inside ``step``."""
        return _find_staged(self._state.model)

    def step(self, batch, key=None) -> dict:
        """One train step.  With telemetry enabled (the default) the
        step's wall latency, outcome, and throughput land in the process
        metrics registry, and — when the tracer is recording — the step
        becomes a ``train.step`` span that parents any PS RPC spans
        issued inside it.  With telemetry disabled the cost over the
        bare step is one module-global load and branch."""
        if not _obs.enabled():
            return self._step_impl(batch, key)
        t0 = time.perf_counter()
        tracer = _obs_tracing.get_tracer()
        if tracer.recording:
            with tracer.span("train.step"):
                metrics = self._step_impl(batch, key)
        else:
            metrics = self._step_impl(batch, key)
        dt = time.perf_counter() - t0
        m = _step_m()
        skipped = bool(metrics.get("skipped"))
        m["steps"].labels(outcome="skipped" if skipped else "ok").inc()
        m["latency"].observe(dt)
        # online goodput accounting: one global load + branch when no
        # meter is installed (obs.goodput.install_meter), same contract
        # as the rest of this seam
        _obs_goodput.record_step(dt, skipped=skipped)
        if not skipped:
            n = _batch_examples(batch)
            if n:
                m["examples"].inc(n)
                if dt > 0:
                    m["eps"].set(n / dt)
            if "grad_norm" in metrics:
                # guarded trainers already fetched this to the host in
                # grad_guard, so the float() here is a cached read, not a
                # fresh device sync
                m["grad_norm"].set(float(metrics["grad_norm"]))
        return metrics

    def _step_impl(self, batch, key=None) -> dict:
        if key is None:
            key = next_key()
        if _fault_hook is not None:
            poisoned = _fault_hook("grad", batch)
            if poisoned is not None:
                batch = poisoned
        if self._has_staged:
            # validate freshness BEFORE the jitted step runs: a step on
            # stale rows would advance the dense params on wrong gradients
            # before push_grads could catch the mistake
            for m in _find_staged(self._state.model):
                if not m.is_fresh():
                    raise RuntimeError(
                        "staged host embedding has no fresh rows: call "
                        "stage(ids) on every module from staged_modules() "
                        "before each training step")
        new_state, metrics = self._train_step(self._state, batch, key)
        ns = metrics.pop("_numerics", None)
        if ns is not None:
            # ring the device scalars as-is (no fetch, no sync)
            _obs_numerics.observe(ns)
        if ns is not None or self.grad_guard is not None:
            # post-fault-hook batch/key stashed so the resilience layer's
            # NaN-provenance post-mortem replays the EXACT inputs —
            # including a fault-hook-poisoned batch.  Guarded trainers
            # stash with or without a flight recorder: provenance is
            # default-on and must not silently replay a clean batch.
            self._last_step_inputs = (batch, key)
        if self.grad_guard is not None and not self.grad_guard(metrics):
            # rejected update: keep the pre-step state, drop the staged
            # grads (never push an anomalous gradient to the host/PS
            # stores — there is no undo on that side)
            metrics.pop("_staged_rows_grads", None)
            metrics["skipped"] = True
            return metrics
        self._state = new_state
        if self._has_staged:
            gs = metrics.pop("_staged_rows_grads")
            for m, g in zip(_find_staged(self._state.model), gs):
                m.push_grads(g)
        return metrics

    def evaluate(self, batch) -> dict:
        """Eval step.  With staged host embeddings (StagedHostEmbedding) the
        caller must ``stage`` the EVAL batch's ids on each module from
        ``staged_modules()`` first — the jitted program reads the staged
        rows leaf, not the batch ids."""
        return self._eval_step(self._state, batch)

    def scan_steps(self, n_steps: int):
        """Compile ``n_steps`` train steps into ONE program (a ``lax.scan``
        over the step body) and return ``run(state, batch, key) ->
        (new_state, last_metrics)`` — the final step's full metrics dict
        (loss plus whatever the loss_fn's aux carries, e.g. MoE routing
        stats), so a compiled loop costs no extra per-metric dispatch.

        Two uses: (1) amortizing per-dispatch host cost when batches repeat
        or are generated on-device — the reference's SubExecutor batches
        kernel launches per run() for the same reason (executor.py:430);
        (2) device-time benchmarking: timing run(k) and run(2k) and
        differencing cancels the fixed dispatch overhead exactly, leaving
        pure device time per step.

        The batch is FIXED across the n steps; the RNG key is split once
        per step inside the scan, so dropout stays honest.  Feed the
        returned state back in (the state argument is donated).  Not
        supported with staged host embeddings: their per-step host
        push/stage cannot live inside a compiled loop."""
        if self._has_staged:
            raise ValueError(
                "scan_steps cannot run staged host embeddings: stage()/"
                "push_grads() are per-step host work (use the io_callback "
                "HostEmbedding or the plain step loop)")
        train_step = self._train_step  # inlined when traced under jit

        def run(state: TrainState, batch, key):
            def body(carry, _):
                st, k = carry
                k, sub = jax.random.split(k)
                st, metrics = train_step(st, batch, sub)
                return (st, k), metrics

            (state, _), stacked = jax.lax.scan(
                body, (state, key), None, length=n_steps)
            return state, jax.tree_util.tree_map(lambda x: x[-1], stacked)

        # the step watcher passes tracer-stage calls through (the scan's
        # program owns the compile), so the scan gets its own counted site
        return _obs_compile.watch(jax.jit(run, donate_argnums=(0,)),
                                  site="train.scan")

    def profile(self, batch, key=None, iters: int = 10) -> dict:
        """Wall-time + cost profile of one train step on the given batch
        (reference executor.profile, executor.py:501).  Includes the
        compiled step's ``memory_analysis()`` byte sizes
        (``argument_bytes``/``output_bytes``/``temp_bytes``) and — with
        telemetry enabled — publishes them as ``hetu_mem_xla_*`` gauges
        on /metrics, next to the planner's predicted peak."""
        from hetu_tpu.exec.profiler import profile_fn
        if key is None:
            key = next_key()
        prof = profile_fn(self._train_step, self._state, batch, key,
                          iters=iters)
        if self.memory_plan is not None:
            prof["memory_plan"] = self.memory_plan.describe()
            prof["predicted_peak_bytes"] = \
                self.memory_plan.predicted_peak_bytes
        if _obs.enabled() and prof.get("temp_bytes") is not None:
            from hetu_tpu.mem.estimator import (reconcile,
                                                record_memory_gauges)
            record_memory_gauges(xla=prof)
            # reconcile the planner's predicted device peak against the
            # compiled step's own memory_analysis bytes: publishes the
            # hetu_mem_estimator_error_ratio gauge, journals
            # mem_estimate_drift outside the 25% band, and feeds the
            # installed calibration store (the measured correction
            # plan_memory(calibration=) later divides by)
            if self.memory_plan is not None:
                xla_peak = (float(prof.get("argument_bytes") or 0.0)
                            + float(prof.get("temp_bytes") or 0.0))
                r = reconcile(self.memory_plan.predicted_peak_bytes,
                              xla_peak, model_sig="train.step")
                prof["estimator_error_ratio"] = r["ratio"]
        return prof


class Executor:
    """Named-subgraph facade for reference API parity (executor.py:430).

    ``Executor({'train': trainer.step, 'validate': trainer.evaluate}})`` —
    or construct from a Trainer directly: ``Executor.from_trainer(trainer)``.
    ``run(name, feed_dict)`` invokes the named step with the feeds.
    """

    def __init__(self, subgraphs: dict, logger=None):
        self.subgraphs = dict(subgraphs)
        self.logger = logger

    @classmethod
    def from_trainer(cls, trainer: Trainer, logger=None) -> "Executor":
        return cls({"train": trainer.step, "validate": trainer.evaluate},
                   logger=logger)

    def run(self, name: str, feed_dict=None, **kw):
        fn = self.subgraphs[name]
        out = fn(feed_dict, **kw) if feed_dict is not None else fn(**kw)
        if self.logger is not None and isinstance(out, dict):
            for k, v in out.items():
                self.logger.log(k, v)
            self.logger.step()
        return out
