"""Elastic gang runtime: sharded + ring-replicated checkpoints,
worker-loss recovery, deterministic rescale.

Hetu's headline capability is trillion-parameter training across many
workers, where the dominant failure mode is losing a *worker*
(preemption, OOM, host death) — not the single-process faults PR 1's
``ResilientTrainer`` survives.  This module adds the gang-level story,
following the Megatron-LM distributed-checkpoint shape and the
elastic-membership designs surveyed in PAPERS.md (Varuna's morphing
under spot preemptions):

1. **Sharded checkpoints with ring replication.**  Each worker durably
   writes its own parameter/optimizer shard (a deterministic slice of
   the flat state dict, ``shard_owner``) through the existing
   ``checkpoint._atomic_write`` CRC path, *plus a replica of its ring
   successor's shard*, plus — on rank 0 — a signed manifest recording
   (step, generation, world size, RNG state, per-shard CRC32s).  Loss of
   any single worker's storage is survivable: its shard is recovered
   from the ring predecessor's replica (journal event
   ``shard_restore``).  Loading composes every shard back into one flat
   state dict and restores it with ``load_state_dict(
   consider_splits=True)``, so a checkpoint taken by an n-worker gang
   restores into a differently-sized gang.

2. **Gang membership.**  :class:`GangMembership` implements heartbeat
   leases with generation numbers over a shared directory — the
   coordination substrate the ``launch.simulate_workers`` harness (and
   any shared-filesystem deployment) provides.  A worker whose lease
   goes stale past ``lease_ttl`` is *lost* (journal ``worker_lost``);
   survivors barrier on a new generation (``gang_rescale``) and resume
   from the newest intact manifest.

3. **Deterministic elastic rescale.**  Per-worker data assignment
   (:func:`gang_data_partition`) and per-worker RNG streams
   (:func:`worker_rng_key`) are pure functions of
   ``(seed, generation, world_size)`` — and the *global* computation is
   invariant under the partition (shards compose back in global index
   order), so an n→n kill/recover replay is bitwise identical to an
   uninterrupted run, and two replays of the same seeded
   :class:`~hetu_tpu.exec.faults.FaultPlan` are bitwise identical to
   each other.

:class:`ElasticGang` is the deterministic in-process simulation of the
whole lifecycle (the chaos-testable runtime: ``worker_kill`` /
``worker_stall`` / ``shard_loss`` fault kinds fire on a step clock);
:class:`GangCheckpointer` + :class:`GangMembership` are the per-process
pieces real multi-process gangs (``simulate_workers``) compose with
``ResilientTrainer(gang=...)``.

Observability: ``hetu_gang_*`` gauges/counters through ``obs.registry``
and ``worker_lost`` / ``gang_rescale`` / ``shard_restore`` events
through ``obs.journal``.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import re
import shutil
import threading
import time
import zlib
from typing import Callable, Optional, Sequence

import numpy as np

from hetu_tpu.core import get_seed_status, next_key, reset_seed_seqnum
from hetu_tpu.core.module import named_parameters
from hetu_tpu.exec import controller as _controller
from hetu_tpu.exec import executor as _executor
from hetu_tpu.exec import faults as _faults
from hetu_tpu.exec import partial as _partial
from hetu_tpu.exec.checkpoint import (CheckpointError, _atomic_write_bytes,
                                      load_checkpoint, load_state_dict,
                                      read_footer_crc, save_checkpoint)
from hetu_tpu.obs import divergence as _obs_divergence
from hetu_tpu.obs import fleet as _obs_fleet
from hetu_tpu.obs import goodput as _obs_goodput
from hetu_tpu.obs import journal as _obs_journal
from hetu_tpu.obs import numerics as _obs_numerics
from hetu_tpu.obs import registry as _obs

__all__ = ["GangError", "GangManifestError", "shard_owner", "ring_neighbor",
           "shard_path", "replica_path", "manifest_path", "save_shard",
           "write_manifest", "read_manifest", "list_manifests",
           "compose_state", "load_gang_checkpoint", "prune_gang",
           "gang_data_partition", "worker_rng_key", "GangCheckpointer",
           "GangMembership", "ElasticGang", "sign_body"]


class GangError(RuntimeError):
    """The gang cannot make progress (e.g. no intact checkpoint to
    rescale from, or every worker lost)."""


class GangManifestError(CheckpointError):
    """A gang manifest could not be used: torn write (unparseable JSON),
    signature mismatch (tampered/corrupt), or missing fields.  Subclasses
    ``CheckpointError`` so resume loops treat it like any other damaged
    checkpoint file: skip with a diagnosis, fall back to an older one."""


# Content signature over the canonical manifest body.  This is
# tamper/torn-*evidence*, not secrecy: anyone with the key string can
# re-sign, but a torn write, a stray editor, or on-disk bit rot cannot
# produce a manifest whose signature still verifies.
_SIGN_KEY = b"hetu-tpu-gang-manifest-v1"
MANIFEST_FORMAT = "hetu-gang-ckpt-v1"

_MANIFEST_RE = re.compile(r"^manifest\.step_(\d+)\.json$")


# ---------------------------------------------------------------- layout

def shard_owner(name: str, world_size: int) -> int:
    """Which rank owns parameter ``name`` in a ``world_size`` gang — a
    pure function of the dotted path alone, so every worker (and a
    differently-sized reloading gang) computes the same assignment
    without coordination."""
    if world_size <= 0:
        raise ValueError(f"world_size must be positive, got {world_size}")
    return zlib.crc32(name.encode()) % world_size


def ring_neighbor(rank: int, world_size: int) -> int:
    """The ring successor whose shard ``rank`` replicates.  Loss of rank
    w's storage is covered by rank ``(w - 1) % world``'s replica."""
    return (rank + 1) % world_size


def worker_dir(gang_dir: str, rank: int) -> str:
    return os.path.join(gang_dir, f"worker_{rank:04d}")


def shard_path(gang_dir: str, rank: int, step: int) -> str:
    return os.path.join(worker_dir(gang_dir, rank),
                        f"shard.step_{step:08d}")


def replica_path(gang_dir: str, holder: int, owner: int, step: int) -> str:
    """The copy of ``owner``'s shard that ``holder`` wrote."""
    return os.path.join(worker_dir(gang_dir, holder),
                        f"replica_{owner:04d}.step_{step:08d}")


def manifest_path(gang_dir: str, step: int) -> str:
    return os.path.join(gang_dir, f"manifest.step_{step:08d}.json")


# ------------------------------------------------------------- telemetry

_gang_metrics = None


def _gang_m() -> dict:
    global _gang_metrics
    if _gang_metrics is None:
        reg = _obs.get_registry()
        _gang_metrics = {
            "generation": reg.gauge(
                "hetu_gang_generation",
                "current gang membership generation (bumps on every "
                "shrink/grow)"),
            "size": reg.gauge(
                "hetu_gang_size", "live workers in the gang"),
            "alive": reg.gauge(
                "hetu_gang_worker_alive",
                "1 while the worker holds a fresh lease; the series is "
                "removed (not frozen) when the worker leaves the gang",
                ("worker",)),
            "lost": reg.counter(
                "hetu_gang_worker_lost_total",
                "workers evicted after a missed heartbeat lease"),
            "rescales": reg.counter(
                "hetu_gang_rescales_total",
                "membership generations committed (shrinks and grows)"),
            "shard_restores": reg.counter(
                "hetu_gang_shard_restores_total",
                "checkpoint shards recovered from a ring replica because "
                "the primary was missing or damaged"),
        }
    return _gang_metrics


# ------------------------------------------------- sharded save / restore

def save_shard(gang_dir: str, rank: int, world_size: int, step: int,
               sd: dict, *, generation: int = 0,
               extra: Optional[dict] = None) -> str:
    """Durably write ``rank``'s slice of the flat state dict ``sd`` plus a
    replica of its ring successor's slice (both through the atomic CRC32
    checkpoint path).  ``sd`` is the full flat ``{dotted.path: array}``
    dict — under data parallelism every worker holds a full replica, so
    the slice is computed locally; a TP/sharded caller passes whatever
    subset it holds and only matching names are written.

    Returns the primary shard path."""
    meta = {"rank": rank, "world_size": world_size, "step": step,
            "generation": generation, **(extra or {})}
    own = {k: v for k, v in sd.items()
           if shard_owner(k, world_size) == rank}
    p = shard_path(gang_dir, rank, step)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    save_checkpoint(p, own, extra=meta)
    # content fingerprint sidecar: the deterministic uint32 fingerprint of
    # the shard's floating entries (obs.numerics host mirror — bitwise
    # the device fingerprint), recorded by the manifest beside the CRC.
    # The CRC proves the BYTES survived; the fingerprint identifies the
    # NUMBERS, so a divergent replica's shard is nameable from manifests
    # alone.  Partial-reduce correction entries (``partialreduce.*``) are
    # in ``sd`` like any parameter, so they are fingerprinted for free.
    fp_body = {"fingerprint": _obs_numerics.host_state_fingerprint(own),
               "groups": _obs_numerics.host_tree_fingerprints(own)}
    _atomic_write_bytes(p + ".fp.json",
                        (json.dumps(fp_body, sort_keys=True) + "\n"
                         ).encode())
    nbr = ring_neighbor(rank, world_size)
    if nbr != rank:
        rep = {k: v for k, v in sd.items()
               if shard_owner(k, world_size) == nbr}
        save_checkpoint(replica_path(gang_dir, rank, nbr, step), rep,
                        extra={**meta, "replica_of": nbr})
    return p


def sign_body(body: dict, key: bytes) -> str:
    """Content signature over a canonical (sorted-JSON, ``sig``-stripped)
    manifest body — the gang-manifest signing rule, shared with every
    artifact family that reuses the format (embed.stream snapshots).
    Tamper/torn-*evidence*, not secrecy."""
    canon = json.dumps({k: v for k, v in body.items() if k != "sig"},
                       sort_keys=True).encode()
    return hashlib.sha256(key + canon).hexdigest()


def _sign(body: dict) -> str:
    return sign_body(body, _SIGN_KEY)


def write_manifest(gang_dir: str, step: int, generation: int,
                   world_size: int, *, rng: Optional[tuple] = None,
                   extra: Optional[dict] = None,
                   wait_timeout: float = 0.0, poll: float = 0.05) -> str:
    """Write the signed manifest for ``step``: per-shard CRC32s (read from
    the 12-byte integrity footers — no payload re-read), generation,
    world size, and the RNG state a resumed gang must replay from.

    ``wait_timeout`` lets the manifest writer (rank 0 of a multi-process
    gang) wait for peers' shard files to land before collecting CRCs;
    the in-process runtime writes all shards itself, so 0 suffices."""
    deadline = time.monotonic() + wait_timeout
    shards = {}
    for r in range(world_size):
        p = shard_path(gang_dir, r, step)
        crc = read_footer_crc(p)
        while crc is None and time.monotonic() < deadline:
            time.sleep(poll)
            crc = read_footer_crc(p)
        if crc is None:
            raise GangError(
                f"cannot write gang manifest for step {step}: shard for "
                f"rank {r} never appeared at {p} (worker crashed before "
                f"its save, or wait_timeout={wait_timeout}s too short)")
        ent = {"crc32": crc, "relpath": os.path.relpath(p, gang_dir)}
        # content fingerprint beside the CRC (from save_shard's sidecar):
        # absent for shards written by an older build — manifests carry it
        # best-effort and loaders never require it (MIGRATING note)
        try:
            with open(p + ".fp.json") as f:
                fp_body = json.load(f)
            ent["fingerprint"] = int(fp_body["fingerprint"])
            ent["fingerprint_groups"] = {
                g: int(v) for g, v in fp_body.get("groups", {}).items()}
        except (OSError, ValueError, KeyError, TypeError):
            pass
        shards[str(r)] = ent
    body = {"format": MANIFEST_FORMAT, "step": int(step),
            "generation": int(generation), "world_size": int(world_size),
            "rng": list(rng if rng is not None else get_seed_status()),
            "extra": dict(extra or {}), "shards": shards}
    body["sig"] = _sign(body)
    path = manifest_path(gang_dir, step)
    _atomic_write_bytes(path, (json.dumps(body, sort_keys=True)
                               + "\n").encode())
    return path


def read_manifest(path: str) -> dict:
    """Parse and verify a manifest; raises :class:`GangManifestError`
    naming the path and the diagnosis (torn vs tampered vs alien)."""
    try:
        with open(path) as f:
            body = json.load(f)
    except OSError as e:
        raise GangManifestError(f"gang manifest {path}: unreadable "
                                f"({e!r})") from e
    except ValueError as e:
        raise GangManifestError(
            f"gang manifest {path}: not parseable JSON ({e}) — most "
            f"likely a torn write; fall back to the previous "
            f"generation") from e
    if not isinstance(body, dict) or body.get("format") != MANIFEST_FORMAT:
        raise GangManifestError(
            f"gang manifest {path}: missing/unknown format tag "
            f"{body.get('format') if isinstance(body, dict) else type(body).__name__!r}")
    if body.get("sig") != _sign(body):
        raise GangManifestError(
            f"gang manifest {path}: signature mismatch — the file was "
            f"modified after signing (partial write, bit rot, or an "
            f"interfering writer); fall back to the previous generation")
    for field in ("step", "generation", "world_size", "shards"):
        if field not in body:
            raise GangManifestError(
                f"gang manifest {path}: missing field {field!r}")
    return body


def list_manifests(gang_dir: str) -> list:
    """All manifests, ascending by step: ``[(step, path)]``."""
    out = []
    try:
        names = os.listdir(gang_dir)
    except (FileNotFoundError, NotADirectoryError):
        return out
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(gang_dir, name)))
    out.sort()
    return out


def compose_state(gang_dir: str, manifest: dict) -> tuple:
    """Reassemble the full flat state dict from a manifest's shards.

    A shard whose primary is missing, damaged, or not the bytes the
    manifest signed (footer CRC != manifest CRC) is recovered from its
    ring predecessor's replica — journaled as ``shard_restore``.  Raises
    :class:`CheckpointError` when a shard is unrecoverable (caller falls
    back to an older manifest).

    Returns ``(sd, restored_ranks)``."""
    world = int(manifest["world_size"])
    step = int(manifest["step"])
    sd: dict = {}
    restored = []
    for r in range(world):
        ent = manifest["shards"][str(r)]
        p = os.path.join(gang_dir, ent["relpath"])
        part = None
        primary_err = None
        try:
            if read_footer_crc(p) != int(ent["crc32"]):
                raise CheckpointError(
                    f"shard {p}: footer CRC does not match the manifest "
                    f"(damaged, replaced, or torn)")
            part, _extra = load_checkpoint(p, restore_rng=False)
        except (CheckpointError, OSError) as e:
            primary_err = e
        if part is None:
            holder = (r - 1) % world
            rp = replica_path(gang_dir, holder, r, step)
            try:
                # the replica was pickled by a different writer, so its
                # byte-level CRC may legitimately differ from the
                # primary's; its OWN integrity footer still guards it
                part, _extra = load_checkpoint(rp, restore_rng=False)
            except (CheckpointError, OSError) as e:
                raise CheckpointError(
                    f"gang step {step}: shard for rank {r} is "
                    f"unrecoverable — primary failed ({primary_err}) and "
                    f"the ring replica at {rp} failed too ({e})") from e
            restored.append(r)
            if _obs.enabled():
                _gang_m()["shard_restores"].inc()
            _obs_journal.record("shard_restore", rank=r, from_rank=holder,
                                step=step,
                                generation=int(manifest["generation"]))
        sd.update(part)
    return sd, restored


def load_gang_checkpoint(gang_dir: str, restore_rng: bool = True) -> tuple:
    """Scan manifests newest-first, skipping torn/tampered ones and ones
    whose shards are unrecoverable, and compose the newest intact gang
    checkpoint.

    Returns ``(step, generation, sd, extra, report)`` — or ``(None, None,
    None, None, report)`` when nothing loads.  ``report`` mirrors
    ``latest_good_checkpoint``: ``[(step, path, diagnosis_or_None)]``."""
    report = []
    for step, path in reversed(list_manifests(gang_dir)):
        try:
            man = read_manifest(path)
            sd, _restored = compose_state(gang_dir, man)
        except CheckpointError as e:
            report.append((step, path, str(e)))
            continue
        if restore_rng and man.get("rng"):
            reset_seed_seqnum(*man["rng"])
        report.append((step, path, None))
        return (int(man["step"]), int(man["generation"]), sd,
                dict(man.get("extra", {})), report)
    return None, None, None, None, report


_STEP_SUFFIX_RE = re.compile(r"\.step_(\d+)(?:\.fp\.json)?$")


def prune_gang(gang_dir: str, keep: int) -> None:
    """Drop manifests of all but the newest ``keep`` steps, plus every
    shard/replica file older than the oldest kept manifest — INCLUDING
    orphans from ``manifest_skipped`` steps (a dead peer makes the
    manifest fail soft but the survivors' shards still land; without the
    sweep they would accumulate forever).  Best-effort, never fatal
    (retention semantics match the monolithic path)."""
    if keep <= 0:
        return
    steps = [s for s, _p in list_manifests(gang_dir)]
    if len(steps) <= keep:
        return
    kept = steps[-keep:]
    cutoff = kept[0]
    doomed = [manifest_path(gang_dir, s) for s in steps[:-keep]]
    for p in glob.glob(os.path.join(gang_dir, "worker_*", "*.step_*")):
        m = _STEP_SUFFIX_RE.search(p)
        # orphaned manifest-less steps newer than the cutoff are spared:
        # they may be mid-save, about to get their manifest
        if m and int(m.group(1)) < cutoff:
            doomed.append(p)
    for p in doomed:
        try:
            os.remove(p)
        except OSError:
            pass


# ------------------------------------------ deterministic elastic rescale

def gang_data_partition(seed: int, generation: int, world_size: int,
                        step: int, global_batch_size: int) -> list:
    """Assign the global batch's row indices to ranks — a pure function
    of ``(seed, generation, world_size, step)``.  The union of the
    returned index arrays is always exactly ``arange(global_batch_size)``
    (a permutation, split near-evenly), so the *global* batch a gang
    composes back in global index order is independent of how many
    workers shared it — the invariance that makes an n→n kill/recover
    replay bitwise identical to an uninterrupted run."""
    if world_size <= 0:
        raise ValueError(f"world_size must be positive, got {world_size}")
    rng = np.random.default_rng(
        [int(seed), int(generation), int(world_size), int(step)])
    perm = rng.permutation(global_batch_size)
    return np.array_split(perm, world_size)


def worker_rng_key(seed: int, generation: int, world_size: int, rank: int):
    """Per-worker PRNG key for rank-local randomness (local shuffles,
    augmentation): a pure function of ``(seed, generation, world_size,
    rank)``, so a rescaled gang re-derives every stream without any state
    handoff from the dead worker."""
    import jax.random as jrandom
    key = jrandom.key(int(seed))
    for x in (int(generation), int(world_size), int(rank)):
        key = jrandom.fold_in(key, x)
    return key


# ------------------------------------------------------ per-process APIs

class GangCheckpointer:
    """One worker's handle on the sharded checkpoint protocol — the
    object ``ResilientTrainer(gang=...)`` routes saves/restores through.

    ``save`` writes this rank's shard + ring replica; the manifest writer
    (rank 0 unless ``writes_manifest`` overrides) additionally waits for
    every peer's shard (``manifest_timeout``), writes the signed
    manifest, and prunes retention.  Call :meth:`rescale` after a
    membership change so subsequent saves carry the new (rank, world,
    generation)."""

    def __init__(self, gang_dir: str, rank: int, world_size: int, *,
                 generation: int = 0, keep: int = 3,
                 manifest_timeout: float = 60.0,
                 writes_manifest: Optional[bool] = None):
        self.gang_dir = gang_dir
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.generation = int(generation)
        self.keep = int(keep)
        self.manifest_timeout = float(manifest_timeout)
        self._writes_manifest = writes_manifest
        os.makedirs(gang_dir, exist_ok=True)

    @property
    def writes_manifest(self) -> bool:
        if self._writes_manifest is None:
            return self.rank == 0
        return bool(self._writes_manifest)

    def rescale(self, rank: int, world_size: int, generation: int) -> None:
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.generation = int(generation)

    def save(self, step: int, sd: dict, extra: Optional[dict] = None) -> str:
        path = save_shard(self.gang_dir, self.rank, self.world_size, step,
                          sd, generation=self.generation, extra=extra)
        if self.writes_manifest:
            try:
                path = write_manifest(self.gang_dir, step, self.generation,
                                      self.world_size,
                                      rng=get_seed_status(), extra=extra,
                                      wait_timeout=self.manifest_timeout)
            except GangError as e:
                # a peer never produced its shard — almost always a dead
                # worker the membership layer is about to evict.  The
                # elastic semantics are to fail SOFT: this checkpoint
                # step simply never commits (shards without a manifest
                # are invisible), and the coming rescale resumes from the
                # previous manifest.
                _obs_journal.record("manifest_skipped", step=step,
                                    generation=self.generation,
                                    reason=str(e))
                return path
            prune_gang(self.gang_dir, self.keep)
        return path

    def load_latest(self, restore_rng: bool = True) -> tuple:
        return load_gang_checkpoint(self.gang_dir, restore_rng=restore_rng)


class GangMembership:
    """Heartbeat leases with generation numbers over a shared directory.

    Each worker renews ``membership/worker_RRRR.lease`` (atomic replace)
    every ``interval`` seconds; a peer whose lease is older than
    ``lease_ttl`` is *lost*.  Survivors agree on a new generation with
    :meth:`rescale`: everyone writes an ack under ``gen_GGGG/`` and waits
    for the surviving set's acks — the barrier the issue's "survivors
    barrier on a new generation" names.  Clean shutdown calls
    :meth:`leave` (removes the lease); a crash leaves the lease to
    expire, which is exactly the detection path.

    The clock is injectable for deterministic tests; production uses
    ``time.time`` because lease ages are compared across processes."""

    def __init__(self, gang_dir: str, rank: int, *, lease_ttl: float = 3.0,
                 interval: float = 0.5, generation: int = 0,
                 clock: Callable[[], float] = time.time):
        self.gang_dir = gang_dir
        self.dir = os.path.join(gang_dir, "membership")
        self.rank = int(rank)
        self.lease_ttl = float(lease_ttl)
        self.interval = float(interval)
        self.generation = int(generation)
        self.clock = clock
        self._beat_n = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._announced: set = set()
        os.makedirs(self.dir, exist_ok=True)

    @classmethod
    def from_env(cls, **kw) -> "GangMembership":
        """Construct from the env the launcher composed
        (``HETU_TPU_GANG_DIR`` + ``HETU_TPU_PROC_ID``)."""
        from hetu_tpu.launch import ENV_GANG_DIR, ENV_PROC_ID
        return cls(os.environ[ENV_GANG_DIR],
                   int(os.environ.get(ENV_PROC_ID, "0")), **kw)

    def _lease_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"worker_{rank:04d}.lease")

    def heartbeat(self) -> None:
        """Renew this worker's lease (atomic tmp+replace: readers never
        see a torn lease)."""
        self._beat_n += 1
        rec = {"rank": self.rank, "generation": self.generation,
               "beat": self._beat_n, "ts": self.clock()}
        # tmp is per-thread: the beat daemon and direct heartbeat() calls
        # (worker step loops, rescale) may renew concurrently
        tmp = (self._lease_path(self.rank)
               + f".tmp.{os.getpid()}.{threading.get_ident()}")
        with open(tmp, "w") as f:
            f.write(json.dumps(rec))
        os.replace(tmp, self._lease_path(self.rank))
        if _obs.enabled():
            _gang_m()["alive"].labels(worker=str(self.rank)).set(1.0)
        # fleet-telemetry publication rides the heartbeat cadence: with no
        # publisher installed (or HETU_OBS=0) this is one global load and
        # a branch
        _obs_fleet.maybe_publish()

    def start(self) -> None:
        """Heartbeat now and keep renewing on a daemon thread.  When the
        launcher exported a snapshot interval
        (:data:`~hetu_tpu.obs.fleet.ENV_OBS_SNAPSHOT`) and no publisher is
        installed yet, this worker starts publishing fleet-telemetry
        snapshots into the gang dir on the heartbeat cadence."""
        if _obs_fleet.get_publisher() is None:
            pub = _obs_fleet.publisher_from_env(self.gang_dir, self.rank)
            if pub is not None:
                _obs_fleet.install_publisher(pub)
        self.heartbeat()
        self._stop.clear()

        def beat():
            while not self._stop.wait(self.interval):
                self.heartbeat()

        self._thread = threading.Thread(target=beat, daemon=True,
                                        name=f"gang-heartbeat-{self.rank}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval + 1.0)
            self._thread = None

    def leave(self) -> None:
        """Clean departure: stop heartbeating and remove the lease so
        peers see an intentional exit, not a lost worker."""
        self.stop()
        pub = _obs_fleet.get_publisher()
        if pub is not None and pub.rank == self.rank \
                and pub.gang_dir == self.gang_dir:
            # force one last snapshot so the fleet surface keeps this
            # worker's final counters/journal after the process exits —
            # then uninstall, so a process that later joins another gang
            # (or rejoins under a new rank) doesn't keep publishing into
            # this gang's dir under the stale rank
            pub.publish()
            _obs_fleet.install_publisher(None)
        try:
            os.remove(self._lease_path(self.rank))
        except OSError:
            pass
        if _obs.enabled():
            _gang_m()["alive"].remove(worker=str(self.rank))

    def read_lease(self, rank: int) -> Optional[dict]:
        try:
            with open(self._lease_path(rank)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def members(self) -> list:
        """Every rank holding a lease file (fresh or stale), sorted."""
        out = []
        for name in os.listdir(self.dir):
            m = re.match(r"^worker_(\d+)\.lease$", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def alive(self, now: Optional[float] = None) -> list:
        """Ranks whose lease age is within ``lease_ttl``."""
        now = self.clock() if now is None else now
        out = []
        for r in self.members():
            lease = self.read_lease(r)
            if lease is not None and now - lease.get("ts", 0) <= self.lease_ttl:
                out.append(r)
        return out

    def lost(self, now: Optional[float] = None) -> list:
        """Members whose lease expired.  Each is journaled as
        ``worker_lost`` once per membership instance (the survivors all
        detect; the journal dedupes per process)."""
        now = self.clock() if now is None else now
        alive = set(self.alive(now))
        out = [r for r in self.members() if r not in alive]
        for r in out:
            if r not in self._announced:
                self._announced.add(r)
                lease = self.read_lease(r) or {}
                if _obs.enabled():
                    _gang_m()["lost"].inc()
                    _gang_m()["alive"].remove(worker=str(r))
                _obs_journal.record(
                    "worker_lost", rank=r, generation=self.generation,
                    reason="lease_expired",
                    age_s=round(now - lease.get("ts", now), 3))
        return out

    def barrier(self, generation: int, ranks: Sequence[int],
                timeout: float = 30.0, poll: float = 0.05) -> None:
        """Write this worker's ack for ``generation`` and wait until every
        rank in ``ranks`` has acked.  Raises ``TimeoutError`` naming the
        stragglers."""
        ack_dir = os.path.join(self.dir, f"gen_{int(generation):08d}")
        os.makedirs(ack_dir, exist_ok=True)
        with open(os.path.join(ack_dir, f"ack_{self.rank:04d}"), "w") as f:
            f.write(str(self.clock()))
        deadline = time.monotonic() + timeout
        want = {int(r) for r in ranks}
        while True:
            have = {int(m.group(1)) for m in
                    (re.match(r"^ack_(\d+)$", n)
                     for n in os.listdir(ack_dir)) if m}
            if want <= have:
                return
            if time.monotonic() > deadline:
                err = TimeoutError(
                    f"gang barrier for generation {generation} timed out: "
                    f"waiting on ranks {sorted(want - have)}")
                err.stragglers = sorted(want - have)
                raise err
            time.sleep(poll)

    def rescale(self, timeout: float = 30.0) -> tuple:
        """Commit a new membership generation after worker loss: the
        surviving set is the current ``alive()`` ranks, the generation is
        bumped, everyone barriers on it, and survivors re-rank densely
        (old ranks sorted → new ranks 0..m-1).

        Returns ``(generation, rank_map)`` where ``rank_map`` maps old
        rank → new rank.  The caller then rebuilds/``rescale``s its
        :class:`GangCheckpointer` and resumes from the manifest."""
        old_world = len(self.members())
        evicted = self.lost()  # journal any not-yet-announced evictions
        survivors = self.alive()
        if self.rank not in survivors:
            survivors = sorted(set(survivors) | {self.rank})
        self.generation += 1
        self.heartbeat()  # lease now carries the new generation
        barrier_t0 = time.monotonic()
        try:
            self.barrier(self.generation, survivors, timeout=timeout)
        except TimeoutError as e:
            # journal hygiene: a stuck rescale barrier must be visible in
            # post-mortems, not only in whichever process saw the raise
            _obs_journal.record(
                "rescale_timeout", generation=self.generation,
                waiting_on=getattr(e, "stragglers", None),
                timeout_s=float(timeout))
            raise
        # barrier wall time is lost time: bill the goodput rescale bucket
        _obs_goodput.record_event("rescale", time.monotonic() - barrier_t0)
        # every survivor acked the new generation, so all of them have
        # observed the eviction — the stale leases can go (otherwise the
        # dead worker would be re-"detected" forever).  Best-effort and
        # idempotent across the survivors racing to do it.
        for r in evicted:
            try:
                os.remove(self._lease_path(r))
            except OSError:
                pass
        rank_map = {old: new for new, old in enumerate(sorted(survivors))}
        if _obs.enabled():
            _gang_m()["generation"].set(self.generation)
            _gang_m()["size"].set(len(survivors))
            _gang_m()["rescales"].inc()
        _obs_journal.record("gang_rescale", generation=self.generation,
                            old_world=old_world,
                            new_world=len(survivors),
                            survivors=sorted(survivors))
        return self.generation, rank_map


# ------------------------------------------------- in-process simulation

class ElasticGang:
    """Deterministic in-process simulation of an elastic data-parallel
    gang — the chaos-testable runtime for the whole lifecycle.

    The gang drives ONE jitted trainer with the lock-step global update
    (under data parallelism every worker's post-step state is identical,
    so simulating N replicas means simulating the global step once); the
    per-worker structure that matters for elasticity is simulated
    faithfully: per-worker *storage* (shard + ring-replica directories),
    per-worker *liveness* (a step-clock heartbeat lease), and per-worker
    *data assignment* (:func:`gang_data_partition`; the global batch is
    genuinely recomposed from the per-worker shards in global index
    order every step, so partition invariance is exercised, not
    assumed).  Honest multi-process behavior is covered by
    ``GangMembership`` + ``GangCheckpointer`` over
    ``launch.simulate_workers``.

    Fault kinds consumed from the installed
    :class:`~hetu_tpu.exec.faults.FaultPlan` at the top of each global
    step (events must set ``worker=``):

    - ``worker_kill``: the target rank stops heartbeating forever.
    - ``worker_stall``: the target misses heartbeats for ``arg`` steps —
      within ``lease_steps`` it rejoins silently; past it, it is evicted
      exactly like a kill (and, being fenced by the generation bump,
      never commits again).
    - ``shard_loss``: the target's shard *directory* is deleted —
      recovery must ride the ring replica (``shard_restore``).

    A worker whose lease expires triggers: ``worker_lost`` journal event
    → generation bump + dense re-rank (``gang_rescale``) → restore from
    the newest intact manifest (state composed from shards, RNG stream
    reset, step counter rewound).  With no manifest yet the gang rewinds
    to its initial state (snapshotted at construction).  ``rejoin()``
    grows the gang back — joiners adopt the survivors' replicated state
    (a live broadcast; the manifest path is for cold joins), so an n→n
    kill/recover run replays to a bitwise-identical end state.

    **Partial reduce** (``partial=PartialReduceConfig(...)``): the step
    gains an *arrival-collection phase* — every live worker's shard
    gradient is staged individually, the deadline cut
    (:meth:`~hetu_tpu.exec.partial.PartialReduceConfig.cut`) picks the
    contributors, and the update is the weighted mean over contributors
    plus any matured late-gradient folds
    (:class:`~hetu_tpu.exec.partial.PartialReducer`).  A
    ``worker_stall`` then models a *straggler*, not a lost worker: the
    stalled rank keeps its lease (it is slow, not dead — riding out
    stragglers without eviction is the point of partial reduce) and its
    gradients arrive late by the remaining stall length; only
    ``worker_kill`` evicts.  The step clock (``sim_time``) charges each
    step ``1 + wait``, where the synchronous barrier
    (``deadline=inf``) waits for the slowest worker and the partial cut
    waits at most the deadline — the throughput the chaos acceptance
    measures.  Pending correction terms ride the sharded checkpoints
    (reserved ``partialreduce.*`` entries), so a kill/recover replay
    restores mid-flight folds bitwise; on rescale, survivors'
    corrections re-key through the rank map and evicted workers' are
    dropped (``stale_drop`` ``reason="worker_lost"``).  Step metrics
    carry an ``arrivals`` field in both modes (the synchronous path
    reports the full world).
    """

    def __init__(self, trainer, gang_dir: str, *, world_size: int,
                 data_fn: Callable[[int], dict], global_batch_size: int,
                 seed: int = 0, save_every: int = 2, keep: int = 4,
                 lease_steps: int = 1,
                 partial: Optional["_partial.PartialReduceConfig"] = None,
                 goodput=None, numerics=None, controller=None,
                 planner=None, broker=None):
        if getattr(trainer, "_has_staged", False):
            raise ValueError(
                "ElasticGang drives dense data-parallel trainers; staged "
                "host embeddings keep per-worker server state the gang "
                "checkpoint does not cover")
        self.trainer = trainer
        self.gang_dir = gang_dir
        self.world_size = int(world_size)
        self.data_fn = data_fn
        self.global_batch_size = int(global_batch_size)
        self.seed = int(seed)
        self.save_every = int(save_every)
        self.keep = int(keep)
        self.lease_steps = int(lease_steps)
        self.generation = 0
        self.step_count = 0
        self.sim_time = 0.0            # step-clock time spent (1 + wait per step)
        self.history: list = []        # every executed (step, loss), incl. replays
        self.losses_by_step: dict = {}  # final lineage: step -> last loss
        self.last_partition: Optional[list] = None
        self.resume_report: list = []  # diagnoses from the last restore
        self._dead: set = set()
        # ranks whose lease the capacity broker revoked (lend()): a
        # subset of _dead so liveness/live_world treat them as gone, but
        # the rescale journals reason="leased", not a death
        self._lent: set = set()
        self._stalled_until: dict = {}
        self._last_beat = {w: 0 for w in range(self.world_size)}
        # a dedicated obs.goodput.GoodputMeter the gang bills in SIM-TIME
        # units (1 + wait per step): pass one explicitly rather than
        # installing a process-wide meter, which would double-count —
        # Trainer.step's seam bills the installed meter in WALL time
        self.goodput = goodput
        # numerics observability (obs.numerics/obs.divergence): True (or a
        # DivergenceDetector) turns on the per-step cross-replica
        # fingerprint check — every live worker's post-update parameter
        # fingerprints (per group, partial-reduce correction entries
        # included) are compared each committed step and a mismatch
        # journals ``replica_divergence`` naming the step/worker/shard —
        # plus NaN provenance on the first poisoned partial-reduce
        # contribution and per-step gradient stats into the installed
        # flight recorder.  Default off: the sim costs nothing new.
        if numerics is True:
            numerics = _obs_divergence.DivergenceDetector()
        self.divergence: Optional[_obs_divergence.DivergenceDetector] = \
            numerics if numerics else None
        self._pending_flips: dict = {}
        self._provenanced_steps: set = set()
        self._last_grad_stats: Optional[dict] = None
        # closed-loop remediation (exec.controller): an attached
        # RuntimeController consumes this gang's signals (lag EWMAs,
        # divergence verdicts) after every committed step and drives the
        # actuators below (set_partial_deadline, quarantine).  None falls
        # back to the process-wide installed controller; with neither,
        # the post-commit seam is one attribute + one global load and a
        # branch.
        self.controller = controller
        # unified-deployment replanning (hetu_tpu/plan.PlanApplier): an
        # attached planner re-plans against the surviving world after
        # every rescale — eviction becomes *planning*, not just
        # re-ranking.  None keeps the legacy behavior exactly.
        self.planner = planner
        # elastic chip market (hetu_tpu/broker.CapacityBroker): the
        # broker leases this gang's chips to the serving fleet (lend /
        # rejoin) and observes committed steps through on_gang_step.
        # The attach runs here because the broker is usually built
        # first, before the gang exists to hand it.
        self.broker = broker
        if broker is not None:
            broker.attach_gang(self)
        self.partial = partial
        self.reducer: Optional[_partial.PartialReducer] = None
        if partial is not None:
            self.reducer = _partial.PartialReducer(partial)
            self._grad_fn, self._apply_fn = _partial.grad_apply_fns(trainer)
        os.makedirs(gang_dir, exist_ok=True)
        # rescue floor for a loss before the first checkpoint: the
        # pristine state + RNG, kept on host
        import jax
        self._initial_sd = {k: np.asarray(jax.device_get(v))
                            for k, v in named_parameters(trainer.state)}
        self._initial_rng = get_seed_status()
        if _obs.enabled():
            m = _gang_m()
            m["generation"].set(0)
            m["size"].set(self.world_size)
            for w in range(self.world_size):
                m["alive"].labels(worker=str(w)).set(1.0)

    # -- gang checkpointing -------------------------------------------------

    def save(self) -> str:
        """Every live rank writes its shard + ring replica; then the
        signed manifest for the current step.  Pending partial-reduce
        correction terms ride along as reserved ``partialreduce.*``
        entries — sharded, ring-replicated, and manifest-signed like any
        parameter."""
        sd = dict(named_parameters(self.trainer.state))
        if self.reducer is not None:
            sd.update(self.reducer.state_entries())
        rng = get_seed_status()
        for r in range(self.world_size):
            save_shard(self.gang_dir, r, self.world_size, self.step_count,
                       sd, generation=self.generation,
                       extra={"step": self.step_count})
        path = write_manifest(self.gang_dir, self.step_count,
                              self.generation, self.world_size, rng=rng,
                              extra={"step": self.step_count})
        prune_gang(self.gang_dir, self.keep)
        return path

    def _restore(self, rank_map: Optional[dict] = None) -> int:
        """Load the newest intact manifest into the trainer (ring replicas
        cover lost shards); falls back to the initial snapshot when no
        checkpoint exists yet.  Returns the restored step.  Partial-reduce
        correction entries are split back out of the composed state and
        reloaded into the reducer (re-keyed through ``rank_map`` after a
        rescale; an evicted worker's corrections are dropped)."""
        step, _gen, sd, _extra, report = load_gang_checkpoint(self.gang_dir)
        self.resume_report = report
        if step is None:
            sd, step = self._initial_sd, 0
            reset_seed_seqnum(*self._initial_rng)
        sd, corr = _partial.split_state_entries(sd)
        if self.reducer is not None:
            self.reducer.load_state_entries(corr, rank_map=rank_map,
                                            step=step)
        self.trainer.state = _to_device(load_state_dict(
            self.trainer.state, sd, consider_splits=True))
        self.step_count = step
        return step

    # -- membership ---------------------------------------------------------

    def _consume_faults(self, step: int) -> None:
        plan = _faults.active_plan()
        if plan is None:
            return
        plan.advance(step)
        while True:
            # require_worker: a simulate_workers-convention event
            # (worker=None, step-as-worker-index) stays PENDING for its
            # own harness instead of being popped here
            f = plan.take("worker_kill", "worker_stall", "shard_loss",
                          "bit_flip", require_worker=True)
            if f is None:
                return
            w = int(f.worker)
            if w >= self.world_size:
                continue  # target already gone at fire time
            if f.kind == "bit_flip":
                # post-reduce corruption: rank w's replica of the updated
                # parameters differs by one bit — consumed by the
                # divergence check after this step commits
                self._pending_flips.setdefault(w, []).append(f)
            elif f.kind == "shard_loss":
                # the STORAGE dies; orthogonal to process liveness (a
                # killed worker's disk is usually the one that vanishes)
                shutil.rmtree(worker_dir(self.gang_dir, w),
                              ignore_errors=True)
            elif w in self._dead:
                continue
            elif f.kind == "worker_kill":
                self._dead.add(w)
            else:  # worker_stall
                # overlapping stalls EXTEND, never shorten: a heavy-tailed
                # schedule's 20-step stall must not be clipped by a later
                # 1-step event on the same worker.  In partial mode the
                # freeze is in SIM-TIME units, so time the gang spends
                # waiting at a barrier drains the stall — a k-unit stall
                # costs the synchronous (deadline=inf) baseline k units
                # once, not k+(k-1)+...+1 (which would quadratically
                # inflate the baseline the throughput gain is measured
                # against); in sync mode it stays the step-indexed
                # missed-heartbeat count the lease compares.
                until = (self.sim_time + float(f.arg or 1)
                         if self.partial is not None
                         else float(step + int(f.arg or 1)))
                self._stalled_until[w] = max(
                    self._stalled_until.get(w, 0), until)

    def _rescale(self, lost: list, step: int) -> None:
        for w in lost:
            _obs_journal.record(
                "worker_lost", rank=w, generation=self.generation,
                step=step,
                reason="leased" if w in self._lent
                else "dead" if w in self._dead else "lease_expired")
            if _obs.enabled():
                _gang_m()["lost"].inc()
                _gang_m()["alive"].remove(worker=str(w))
        survivors = [w for w in range(self.world_size) if w not in lost]
        if not survivors:
            raise GangError("every worker lost — nothing left to rescale")
        old_world = self.world_size
        remap = {old: new for new, old in enumerate(survivors)}
        self.generation += 1
        self.world_size = len(survivors)
        self._dead = set()
        self._lent = set()
        self._stalled_until = {remap[o]: v for o, v in
                               self._stalled_until.items() if o in remap}
        self._pending_flips = {remap[o]: v for o, v in
                               self._pending_flips.items() if o in remap}
        if self.divergence is not None:
            self.divergence.rescaled()
        resumed = self._restore(rank_map=remap)
        self._last_beat = {w: resumed for w in range(self.world_size)}
        _obs_journal.record("gang_rescale", generation=self.generation,
                            old_world=old_world, new_world=self.world_size,
                            resumed_step=resumed)
        if _obs.enabled():
            m = _gang_m()
            m["generation"].set(self.generation)
            m["size"].set(self.world_size)
            m["rescales"].inc()
            for w in range(self.world_size):
                m["alive"].labels(worker=str(w)).set(1.0)
        if self.planner is not None:
            # re-plan against the survivors (journal: plan_emit +
            # plan_apply) — deterministic, so a same-seed replay emits
            # the byte-identical signed plan at the same step
            self.planner.replan_for_gang(self, trigger="gang_rescale")

    # -- controller actuators -----------------------------------------------

    def set_partial_deadline(self, deadline: float, *,
                             source: str = "controller"
                             ) -> "_partial.PartialReduceConfig":
        """Swap in a retuned partial-reduce deadline (clamped by the
        policy's own rails) — the controller's deadline actuator.  Both
        the gang's cut policy and the reducer's journal view move
        together, so the very next ``partial_step`` event carries the
        new ``deadline_source``."""
        if self.partial is None:
            raise ValueError("gang runs the synchronous barrier: there "
                             "is no partial-reduce deadline to tune")
        cfg = dataclasses.replace(self.partial,
                                  deadline=self.partial.clamp(deadline),
                                  deadline_source=source)
        self.partial = cfg
        self.reducer.config = cfg
        return cfg

    @property
    def live_world(self) -> int:
        """Workers currently live: in the membership and not killed or
        quarantined (the lease check has not necessarily evicted the
        dead ones yet)."""
        return self.world_size - len(self._dead)

    def can_quarantine(self, worker: int) -> bool:
        """Whether evicting ``worker`` is safe: it must be a live rank
        and not the LAST live one — remediation must never turn a
        divergent run into a dead one (with another worker already down,
        quarantining the sole survivor would leave nothing to rescale).
        The controller consults this before deciding, so dry-run
        decisions match what an active controller would actually do."""
        w = int(worker)
        return (0 <= w < self.world_size and w not in self._dead
                and self.live_world >= 2)

    def quarantine(self, worker: int) -> bool:
        """Evict ``worker`` from the gang — the controller's divergence
        actuator.  Its lease is revoked (the next step's liveness check
        sees it lost and rescales) and its shard *storage* is dropped:
        a replica whose post-update state diverged cannot be trusted to
        have written honest bytes either, so the rescale's restore
        recovers its shard from the ring predecessor's replica
        (``shard_restore``) instead.  Returns False — acting nothing —
        when the worker is already gone or is the last live one
        (:meth:`can_quarantine`)."""
        w = int(worker)
        if not self.can_quarantine(w):
            return False
        self._dead.add(w)
        # revoke the lease outright: eviction at the NEXT step, not after
        # lease_steps of silence — quarantine is a decision, not a timeout
        self._last_beat[w] = -(10 ** 9)
        shutil.rmtree(worker_dir(self.gang_dir, w), ignore_errors=True)
        return True

    def lend(self, n: int = 1) -> list:
        """Release the ``n`` highest live ranks to the capacity broker
        (hetu_tpu/broker): checkpoint NOW, then revoke their leases so
        the very next step's liveness check rescales the gang down with
        ZERO replayed steps — the manifest written here is at the
        current step, so the rescale's restore rewinds nowhere and the
        RNG seqnum resumes exactly where an uninterrupted run would be.
        That save-at-lend is what makes the post-lend loss trajectory
        bitwise equal to an uninterrupted run (partition invariance
        covers the world change itself).  Returns the lent ranks; the
        broker hands them back through :meth:`rejoin`."""
        n = int(n)
        if n < 1:
            raise ValueError(f"lend needs n >= 1, got {n}")
        live = [w for w in range(self.world_size) if w not in self._dead]
        if len(live) - n < 1:
            raise GangError(
                f"cannot lend {n} of {len(live)} live workers — the "
                f"gang must keep at least one")
        self.save()
        lent = live[-n:]
        for w in lent:
            # a lent rank is gone-but-not-dead: _dead drives liveness
            # and live_world; _lent re-labels the eviction journal
            self._dead.add(w)
            self._lent.add(w)
            # revoke outright (the quarantine idiom): eviction at the
            # NEXT step, not after lease_steps of silence — a grant is
            # a decision, not a timeout.  Storage stays: the shard is
            # honest, the ring replica set must survive the restore.
            self._last_beat[w] = -(10 ** 9)
        return lent

    def rejoin(self, n: int = 1) -> None:
        """Grow the gang by ``n`` workers (preempted capacity coming
        back).  Joiners adopt the survivors' replicated state; the data
        partition and worker keys re-derive from the bumped generation."""
        old_world = self.world_size
        self.world_size += int(n)
        self.generation += 1
        for w in range(old_world, self.world_size):
            self._last_beat[w] = self.step_count
        _obs_journal.record("gang_rescale", generation=self.generation,
                            old_world=old_world, new_world=self.world_size,
                            resumed_step=self.step_count)
        if _obs.enabled():
            m = _gang_m()
            m["generation"].set(self.generation)
            m["size"].set(self.world_size)
            m["rescales"].inc()
            for w in range(old_world, self.world_size):
                m["alive"].labels(worker=str(w)).set(1.0)

    # -- the step loop ------------------------------------------------------

    def _one_step(self) -> Optional[dict]:
        s = self.step_count + 1
        self._consume_faults(s)
        for w in range(self.world_size):
            # under partial reduce a stalled worker is a STRAGGLER, not a
            # lost worker: it keeps heartbeating (slow, not dead) and its
            # lateness is handled by the deadline cut, never the lease
            beating = (self.partial is not None
                       or s >= self._stalled_until.get(w, 0))
            if w not in self._dead and beating:
                self._last_beat[w] = s
        lost = [w for w in range(self.world_size)
                if s - self._last_beat[w] > self.lease_steps]
        if lost:
            self._rescale(lost, s)
            return None  # the step counter rewound; the loop re-drives
        gb = self.data_fn(s)
        parts = gang_data_partition(self.seed, self.generation,
                                    self.world_size, s,
                                    self.global_batch_size)
        # each worker materializes its shard, then the gang composes the
        # GLOBAL batch back in global index order — recomposition is the
        # partition-invariance the n→n bitwise guarantee rests on
        shards = [{k: np.asarray(v)[p] for k, v in gb.items()}
                  for p in parts]
        self.last_partition = parts
        if self.partial is not None:
            metrics = self._partial_step(s, shards, parts)
        else:
            inv = np.argsort(np.concatenate(parts), kind="stable")
            import jax.numpy as jnp
            batch = {k: jnp.asarray(
                np.concatenate([sh[k] for sh in shards])[inv]) for k in gb}
            metrics = self.trainer.step(batch, next_key())
            metrics["arrivals"] = self.world_size
            self.sim_time += 1.0
            if self.goodput is not None:
                # replayed step ids after a rescale rewind land in the
                # "rescale" bucket via the meter's step high-water mark
                self.goodput.record_step(
                    1.0, step=s, skipped=bool(metrics.get("skipped")))
        self.step_count = s
        loss = float(metrics["loss"])
        self.history.append((s, loss))
        self.losses_by_step[s] = loss
        if self.divergence is not None:
            self._check_divergence(s)
        if self.save_every > 0 and s % self.save_every == 0:
            self.save()
        # closed-loop remediation rides the committed step, AFTER the
        # save: a quarantine's storage drop must outlive this step's
        # shard writes so the rescale restore exercises the ring replica
        _controller.maybe_gang_step(self, s, metrics)
        if self.broker is not None:
            self.broker.on_gang_step(self, s)
        return metrics

    # -- numerics observability ---------------------------------------------

    def _replica_state(self) -> dict:
        """Host flat view of the post-update parameters every replica
        must hold bitwise — pending partial-reduce corrections included
        (they persist as ``partialreduce.*`` entries, so a diverged
        correction term is nameable like any parameter shard)."""
        import jax
        sd = {k: np.asarray(jax.device_get(v)) for k, v in
              named_parameters(self.trainer.state.model)}
        if self.reducer is not None:
            sd.update(self.reducer.state_entries())
        return sd

    def _check_divergence(self, s: int) -> None:
        """Compare every live worker's post-update parameter fingerprints
        for step ``s``.  The lock-step simulation holds ONE set of
        parameters, so healthy replicas agree by construction; an
        injected ``bit_flip`` fault perturbs the target rank's replica
        view by one bit, and the detector must name it."""
        sd = self._replica_state()
        fps = _obs_numerics.host_tree_fingerprints(sd)
        per_worker = {}
        for w in range(self.world_size):
            flips = self._pending_flips.pop(w, None)
            per_worker[w] = (fps if not flips
                             else _flipped_fingerprints(sd, fps, flips))
        if self.partial is not None:
            # ring the step's numbers (partial mode bypasses the
            # Trainer.step seam, so the gang feeds the recorder itself);
            # the post-update fingerprints ride along for the snapshot-
            # cadence gauge publication
            stats: dict = {"param_fp": fps}
            if self._last_grad_stats is not None:
                stats["grad"] = self._last_grad_stats
                self._last_grad_stats = None
            _obs_numerics.observe(stats, step=s)
        self.divergence.check(s, per_worker)

    def _maybe_provenance(self, s: int, model, shard: dict, key) -> None:
        """NaN provenance for one poisoned partial-reduce contribution:
        interpret the grad jaxpr on the exact (model, shard, key) and
        journal the first non-finite producer — once per step, post-
        mortem path only."""
        if self.divergence is None or s in self._provenanced_steps:
            return
        self._provenanced_steps.add(s)
        try:
            rep = _obs_numerics.loss_provenance(
                self.trainer.loss_fn, model,
                {k: v for k, v in shard.items()}, key)
        except Exception as e:
            _obs_journal.record("nan_provenance", step=s,
                                op="provenance_error", origin="error",
                                error=str(e))
            return
        if rep is not None:
            _obs_journal.record(
                "nan_provenance", step=s, op=rep["op"],
                origin=rep["origin"], site=rep.get("site"),
                **({"leaf": rep["leaf"]} if "leaf" in rep else {}))

    def _partial_step(self, s: int, shards: list, parts: list) -> dict:
        """The arrival-collection phase: stage every live worker's shard
        gradient, apply the deadline cut, reduce over contributors plus
        matured folds, and stash the late gradients as corrections."""
        import jax
        import jax.numpy as jnp
        t0 = time.perf_counter()
        plan = _faults.active_plan()
        poisoned: set = set()
        if plan is not None:
            while True:
                # gang-convention grad_nan (worker= set): poison that
                # rank's shard so ITS contribution goes non-finite — the
                # NaN-late-fold chaos shape
                f = plan.take("grad_nan", require_worker=True)
                if f is None:
                    break
                if int(f.worker) < self.world_size:
                    poisoned.add(int(f.worker))
            # untargeted grad_nan = the sync path's whole-batch poisoning
            # (executor's _fault_hook seam, which this path bypasses):
            # every shard goes NaN, so the same plan drains — and injects
            # the same chaos — in either mode
            while plan.take("grad_nan", require_worker=False) is not None:
                poisoned.update(range(self.world_size))
        # arrival delay = how far into the future (in sim-time units) each
        # worker's frozen-until lies at the START of this step
        delays = {w: float(max(0.0, self._stalled_until.get(w, 0)
                               - self.sim_time))
                  for w in range(self.world_size)}
        ontime, wait, degraded = self.partial.cut(delays)
        # straggler attribution: fold this cut's per-worker delays into
        # the arrival-lag EWMAs (hetu_partial_worker_lag_seconds{worker=})
        self.reducer.lags.observe(delays)
        self.sim_time += 1.0 + wait
        key = next_key()  # ONE global draw per step, like the sync path
        model = self.trainer.state.model
        contributions: dict = {}
        losses: dict = {}
        template = None
        nonfinite_seen = False
        for w in range(self.world_size):
            n = float(len(parts[w]))
            if w not in ontime:
                delay = int(np.ceil(delays[w]))
                if delay > self.partial.tau:
                    # born stale: this gradient can never fold within tau,
                    # so skip the jitted grad entirely — a 50-step
                    # straggler must not cost 50 dead gradient
                    # computations.  stage_late drops it at the door with
                    # the same journal/counter record either way.
                    self.reducer.stage_late(w, s, s + delay, n, {})
                    continue
            shard = {k: jnp.asarray(v) for k, v in shards[w].items()}
            if w in poisoned:
                shard = _faults._poison_batch(shard)
            loss, grads = self._grad_fn(model, shard,
                                        jax.random.fold_in(key, w))
            flat = {}
            for name, g in named_parameters(grads):
                a = np.asarray(g)
                if np.issubdtype(a.dtype, np.floating):
                    flat[name] = a
            losses[w] = (n, float(loss))
            if self.divergence is not None and (
                    not np.isfinite(losses[w][1])
                    or not _partial._is_finite(flat)):
                # numerics post-mortem on the poisoned contribution: the
                # provenance interpreter sees the exact (model, shard,
                # key) that went non-finite, so it names where the NaN
                # entered (the poisoned input leaf, or the op that bore
                # it); once per step, cold path only
                nonfinite_seen = True
                _obs_numerics.note_outcome(False, step=s,
                                           signal="contribution")
                self._maybe_provenance(s, model, shard,
                                       jax.random.fold_in(key, w))
            if w in ontime:
                if template is None:
                    template = grads
                contributions[w] = (n, flat)
            else:
                self.reducer.stage_late(w, s, s + int(np.ceil(delays[w])),
                                        n, flat)
        combined, info = self.reducer.reduce(s, contributions,
                                             degraded=degraded, waited=wait)
        if self.divergence is not None:
            if not nonfinite_seen:
                _obs_numerics.note_outcome(True, step=s,
                                           signal="contribution")
            if combined is not None:
                # the reduced gradient's per-group stats ride the flight
                # recorder ring (host numpy — the gradients are already
                # on host in this harness, no device sync added)
                self._last_grad_stats = _obs_numerics.host_group_stats(
                    combined)
        if combined is not None:
            gtree = load_state_dict(template, combined)
            self.trainer.state = self._apply_fn(self.trainer.state, gtree)
        # reported loss: the used on-time contributors; when a step commits
        # on folds alone (every on-time gradient was non-finite), fall back
        # to whichever live workers' losses ARE finite this step, so a
        # committed step never records NaN into the lineage
        report = [w for w in info["used"] if w in losses]
        if not report:
            report = [w for w in sorted(losses)
                      if np.isfinite(losses[w][1])]
        total = sum(losses[w][0] for w in report)
        loss = (sum(losses[w][0] * losses[w][1] for w in report) / total
                if total else float("nan"))
        if _obs.enabled():
            # keep the hetu_step_* dashboard series alive: this path
            # bypasses Trainer.step, which is where they normally come
            # from — a gang flipped to partial mode must not flatline
            # step latency / outcome / examples-per-sec monitoring
            dt = time.perf_counter() - t0
            sm = _executor._step_m()
            sm["steps"].labels(
                outcome="skipped" if combined is None else "ok").inc()
            sm["latency"].observe(dt)
            if combined is not None:
                committed = int(sum(contributions[w][0]
                                    for w in info["used"]))
                if committed:
                    sm["examples"].inc(committed)
                    if dt > 0:
                        sm["eps"].set(committed / dt)
        if self.goodput is not None:
            # sim-time accounting: the step cost 1 + wait units, the wait
            # attributed to the slowest CONTRIBUTOR at the cut — cut()
            # computes wait over the on-time set (everyone on a degraded
            # step), so a dropped worker past the deadline never gets
            # billed for wait it did not cause (lowest rank wins ties,
            # so seeded replays attribute identically)
            straggler = (max(sorted(ontime), key=lambda w: delays[w])
                         if wait > 0 and ontime else None)
            self.goodput.record_step(1.0 + wait, step=s, waited=wait,
                                     straggler=straggler,
                                     skipped=combined is None)
        return {"loss": loss, "arrivals": info["arrivals"],
                "late_folds": info["late_folds"],
                "dropped": info["dropped"], "degraded": info["degraded"],
                "waited": wait}

    def run_until(self, target_step: int) -> None:
        """Drive global steps (including any rescale/replay detours) until
        the gang has committed ``target_step``."""
        guard = 0
        while self.step_count < target_step:
            self._one_step()
            guard += 1
            if guard > 100 * target_step + 1000:
                raise GangError(
                    f"gang cannot reach step {target_step}: stuck "
                    f"rescaling at step {self.step_count}")


def _to_device(tree):
    # mirror of resilience._to_device: only lift numpy leaves, keeping
    # python scalars weakly typed so resumed jit programs promote the
    # same way and the lineage stays bitwise
    import jax.numpy as jnp
    import jax.tree_util as jtu
    return jtu.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, tree)


def _flipped_fingerprints(sd: dict, fps: dict, flips: list) -> dict:
    """Fingerprints of a replica whose state differs from ``sd`` by the
    injected bit flips: honestly re-fingerprint a perturbed copy of the
    target array (never fake the fingerprint directly) so the detector
    is exercised end to end.  Each fault's ``arg`` indexes the flipped
    bit; the target is the first floating entry in sorted-name order —
    deterministic, so seeded replays diverge identically."""
    names = sorted(n for n in sd
                   if np.issubdtype(np.asarray(sd[n]).dtype, np.floating)
                   and np.asarray(sd[n]).size > 0)
    if not names:
        return fps
    out = dict(fps)
    target = names[0]
    a = np.asarray(sd[target]).copy()
    for f in flips:
        bit = int(f.arg or 0)
        if a.dtype.itemsize == 8:
            u = a.reshape(-1).view(np.uint64)
        elif a.dtype.itemsize == 4:
            u = a.reshape(-1).view(np.uint32)
        elif a.dtype.itemsize == 2:
            u = a.reshape(-1).view(np.uint16)
        else:
            u = a.reshape(-1).view(np.uint8)
        width = u.dtype.itemsize * 8
        u[(bit // width) % u.size] ^= np.asarray(
            1 << (bit % width), u.dtype)
    from hetu_tpu.obs.numerics import _group_of, host_tree_fingerprints
    group = _group_of(target, 2)
    members = {n: (a if n == target else sd[n]) for n in sd
               if _group_of(n, 2) == group}
    out.update(host_tree_fingerprints(members))
    return out
