"""Resilient training driver: the survival layer over ``exec.Trainer``.

Hetu's headline features are survival features — the cache-enabled PS
tolerates worker churn (HET, VLDB'22) and partial reduce rides out
stragglers (SIGMOD'21) — and the repo already has the low-level pieces
(PS reconnect/backoff in ``embed/net.py``, atomic/async checkpoints in
``exec/checkpoint.py``).  ``ResilientTrainer`` composes them into a
training loop that actually survives faults:

1. **Periodic async checkpointing** with rolling retention, a CRC32
   integrity footer on every file (``checkpoint._atomic_write``), and
   **auto-resume** that scans ``ckpt.step_*`` files newest-first and skips
   corrupt/torn ones with a clear ``CheckpointCorrupt``/``CheckpointError``
   diagnosis.
2. **NaN/Inf anomaly policy** on loss and grad-norm: skip-step (the update
   is rejected BEFORE it is committed or staged-embedding grads are pushed
   — via ``Trainer.grad_guard``), then rollback-to-last-checkpoint after
   ``max_consecutive_anomalies`` anomalies in a row.  A skipped step also
   rewinds the global RNG seqnum, so the surviving steps replay the exact
   key sequence of an uninjected run — fault-injected lineage stays bitwise
   identical (the chaos tests assert this).
3. **Preemption handling**: SIGTERM/SIGINT set a flag; at the next step
   boundary the driver performs a final SYNCHRONOUS save and raises
   :class:`Preempted` — the TPU-preemption shape (the maintenance notice
   arrives as SIGTERM, the process has seconds, the checkpoint must land).
4. **Per-step watchdog**: the device program runs under a deadline; a hang
   raises :class:`BackendUnresponsive` instead of wedging forever — the
   ``backend_unreachable`` failure in ``BENCH_r05.json`` sat for 240 s with
   no watchdog; this is that watchdog.

Faults are injected deterministically by ``exec.faults`` (the plan's step
counter is advanced here, at the top of every step).
"""

from __future__ import annotations

import os
import re
import signal
import threading
import time
from typing import Any, Optional

import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np

from hetu_tpu.core import get_seed_status, next_key, reset_seed_seqnum
from hetu_tpu.core.module import named_parameters
from hetu_tpu.exec import controller as _controller
from hetu_tpu.exec import faults as _faults
from hetu_tpu.exec.checkpoint import (AsyncCheckpointer, CheckpointError,
                                      load_checkpoint, load_state_dict,
                                      save_checkpoint)
from hetu_tpu.exec.partial import split_state_entries as _split_partial
from hetu_tpu.obs import goodput as _obs_goodput
from hetu_tpu.obs import journal as _obs_journal
from hetu_tpu.obs import numerics as _obs_numerics
from hetu_tpu.obs import registry as _obs

__all__ = ["ResilientTrainer", "BackendUnresponsive", "Preempted",
           "TrainingDiverged", "list_checkpoints", "latest_good_checkpoint",
           "checkpoint_path"]


class BackendUnresponsive(RuntimeError):
    """The device program did not complete within the watchdog deadline —
    a hung backend (dead TPU tunnel, wedged collective), not a slow step."""


class Preempted(Exception):
    """Raised at the step boundary after the final synchronous save that a
    SIGTERM/SIGINT triggered.  ``step`` is the last completed driver step;
    the checkpoint for it is on disk when this propagates."""

    def __init__(self, step: int, signum: int):
        super().__init__(
            f"preempted by signal {signum} at step {step}; final "
            f"checkpoint saved — restart and resume() to continue")
        self.step = step
        self.signum = signum


class TrainingDiverged(RuntimeError):
    """Anomalies kept coming after a rollback was impossible (no usable
    checkpoint) — the run cannot make progress."""


_CKPT_RE = re.compile(r"^ckpt\.step_(\d+)$")

# Resilience-event counters (the journal carries the full records; these
# are the scrapeable aggregates).  Built on first event, never while
# telemetry is disabled.
_res_metrics = None


def _res_m() -> dict:
    global _res_metrics
    if _res_metrics is None:
        reg = _obs.get_registry()
        _res_metrics = {
            "anomalies": reg.counter(
                "hetu_anomaly_skips_total",
                "train steps rejected by the NaN/Inf anomaly policy"),
            "rollbacks": reg.counter(
                "hetu_rollbacks_total",
                "checkpoint rollbacks after consecutive anomalies"),
            "watchdog": reg.counter(
                "hetu_watchdog_fires_total",
                "steps abandoned by the per-step watchdog"),
            "preemptions": reg.counter(
                "hetu_preemptions_total",
                "SIGTERM/SIGINT preemptions honored at a step boundary"),
        }
    return _res_metrics


def checkpoint_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt.step_{step:08d}")


def list_checkpoints(ckpt_dir: str) -> list:
    """All ``ckpt.step_*`` files, ascending by step: ``[(step, path)]``."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return out
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    out.sort()
    return out


def latest_good_checkpoint(ckpt_dir: str, restore_rng: bool = True):
    """Scan ``ckpt.step_*`` newest-first, skipping corrupt/torn files.

    Returns ``(step, path, state, extra, report)`` for the newest loadable
    checkpoint, or ``(None, None, None, None, report)`` when none loads.
    ``report`` lists every file examined as ``(step, path, diagnosis)``
    where diagnosis is ``None`` for the good one and the
    ``CheckpointError`` message (corrupt vs torn, from the CRC footer) for
    the skipped ones."""
    report = []
    for step, path in reversed(list_checkpoints(ckpt_dir)):
        try:
            state, extra = load_checkpoint(path, restore_rng=restore_rng)
        except CheckpointError as e:
            report.append((step, path, str(e)))
            continue
        except OSError as e:  # vanished between listdir and open
            report.append((step, path, f"unreadable: {e!r}"))
            continue
        report.append((step, path, None))
        return step, path, state, extra, report
    return None, None, None, None, report


def _staged_prefixes(tree) -> list:
    """Dotted-path prefixes of every StagedHostEmbedding subtree (in the
    model AND in optimizer moment trees, which mirror its structure).
    Their leaves are transient staging buffers whose shape tracks the last
    batch — the durable table state lives host/server-side and is
    checkpointed by the table's own save/autosave, so these are excluded
    from resilience checkpoints."""
    def is_staged(x):
        return getattr(x, "is_staged_host_embedding", False)

    prefixes = []
    for path, leaf in jtu.tree_flatten_with_path(
            tree, is_leaf=is_staged)[0]:
        if is_staged(leaf):
            name = ".".join(
                str(getattr(k, "name", getattr(k, "idx",
                                               getattr(k, "key", k))))
                for k in path)
            prefixes.append(name + ".")
    return prefixes


def _to_device(tree):
    # only lift numpy leaves: a python-scalar leaf must keep its weak
    # dtype, or resumed jit programs would promote differently and break
    # bitwise lineage
    return jtu.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, tree)


class ResilientTrainer:
    """Fault-surviving driver around a built :class:`~hetu_tpu.exec.Trainer`.

    ::

        tr = Trainer(model, opt, loss_fn, donate=False)
        rt = ResilientTrainer(tr, "ckpts/", save_every=100, keep=3,
                              step_timeout=300.0, handle_signals=True)
        start = rt.resume() or 0            # picks up after a crash
        for step, batch in enumerate(data, start + 1):
            metrics = rt.step(batch)        # may raise Preempted/
                                            #   BackendUnresponsive

    ``donate=False`` on the Trainer is REQUIRED when the anomaly policy is
    active: skip-step keeps the pre-step state alive, which donation would
    have handed to XLA.

    Knobs: ``save_every`` (checkpoint cadence in steps; 0 disables),
    ``keep`` (rolling retention), ``anomaly_policy`` (``"skip"`` |
    ``"raise"`` | ``"off"``), ``max_consecutive_anomalies`` (K: rollback to
    the last checkpoint after K rejected steps in a row),
    ``step_timeout`` (watchdog deadline in seconds; None disables — the
    deadline covers whatever the step does, INCLUDING the first step's jit
    compilation: warm the trainer up first or size it for compile+run),
    ``handle_signals`` (install SIGTERM/SIGINT final-save handlers),
    ``gang`` (a :class:`~hetu_tpu.exec.gang.GangCheckpointer`: saves
    become this worker's shard + ring replica + — on the manifest writer
    — the signed gang manifest, and resume/rollback compose the newest
    intact manifest instead of scanning monolithic files),
    ``partial`` (a :class:`~hetu_tpu.exec.partial.PartialReducer`: the
    reducer's pending late-gradient correction terms become part of
    every checkpoint — as reserved ``partialreduce.*`` state entries, so
    with ``gang=`` they are sharded, ring-replicated, and
    manifest-signed — and resume/rollback restore them, keeping
    kill/recover replays bitwise even mid-fold).

    Composition of partial reduce with the NaN/Inf anomaly policy: a
    non-finite *late fold* is rolled back by the reducer itself — the
    fold, not the step (``stale_drop`` with ``reason="nonfinite"`` in
    the journal) — so the guard here only ever skips steps whose own
    gradients are anomalous; checkpoints taken with ``partial=`` remain
    loadable by a partial-less trainer (the reserved entries are split
    out before ``load_state_dict``).

    ``resume()`` auto-detects the checkpoint format either way: gang
    manifests in ``ckpt_dir`` are preferred when present, and monolithic
    ``ckpt.step_*`` files remain loadable (including as the fallback when
    every manifest is torn).

    With PS-backed embeddings (``RemoteHostEmbedding``) note the division
    of labor: skip-step protects the server too (anomalous grads are
    rejected before the push), but checkpoint ROLLBACK only rewinds worker
    state — pair it with the table's own ``autosave``/``restore_path`` for
    server-side state.
    """

    def __init__(self, trainer, ckpt_dir: str, *, save_every: int = 100,
                 keep: int = 3, anomaly_policy: str = "skip",
                 max_consecutive_anomalies: int = 3,
                 step_timeout: Optional[float] = None,
                 handle_signals: bool = False, gang=None, partial=None,
                 nan_provenance: bool = True):
        if anomaly_policy not in ("skip", "raise", "off"):
            raise ValueError(
                f"anomaly_policy must be 'skip', 'raise' or 'off', "
                f"got {anomaly_policy!r}")
        if anomaly_policy != "off" and getattr(trainer, "donate", False):
            raise ValueError(
                "the anomaly policy must keep the pre-step state alive "
                "across a rejected update: build the Trainer with "
                "donate=False (and no sharding strategy, which always "
                "donates)")
        self.trainer = trainer
        self.ckpt_dir = ckpt_dir
        self.save_every = int(save_every)
        self.keep = int(keep)
        self.anomaly_policy = anomaly_policy
        self.max_consecutive_anomalies = int(max_consecutive_anomalies)
        self.step_timeout = step_timeout
        self.gang = gang
        self.partial = partial
        # numerics post-mortem: on the FIRST anomaly of a streak, dump
        # the flight-recorder ring (obs.numerics.install) and interpret
        # the step's jaxpr to name the first non-finite producer.  Cold
        # path only — a healthy run never pays for it.
        self.nan_provenance = bool(nan_provenance)
        if gang is not None and (os.path.normpath(gang.gang_dir)
                                 != os.path.normpath(ckpt_dir)):
            # save() writes where the gang points but resume()/rollback
            # scan ckpt_dir — a silent mismatch would lose every
            # checkpoint on restart
            raise ValueError(
                f"gang.gang_dir {gang.gang_dir!r} must be ckpt_dir "
                f"{ckpt_dir!r}: saves would land in one directory and "
                f"resume would scan the other")
        os.makedirs(ckpt_dir, exist_ok=True)
        self._ck = AsyncCheckpointer()
        self._step = 0
        self._consec = 0
        self._saved = [p for _s, p in list_checkpoints(ckpt_dir)]
        self._preempt_signum: Optional[int] = None
        self._old_handlers: dict = {}
        # watchdog bookkeeping: each guarded step runs under an epoch; a
        # timed-out epoch is abandoned, and the guard rejects its late
        # commit so a zombie step thread can never mutate trainer state
        # (or push staged grads) behind the caller's back.  The fence lock
        # makes guard-passage and abandonment mutually exclusive: a step
        # whose guard already passed is PAST the point of no return
        # (_committing), and the timeout path then waits for its commit
        # instead of falsely reporting that nothing was committed.
        self._epoch = 0
        self._abandoned: set = set()
        self._committing: set = set()
        self._fence_lock = threading.Lock()
        self._warned_loss_only = False
        self._tls = threading.local()
        # observability for tests/operators
        self.anomalies: list = []    # [(step, loss, grad_norm)]
        self.rollbacks: list = []    # [(at_step, to_step)]
        self.resume_report: list = []
        # the guard is installed even with the anomaly policy off: it is
        # also the commit gate that fences abandoned (timed-out) steps
        trainer.grad_guard = self._guard
        if handle_signals:
            self._install_signals()

    # -- lifecycle ----------------------------------------------------------

    @property
    def step_count(self) -> int:
        """Driver step counter (1-based; checkpoint names use it)."""
        return self._step

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        """Wait out any in-flight save, restore signal handlers, and
        detach the commit gate so the trainer returns to plain
        semantics."""
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)
        self._old_handlers = {}
        # == not `is`: each self._guard access builds a fresh bound method
        if getattr(self.trainer, "grad_guard", None) == self._guard:
            self.trainer.grad_guard = None
        self._ck.wait()

    def _install_signals(self):
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._old_handlers[sig] = signal.signal(sig, self._on_signal)

    def _on_signal(self, signum, frame):
        # only flag it: the handler may run at any bytecode boundary, and
        # saving device state mid-step would snapshot garbage.  The step
        # loop finishes the current step, saves synchronously, raises.
        self._preempt_signum = signum

    # -- resume -------------------------------------------------------------

    def _latest_gang_state(self):
        """(step, sd, extra, report) from the newest intact gang manifest
        in ``ckpt_dir`` — or (None, None, None, report).  Tried whenever a
        gang checkpointer is attached OR manifests are present (format
        auto-detection); keeps the gang generation in sync."""
        from hetu_tpu.exec import gang as _gang
        if self.gang is None and not _gang.list_manifests(self.ckpt_dir):
            return None, None, None, []
        step, generation, sd, extra, report = _gang.load_gang_checkpoint(
            self.ckpt_dir)
        if step is not None and self.gang is not None:
            # never LOWER the generation: after a rescale the newest
            # manifest usually predates the bump, and regressing would
            # void the generation fence (an evicted zombie could sign
            # manifests indistinguishable from the survivors')
            self.gang.generation = max(self.gang.generation,
                                       int(generation))
        return step, sd, extra, report

    def resume(self) -> Optional[int]:
        """Load the newest intact checkpoint (skipping corrupt/torn files
        with a diagnosis in ``resume_report``), restore trainer state and
        the RNG stream, and return the resumed step — or None for a fresh
        start.  Gang manifests (sharded + ring-replicated checkpoints)
        are auto-detected and preferred; monolithic ``ckpt.step_*`` files
        remain the fallback."""
        step, sd, extra, report = self._latest_gang_state()
        if step is not None:
            self.resume_report = report
            self._load_into_trainer(sd, consider_splits=True)
            self._step = int((extra or {}).get("step", step))
            self._consec = 0
            _obs_journal.record("resume", step=self._step, format="gang")
            return self._step
        mstep, path, state, mextra, mreport = latest_good_checkpoint(
            self.ckpt_dir)
        self.resume_report = report + mreport
        if mstep is None:
            return None
        self._load_into_trainer(state)
        self._step = int(mextra.get("step", mstep))
        self._consec = 0
        _obs_journal.record("resume", step=self._step, path=path)
        return self._step

    def _capture(self) -> dict:
        """Flat {dotted.path: array} view of the trainer state — NOT a
        pickled tree: the tree may carry unpicklable static metadata
        (e.g. RemoteHostEmbedding's ctypes PS clients), and a flat dict
        also reloads across a re-built (even re-sharded) trainer of the
        same architecture.  Staged-embedding staging buffers are dropped
        (see ``_staged_prefixes``).

        Leaves are NOT copied here: the checkpoint layer's payload
        snapshot (``_make_payload``) does the one host copy — doing it in
        both layers would double per-save copy time and peak memory."""
        sd = dict(named_parameters(self.trainer.state))
        prefixes = _staged_prefixes(self.trainer.state)
        if prefixes:
            sd = {k: v for k, v in sd.items()
                  if not any(k.startswith(p) for p in prefixes)}
        if self.partial is not None:
            # pending correction terms are training state: losing them on
            # a kill would silently forget late gradients the replay then
            # cannot reproduce
            sd.update(self.partial.state_entries())
        return sd

    def _load_into_trainer(self, sd: dict,
                           consider_splits: bool = False) -> None:
        sd, corr = _split_partial(sd)
        if self.partial is not None:
            self.partial.load_state_entries(corr)
        self.trainer.state = _to_device(load_state_dict(
            self.trainer.state, sd, consider_splits=consider_splits))

    # -- checkpointing ------------------------------------------------------

    def save(self, sync: bool = False) -> str:
        """Checkpoint the current state (async by default) and prune the
        rolling retention window.  With a gang checkpointer attached the
        save is this worker's shard + ring replica (+ manifest on the
        writer rank) and is synchronous: the manifest must not sign a
        shard that is still in flight."""
        if self.gang is not None:
            self._ck.wait()  # order after any in-flight monolithic save
            return self.gang.save(self._step, self._capture(),
                                  extra={"step": self._step})
        path = checkpoint_path(self.ckpt_dir, self._step)
        self._ck.save(path, self._capture(), extra={"step": self._step})
        if sync:
            self._ck.wait()
        if path not in self._saved:
            self._saved.append(path)
        while self.keep > 0 and len(self._saved) > self.keep:
            old = self._saved.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass  # already gone (or never landed) — retention is
                #       best-effort, never fatal
        return path

    def _rollback(self) -> int:
        # the in-flight async save (if any) holds a pre-anomaly snapshot;
        # make it durable before scanning so we roll back as little as
        # possible
        t0 = time.perf_counter()
        self._ck.wait()
        gstep, gsd, gextra, greport = self._latest_gang_state()
        if gstep is not None:
            step, state, extra, report = gstep, gsd, gextra or {}, greport
        else:
            step, _path, state, extra, report = latest_good_checkpoint(
                self.ckpt_dir)
        if step is None:
            raise TrainingDiverged(
                f"{self._consec} consecutive anomalous steps and no intact "
                f"checkpoint to roll back to in {self.ckpt_dir!r} "
                f"(scanned: {[(s, d) for s, _p, d in greport + report]})")
        self._load_into_trainer(state, consider_splits=gstep is not None)
        self.rollbacks.append((self._step, int(extra.get("step", step))))
        if _obs.enabled():
            _res_m()["rollbacks"].inc()
            _obs_journal.record("rollback", at_step=self._step,
                                to_step=int(extra.get("step", step)))
        # the flight recorder's ring holds the steps that led here — dump
        # it before the restore makes them unreconstructable (no-op with
        # no recorder installed)
        _obs_numerics.dump("rollback", step=self._step)
        # the restore itself is lost time: bill it to the goodput
        # "rollback" bucket (the rejected steps were billed there by the
        # Trainer.step seam as they happened)
        _obs_goodput.record_event("rollback", time.perf_counter() - t0)
        self._step = int(extra.get("step", step))
        return self._step

    # -- the guarded step ---------------------------------------------------

    def _guard(self, metrics: dict) -> bool:
        """``Trainer.grad_guard`` hook: accept the update only when loss
        and grad-norm are finite AND the step was not abandoned by the
        watchdog.  Runs before the state commit and before staged/PS
        gradient pushes."""
        # a zombie thread whose step already blew the deadline must not
        # commit: the driver has moved on (resume/rollback) and a late
        # commit — worse, a late PS push — would corrupt the lineage.
        # Under the fence lock so the decision is atomic against the
        # timeout path: either this step is already abandoned (reject), or
        # it is marked committing and the timeout path waits for it.
        epoch = getattr(self._tls, "epoch", None)
        if epoch is not None:
            with self._fence_lock:
                if epoch in self._abandoned:
                    self._abandoned.discard(epoch)
                    return False
                self._committing.add(epoch)
        if self.anomaly_policy == "off":
            return True
        if "grad_norm" not in metrics and not self._warned_loss_only:
            # the Trainer was jitted before the guard attached, so the
            # cached program carries no grad_norm — detection degrades to
            # loss-only.  Say so once instead of silently weakening.
            self._warned_loss_only = True
            import warnings
            warnings.warn(
                "ResilientTrainer anomaly detection is LOSS-ONLY for this "
                "trainer: it ran a step before ResilientTrainer wrapped "
                "it, so the jitted program has no grad_norm metric.  Wrap "
                "the Trainer before its first step for full NaN/Inf "
                "gradient detection.", RuntimeWarning, stacklevel=2)
        loss = float(metrics.get("loss", 0.0))
        gnorm = float(metrics.get("grad_norm", 0.0))
        finite = bool(np.isfinite(loss) and np.isfinite(gnorm))
        # streak accounting from values already fetched to host — the
        # hetu_numerics_nonfinite_streak gauge costs no extra sync (and
        # is one global load + branch with no recorder installed)
        _obs_numerics.note_outcome(finite, step=self._step)
        if finite:
            return True
        if self.anomaly_policy == "raise":
            raise TrainingDiverged(
                f"non-finite training signal at step {self._step}: "
                f"loss={loss}, grad_norm={gnorm}")
        self.anomalies.append((self._step, loss, gnorm))
        if _obs.enabled():
            _res_m()["anomalies"].inc()
            _obs_journal.record("nan_skip", step=self._step, loss=loss,
                                grad_norm=gnorm)
        return False

    def _run_step(self, batch, key):
        def body():
            _faults.fire("step_begin")  # deterministic hang injection
            return self.trainer.step(batch, key)

        if self.step_timeout is None:
            return body()
        box: dict = {}
        self._epoch += 1
        epoch = self._epoch

        def target():
            self._tls.epoch = epoch  # read back by _guard for fencing
            try:
                box["out"] = body()
            except BaseException as e:  # surfaced on the caller thread
                box["err"] = e

        th = threading.Thread(target=target, daemon=True,
                              name=f"resilient-step-{self._step}")
        th.start()
        th.join(self.step_timeout)
        if th.is_alive():
            # abandon-or-wait, atomic against the guard: if the guard
            # already passed (epoch in _committing) the step is mid-commit
            # — wait it out rather than falsely report nothing committed;
            # otherwise abandon it so the eventual guard call rejects.
            with self._fence_lock:
                committing = epoch in self._committing
                if not committing:
                    self._abandoned.add(epoch)
            if _obs.enabled():
                _res_m()["watchdog"].inc()
                _obs_journal.record("watchdog_fired", step=self._step,
                                    timeout_s=self.step_timeout,
                                    committing=committing)
            if not committing:
                last = self._saved[-1] if self._saved else None
                raise BackendUnresponsive(
                    f"train step {self._step} did not complete within "
                    f"{self.step_timeout}s — hung device program or dead "
                    f"backend (the BENCH_r05 'backend_unreachable' "
                    f"shape); if this was the first step, jit compilation "
                    f"may have blown the deadline — warm the trainer up "
                    f"or raise step_timeout; last checkpoint: "
                    f"{last or 'none'}; nothing was committed")
            th.join(self.step_timeout)
            if th.is_alive():
                # past the commit gate, so the state swap / staged PS push
                # is merely BLOCKED, not fenced — it may still land when
                # the link unblocks.  Be explicit: this process must be
                # restarted, not resumed in place.
                raise BackendUnresponsive(
                    f"train step {self._step} passed its commit gate but "
                    f"the commit (state swap / staged PS push) is still "
                    f"blocked after another {self.step_timeout}s — "
                    f"stalled PS/host link; the commit MAY still land "
                    f"when it unblocks, so restart the process instead "
                    f"of resuming in-place")
        with self._fence_lock:
            self._committing.discard(epoch)
        if "err" in box:
            raise box["err"]
        return box["out"]

    def step(self, batch, key=None) -> dict:
        """One guarded training step.

        Returns the metrics dict; a rejected (anomalous) step returns with
        ``skipped=True`` and leaves trainer state AND the global RNG stream
        exactly as before the call, so the surviving lineage is bitwise
        identical to an uninjected run.  After
        ``max_consecutive_anomalies`` rejections in a row the state is
        rolled back to the newest intact checkpoint (``rolled_back_to`` in
        the metrics).  Raises :class:`Preempted` after the final save when
        a SIGTERM/SIGINT arrived, and :class:`BackendUnresponsive` when the
        step blows the watchdog deadline."""
        self._maybe_preempt()
        self._step += 1
        plan = _faults.active_plan()
        if plan is not None:
            plan.advance(self._step)
        rng0 = get_seed_status()
        if key is None:
            # draw on the driver thread: a watchdog-abandoned step thread
            # must never touch the global RNG stream after the driver has
            # resumed/rolled back (it would shift every later key)
            key = next_key()
        metrics = self._run_step(batch, key)
        if metrics.get("skipped"):
            # un-consume the step: RNG seqnum back, driver step back (the
            # skipped number is reused), anomaly accounting forward
            reset_seed_seqnum(*rng0)
            self._step -= 1
            self._consec += 1
            if self._consec == 1:
                # first anomaly of a streak: numerics post-mortem (flight
                # dump + jaxpr provenance) before any rollback mutates
                # the state the NaN was born under
                self._numerics_postmortem(self._step + 1, batch, key)
            if self._consec >= self.max_consecutive_anomalies:
                metrics["rolled_back_to"] = self._rollback()
                self._consec = 0
        else:
            self._consec = 0
            if self.save_every > 0 and self._step % self.save_every == 0:
                self.save()
        # closed-loop remediation (exec.controller): an installed
        # controller re-evaluates the partial-reduce deadline from this
        # trainer's reducer lag EWMAs — one global load + branch when
        # none is installed (the obs seam contract)
        _controller.maybe_after_train_step(self, self._step, metrics)
        self._maybe_preempt()
        return metrics

    def _numerics_postmortem(self, step: int, batch, key) -> None:
        """First-anomaly-of-a-streak forensics: dump the flight-recorder
        ring (``flight_dump``, no-op without an installed recorder) and
        interpret the step's ``value_and_grad`` jaxpr to journal
        ``nan_provenance`` naming the first non-finite producer.  The
        trainer's stashed post-fault-hook inputs are preferred so an
        injected poison is replayed exactly."""
        _obs_numerics.dump("nan_skip", step=step)
        if not (self.nan_provenance and _obs.enabled()):
            return
        stashed = getattr(self.trainer, "_last_step_inputs", None)
        if stashed is not None:
            batch, key = stashed
        try:
            rep = _obs_numerics.loss_provenance(
                self.trainer.loss_fn, self.trainer.state.model, batch,
                key)
        except Exception as e:
            _obs_journal.record("nan_provenance", step=step,
                                op="provenance_error", origin="error",
                                error=str(e))
            return
        if rep is not None:
            _obs_journal.record(
                "nan_provenance", step=step, op=rep["op"],
                origin=rep["origin"], site=rep.get("site"),
                **({"leaf": rep["leaf"]} if "leaf" in rep else {}))

    def _maybe_preempt(self):
        if self._preempt_signum is None:
            return
        signum, self._preempt_signum = self._preempt_signum, None
        self._ck.wait()  # order after any in-flight periodic save
        if self.gang is not None:
            self.gang.save(self._step, self._capture(),
                           extra={"step": self._step})
        else:
            save_checkpoint(checkpoint_path(self.ckpt_dir, self._step),
                            self._capture(), extra={"step": self._step})
        if _obs.enabled():
            _res_m()["preemptions"].inc()
            _obs_journal.record("preemption", step=self._step,
                                signum=signum)
        raise Preempted(self._step, signum)
