"""Closed-loop remediation: a controller that acts on the telemetry plane.

PRs 6-10 built every signal a human SRE would watch — straggler-lag
EWMAs (``WorkerLagEWMA``), SLO burn rates and the shed-pressure gauge
(``obs.slo``), compile-storm gauges (``obs.compile.StormDetector``),
replica-divergence verdicts (``obs.divergence``) — but they only
rendered on ``/fleet`` endpoints: a dying run still died.  This module
closes the loop.  A rank-0 :class:`RuntimeController` consumes those
signals and drives the *existing* actuators through journaled,
seeded-replayable ``remediation`` events:

1. **Partial-reduce deadline auto-tuning** — each committed gang step,
   the per-worker arrival-lag EWMAs propose a deadline that covers the
   healthy ``cover_fraction`` of the gang with ``headroom`` slack:
   tighten when the gang is healthy, relax under injected pareto
   stalls.  The proposal is clamped by the policy's own
   :meth:`~hetu_tpu.exec.partial.PartialReduceConfig.clamp` bounds and
   hysteresis-damped (relative deadband + a ``cooldown_steps`` refractory
   period), so the deadline never oscillates.  Tuned cuts journal
   ``deadline_source="controller"`` on their ``partial_step`` events, so
   replays distinguish tuned from configured cuts.

2. **Divergence quarantine** — a fresh ``replica_divergence`` verdict
   (the PR-10 detector naming step/worker/shard) evicts the divergent
   replica's lease (:meth:`~hetu_tpu.exec.gang.ElasticGang.quarantine`:
   the rank stops renewing and its *suspect* shard storage is dropped),
   the gang ``rescale()``s, and the restore recovers that rank's shard
   from its ring neighbor's replica (``shard_restore``) — a completed
   run instead of a lost one.

3. **Admission shedding** — sustained SLO burn (the shed-pressure gauge
   at or above ``shed_on`` for ``sustain_ticks`` consecutive scheduler
   ticks) engages :meth:`~hetu_tpu.serve.batcher.ContinuousBatcher.
   set_shed`: ``submit`` rejects with a distinguishable ``/infer`` error
   (``AdmissionShed``, counted ``hetu_serve_shed_total{reason=
   controller}``) until pressure stays at or below ``shed_off`` for
   ``sustain_ticks`` ticks.

4. **Compile-storm bucket freeze** — while the recompile-storm gauge is
   up, serving prompt-bucket *growth* freezes: a prompt whose prefill
   bucket has not been compiled yet is rejected (reason
   ``bucket_freeze``) instead of adding fuel to the storm; already-warm
   buckets keep serving.  The freeze lifts when the gauge clears.

Every decision — acted or not — is a ``remediation`` journal event
carrying ``action`` / ``signal`` / ``dry_run`` plus the decision's
numbers, so chaos acceptance stays bitwise: inject the seeded fault
distribution, assert the controller's action sequence and the recovered
goodput across same-seed runs.  **Dry-run mode**
(``ControllerConfig(dry_run=True)``) journals identical ``would_act``
decisions while actuating nothing — the deadline decisions evolve
against an internal shadow value, so the decision stream is the same
pure function of the signals the active controller would see — the
audit trail a production rollout needs before flipping the switch.

The seams match the obs conventions: :func:`maybe_gang_step` /
:func:`maybe_serve_tick` / :func:`maybe_after_train_step` are one
global load + branch when no controller is installed (the
``Trainer.step`` overhead contract).  A controller is attached
explicitly (``ElasticGang(controller=...)`` /
``ServingEngine(controller=...)``) or installed process-wide with
:func:`install` / :func:`use` — the installed one also backs the
``/controller`` endpoint (``obs/server.py``) and its ``hetu_ctrl_*``
metrics ride the PR-8 fleet snapshots into ``/fleet/controller``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import os
import weakref
from typing import Optional

from hetu_tpu.obs import compile as _obs_compile
from hetu_tpu.obs import journal as _obs_journal
from hetu_tpu.obs import registry as _obs

__all__ = ["ControllerConfig", "RuntimeController", "get_controller",
           "install", "use", "maybe_gang_step", "maybe_serve_tick",
           "maybe_after_train_step", "controller_smoke"]

_ENV_PREFIX = "HETU_TPU_CTRL_"


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """The remediation policy — every knob of the four control loops.

    Deadline tuning: ``proposed = clamp(headroom * lag[q])`` where
    ``lag[q]`` is the ``cover_fraction`` order statistic of the
    per-worker arrival-lag EWMAs (cover the healthy majority, let the
    tail fold late — covering the *worst* straggler would re-derive the
    full barrier partial reduce exists to break).  The controller acts
    only when the proposal moves more than ``hysteresis`` of the larger
    of (current, proposed) and at least ``cooldown_steps`` after its
    last retune — the two dampers that make oscillation impossible.

    Shedding: engage at shed-pressure >= ``shed_on`` sustained for
    ``sustain_ticks`` scheduler ticks; release at <= ``shed_off``
    sustained equally long (the on/off gap is the third hysteresis
    band).  ``dry_run`` journals every decision as ``would_act`` and
    touches nothing.
    """

    enabled: bool = True
    dry_run: bool = False
    # 1: partial-reduce deadline auto-tuning
    tune_deadline: bool = True
    headroom: float = 1.5
    cover_fraction: float = 0.75
    hysteresis: float = 0.25
    cooldown_steps: int = 4
    # 2: divergence quarantine
    quarantine: bool = True
    # 2b: serving flap quarantine (PR 20): a replica declared lost this
    # many times (a hang/recover cycle that keeps repeating) is
    # quarantined on its FailoverMonitor — never restored on heartbeat
    # recovery — instead of oscillating in and out of the placement
    # ranking.  Gated by the same ``quarantine`` switch as loop 2.
    replica_flap_threshold: int = 2
    # 3: SLO-burn admission shedding
    shed: bool = True
    shed_on: float = 0.9
    shed_off: float = 0.25
    sustain_ticks: int = 3
    # 3b: tenant-scoped shedding (multi-tenant engines only): batch-
    # class tenants engage at shed_on * batch_shed_factor — throughput
    # traffic is the first to go under sustained burn, latency-class
    # tenants shed only on their OWN burn at the full threshold
    batch_shed_factor: float = 0.5
    # 4: compile-storm bucket freeze
    freeze_buckets: bool = True
    # 5: ledger-backed memory pressure (PR 17): when the installed
    # MemoryLedger's worst-pool occupancy holds at or above ``mem_on``
    # for ``sustain_ticks`` ticks, defrag the engine's pool and shed
    # admission; release at or below ``mem_off`` sustained equally long
    # (the same hysteresis discipline as the SLO shed loop)
    mem_pressure: bool = True
    mem_on: float = 0.92
    mem_off: float = 0.75

    def __post_init__(self):
        if self.headroom <= 0:
            raise ValueError(f"headroom must be > 0, got {self.headroom}")
        if not 0.0 < self.cover_fraction <= 1.0:
            raise ValueError(f"cover_fraction must be in (0, 1], got "
                             f"{self.cover_fraction}")
        if self.hysteresis < 0:
            raise ValueError(f"hysteresis must be >= 0, got "
                             f"{self.hysteresis}")
        if self.cooldown_steps < 0:
            raise ValueError(f"cooldown_steps must be >= 0, got "
                             f"{self.cooldown_steps}")
        if not 0.0 <= self.shed_off <= self.shed_on:
            raise ValueError(
                f"need 0 <= shed_off <= shed_on (the hysteresis band), "
                f"got shed_off={self.shed_off} shed_on={self.shed_on}")
        if not 0.0 < self.shed_on <= 1.0:
            raise ValueError(f"shed_on is a shed-pressure fraction in "
                             f"(0, 1], got {self.shed_on}")
        if self.sustain_ticks < 1:
            raise ValueError(f"sustain_ticks must be >= 1, got "
                             f"{self.sustain_ticks}")
        if self.replica_flap_threshold < 1:
            raise ValueError(f"replica_flap_threshold must be >= 1, got "
                             f"{self.replica_flap_threshold}")
        if not 0.0 < self.batch_shed_factor <= 1.0:
            raise ValueError(f"batch_shed_factor scales shed_on for "
                             f"batch-class tenants, must be in (0, 1], "
                             f"got {self.batch_shed_factor}")
        if not 0.0 <= self.mem_off <= self.mem_on:
            raise ValueError(
                f"need 0 <= mem_off <= mem_on (the hysteresis band), "
                f"got mem_off={self.mem_off} mem_on={self.mem_on}")
        if not 0.0 < self.mem_on <= 1.0:
            raise ValueError(f"mem_on is a used-page fraction in (0, 1], "
                             f"got {self.mem_on}")

    @classmethod
    def from_env(cls, **overrides) -> "ControllerConfig":
        """Policy from the environment (``HETU_TPU_CTRL_*``), explicit
        ``overrides`` winning — deployment config, not code.  Booleans
        parse 1/true/yes (case-insensitive)."""
        spec = {"enabled": bool, "dry_run": bool, "tune_deadline": bool,
                "headroom": float, "cover_fraction": float,
                "hysteresis": float, "cooldown_steps": int,
                "quarantine": bool, "replica_flap_threshold": int,
                "shed": bool, "shed_on": float,
                "shed_off": float, "sustain_ticks": int,
                "batch_shed_factor": float, "freeze_buckets": bool,
                "mem_pressure": bool, "mem_on": float, "mem_off": float}
        kw = {}
        for field, typ in spec.items():
            raw = os.environ.get(_ENV_PREFIX + field.upper())
            if raw is None:
                continue
            if typ is bool:
                kw[field] = raw.strip().lower() in ("1", "true", "yes")
            else:
                kw[field] = typ(raw)
        kw.update(overrides)
        return cls(**kw)


# ------------------------------------------------------------- telemetry

def _ctrl_families(reg) -> dict:
    """The ``hetu_ctrl_*`` families on ``reg`` (idempotent: identical
    re-registration returns the existing family)."""
    return {
            "actions": reg.counter(
                "hetu_ctrl_actions_total",
                "remediation actions the controller APPLIED, by action "
                "(deadline_retune, quarantine, admission_shed, "
                "admission_release, bucket_freeze, bucket_unfreeze)",
                ("action",)),
            "would_act": reg.counter(
                "hetu_ctrl_would_act_total",
                "remediation decisions a DRY-RUN controller journaled "
                "without actuating, by action — the rollout audit trail",
                ("action",)),
            "deadline": reg.gauge(
                "hetu_ctrl_deadline_seconds",
                "the controller's current partial-reduce deadline "
                "(step-clock units in the in-process gang, wall seconds "
                "over a GradientBoard); tracks the shadow value in dry "
                "run"),
            "shed_active": reg.gauge(
                "hetu_ctrl_shed_active",
                "1 while controller admission shedding is engaged "
                "(sustained SLO burn), else 0"),
            "freeze_active": reg.gauge(
                "hetu_ctrl_freeze_active",
                "1 while serving prompt-bucket growth is frozen (compile "
                "storm), else 0"),
            "mem_active": reg.gauge(
                "hetu_ctrl_mem_pressure_active",
                "1 while the ledger-backed memory-pressure remediation "
                "is latched (sustained pool occupancy), else 0"),
        }


class RuntimeController:
    """The rank-0 signals → actuators loop.

    Stateless about the systems it controls beyond what determinism
    needs: a shadow deadline (so dry-run decisions evolve identically to
    an active controller's), the divergence-event cursor, the shed/freeze
    latches and their sustain streaks.  Every method is driven by the
    controlled system's own clock/step, so a seeded replay reproduces the
    decision sequence bitwise."""

    def __init__(self, config: Optional[ControllerConfig] = None, *,
                 registry: Optional[_obs.MetricsRegistry] = None,
                 history: int = 512, planner=None):
        self.config = config if config is not None else ControllerConfig()
        # unified-deployment replanning (hetu_tpu/plan.PlanApplier): an
        # attached planner turns remediation into planning — a
        # quarantine decision re-plans against the surviving fleet, a
        # sustained-SLO-burn shed engage re-plans the serving tier.
        # Dry-run flows through: the planner journals the identical
        # decision and actuates nothing.  None = legacy behavior.
        self.planner = planner
        # metrics land on the process registry by default; a private one
        # (controller_smoke, tests) keeps hetu_ctrl_* series unpolluted
        self._reg = registry
        self._metrics = None
        # decision history: journal-field form, bounded to the newest
        # `history` entries (the journal is the unbounded record; a
        # long-lived controller must not grow — or ship on every
        # /controller scrape — weeks of decision dicts)
        self.history = int(history)
        self.actions: list = []
        self.actions_total = 0
        # deadline-tuning state: the shadow deadline the decisions are
        # made against (== the actuated deadline when not dry_run)
        self._deadline: Optional[float] = None
        self._last_retune_step: Optional[int] = None
        # quarantine state (_quarantined holds CURRENT-generation ranks:
        # a rescale renumbers survivors, so it resets per generation)
        self._div_cursor = 0
        self._quarantined: set = set()
        self._quarantine_gen: Optional[int] = None
        # serve state is PER ENGINE: one installed controller may drive
        # several ServingEngines, and engine A's latch must never be
        # released (or its sustain streak polluted) by engine B's ticks.
        # Weak keys: a departed engine needs no release.
        self._serve_state: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        # serving flap-quarantine state is PER FailoverMonitor (one
        # installed controller may watch several fleets): monitor -> the
        # set of replicas already decided.  The latch IS the hysteresis
        # — one quarantine decision per replica, in dry run too, so the
        # decision stream matches an active controller's even though a
        # dry-run replica keeps recovering and failing.
        self._fleet_state: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()

    # -- the decision record --------------------------------------------------

    def _m(self) -> dict:
        if self._metrics is None:
            self._metrics = _ctrl_families(
                self._reg if self._reg is not None
                else _obs.get_registry())
        return self._metrics

    def _act(self, action: str, signal: str, **fields) -> dict:
        rec = {"action": action, "signal": signal,
               "dry_run": bool(self.config.dry_run), **fields}
        self.actions.append(rec)
        self.actions_total += 1
        if len(self.actions) > self.history:
            del self.actions[:len(self.actions) - self.history]
        if _obs.enabled():
            m = self._m()
            key = "would_act" if self.config.dry_run else "actions"
            m[key].labels(action=action).inc()
        _obs_journal.record("remediation", action=action, signal=signal,
                            dry_run=bool(self.config.dry_run), **fields)
        return rec

    # -- loop 1+2: the training gang -----------------------------------------

    def after_step(self, gang, step: int, metrics: Optional[dict] = None
                   ) -> None:
        """One committed :class:`~hetu_tpu.exec.gang.ElasticGang` step:
        consume fresh divergence verdicts, then re-evaluate the
        partial-reduce deadline.  Called by the gang's post-commit seam
        (after the step's checkpoint save, so a quarantine's storage drop
        is not immediately rewritten)."""
        if not self.config.enabled:
            return
        self._maybe_quarantine(gang, step)
        if gang.partial is not None and gang.reducer is not None:
            self._maybe_retune(step, gang.partial, gang.reducer.lags.lag,
                               actuate=gang.set_partial_deadline)

    def _maybe_quarantine(self, gang, step: int) -> None:
        det = getattr(gang, "divergence", None)
        if det is None or not self.config.quarantine:
            return
        if gang.generation != self._quarantine_gen:
            # a rescale densely renumbered the survivors (or we just
            # attached to a gang that may have rescaled before we were
            # watching): rank ids decided under the old numbering are
            # stale — reset the quarantined set so a reused index is not
            # masked, and skip findings recorded before the current
            # generation (the detector's generation_cursor), whose
            # worker fields name ranks that no longer exist as such
            self._quarantine_gen = gang.generation
            self._quarantined = set()
            self._div_cursor = max(self._div_cursor,
                                   det.generation_cursor)
        events = det.events
        while self._div_cursor < len(events):
            f = events[self._div_cursor]
            self._div_cursor += 1
            w = int(f["worker"])
            # already decided, already dead, or the LAST live worker —
            # remediation must never leave nothing to rescale.  In dry
            # run the gang never actually evicts, so earlier would-act
            # quarantines count as shadow evictions: the decision stream
            # stays the one an active controller would produce (it would
            # not quarantine both workers of a 2-gang either).
            shadow = (len(self._quarantined) if self.config.dry_run
                      else 0)
            if w in self._quarantined or not gang.can_quarantine(w) \
                    or gang.live_world - shadow < 2:
                continue
            self._quarantined.add(w)
            self._act("quarantine", "replica_divergence", step=int(step),
                      worker=w, shard=f["shard"],
                      divergent_step=int(f["step"]))
            if not self.config.dry_run:
                gang.quarantine(w)
            if self.planner is not None \
                    and getattr(gang, "planner", None) is None:
                # re-plan against the post-eviction world now (a gang
                # with its OWN attached planner re-plans at the rescale
                # instead — never both, one decision per trigger).  In
                # dry run the eviction never happened, so the surviving
                # world is computed from the shadow-quarantine count:
                # the decision stream matches an active controller's.
                survivors = gang.live_world - (len(self._quarantined)
                                               if self.config.dry_run
                                               else 0)
                self.planner.replan_for_gang(
                    gang, trigger="quarantine",
                    dry_run=self.config.dry_run, train_world=survivors)

    def _maybe_retune(self, step: int, config, lags: dict,
                      actuate) -> None:
        """The shared deadline-tuning core (in-process gang and
        per-process :class:`~hetu_tpu.exec.resilience.ResilientTrainer`
        paths): propose from the lag EWMAs, clamp, damp, act.
        ``actuate(new_deadline)`` applies it; ``config`` is the current
        :class:`~hetu_tpu.exec.partial.PartialReduceConfig`."""
        if not self.config.tune_deadline or not lags:
            return
        if self._deadline is None:
            self._deadline = float(config.deadline)
            if _obs.enabled():
                self._m()["deadline"].set(self._deadline)
        vals = sorted(float(v) for v in lags.values())
        idx = min(len(vals) - 1,
                  max(0, math.ceil(self.config.cover_fraction
                                   * len(vals)) - 1))
        proposed = config.clamp(self.config.headroom * vals[idx])
        cur = self._deadline
        if self._last_retune_step is not None and \
                step - self._last_retune_step < self.config.cooldown_steps:
            return
        if math.isfinite(cur):
            if abs(proposed - cur) <= \
                    self.config.hysteresis * max(cur, proposed):
                return
        elif not math.isfinite(proposed):
            # inf -> inf (full-barrier config, unbounded clamp): the
            # hysteresis band is inf-poisoned AND there is no change —
            # any FINITE proposal against an inf deadline always acts
            return
        self._deadline = proposed
        self._last_retune_step = int(step)
        self._act("deadline_retune", "worker_lag_ewma", step=int(step),
                  # inf (the synchronous-barrier start) has no strict-
                  # JSON form: the journal carries null, the `new` side
                  # is always the finite clamped proposal
                  old=round(cur, 6) if math.isfinite(cur) else None,
                  new=round(proposed, 6),
                  covered_lag=round(vals[idx], 6))
        if not self.config.dry_run:
            actuate(proposed)
        if _obs.enabled():
            self._m()["deadline"].set(proposed)

    def after_train_step(self, trainer, step: int,
                         metrics: Optional[dict] = None) -> None:
        """The per-process form: a
        :class:`~hetu_tpu.exec.resilience.ResilientTrainer` carrying a
        :class:`~hetu_tpu.exec.partial.PartialReducer` (the multi-process
        ``GradientBoard`` gangs) gets the same deadline loop — the
        reducer's lag EWMAs (fed by ``GradientBoard.collect`` or the
        harness) propose, and acting replaces ``reducer.config`` so the
        next ``collect(deadline_s=reducer.config.deadline)`` runs the
        tuned cut."""
        if not self.config.enabled:
            return
        red = getattr(trainer, "partial", None)
        if red is None:
            return

        def actuate(new):
            red.config = dataclasses.replace(
                red.config, deadline=float(new),
                deadline_source="controller")

        self._maybe_retune(step, red.config, red.lags.lag, actuate=actuate)

    def on_replica_lost(self, monitor, replica: int,
                        lost_count: int) -> None:
        """Serving-fleet flap quarantine (the
        :class:`~hetu_tpu.serve.fleet.failover.FailoverMonitor` seam):
        the monitor reports every ``replica_lost`` declaration with the
        replica's cumulative loss count; at ``replica_flap_threshold``
        the replica is quarantined — never restored on heartbeat
        recovery — so a hang/recover cycle that keeps repeating stops
        oscillating the placement ranking.  One decision per replica per
        monitor; dry run journals the identical ``quarantine_replica``
        decision and leaves the monitor's restore behavior untouched."""
        if not self.config.enabled or not self.config.quarantine:
            return
        if int(lost_count) < self.config.replica_flap_threshold:
            return
        decided = self._fleet_state.get(monitor)
        if decided is None:
            decided = set()
            self._fleet_state[monitor] = decided
        if replica in decided:
            return
        decided.add(replica)
        self._act("quarantine_replica", "replica_flap",
                  replica=int(replica), lost=int(lost_count))
        if not self.config.dry_run:
            monitor.quarantine(replica)

    # -- loop 3+4: the serving engine ----------------------------------------

    def on_serve_tick(self, engine) -> None:
        """One :class:`~hetu_tpu.serve.engine.ServingEngine` scheduler
        tick: latch/release the compile-storm bucket freeze and the
        SLO-burn admission shed.  Driven by the engine's injectable
        clock, so deterministic tests replay the decisions exactly."""
        if not self.config.enabled:
            return
        if self.config.freeze_buckets:
            self._maybe_freeze(engine)
        if self.config.shed:
            self._maybe_shed(engine)
        if self.config.mem_pressure:
            self._maybe_mem(engine)

    def _serve_st(self, engine) -> dict:
        st = self._serve_state.get(engine)
        if st is None:
            st = {"shed_active": False, "freeze_active": False,
                  "shed_streak": 0, "ok_streak": 0,
                  # memory-pressure latch (PR 17): mem_shed remembers
                  # whether THIS loop engaged the batcher's shed, so a
                  # release never unlatches the SLO loop's shed
                  "mem_active": False, "mem_shed": False,
                  "mem_streak": 0, "mem_ok_streak": 0,
                  # tenant-scoped latches (multi-tenant engines):
                  # tid -> {"active", "shed_streak", "ok_streak"}
                  "tenants": {}}
            self._serve_state[engine] = st
        return st

    @property
    def shed_active(self) -> bool:
        """Any driven engine currently latched shedding (global or
        tenant-scoped)."""
        return any(st["shed_active"]
                   or any(t["active"] for t in st["tenants"].values())
                   for st in self._serve_state.values())

    @property
    def freeze_active(self) -> bool:
        """Any driven engine currently latched frozen."""
        return any(st["freeze_active"]
                   for st in self._serve_state.values())

    @property
    def mem_pressure_active(self) -> bool:
        """Any driven engine currently latched on ledger memory
        pressure."""
        return any(st["mem_active"]
                   for st in self._serve_state.values())

    def _maybe_freeze(self, engine) -> None:
        st = self._serve_st(engine)
        storm = _obs_compile.get_storm()
        recent = storm.recent()
        storming = recent > storm.threshold
        if storming and not st["freeze_active"]:
            warm = sorted(engine._prefill_buckets)
            if not warm:
                # nothing is warm yet (e.g. a training-side storm hit a
                # freshly started engine): freezing "growth" would be a
                # total outage, strictly worse than compiling — defer
                # until the engine has served at least one bucket
                return
            st["freeze_active"] = True
            self._act("bucket_freeze", "compile_storm", recent=int(recent),
                      threshold=int(storm.threshold), warm_buckets=warm)
            if not self.config.dry_run:
                engine.freeze_bucket_growth = True
        elif not storming and st["freeze_active"]:
            st["freeze_active"] = False
            self._act("bucket_unfreeze", "compile_storm",
                      recent=int(recent), threshold=int(storm.threshold))
            if not self.config.dry_run:
                engine.freeze_bucket_growth = False
        if _obs.enabled():
            self._m()["freeze_active"].set(1.0 if self.freeze_active
                                           else 0.0)

    def _maybe_shed(self, engine) -> None:
        st = self._serve_st(engine)
        if getattr(engine.slo, "multi_tenant", False):
            # the scoped policy: per-tenant burn drives per-tenant
            # latches, so a flooding tenant's aggregate burn can never
            # close a victim's door.  The switch is monotone (tenant
            # windows never un-observe), so a replay flips policies at
            # the same tick.  Engines that only ever see the default
            # tenant stay on the legacy global path below, bit for bit.
            if st["shed_active"]:
                # a burn latched the GLOBAL door before the first tenant
                # was observed (a tenant request in flight at engage time
                # flips multi_tenant when it resolves).  The scoped loop
                # only ever manages per-tenant latches, and this path
                # never runs the global release again — left alone the
                # legacy latch strands every tenant shut forever.  Hand
                # the latch over: release it here (memory pressure may
                # still be holding the shared batcher latch, same rule
                # as the release below) and let the scoped streaks
                # re-engage per tenant if the burn is real.
                st["shed_active"] = False
                st["shed_streak"] = 0
                st["ok_streak"] = 0
                self._act("admission_release", "tenant_policy_switch")
                if not self.config.dry_run and not st["mem_shed"]:
                    engine.batcher.clear_shed()
            self._maybe_shed_tenants(engine, st)
            return
        pressure = float(engine.slo.shed_pressure())
        if pressure >= self.config.shed_on:
            st["shed_streak"] += 1
            st["ok_streak"] = 0
        elif pressure <= self.config.shed_off:
            st["ok_streak"] += 1
            st["shed_streak"] = 0
        else:
            # inside the hysteresis band: sustain nothing, hold the latch
            st["shed_streak"] = 0
            st["ok_streak"] = 0
        if not st["shed_active"] \
                and st["shed_streak"] >= self.config.sustain_ticks:
            st["shed_active"] = True
            self._act("admission_shed", "slo_burn",
                      pressure=round(pressure, 6),
                      sustained_ticks=int(st["shed_streak"]))
            if not self.config.dry_run:
                engine.batcher.set_shed(
                    "controller shed: sustained SLO burn (shed pressure "
                    f"{pressure:.3f} >= {self.config.shed_on})")
            if self.planner is not None:
                # sustained SLO burn: the serving tier is under-planned
                # — re-plan it (the decision journals now; the plan's
                # structural axes apply at the next fleet construction)
                self.planner.replan_for_engine(
                    engine, trigger="slo_burn",
                    dry_run=self.config.dry_run)
        elif st["shed_active"] \
                and st["ok_streak"] >= self.config.sustain_ticks:
            st["shed_active"] = False
            self._act("admission_release", "slo_burn",
                      pressure=round(pressure, 6),
                      sustained_ticks=int(st["ok_streak"]))
            # the memory loop shares the batcher's global shed latch:
            # only clear it when memory pressure is not also holding it
            if not self.config.dry_run and not st["mem_shed"]:
                engine.batcher.clear_shed()
        if _obs.enabled():
            self._m()["shed_active"].set(1.0 if self.shed_active else 0.0)

    def _maybe_shed_tenants(self, engine, st: dict) -> None:
        """The scoped shed loop: one streak/hysteresis machine per
        observed (tenant, class), same sustain discipline as the global
        path, engaging :meth:`~hetu_tpu.serve.batcher.ContinuousBatcher.
        set_tenant_shed` instead of the global latch.  Batch-class
        tenants engage at ``shed_on * batch_shed_factor`` (and release
        at the equally scaled ``shed_off``): under sustained burn the
        throughput tier is shed FIRST, and a latency-class tenant is
        shed only when its OWN windows burn at the full threshold."""
        cfg = self.config
        observed = engine.slo.observed_tenants()
        for tid in sorted(observed):
            klass = observed[tid]
            ts = st["tenants"].get(tid)
            if ts is None:
                ts = {"active": False, "shed_streak": 0, "ok_streak": 0}
                st["tenants"][tid] = ts
            factor = cfg.batch_shed_factor if klass == "batch" else 1.0
            on = cfg.shed_on * factor
            off = cfg.shed_off * factor
            pressure = float(engine.slo.tenant_shed_pressure(tid))
            if pressure >= on:
                ts["shed_streak"] += 1
                ts["ok_streak"] = 0
            elif pressure <= off:
                ts["ok_streak"] += 1
                ts["shed_streak"] = 0
            else:
                ts["shed_streak"] = 0
                ts["ok_streak"] = 0
            if not ts["active"] \
                    and ts["shed_streak"] >= cfg.sustain_ticks:
                ts["active"] = True
                reason = (f"controller shed: sustained SLO burn by "
                          f"tenant {tid} ({klass}-class, shed pressure "
                          f"{pressure:.3f} >= {on:g})")
                self._act("admission_shed", "slo_burn", tenant=tid,
                          klass=klass, pressure=round(pressure, 6),
                          sustained_ticks=int(ts["shed_streak"]))
                _obs_journal.record("tenant_shed", tenant=tid,
                                    engaged=True, reason="slo_burn",
                                    klass=klass,
                                    pressure=round(pressure, 6))
                if not cfg.dry_run:
                    engine.batcher.set_tenant_shed(tid, reason)
            elif ts["active"] and ts["ok_streak"] >= cfg.sustain_ticks:
                ts["active"] = False
                self._act("admission_release", "slo_burn", tenant=tid,
                          klass=klass, pressure=round(pressure, 6),
                          sustained_ticks=int(ts["ok_streak"]))
                _obs_journal.record("tenant_shed", tenant=tid,
                                    engaged=False, reason="slo_burn",
                                    klass=klass,
                                    pressure=round(pressure, 6))
                if not cfg.dry_run:
                    engine.batcher.clear_tenant_shed(tid)
        if _obs.enabled():
            self._m()["shed_active"].set(1.0 if self.shed_active else 0.0)

    def _maybe_mem(self, engine) -> None:
        """The ledger-backed memory loop: the installed
        :class:`~hetu_tpu.obs.memledger.MemoryLedger`'s worst-pool
        occupancy sustained at or above ``mem_on`` for ``sustain_ticks``
        ticks first defrags the engine's KV pool (reclaiming
        fragmentation is free capacity), then sheds admission if
        occupancy alone keeps the pool pinned; releases at or below
        ``mem_off`` sustained equally long.  No ledger installed means
        no signal — the loop is inert, not guessing."""
        from hetu_tpu.obs import memledger as _memledger
        led = _memledger.get_ledger()
        if led is None:
            return
        st = self._serve_st(engine)
        cfg = self.config
        pressure = float(led.memory_pressure())
        if pressure >= cfg.mem_on:
            st["mem_streak"] += 1
            st["mem_ok_streak"] = 0
        elif pressure <= cfg.mem_off:
            st["mem_ok_streak"] += 1
            st["mem_streak"] = 0
        else:
            st["mem_streak"] = 0
            st["mem_ok_streak"] = 0
        if not st["mem_active"] \
                and st["mem_streak"] >= cfg.sustain_ticks:
            st["mem_active"] = True
            moved = 0
            if not cfg.dry_run:
                moved = int(engine.pool.defrag())
            still = float(led.memory_pressure())
            action = ("memory_shed" if still >= cfg.mem_on
                      else "memory_defrag")
            self._act(action, "memory_pressure",
                      pressure=round(pressure, 6),
                      moved_pages=moved,
                      sustained_ticks=int(st["mem_streak"]))
            _obs_journal.record("memory_pressure",
                                pressure=round(pressure, 6),
                                component="kv_pool", action=action)
            if action == "memory_shed" and not cfg.dry_run:
                st["mem_shed"] = True
                engine.batcher.set_shed(
                    "controller shed: sustained memory pressure "
                    f"({pressure:.3f} >= {cfg.mem_on})")
        elif st["mem_active"] \
                and st["mem_ok_streak"] >= cfg.sustain_ticks:
            st["mem_active"] = False
            self._act("memory_release", "memory_pressure",
                      pressure=round(pressure, 6),
                      sustained_ticks=int(st["mem_ok_streak"]))
            _obs_journal.record("memory_pressure",
                                pressure=round(pressure, 6),
                                component="kv_pool",
                                action="memory_release")
            if st["mem_shed"]:
                st["mem_shed"] = False
                # the SLO loop shares this latch: leave it held if that
                # loop is still latched shedding
                if not cfg.dry_run and not st["shed_active"]:
                    engine.batcher.clear_shed()
        if _obs.enabled():
            self._m()["mem_active"].set(
                1.0 if self.mem_pressure_active else 0.0)

    def release(self) -> None:
        """Release every latch this controller actuated (admission shed,
        bucket freeze) on every engine it drove, and reset the sustain
        streaks — a departing controller must not strand an engine
        rejecting traffic with nobody left to unlatch it.  Called by
        :func:`use` on scope exit; long-lived installed controllers
        should call it when decommissioned.  Idempotent."""
        for eng in list(self._serve_state):
            st = self._serve_state[eng]
            if st["shed_active"]:
                st["shed_active"] = False
                self._act("admission_release", "controller_detach")
                if getattr(eng.batcher, "shedding", False):
                    eng.batcher.clear_shed()
            for tid, ts in st["tenants"].items():
                if ts["active"]:
                    ts["active"] = False
                    self._act("admission_release", "controller_detach",
                              tenant=tid)
                    _obs_journal.record("tenant_shed", tenant=tid,
                                        engaged=False,
                                        reason="controller_detach")
                    if eng.batcher.tenant_shed_reason(tid) is not None:
                        eng.batcher.clear_tenant_shed(tid)
                ts["shed_streak"] = 0
                ts["ok_streak"] = 0
            if st["freeze_active"]:
                st["freeze_active"] = False
                self._act("bucket_unfreeze", "controller_detach")
                if getattr(eng, "freeze_bucket_growth", False):
                    eng.freeze_bucket_growth = False
            if st["mem_active"]:
                st["mem_active"] = False
                self._act("memory_release", "controller_detach")
                _obs_journal.record("memory_pressure", pressure=0.0,
                                    component="kv_pool",
                                    action="memory_release")
                if st["mem_shed"]:
                    st["mem_shed"] = False
                    if getattr(eng.batcher, "shedding", False):
                        eng.batcher.clear_shed()
            st["shed_streak"] = 0
            st["ok_streak"] = 0
            st["mem_streak"] = 0
            st["mem_ok_streak"] = 0
        if _obs.enabled():
            m = self._m()
            m["shed_active"].set(0.0)
            m["freeze_active"].set(0.0)
            m["mem_active"].set(0.0)

    # -- read side -------------------------------------------------------------

    def summary(self) -> dict:
        """The ``/controller`` payload: policy, live latches, the tuned
        deadline (shadow value in dry run), and the newest ``history``
        decisions in journal-field form (``actions_total`` counts every
        decision ever made; the journal is the unbounded record)."""
        return {
            "installed": True,
            "config": dataclasses.asdict(self.config),
            "dry_run": bool(self.config.dry_run),
            # an inf deadline (the full-barrier start) has no strict-
            # JSON form; the payload carries null until a retune
            "deadline": (self._deadline
                         if self._deadline is None
                         or math.isfinite(self._deadline) else None),
            "shed_active": bool(self.shed_active),
            "tenant_shed_active": sorted(
                {tid for st in self._serve_state.values()
                 for tid, ts in st["tenants"].items() if ts["active"]}),
            "freeze_active": bool(self.freeze_active),
            "mem_pressure_active": bool(self.mem_pressure_active),
            "quarantined": sorted(self._quarantined),
            "actions_total": int(self.actions_total),
            "actions": list(self.actions),
        }


# --------------------------------------------------- process-wide seams

_active: Optional[RuntimeController] = None


def get_controller() -> Optional[RuntimeController]:
    return _active


def install(controller: Optional[RuntimeController]
            ) -> Optional[RuntimeController]:
    """Install ``controller`` process-wide (None uninstalls): the
    fallback the gang/serve/trainer seams consult when no controller was
    attached explicitly, and the object ``/controller`` serves."""
    global _active
    _active = controller
    return controller


@contextlib.contextmanager
def use(controller: RuntimeController):
    """Install for the block, restore the previous controller on exit —
    releasing any latch the scoped controller actuated (once it is
    uninstalled, nothing would ever unlatch a shed/frozen engine)."""
    global _active
    prev = _active
    _active = controller
    try:
        yield controller
    finally:
        _active = prev
        controller.release()


def maybe_gang_step(gang, step: int, metrics: Optional[dict] = None) -> None:
    """The :class:`~hetu_tpu.exec.gang.ElasticGang` post-commit seam:
    one attribute + one global load and a branch when no controller is
    attached or installed — the obs overhead contract."""
    c = gang.controller if gang.controller is not None else _active
    if c is None:
        return
    c.after_step(gang, step, metrics)


def maybe_serve_tick(engine) -> None:
    """The :class:`~hetu_tpu.serve.engine.ServingEngine` per-tick seam
    (same disabled-cost contract as :func:`maybe_gang_step`)."""
    c = engine.controller if engine.controller is not None else _active
    if c is None:
        return
    c.on_serve_tick(engine)


def maybe_after_train_step(trainer, step: int,
                           metrics: Optional[dict] = None) -> None:
    """The :class:`~hetu_tpu.exec.resilience.ResilientTrainer` post-step
    seam: one global load + branch when no controller is installed."""
    c = _active
    if c is None:
        return
    c.after_train_step(trainer, step, metrics)


# ------------------------------------------------------------ the smoke

def controller_smoke(steps: int = 16, seed: int = 0) -> dict:
    """Seeded 2-worker in-process deadline-retune smoke — the closed
    loop end to end on a tiny MLP gang: healthy early steps tighten the
    deadline toward its clamp floor, an injected mid-run stall relaxes
    it back.  Deterministic (two calls return identical dicts); reused
    by the tier-1 controller smoke test and by ``bench.py`` train lines
    (``controller`` summary field, ``HETU_TPU_BENCH_CONTROLLER=0``
    skips).  Journals into a private journal and meters into a private
    registry, so it never pollutes the caller's event stream or the
    process ``hetu_ctrl_*`` series."""
    import tempfile

    import numpy as np

    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import faults as _faults
    from hetu_tpu.exec.executor import Trainer
    from hetu_tpu.exec.gang import ElasticGang
    from hetu_tpu.exec.partial import PartialReduceConfig
    from hetu_tpu.models import MLP
    from hetu_tpu.optim import SGDOptimizer
    from hetu_tpu.ops import softmax_cross_entropy_sparse

    set_random_seed(seed)
    model = MLP((8, 16, 3))

    def loss_fn(model, batch, key):
        logits = model(batch["x"])
        return softmax_cross_entropy_sparse(logits, batch["y"]).mean(), {}

    trainer = Trainer(model, SGDOptimizer(0.1), loss_fn, donate=False)
    rng = np.random.default_rng(seed)
    data = []
    for _ in range(steps):
        x = rng.standard_normal((16, 8)).astype(np.float32)
        data.append({"x": x, "y": (x[:, 0] > 0).astype(np.int32)})
    cfg = PartialReduceConfig(deadline=4.0, tau=4, min_deadline=0.5,
                              max_deadline=8.0)
    ctrl = RuntimeController(
        ControllerConfig(cooldown_steps=2, quarantine=False, shed=False,
                         freeze_buckets=False),
        registry=_obs.MetricsRegistry())
    with tempfile.TemporaryDirectory() as d, \
            _obs_journal.use(_obs_journal.EventJournal(clock=lambda: 0.0)):
        gang = ElasticGang(trainer, d, world_size=2,
                           data_fn=lambda s: data[s - 1],
                           global_batch_size=16, seed=seed, save_every=0,
                           partial=cfg, controller=ctrl)
        plan = _faults.FaultPlan(
            [(steps // 2, _faults.Fault("worker_stall", worker=1,
                                        arg=4.0))])
        with _faults.inject(plan):
            gang.run_until(steps)
    by_action: dict = {}
    for a in ctrl.actions:
        by_action[a["action"]] = by_action.get(a["action"], 0) + 1
    return {"actions": len(ctrl.actions), "by_action": by_action,
            "final_deadline": round(float(gang.partial.deadline), 6),
            "deadline_source": gang.partial.deadline_source,
            "clamp": [cfg.min_deadline, cfg.max_deadline]}
