"""Scalar logging.

Reference: python/hetu/logger.py — ``HetuLogger:28`` buffers scalars and
flushes per step; ``dist_log`` NCCL-reduces a scalar across ranks before
logging; ``WandbLogger:90`` is the wandb backend.  TPU-native: cross-device
reduction happens inside the jitted step (psum/pmean), so the logger only
needs host-side buffering; a process-0 gate covers multi-host.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Optional

import jax
import numpy as np

__all__ = ["Logger", "WandbLogger"]


class Logger:
    def __init__(self, log_every: int = 1, file=None, is_main: Optional[bool] = None):
        self.log_every = log_every
        self.file = file or sys.stderr
        self.buffer: dict = {}
        self._step = 0
        self.is_main = (
            is_main if is_main is not None else jax.process_index() == 0
        )
        self._t0 = time.time()

    def log(self, key: str, value) -> None:
        self.buffer.setdefault(key, []).append(float(np.asarray(value)))

    def multi_log(self, scalars: dict) -> None:
        for k, v in scalars.items():
            self.log(k, v)

    def step(self) -> None:
        self._step += 1
        if self._step % self.log_every == 0:
            self.flush()

    def flush(self) -> None:
        if not self.buffer or not self.is_main:
            self.buffer.clear()
            return
        means = {k: float(np.mean(v)) for k, v in self.buffer.items()}
        line = {"step": self._step, "t": round(time.time() - self._t0, 2), **means}
        print(json.dumps(line), file=self.file, flush=True)
        self.buffer.clear()


class WandbLogger(Logger):
    """wandb backend (reference logger.py:90); degrades to Logger if wandb
    is unavailable (this image has no wandb and zero egress)."""

    def __init__(self, project: str = "hetu-tpu", config: Optional[dict] = None,
                 **kw):
        super().__init__(**kw)
        self._wandb = None
        if self.is_main:
            try:
                import wandb  # noqa: PLC0415

                self._wandb = wandb
                wandb.init(project=project, config=config or {})
            except Exception:
                self._wandb = None

    def flush(self) -> None:
        if self._wandb is not None and self.buffer and self.is_main:
            self._wandb.log(
                {k: float(np.mean(v)) for k, v in self.buffer.items()},
                step=self._step,
            )
            self.buffer.clear()
        else:
            super().flush()
