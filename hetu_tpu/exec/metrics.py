"""Evaluation metrics.

Reference: python/hetu/metrics.py (AUC:120 via thresholded confusion
matrices, f_score:315, precision/recall/accuracy).  Host-side numpy
implementations with the same capability surface.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "accuracy", "confusion_matrix", "precision", "recall", "f_score", "auc_roc",
    "auc_pr",
]


try:  # numpy >= 2.0 renamed trapz; keep importing on 1.x
    _trapezoid = np.trapezoid
except AttributeError:  # pragma: no cover - numpy 1.x
    _trapezoid = np.trapz


def _np(x):
    return np.asarray(x)


def accuracy(pred_labels, true_labels) -> float:
    pred_labels, true_labels = _np(pred_labels), _np(true_labels)
    return float((pred_labels == true_labels).mean())


def confusion_matrix(pred, truth, threshold: float = 0.5):
    """Binary confusion counts (tp, fp, fn, tn) at a threshold
    (reference metrics.py thresholded counters)."""
    pred, truth = _np(pred).ravel(), _np(truth).ravel()
    p = pred >= threshold
    t = truth.astype(bool)
    tp = int(np.sum(p & t))
    fp = int(np.sum(p & ~t))
    fn = int(np.sum(~p & t))
    tn = int(np.sum(~p & ~t))
    return tp, fp, fn, tn


def precision(pred, truth, threshold: float = 0.5) -> float:
    tp, fp, fn, tn = confusion_matrix(pred, truth, threshold)
    return tp / max(tp + fp, 1)


def recall(pred, truth, threshold: float = 0.5) -> float:
    tp, fp, fn, tn = confusion_matrix(pred, truth, threshold)
    return tp / max(tp + fn, 1)


def f_score(pred, truth, threshold: float = 0.5, beta: float = 1.0) -> float:
    """F-beta (reference metrics.py:315)."""
    p = precision(pred, truth, threshold)
    r = recall(pred, truth, threshold)
    if p + r == 0:
        return 0.0
    b2 = beta * beta
    return (1 + b2) * p * r / (b2 * p + r)


def auc_roc(scores, truth) -> float:
    """ROC-AUC by rank statistic (equivalent to the reference's threshold
    sweep metrics.py:120, exact rather than binned)."""
    scores, truth = _np(scores).ravel(), _np(truth).ravel().astype(bool)
    n_pos = int(truth.sum())
    n_neg = truth.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    # average ranks for ties
    sorted_scores = scores[order]
    i = 0
    r = 1
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (r + r + (j - i))
        r += j - i + 1
        i = j + 1
    return float((ranks[truth].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def auc_pr(scores, truth, num_thresholds: int = 200) -> float:
    """PR-AUC via threshold sweep (reference metrics.py ROC-PR)."""
    scores, truth = _np(scores).ravel(), _np(truth).ravel().astype(bool)
    thresholds = np.linspace(scores.min(), scores.max(), num_thresholds)
    ps, rs = [], []
    for th in thresholds[::-1]:
        p = scores >= th
        tp = np.sum(p & truth)
        fp = np.sum(p & ~truth)
        fn = np.sum(~p & truth)
        ps.append(tp / max(tp + fp, 1))
        rs.append(tp / max(tp + fn, 1))
    return float(_trapezoid(ps, rs))
