"""Execution-layer profiling: per-section timers, per-primitive graph
profiles, compiled-cost analysis, and trace capture.

Reference surfaces being covered (SURVEY §5.1):
- ``HetuTimer`` — the timer subexecutor's per-node/per-type accumulation
  (timer_subexecutor.py:21, ``logOut`` with node/type granularity);
- ``HetuProfiler`` — per-op re-execution profiling behind
  ``executor.profile(...)`` (profiler.py:55, executor.py:501);
- XLA-native extras the reference lacks: ``compiled_cost`` reads the
  compiler's own flop/byte analysis, ``trace`` captures a profile for
  TensorBoard/XProf (jax.profiler), which replaces CUDA-event timing.
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["HetuTimer", "audit_donation", "device_op_breakdown",
           "profile_fn", "compiled_cost", "primitive_counts", "trace"]


class HetuTimer:
    """Named-section wall timer with accumulation.

    >>> timer = HetuTimer()
    >>> with timer("forward"):
    ...     out = model(x)
    >>> timer.log_out()
    """

    def __init__(self, sync: bool = True):
        self.totals: dict = defaultdict(float)
        self.counts: dict = defaultdict(int)
        self.sync = sync
        self._last_result: Any = None

    @contextlib.contextmanager
    def __call__(self, name: str, result: Any = None):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            if self.sync and self._last_result is not None:
                jax.block_until_ready(self._last_result)
                self._last_result = None
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def observe(self, result: Any) -> Any:
        """Register a jax value to block on at section exit (async dispatch
        means exit-time sync is needed for honest timings)."""
        self._last_result = result
        return result

    def mean(self, name: str) -> float:
        return self.totals[name] / max(self.counts[name], 1)

    def log_out(self, printer: Callable = print) -> dict:
        """Per-section totals/means (timer_subexecutor logOut)."""
        stats = {name: {"total_s": self.totals[name],
                        "count": self.counts[name],
                        "mean_s": self.mean(name)}
                 for name in sorted(self.totals)}
        for name, s in stats.items():
            printer(f"[hetu-timer] {name}: total {s['total_s']*1e3:.2f}ms "
                    f"count {s['count']} mean {s['mean_s']*1e3:.3f}ms")
        return stats

    def reset(self):
        self.totals.clear()
        self.counts.clear()


def primitive_counts(fn: Callable, *example_args) -> dict:
    """Per-primitive equation counts + analytic flops where known — the
    node/type granularity of the reference's timer subexecutor, read off
    the jaxpr instead of timed per-op replays."""
    closed = jax.make_jaxpr(fn)(*example_args)
    counts: dict = defaultdict(int)
    flops: dict = defaultdict(float)

    def visit(jaxpr):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            inner = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr"))
            if inner is not None and prim in (
                    "pjit", "jit", "closed_call", "core_call",
                    "custom_jvp_call", "custom_vjp_call", "remat",
                    "remat2", "checkpoint"):
                visit(inner.jaxpr if hasattr(inner, "jaxpr") else inner)
                continue
            counts[prim] += 1
            if prim == "dot_general":
                ((lc, _rc), (lb, _rb)) = eqn.params["dimension_numbers"]
                lhs = eqn.invars[0].aval
                out = eqn.outvars[0].aval
                k = np.prod([lhs.shape[d] for d in lc], initial=1.0)
                flops[prim] += 2.0 * k * np.prod(out.shape, initial=1.0)
            elif prim == "conv_general_dilated":
                rhs = eqn.invars[1].aval
                out = eqn.outvars[0].aval
                # 2 * out_elems * (kernel spatial * in_channels)
                per_out = 2.0 * np.prod(rhs.shape, initial=1.0) / rhs.shape[
                    eqn.params["dimension_numbers"][1][0]]
                flops[prim] += per_out * np.prod(out.shape, initial=1.0)

    visit(closed.jaxpr)
    return {"counts": dict(counts), "flops": dict(flops),
            "total_flops": float(sum(flops.values()))}


def compiled_cost(fn: Callable, *example_args, static_argnums=()) -> dict:
    """XLA's own cost analysis of the compiled executable (flops, bytes
    accessed, peak memory when the backend reports it)."""
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*example_args)
    compiled = lowered.compile()
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out["flops"] = float(ca.get("flops", 0.0))
        out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:  # backend without cost analysis
        pass
    out.update(_memory_stats(compiled))
    return out


def _memory_stats(compiled) -> dict:
    """argument/output/alias/temp byte sizes of a compiled executable
    (empty dict on backends without memory analysis)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    return {
        "argument_bytes": float(getattr(mem, "argument_size_in_bytes", 0)),
        "output_bytes": float(getattr(mem, "output_size_in_bytes", 0)),
        "aliased_bytes": float(getattr(mem, "alias_size_in_bytes", 0)),
        "temp_bytes": float(getattr(mem, "temp_size_in_bytes", 0)),
    }


def audit_donation(trainer, batch, key=None) -> dict:
    """Donation/aliasing audit of the trainer's compiled train step — the
    TPU-rebuild replacement SURVEY §5.2 prescribes for the reference's
    manual CUDA stream/event race discipline (executor.py:1227-1246):
    XLA's dataflow semantics remove stream races, and what remains worth
    auditing is whether the train state's buffers are actually DONATED
    (aliased input→output) or silently copied.  A sharding change, dtype
    drift between ``opt.init`` and ``opt.update``, or a state leaf that
    stops being returned all break donation quietly — at BERT-large that
    is gigabytes of extra peak HBM.

    Returns {"argument_bytes", "output_bytes", "aliased_bytes",
    "temp_bytes", "donated_fraction", "unusable": [messages]} where
    ``unusable`` captures XLA's "donated buffers were not usable"
    warnings.  Numeric keys are 0.0 when the step cannot be lowered or
    compiled (the failure is recorded under "error") or the backend
    reports no memory analysis — the report degrades, it never raises.
    """
    import warnings

    key = jax.random.key(0) if key is None else key
    out: dict = {"argument_bytes": 0.0, "output_bytes": 0.0,
                 "aliased_bytes": 0.0, "temp_bytes": 0.0,
                 "donated_fraction": 0.0, "unusable": []}
    # a warm persistent compilation cache serves a deserialized executable
    # whose memory_analysis reports zero aliased bytes, and XLA's "donated
    # buffers were not usable" warnings only fire on a real compile — the
    # audit must observe one.  Unsetting the dir alone is not enough: the
    # cache instance is created once at first use and later config changes
    # are ignored, so reset it (it lazily re-initializes from the restored
    # config on the next cached compile).
    cache_dir_was = jax.config.jax_compilation_cache_dir

    def _reset_cache():
        try:
            from jax._src.compilation_cache import reset_cache
            reset_cache()
        except Exception:
            pass

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            _reset_cache()
            lowered = trainer._train_step.lower(trainer.state, batch, key) \
                if hasattr(trainer._train_step, "lower") else None
            compiled = lowered.compile() if lowered is not None else None
        except Exception as e:  # honor the degrade-don't-raise contract
            out["error"] = f"{type(e).__name__}: {e}"
            compiled = None
        finally:
            jax.config.update("jax_compilation_cache_dir", cache_dir_was)
            _reset_cache()
    out["unusable"] = [str(w.message) for w in caught
                       if "donated" in str(w.message).lower()]
    if compiled is None:
        return out
    out.update(_memory_stats(compiled))
    if out["argument_bytes"]:
        out["donated_fraction"] = out["aliased_bytes"] / out["argument_bytes"]
    return out


def profile_fn(fn: Callable, *example_args, iters: int = 10,
               warmup: int = 2) -> dict:
    """Wall-time + cost profile of a jitted function — the
    ``executor.profile(feed_shapes, ...)`` capability (executor.py:501).

    Returns {mean_s, p50_s, min_s, flops, achieved_flops, counts...}.
    """
    jitted = jax.jit(fn)
    for _ in range(max(warmup, 1)):
        out = jitted(*example_args)
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = jitted(*example_args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    prof = {"mean_s": float(np.mean(times)),
            "p50_s": float(np.median(times)),
            "min_s": float(np.min(times)),
            "iters": iters}
    prof.update(compiled_cost(fn, *example_args))
    prims = primitive_counts(fn, *example_args)
    prof["primitive_counts"] = prims["counts"]
    if "flops" not in prof or not prof["flops"]:
        prof["flops"] = prims["total_flops"]
    if prof.get("flops"):
        prof["achieved_flops"] = prof["flops"] / prof["p50_s"]
    return prof


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XProf/TensorBoard trace of the enclosed block
    (replaces the reference's CUDA-event timing paths on TPU)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def device_op_breakdown(logdir: str, *, steps: int = 1, top: int = 0):
    """Parse the newest ``*.trace.json.gz`` under ``logdir`` (written by
    ``trace()``) into per-op device time — the analysis loop behind the
    round-4 attention-layout and non-MXU-residue findings (ROADMAP 4b/4c),
    promoted from a script to API.

    Groups device-timeline events by XLA's ``deduplicated_name`` (repeats
    of the same fusion across layers aggregate), filters host frames and
    program envelopes, and divides by ``steps`` (trace ``steps``
    iterations for stable numbers).  Returns ``(per_op, totals)``:
    ``per_op`` maps op name -> seconds/step (all ops, or the ``top``
    largest), ``totals`` has ``device_s`` and ``copy_s`` (relayout
    ``copy.*``/``copy_fusion*`` ops — the layout-health number;
    ``transpose_jvp*``-style SCOPE names are not data transposes and are
    not counted).
    """
    import glob
    import gzip
    import json

    paths = glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no trace under {logdir}")
    with gzip.open(sorted(paths)[-1], "rt") as f:
        events = json.load(f).get("traceEvents", [])
    dev_pids = {ev.get("pid") for ev in events
                if ev.get("ph") == "M" and ev.get("name") == "process_name"
                and any(s in ev.get("args", {}).get("name", "")
                        for s in ("TPU", "Tensor", "Device", "/device"))}
    per = defaultdict(float)
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if dev_pids and ev.get("pid") not in dev_pids:
            continue
        name = (ev.get("args", {}).get("deduplicated_name")
                or ev.get("name", ""))
        if (not name or name.isdigit() or name.startswith(("$", "jit_"))
                or "(" in name):
            continue  # host python frames / program envelopes
        per[name] += ev["dur"] / 1e6 / steps
    totals = {
        "device_s": sum(per.values()),
        "copy_s": sum(v for k, v in per.items()
                      if k.startswith(("copy.", "copy_fusion"))),
    }
    ranked = dict(sorted(per.items(), key=lambda kv: -kv[1]))
    if top:
        ranked = dict(list(ranked.items())[:top])
    # calibration seam: the parsed per-op device table is a measured
    # signal — fold it into the installed profile store (one global
    # load + branch when none is installed)
    from hetu_tpu.obs.calibration import note_op_breakdown
    note_op_breakdown(per, totals)
    return ranked, totals
