from hetu_tpu.exec.executor import Executor, Trainer, TrainState
from hetu_tpu.exec.checkpoint import (
    AsyncCheckpointer,
    CheckpointCorrupt,
    CheckpointError,
    load_checkpoint,
    load_state_dict,
    save_checkpoint,
    state_dict,
)
from hetu_tpu.exec.logger import Logger, WandbLogger
from hetu_tpu.exec.profiler import audit_donation
from hetu_tpu.exec.resilience import (
    BackendUnresponsive,
    Preempted,
    ResilientTrainer,
    TrainingDiverged,
    latest_good_checkpoint,
    list_checkpoints,
)
from hetu_tpu.exec.gang import (
    ElasticGang,
    GangCheckpointer,
    GangError,
    GangManifestError,
    GangMembership,
    gang_data_partition,
    load_gang_checkpoint,
    worker_rng_key,
)
from hetu_tpu.exec.partial import (
    GradientBoard,
    PartialReduceConfig,
    PartialReducer,
)
from hetu_tpu.exec.controller import ControllerConfig, RuntimeController
from hetu_tpu.exec import controller, faults, gang, metrics, partial
