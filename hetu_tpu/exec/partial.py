"""Partial-reduce: straggler-tolerant bounded-staleness gradient collectives.

Hetu's SIGMOD'21 capability: a synchronous data-parallel step is only as
fast as its slowest worker, and on shared clusters the slowest worker is
routinely 2-10x the median (GC pauses, co-tenant interference, flaky
NICs).  Partial reduce breaks the full barrier: each step reduces over
whichever workers' gradients arrive within a **deadline**, scaling by
the actual contributor count, and a late gradient is *not* discarded —
it folds into a per-worker **correction term** applied at that worker's
next on-time step, bounded by a **staleness limit** ``tau`` beyond which
it is dropped and journaled.

The policy is one dataclass, :class:`PartialReduceConfig`:

- ``deadline`` — extra wait (step-clock units in the deterministic
  in-process gang; wall seconds on a real :class:`GradientBoard`) the
  reduce grants arrivals each step.  ``float("inf")`` degrades to the
  synchronous full barrier (the baseline the chaos tests measure
  against).
- ``tau`` — staleness bound in steps: a correction older than ``tau``
  at fold time is dropped (journal ``stale_drop``).
- ``min_arrivals`` — quorum floor: when fewer workers make the deadline
  the step degrades gracefully to *waiting out the full barrier* rather
  than reducing over a quorum too small to trust.

Determinism contract: everything here is a pure function of the arrival
schedule.  :class:`PartialReducer` keeps no wall-clock state — folds and
drops are decided by integer step arithmetic, reductions run in sorted
worker/origin order — so replaying a seeded
:class:`~hetu_tpu.exec.faults.FaultPlan` of ``worker_stall`` events
through :class:`~hetu_tpu.exec.gang.ElasticGang` reproduces
bitwise-identical journals, correction terms, and final parameters (the
``tests/test_partial.py`` acceptance bar).  Pending corrections are part
of the training state: :func:`PartialReducer.state_entries` renders them
as flat ``{name: array}`` entries that ride the sharded + ring-replicated
gang checkpoints, so a kill/recover replay restores mid-flight folds
bitwise (``split_state_entries`` separates them back out on load).

Composition with NaN-skip (``exec.resilience``): a non-finite *late
fold* rolls back **the fold, not the step** — the poisoned correction is
dropped (``stale_drop`` with ``reason="nonfinite"``) and the step
commits on the healthy contributions, so ``ResilientTrainer``'s anomaly
guard only ever sees genuine step-level NaNs.

Observability: ``hetu_partial_arrivals_total{outcome}``,
``hetu_partial_late_folds_total``, ``hetu_partial_dropped_total{reason}``
counters, the ``hetu_partial_staleness_age_steps`` histogram, and
``partial_step`` / ``late_fold`` / ``stale_drop`` journal kinds.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from hetu_tpu.obs import journal as _obs_journal
from hetu_tpu.obs import registry as _obs

__all__ = ["PartialReduceConfig", "PartialReducer", "GradientBoard",
           "WorkerLagEWMA", "grad_apply_fns", "split_state_entries",
           "STATE_PREFIX"]

# Reserved dotted-path prefix for pending-correction checkpoint entries.
# shard_owner() hashes these names like any parameter, so corrections are
# sharded + ring-replicated + manifest-signed for free.
STATE_PREFIX = "partialreduce."

_ENTRY_RE = re.compile(r"^w(\d+)\.t(\d+)\.a(\d+)\.n([0-9a-f]{16})\.(.+)$")

# Staleness ages are small integers (steps), not latencies.
_AGE_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0)


@dataclasses.dataclass(frozen=True)
class PartialReduceConfig:
    """Policy knobs for deadline-based partial gradient reduction.

    ``deadline``: how much extra the step waits for arrivals (step-clock
    units in the in-process gang, wall seconds over a
    :class:`GradientBoard`).  0 reduces over instant arrivals only;
    ``inf`` is the synchronous full barrier.
    ``tau``: staleness bound in steps for late-gradient folds.
    ``min_arrivals``: quorum floor below which the step degrades to the
    full barrier instead of trusting a tiny contributor set.
    ``min_deadline``/``max_deadline``: the :meth:`clamp` bounds an
    online tuner (``exec.controller``) must stay inside — the operator's
    hard rails around any automated policy.
    ``deadline_source``: ``"static"`` (configured), ``"controller"``
    (auto-tuned), or ``"planner"`` (set by an applied deployment Plan);
    surfaced on every ``partial_step`` journal event so replays
    distinguish tuned from configured cuts.
    """

    deadline: float = 0.0
    tau: int = 4
    min_arrivals: int = 1
    min_deadline: float = 0.0
    max_deadline: float = float("inf")
    deadline_source: str = "static"

    def __post_init__(self):
        if self.deadline < 0:
            raise ValueError(f"deadline must be >= 0, got {self.deadline}")
        if self.tau < 0:
            raise ValueError(f"tau must be >= 0, got {self.tau}")
        if self.min_arrivals < 1:
            raise ValueError(
                f"min_arrivals must be >= 1, got {self.min_arrivals}")
        if self.min_deadline < 0:
            raise ValueError(
                f"min_deadline must be >= 0, got {self.min_deadline}")
        if self.max_deadline < self.min_deadline:
            raise ValueError(
                f"max_deadline {self.max_deadline} < min_deadline "
                f"{self.min_deadline}")
        if self.deadline_source not in ("static", "controller",
                                        "planner"):
            raise ValueError(
                f"deadline_source must be 'static', 'controller', or "
                f"'planner', got {self.deadline_source!r}")

    def clamp(self, deadline: float) -> float:
        """Pin a proposed deadline inside ``[min_deadline,
        max_deadline]`` — the rails the controller's auto-tuning may
        never leave."""
        return min(max(float(deadline), self.min_deadline),
                   self.max_deadline)

    @classmethod
    def from_env(cls, **kw) -> Optional["PartialReduceConfig"]:
        """Build from the deadline the launcher plumbed through
        (``launch.simulate_workers(partial_deadline=...)`` →
        ``HETU_TPU_PARTIAL_DEADLINE``); None when the env is unset.
        Remaining knobs (``tau``, ``min_arrivals``) pass through ``kw``."""
        from hetu_tpu.launch import ENV_PARTIAL_DEADLINE
        raw = os.environ.get(ENV_PARTIAL_DEADLINE)
        if raw is None:
            return None
        return cls(deadline=float(raw), **kw)

    def cut(self, delays: Dict[int, float]) -> Tuple[list, float, bool]:
        """The deadline cut for one step: given each live worker's arrival
        delay, return ``(contributors, wait, degraded)``.

        Contributors are the workers whose delay is within ``deadline``;
        when they number fewer than ``min_arrivals`` the step *degrades*
        to the full barrier (everyone contributes, the step waits out the
        slowest — the graceful floor).  ``wait`` is the step-clock time
        spent waiting on the slowest contributor."""
        ontime = sorted(w for w, d in delays.items() if d <= self.deadline)
        required = min(self.min_arrivals, len(delays))
        if len(ontime) < required:
            everyone = sorted(delays)
            wait = max(delays.values()) if delays else 0.0
            return everyone, float(wait), True
        wait = max((delays[w] for w in ontime), default=0.0)
        return ontime, float(wait), False


# ------------------------------------------------------------- telemetry

_partial_metrics = None


def _partial_m() -> dict:
    global _partial_metrics
    if _partial_metrics is None:
        reg = _obs.get_registry()
        _partial_metrics = {
            "arrivals": reg.counter(
                "hetu_partial_arrivals_total",
                "gradient arrivals at the partial-reduce cut, by outcome "
                "(ontime = entered the step's reduce at the cut — on a "
                "degraded full-barrier step this includes the waited-out "
                "stragglers; late = staged as a correction term)",
                ("outcome",)),
            "late_folds": reg.counter(
                "hetu_partial_late_folds_total",
                "late gradients folded into a step as correction terms"),
            "degraded": reg.counter(
                "hetu_partial_degraded_steps_total",
                "steps that fell below min_arrivals at the deadline and "
                "degraded to the full barrier — a persistently degraded "
                "gang is under-provisioned for its deadline"),
            "dropped": reg.counter(
                "hetu_partial_dropped_total",
                "contributions dropped instead of folded (stale = past "
                "tau, nonfinite = NaN/Inf late fold rolled back, "
                "nonfinite_contribution = the step's own on-time gradient "
                "was NaN/Inf, worker_lost = owner evicted before its next "
                "on-time step)", ("reason",)),
            "age": reg.histogram(
                "hetu_partial_staleness_age_steps",
                "staleness age (steps) of late contributions at fold or "
                "drop time", buckets=_AGE_BUCKETS),
            "lag": reg.gauge(
                "hetu_partial_worker_lag_seconds",
                "EWMA of each worker's gradient arrival lag at the "
                "partial-reduce cut (step-clock units in the in-process "
                "gang, wall seconds over a GradientBoard) — the "
                "straggler-attribution signal /fleet/stragglers ranks "
                "and the future adaptive deadline consumes", ("worker",)),
        }
    return _partial_metrics


class WorkerLagEWMA:
    """Per-worker arrival-lag EWMA — the straggler attribution state.

    ``observe(delays)`` folds one cut's per-worker arrival delays into
    exponentially-weighted means (iteration in sorted rank order, plain
    float arithmetic: two same-schedule runs produce bitwise-identical
    EWMAs) and mirrors them to
    ``hetu_partial_worker_lag_seconds{worker=}``.  ``remap`` re-keys
    survivors through a rescale's rank map and removes evicted workers'
    gauge series (the elastic-membership convention: departed workers
    disappear from scrapes instead of freezing)."""

    def __init__(self, alpha: float = 0.25):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.lag: Dict[int, float] = {}

    def observe(self, delays: Dict[int, float]) -> None:
        for w in sorted(delays):
            d = float(delays[w])
            prev = self.lag.get(w)
            cur = d if prev is None else \
                (1.0 - self.alpha) * prev + self.alpha * d
            self.lag[int(w)] = cur
            if _obs.enabled():
                _partial_m()["lag"].labels(worker=str(w)).set(cur)

    def remap(self, rank_map: Dict[int, int]) -> None:
        old = self.lag
        self.lag = {}
        for w in sorted(old):
            if _obs.enabled():
                _partial_m()["lag"].remove(worker=str(w))
            if w in rank_map:
                self.lag[int(rank_map[w])] = old[w]
        for w, v in sorted(self.lag.items()):
            if _obs.enabled():
                _partial_m()["lag"].labels(worker=str(w)).set(v)

    def top(self, k: int = 5) -> list:
        """Worst-first ``[(worker, ewma_lag)]`` — the local form of the
        ``/fleet/stragglers`` report."""
        return sorted(self.lag.items(), key=lambda e: (-e[1], e[0]))[:k]


def _is_finite(flat: dict) -> bool:
    for v in flat.values():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
            return False
    return True


class PartialReducer:
    """Bounded-staleness gradient combiner — the piece both harnesses
    share: :class:`~hetu_tpu.exec.gang.ElasticGang` drives it on the
    deterministic step clock, a multi-process gang drives it over a
    :class:`GradientBoard`.

    Gradients are flat ``{dotted.path: array}`` dicts (the state-dict
    form); ``reduce`` returns the weighted mean over the on-time
    contributions plus any matured correction folds — weights are shard
    sizes, so the result is the exact per-example mean over every folded
    sample.  All iteration is in sorted (worker, origin) order and all
    arithmetic is plain float64-accumulating numpy, so the combine is
    bitwise-reproducible for a given arrival schedule."""

    def __init__(self, config: PartialReduceConfig):
        self.config = config
        # pending[worker] = [{origin, arrival, weight, grads}], sorted by
        # origin — each entry is one late gradient awaiting its owner's
        # next on-time step
        self.pending: Dict[int, list] = {}
        # straggler attribution: the harness feeds each cut's delays in
        # (ElasticGang on the step clock, GradientBoard on wall time)
        self.lags = WorkerLagEWMA()

    # -- staging ------------------------------------------------------------

    def stage_late(self, worker: int, origin_step: int, arrival_step: int,
                   weight: float, grads: dict) -> bool:
        """Stage a gradient that missed the deadline.  Returns False (and
        journals ``stale_drop``) when the arrival alone already exceeds
        ``tau`` — a stall that long can never fold in time, so it is
        dropped at the door instead of accumulating."""
        worker, origin = int(worker), int(origin_step)
        arrival = int(arrival_step)
        age_at_arrival = arrival - origin
        if _obs.enabled():
            _partial_m()["arrivals"].labels(outcome="late").inc()
        if age_at_arrival > self.config.tau:
            self._drop(worker, origin, origin, age_at_arrival, "stale")
            return False
        entry = {"origin": origin, "arrival": arrival,
                 "weight": float(weight),
                 "grads": {k: np.asarray(v) for k, v in grads.items()}}
        lst = self.pending.setdefault(worker, [])
        lst.append(entry)
        lst.sort(key=lambda e: (e["origin"], e["arrival"]))
        return True

    def _drop(self, worker: int, origin: int, step: int, age: int,
              reason: str) -> None:
        if _obs.enabled():
            _partial_m()["dropped"].labels(reason=reason).inc()
            _partial_m()["age"].observe(float(age))
        _obs_journal.record("stale_drop", step=int(step), worker=int(worker),
                            origin_step=int(origin), age=int(age),
                            reason=reason)

    # -- the reduce ---------------------------------------------------------

    def reduce(self, step: int, contributions: Dict[int, tuple], *,
               degraded: bool = False, waited: float = 0.0) -> tuple:
        """Combine one step's on-time contributions with every matured
        correction fold.

        ``contributions``: ``{worker: (weight, flat_grads)}`` — the
        workers that made the deadline cut (or everyone, on a degraded
        full-barrier step).  A non-finite contribution is excluded and
        journaled; a non-finite *fold* is rolled back — the fold, not the
        step (``stale_drop`` with ``reason="nonfinite"``).  Matured
        pendings older than ``tau`` are dropped, including those of
        workers not contributing this step (so a worker that never comes
        back cannot pin memory forever).

        Returns ``(combined_flat_or_None, info)`` where ``info`` carries
        ``arrivals`` (offered on-time contributions), ``used`` (the
        workers whose current gradient entered the reduce),
        ``late_folds``, ``dropped``, ``degraded``.  ``None`` means no
        usable gradient this step (every contribution non-finite)."""
        step = int(step)
        used_terms: list = []   # (weight, flat_grads) in deterministic order
        used_workers: list = []
        folds = drops = 0
        if degraded and _obs.enabled():
            _partial_m()["degraded"].inc()
        for w in sorted(contributions):
            weight, grads = contributions[w]
            if _obs.enabled():
                # every on-time ARRIVAL counts, finite or not, so the
                # counter agrees with the journal's arrivals field and
                # dropped/arrivals ratios stay <= 1 under NaN chaos
                _partial_m()["arrivals"].labels(outcome="ontime").inc()
            if not _is_finite(grads):
                # distinct from a rolled-back FOLD ("nonfinite"): here the
                # step's own gradient was poisoned, no correction involved
                self._drop(w, step, step, 0, "nonfinite_contribution")
                drops += 1
            else:
                used_terms.append((float(weight), grads))
                used_workers.append(w)
            f, d = self._fold_for(w, step, used_terms)
            folds += f
            drops += d
        # sweep non-contributors' matured pendings past tau (the owner may
        # be stalled indefinitely; tau bounds how long we hold its mass)
        for w in sorted(set(self.pending) - set(contributions)):
            keep = []
            for e in self.pending[w]:
                age = step - e["origin"]
                if e["arrival"] <= step and age > self.config.tau:
                    self._drop(w, e["origin"], step, age, "stale")
                    drops += 1
                else:
                    keep.append(e)
            if keep:
                self.pending[w] = keep
            else:
                del self.pending[w]
        info = {"arrivals": len(contributions), "used": used_workers,
                "late_folds": folds, "dropped": drops,
                "degraded": bool(degraded)}
        if not used_terms:
            _obs_journal.record("partial_step", step=step,
                                arrivals=len(contributions), late_folds=folds,
                                dropped=drops, degraded=bool(degraded),
                                waited=float(waited),
                                deadline_source=self.config.deadline_source,
                                skipped=True)
            return None, info
        total = sum(wt for wt, _g in used_terms)
        keys = sorted(used_terms[0][1])
        combined = {}
        for k in keys:
            acc = None
            for wt, g in used_terms:
                term = wt * np.asarray(g[k], np.float64)
                acc = term if acc is None else acc + term
            combined[k] = (acc / total).astype(
                np.asarray(used_terms[0][1][k]).dtype)
        _obs_journal.record("partial_step", step=step,
                            arrivals=len(contributions), late_folds=folds,
                            dropped=drops, degraded=bool(degraded),
                            waited=float(waited),
                            deadline_source=self.config.deadline_source)
        return combined, info

    def _fold_for(self, worker: int, step: int, used_terms: list) -> tuple:
        """Fold ``worker``'s matured pendings into ``used_terms`` (it is
        on time this step); drop the over-``tau`` and non-finite ones.
        Returns ``(folds, drops)``."""
        folds = drops = 0
        keep = []
        for e in self.pending.get(worker, []):
            if e["arrival"] > step:
                keep.append(e)
                continue
            age = step - e["origin"]
            if age > self.config.tau:
                self._drop(worker, e["origin"], step, age, "stale")
                drops += 1
            elif not _is_finite(e["grads"]):
                # the NaN-late-fold contract: roll back the FOLD, not the
                # step — the healthy contributions still commit
                self._drop(worker, e["origin"], step, age, "nonfinite")
                drops += 1
            else:
                used_terms.append((e["weight"], e["grads"]))
                folds += 1
                if _obs.enabled():
                    _partial_m()["late_folds"].inc()
                    _partial_m()["age"].observe(float(age))
                _obs_journal.record("late_fold", step=step, worker=worker,
                                    origin_step=e["origin"], age=age)
        if keep:
            self.pending[worker] = keep
        else:
            self.pending.pop(worker, None)
        return folds, drops

    # -- persistence --------------------------------------------------------

    def pending_count(self) -> int:
        return sum(len(v) for v in self.pending.values())

    def state_entries(self) -> dict:
        """Pending corrections as flat checkpoint entries
        (``partialreduce.wRRRR.tSSSSSSSS.aSSSSSSSS.nNNNN.<param>``) — the
        form :class:`~hetu_tpu.exec.gang.GangCheckpointer` shards,
        replicates, and signs like any parameter, so kill/recover replays
        restore mid-flight folds bitwise."""
        import struct
        out = {}
        for w in sorted(self.pending):
            for e in self.pending[w]:
                # the weight is encoded as its IEEE-754 bits (16 hex
                # chars): exact float round-trip, and no '.' to collide
                # with the dotted-name delimiter
                wbits = struct.pack(">d", float(e["weight"])).hex()
                base = (f"{STATE_PREFIX}w{w:04d}.t{e['origin']:08d}"
                        f".a{e['arrival']:08d}.n{wbits}")
                for name, arr in e["grads"].items():
                    out[f"{base}.{name}"] = np.asarray(arr)
        return out

    def load_state_entries(self, entries: dict,
                           rank_map: Optional[dict] = None,
                           step: Optional[int] = None) -> None:
        """Rebuild pending corrections from checkpoint entries, replacing
        the current state.  After a rescale, ``rank_map`` (old rank → new
        rank, from ``GangMembership.rescale``) re-keys survivors'
        corrections; an evicted worker's corrections are dropped and
        journaled (``reason="worker_lost"``)."""
        import struct
        groups: dict = {}
        for key, val in entries.items():
            m = _ENTRY_RE.match(key[len(STATE_PREFIX):])
            if not m:
                raise ValueError(
                    f"unparseable partial-reduce state entry {key!r}")
            w, t, a, name = (int(m.group(1)), int(m.group(2)),
                             int(m.group(3)), m.group(5))
            n = struct.unpack(">d", bytes.fromhex(m.group(4)))[0]
            groups.setdefault((w, t, a, n), {})[name] = np.asarray(val)
        self.pending = {}
        if rank_map is not None:
            # the lag EWMAs follow the same re-ranking the corrections do
            self.lags.remap(rank_map)
        for (w, t, a, n), grads in sorted(groups.items()):
            if rank_map is not None:
                if w not in rank_map:
                    self._drop(w, t, step if step is not None else a,
                               (step - t) if step is not None else (a - t),
                               "worker_lost")
                    continue
                w = rank_map[w]
            self.pending.setdefault(w, []).append(
                {"origin": t, "arrival": a, "weight": float(n),
                 "grads": grads})
        for lst in self.pending.values():
            lst.sort(key=lambda e: (e["origin"], e["arrival"]))


def split_state_entries(sd: dict) -> tuple:
    """Split a flat state dict into ``(params, partial_entries)`` — the
    load-side inverse of merging :meth:`PartialReducer.state_entries`
    into a checkpoint.  Always safe to call: a checkpoint written without
    partial reduce just yields an empty second dict."""
    params, entries = {}, {}
    for k, v in sd.items():
        (entries if k.startswith(STATE_PREFIX) else params)[k] = v
    return params, entries


# ---------------------------------------------------- trainer primitives

def grad_apply_fns(trainer) -> tuple:
    """Split a built :class:`~hetu_tpu.exec.Trainer` into the per-worker
    gradient-staging primitives partial reduce needs:

    - ``grad_fn(model, batch, key) -> (loss, grads)`` — one worker's
      shard gradient at the current parameters (jitted).
    - ``apply_fn(state, grads) -> new_state`` — one optimizer update
      from an already-combined gradient tree (jitted).

    Loss functions that return an updated model in ``aux`` (BatchNorm-
    style functional state) are not supported on the partial path — the
    contributors' model updates would not compose."""
    import jax

    from hetu_tpu.core.module import trainable_mask
    from hetu_tpu.exec.executor import TrainState

    if getattr(trainer, "strategy", None) is not None:
        raise ValueError(
            "partial reduce cannot drive a Trainer built with a sharding "
            "strategy: the per-worker grad/apply primitives re-jit "
            "loss_fn/optimizer without the strategy's mesh and would "
            "silently run unsharded — drive a plain data-parallel Trainer "
            "(the partial cut IS the data-parallel axis here)")
    loss_fn = trainer.loss_fn
    optimizer = trainer.optimizer
    mask = trainable_mask(trainer.state.model)

    @jax.jit
    def grad_fn(model, batch, key):
        def wrapped(m):
            loss, aux = loss_fn(m, batch, key)
            if isinstance(aux, dict) and "model" in aux:
                raise ValueError(
                    "partial reduce cannot drive a loss_fn with functional "
                    "model state (aux['model']): per-worker state updates "
                    "do not compose across the partial cut")
            return loss

        return jax.value_and_grad(wrapped)(model)

    @jax.jit
    def apply_fn(state, grads):
        params, opt_state = optimizer.update(
            grads, state.opt_state, state.model, mask=mask)
        return TrainState(params, opt_state)

    return grad_fn, apply_fn


# ------------------------------------------------- multi-process arrivals

class GradientBoard:
    """File-based per-step gradient exchange for multi-process gangs —
    the arrival substrate over the shared gang directory that
    ``launch.simulate_workers(gang_dir=..., partial_deadline=...)``
    provides (the in-process :class:`~hetu_tpu.exec.gang.ElasticGang`
    simulates arrivals on the step clock instead and never touches
    this).

    Posts are atomic (tmp + ``os.replace``), so a reader never sees a
    torn gradient; the wall-clock ``collect`` deadline is inherently
    non-deterministic — the bitwise replay guarantees live in the
    step-clock harness."""

    def __init__(self, gang_dir: str):
        self.dir = os.path.join(gang_dir, "partial")
        # wall-clock straggler attribution on the multi-process path:
        # collect() feeds each rank's observed arrival lag per step
        self.lags = WorkerLagEWMA()

    def _path(self, step: int, rank: int) -> str:
        return os.path.join(self.dir, f"step_{int(step):08d}",
                            f"grad_{int(rank):04d}.npz")

    def post(self, step: int, rank: int, weight: float, grads: dict) -> str:
        """Publish ``rank``'s gradient for ``step``."""
        path = self._path(step, rank)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, __weight__=np.asarray(float(weight)),
                     **{k: np.asarray(v) for k, v in grads.items()})
        os.replace(tmp, path)
        return path

    def take(self, step: int, rank: int) -> Optional[tuple]:
        """``(weight, flat_grads)`` for a posted gradient, or None when it
        has not arrived yet."""
        try:
            with np.load(self._path(step, rank)) as z:
                weight = float(z["__weight__"])
                grads = {k: z[k] for k in z.files if k != "__weight__"}
        except (OSError, ValueError):
            return None
        return weight, grads

    def collect(self, step: int, ranks: Sequence[int], *,
                deadline_s: float, min_arrivals: int = 1,
                poll: float = 0.01, barrier_timeout: float = 120.0) -> tuple:
        """Gather arrivals for ``step`` until every rank posted or the
        deadline passes with at least ``min_arrivals`` present (below the
        quorum the collect keeps waiting — the full-barrier degrade).
        Returns ``({rank: (weight, grads)}, missing_ranks, degraded)`` —
        pass ``degraded`` on to :meth:`PartialReducer.reduce` (and into
        the cut record) so the under-provisioned-gang telemetry fires on
        the multi-process path too; raises ``TimeoutError`` past
        ``barrier_timeout`` (a wedged gang, not a straggler)."""
        want = [int(r) for r in ranks]
        got: dict = {}
        arrived: dict = {}
        t0 = time.monotonic()
        deadline = t0 + float(deadline_s)
        hard = t0 + float(barrier_timeout)
        required = min(int(min_arrivals), len(want))
        degraded = False
        while True:
            for r in want:
                if r not in got:
                    hit = self.take(step, r)
                    if hit is not None:
                        got[r] = hit
                        arrived[r] = time.monotonic() - t0
            if len(got) == len(want):
                break
            now = time.monotonic()
            if now > deadline:
                if not degraded and len(got) >= required:
                    break
                # below quorum at the deadline: the decision is made once,
                # and it is the FULL barrier (mirror of cut()'s degraded
                # step) — not "first moment the quorum fills in"
                degraded = True
            if now > hard:
                raise TimeoutError(
                    f"partial-reduce collect for step {step} wedged: only "
                    f"{sorted(got)} of {want} posted within "
                    f"{barrier_timeout}s")
            time.sleep(poll)
        # ranks that never posted are the REAL stragglers: attribute the
        # full time we waited as their lag floor (they took at least that
        # long), matching the in-process path which observes every rank
        elapsed = time.monotonic() - t0
        for r in want:
            if r not in arrived:
                arrived[r] = elapsed
        self.lags.observe(arrived)
        return got, [r for r in want if r not in got], degraded

    # The cut record: one worker (rank 0 by convention) runs the wall-
    # clock deadline and COMMITS the contributor set; every other worker
    # reduces over exactly that set, so the whole gang applies the same
    # update even though each rank observes arrivals at different times.
    # Late folds then re-derive deterministically on every rank: a
    # gradient cut out at its origin step is staged with
    # ``arrival = origin + 1`` (a rule, not an observation) and folds at
    # its owner's next committed-contributor step.

    def _cut_path(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{int(step):08d}", "cut.json")

    def post_cut(self, step: int, contributors: Sequence[int],
                 degraded: bool = False) -> str:
        """Commit the contributor set (and whether the step degraded to
        the full barrier) for ``step`` — atomic; the decider rank calls
        this after its :meth:`collect`."""
        import json
        path = self._cut_path(step)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"step": int(step),
                       "contributors": sorted(int(r) for r in contributors),
                       "degraded": bool(degraded)},
                      f)
        os.replace(tmp, path)
        return path

    def read_cut(self, step: int, *, timeout_s: float = 120.0,
                 poll: float = 0.01) -> dict:
        """Wait for the decider's committed cut record for ``step``:
        ``{"step", "contributors", "degraded"}``."""
        import json
        deadline = time.monotonic() + float(timeout_s)
        while True:
            try:
                with open(self._cut_path(step)) as f:
                    return json.load(f)
            except (OSError, ValueError):
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no cut record for step {step} within {timeout_s}s — "
                    f"the decider rank is gone or wedged")
            time.sleep(poll)
