"""Graph visualization — the reference's graphboard (python/graphboard/
graph2fig.py:11 renders the executor DAG with graphviz behind a tiny HTTP
page).

TPU-native: the graph is the jaxpr.  ``to_dot`` renders any traceable
function (or an already-made jaxpr) as graphviz dot text; ``show`` serves it
over HTTP, rendering to SVG via the ``dot`` binary when present and falling
back to the raw dot source otherwise (zero hard dependencies).
"""

from __future__ import annotations

import html
import itertools
import shutil
import subprocess
from typing import Any, Callable, Optional

__all__ = ["to_dot", "render_svg", "show"]

_PALETTE = {
    "dot_general": "#c6dbef", "conv_general_dilated": "#c6dbef",
    "add": "#e5f5e0", "mul": "#e5f5e0", "sub": "#e5f5e0", "div": "#e5f5e0",
    "reduce_sum": "#fee6ce", "reduce_max": "#fee6ce", "reduce_min": "#fee6ce",
    "custom_jvp_call": "#ddd", "pjit": "#fde0ef",
    "broadcast_in_dim": "#f7f7f7", "reshape": "#f7f7f7",
    "transpose": "#f7f7f7", "concatenate": "#f7f7f7",
}


def _avals(v) -> str:
    a = v.aval
    shape = "x".join(map(str, a.shape)) if a.shape else "scalar"
    return f"{a.dtype}[{shape}]"


def to_dot(fn_or_jaxpr: Any, *example_args, name: str = "hetu_tpu",
           collapse_calls: bool = True) -> str:
    """Graphviz dot text for a function's jaxpr (or a ClosedJaxpr).

    ``collapse_calls`` keeps pjit/custom_jvp sub-jaxprs as single boxes
    (layer-level view); pass False to inline them (kernel-level view).
    """
    import jax

    if hasattr(fn_or_jaxpr, "jaxpr"):
        closed = fn_or_jaxpr
    else:
        closed = jax.make_jaxpr(fn_or_jaxpr)(*example_args)

    lines = [f'digraph "{name}" {{',
             '  rankdir=TB; node [shape=box, style="rounded,filled", '
             'fillcolor="#f7f7f7", fontname="Helvetica", fontsize=10];']
    counter = itertools.count()
    node_of: dict[int, str] = {}

    def node_id() -> str:
        return f"n{next(counter)}"

    def declare(nid: str, label: str, color: str = "#f7f7f7",
                shape: str = "box"):
        lines.append(f'  {nid} [label="{html.escape(label)}", '
                     f'fillcolor="{color}", shape={shape}];')

    def walk(jaxpr, prefix: str):
        for v in jaxpr.constvars:
            nid = node_id()
            node_of[id(v)] = nid
            declare(nid, f"const\n{_avals(v)}", "#fff7bc", "ellipse")
        for i, v in enumerate(jaxpr.invars):
            nid = node_id()
            node_of[id(v)] = nid
            declare(nid, f"{prefix}in{i}\n{_avals(v)}", "#deebf7", "ellipse")
        from jax._src.core import Literal
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            inner = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                     if prim in ("pjit", "custom_jvp_call", "custom_vjp_call",
                                 "remat", "checkpoint") else None)
            if inner is not None and not collapse_calls:
                inner_jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
                walk(inner_jaxpr, prefix + prim + ".")
                # connect call boundary by aliasing vars
                for outer_v, inner_v in zip(eqn.invars, inner_jaxpr.invars):
                    if not isinstance(outer_v, Literal) and id(outer_v) in node_of:
                        lines.append(
                            f'  {node_of[id(outer_v)]} -> {node_of[id(inner_v)]} '
                            '[style=dashed];')
                for outer_v, inner_v in zip(eqn.outvars, inner_jaxpr.outvars):
                    if id(inner_v) in node_of:
                        node_of[id(outer_v)] = node_of[id(inner_v)]
                continue
            nid = node_id()
            label = prim
            if inner is not None:
                fn_name = eqn.params.get("name", "")
                label = f"{prim}\n{fn_name}" if fn_name else prim
            label += "\n" + ", ".join(_avals(v) for v in eqn.outvars[:2])
            declare(nid, label, _PALETTE.get(prim, "#f7f7f7"))
            for v in eqn.invars:
                if isinstance(v, Literal):
                    continue
                src = node_of.get(id(v))
                if src:
                    lines.append(f'  {src} -> {nid};')
            for v in eqn.outvars:
                node_of[id(v)] = nid
        return jaxpr.outvars

    outvars = walk(closed.jaxpr, "")
    for i, v in enumerate(outvars):
        nid = node_id()
        declare(nid, f"out{i}\n{_avals(v)}", "#fcbba1", "ellipse")
        src = node_of.get(id(v))
        if src:
            lines.append(f'  {src} -> {nid};')
    lines.append("}")
    return "\n".join(lines)


def render_svg(dot_text: str) -> Optional[str]:
    """SVG via the graphviz `dot` binary, or None when unavailable."""
    exe = shutil.which("dot")
    if exe is None:
        return None
    out = subprocess.run([exe, "-Tsvg"], input=dot_text.encode(),
                         capture_output=True)
    if out.returncode != 0:
        return None
    return out.stdout.decode()


def show(fn: Callable, *example_args, port: int = 9001,
         open_browser: bool = False, blocking: bool = True):
    """Serve the graph on http://localhost:port (graph2fig.py:11 ``show``)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    dot_text = to_dot(fn, *example_args)
    svg = render_svg(dot_text)
    body = svg if svg is not None else f"<pre>{html.escape(dot_text)}</pre>"
    page = f"<html><head><title>hetu-tpu graphboard</title></head><body>{body}</body></html>"

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            payload = dot_text.encode() if self.path == "/dot" else page.encode()
            ctype = "text/plain" if self.path == "/dot" else "text/html"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    server = HTTPServer(("127.0.0.1", port), Handler)
    if open_browser:
        import webbrowser
        webbrowser.open(f"http://127.0.0.1:{server.server_address[1]}/")
    if blocking:
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            server.server_close()
    return server
