"""Deterministic fault-injection harness for the resilience layer.

A ``FaultPlan`` is a seeded, fully deterministic schedule of ``(step,
Fault)`` events.  Installing a plan wires lightweight hooks into the real
production code paths — no monkeypatching, the seams ship in the modules
themselves and cost one ``is None`` check when no plan is active:

=================  ====================================  ===================
fault kind         hook site (module seam)               effect
=================  ====================================  ===================
``ps_socket_kill`` ``embed.net.RemoteEmbeddingTable``    the next RPC at the
                   ``._rpc`` (``net._fault_hook``)       step reports dead-
                                                         socket status -10
                                                         and must survive
                                                         via the reconnect
                                                         protocol
``ckpt_truncate``  ``exec.checkpoint._atomic_write``     the just-written
``ckpt_corrupt``   (``checkpoint._fault_hook``)          checkpoint file is
                                                         truncated to half /
                                                         has a payload byte
                                                         flipped on disk
``grad_nan``       ``exec.executor.Trainer.step``        the step's batch is
                   (``executor._fault_hook``)            NaN-poisoned, so
                                                         loss and every
                                                         gradient go NaN
``hang``           ``exec.resilience`` step body         the step body
                   (via :func:`fire` ``"step_begin"``)   sleeps ``arg``
                                                         seconds — the
                                                         unresponsive-
                                                         backend shape the
                                                         watchdog must catch
``worker_kill``    ``launch.simulate_workers(faults=)``  worker ``step`` is
                                                         signalled after
                                                         ``arg`` seconds; in
                                                         ``exec.gang`` (with
                                                         ``worker=`` set) the
                                                         target rank dies at
                                                         the scheduled step
``worker_stall``   ``launch.simulate_workers(faults=)``  the worker process is
                   / ``exec.gang.ElasticGang``           SIGSTOP'd for
                                                         ``duration`` seconds
                                                         (process harness) or
                                                         rank ``worker`` stops
                                                         heartbeating for
                                                         ``arg`` steps (gang)
``shard_loss``     ``exec.gang.ElasticGang``             rank ``worker``'s
                                                         shard directory is
                                                         deleted — recovery
                                                         must ride the ring
                                                         replica
``bit_flip``       ``exec.gang.ElasticGang``             one bit of rank
                   (divergence check)                    ``worker``'s
                                                         post-update replica
                                                         is flipped (``arg``
                                                         indexes the bit) —
                                                         the divergence
                                                         detector must name
                                                         the step/worker/
                                                         shard
``compile_storm``  ``serve.engine.ServingEngine.step``   ``arg`` synthetic
                                                         distinct-shape
                                                         compiles are noted
                                                         into the process
                                                         StormDetector at the
                                                         scheduled scheduler
                                                         tick (default:
                                                         threshold+1) — the
                                                         controller must
                                                         freeze bucket growth
``replica_crash``  ``serve.engine.ServingEngine.step``   the replica dies
                   (with ``worker=`` = replica index)    permanently at the
                                                         scheduled scheduler
                                                         tick: no more beats,
                                                         KV pages
                                                         unexportable — the
                                                         failover monitor
                                                         must mark it
                                                         ``failed`` and
                                                         re-home every
                                                         in-flight request
                                                         via re-prefill
``decode_hang``    ``serve.engine.ServingEngine.step``   the replica goes
                   (with ``worker=`` = replica index)    silent for ``arg``
                                                         scheduler ticks (no
                                                         work, no beats),
                                                         then recovers — a
                                                         hang past the lease
                                                         triggers failover
                                                         with KV salvage; a
                                                         recovered replica is
                                                         restored (and may
                                                         flap into the
                                                         controller's
                                                         quarantine)
``migrate_drop``   ``serve.fleet`` migration transit     the next KV
                   (disagg hand-off or failover          migration record is
                   salvage)                              dropped in transit —
                                                         the importer must
                                                         fall back to
                                                         re-prefill, never
                                                         serve a torn record
=================  ====================================  ===================

Two scheduling conventions coexist for the worker-targeted kinds: in
``simulate_workers`` the event's *step* is the worker index and ``worker``
is left None (wall-clock chaos); in the gang runtime the step is the
1-based global training step and ``worker=`` names the target rank at
fire time (deterministic step-clock chaos).  Each harness only consumes
events written in its own convention.  ``grad_nan`` follows the same
rule: untargeted events poison the whole batch at the ``Trainer.step``
seam, while ``worker=``-targeted ones poison a single rank's shard in
the gang's partial-reduce path (the NaN-late-fold chaos shape).  Under
the gang's partial-reduce mode a ``worker_stall`` models a *straggler*
(late gradient arrivals for ``arg`` steps), not a missed heartbeat;
``FaultPlan.random(n_workers=..., stall_steps=...)`` draws realistic
(heavy-tailed by default) stall lengths for such schedules.

Every event fires exactly once; ``plan.fired`` records what actually
triggered, so chaos tests can assert the schedule was exercised.  Two plans
built from the same seed are identical (``FaultPlan.random``), and a plan
replayed against the same training run injects at the same steps — the
lineage tests rely on this to compare a faulted run bitwise against a clean
one.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import signal as _signal
import threading
import time
from typing import Iterable, Optional, Union

__all__ = ["Fault", "FaultPlan", "install", "uninstall", "inject", "fire",
           "active_plan", "KINDS"]

KINDS = ("ps_socket_kill", "ckpt_truncate", "ckpt_corrupt", "grad_nan",
         "hang", "worker_kill", "worker_stall", "shard_loss", "bit_flip",
         "compile_storm", "replica_crash", "decode_hang", "migrate_drop")

# C-client dead-socket status (net.RemoteEmbeddingTable._NET_ERRS)
_DEAD_SOCKET = -10


@dataclasses.dataclass(frozen=True)
class Fault:
    """One injectable fault.  ``arg`` is kind-specific: sleep seconds for
    ``hang``, kill/stall delay seconds for ``worker_kill``/``worker_stall``
    under ``simulate_workers``, stall length in steps for ``worker_stall``
    under the gang runtime (unused otherwise).  ``sig`` is the signal a
    ``worker_kill`` delivers (default SIGKILL).  ``worker`` names the
    target rank for gang-runtime events (None = the ``simulate_workers``
    convention, where the event's *step* is the worker index).
    ``duration`` is the SIGSTOP length in seconds for a process-level
    ``worker_stall``."""

    kind: str
    arg: Optional[float] = None
    sig: Optional[int] = None
    worker: Optional[int] = None
    duration: Optional[float] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")


class FaultPlan:
    """A deterministic schedule of ``(step, fault)`` events.

    Steps are the 1-based driver step counter (``ResilientTrainer``
    advances the plan at the top of every step; standalone users call
    :meth:`advance` themselves).  For ``worker_kill`` events the "step" is
    reinterpreted by ``launch.simulate_workers`` as the worker index.
    """

    def __init__(self, events: Iterable[tuple]):
        self._lock = threading.Lock()
        self._events: list = []
        for step, fault in events:
            if isinstance(fault, str):
                fault = Fault(fault)
            self._events.append((int(step), fault))
        self._events.sort(key=lambda e: e[0])
        self._step = 0
        self.fired: list = []  # [(step, Fault)] in firing order

    @classmethod
    def random(cls, seed: int, n_steps: int, *,
               kinds: Iterable[str] = ("ps_socket_kill", "grad_nan"),
               rate: float = 0.05, n_workers: Optional[int] = None,
               stall_steps=("pareto", 1.5, 1.0)) -> "FaultPlan":
        """Seeded random schedule: each step draws each kind independently
        with probability ``rate``.  Same seed → bit-identical plan.

        With ``n_workers`` set, the worker-targeted kinds
        (``worker_kill`` / ``worker_stall`` / ``shard_loss``) are emitted
        in the gang step-clock convention — a uniformly drawn target rank
        in ``worker=`` — and ``worker_stall`` additionally draws its
        length in steps from the ``stall_steps`` distribution, so chaos
        runs model realistic straggler schedules instead of unit stalls.
        ``stall_steps`` specs: a bare number (constant), ``("const", k)``,
        ``("uniform", lo, hi)`` (inclusive), ``("geometric", p)``, or the
        heavy-tailed default ``("pareto", shape, scale)`` — a shifted
        Pareto (Lomax + scale), matching the long-tail stragglers
        measured on shared clusters: most stalls are ~1 step, a few are
        10x that.  Draws happen only for steps where the event fires, in
        (step, kind) order, so the schedule stays a pure function of the
        seed."""
        import numpy as np
        rng = np.random.default_rng(seed)
        worker_kinds = ("worker_kill", "worker_stall", "shard_loss")
        events = []
        for step in range(1, n_steps + 1):
            for kind in kinds:
                if rng.random() < rate:
                    if n_workers is not None and kind in worker_kinds:
                        w = int(rng.integers(n_workers))
                        if kind == "worker_stall":
                            events.append((step, Fault(
                                kind, worker=w,
                                arg=float(_draw_stall(rng, stall_steps)))))
                        else:
                            events.append((step, Fault(kind, worker=w)))
                    else:
                        events.append((step, Fault(kind)))
        return cls(events)

    # -- schedule interface -------------------------------------------------

    def advance(self, step: int) -> None:
        """Set the current step; hooks fire events scheduled for it."""
        with self._lock:
            self._step = int(step)

    def take(self, *kinds: str, late_ok: bool = False,
             now: Optional[int] = None,
             require_worker: Optional[bool] = None) -> Optional[Fault]:
        """Pop (at most) one pending event of the given kinds scheduled for
        step ``now`` (default: the current step; with ``late_ok``, at or
        before it).  Thread-safe: concurrent hook calls (e.g. the shard
        router's parallel pulls) fire the event exactly once.

        ``require_worker=True`` only matches events with ``worker=`` set
        (the gang runtime's step-clock convention), leaving
        ``simulate_workers``-convention events pending for their own
        harness — the each-harness-consumes-its-own-convention rule."""
        with self._lock:
            at = self._step if now is None else int(now)
            for i, (step, fault) in enumerate(self._events):
                hit = step == at or (late_ok and step <= at)
                if require_worker is not None and \
                        (fault.worker is not None) != require_worker:
                    continue
                if hit and fault.kind in kinds:
                    del self._events[i]
                    self.fired.append((step, fault))
                    return fault
        return None

    def worker_events(self, kind: str,
                      n_workers: Optional[int] = None) -> list:
        """``[(worker_index, delay_seconds, payload)]`` for every pending
        ``simulate_workers``-convention event of ``kind`` — the payload is
        the signal for ``worker_kill`` (default SIGKILL) and the SIGSTOP
        duration in seconds for ``worker_stall`` (default 1.0).

        ``launch.simulate_workers(faults=plan)`` passes its gang size so
        an event aimed at a worker that does not exist stays pending (and
        shows up in ``remaining()``) instead of being reported as fired;
        gang-runtime events (``worker=`` set, step-scheduled) likewise
        stay pending for ``ElasticGang`` instead of being misread as a
        worker index here."""
        if kind not in ("worker_kill", "worker_stall"):
            raise ValueError(
                f"worker_events handles 'worker_kill'/'worker_stall', "
                f"got {kind!r}")
        out = []
        with self._lock:
            rest = []
            for step, fault in self._events:
                in_range = n_workers is None or 0 <= step < n_workers
                if fault.kind == kind and fault.worker is None and in_range:
                    if kind == "worker_kill":
                        payload = fault.sig or _signal.SIGKILL
                    else:
                        payload = (fault.duration
                                   if fault.duration is not None else 1.0)
                    out.append((step, fault.arg or 0.0, payload))
                    self.fired.append((step, fault))
                else:
                    rest.append((step, fault))
            self._events = rest
        return out

    def worker_kills(self, n_workers: Optional[int] = None) -> list:
        """``[(worker_index, delay_seconds, signal)]`` — thin wrapper over
        :meth:`worker_events`."""
        return self.worker_events("worker_kill", n_workers)

    def worker_stalls(self, n_workers: Optional[int] = None) -> list:
        """``[(worker_index, delay_seconds, stall_seconds)]`` — thin
        wrapper over :meth:`worker_events`."""
        return self.worker_events("worker_stall", n_workers)

    def remaining(self) -> list:
        """Events that have not fired (a clean chaos run drains the plan)."""
        with self._lock:
            return list(self._events)

    # -- hook dispatch ------------------------------------------------------

    def _fire(self, site: str, payload=None):
        if site == "ps_rpc":
            if self.take("ps_socket_kill") is not None:
                return _DEAD_SOCKET
            return None
        if site == "ckpt_write":
            # checkpoint writes are asynchronous: the background write for
            # step N can land while the plan is already at step N+k, so an
            # event is matched against the STEP IN THE FILENAME when the
            # path is a resilience checkpoint (ckpt.step_NNN) — fully
            # deterministic regardless of writer timing; other paths fall
            # back to the plan step.  ``late_ok``: fire on the first write
            # at or after the scheduled step.
            from hetu_tpu.exec.checkpoint import _STEP_IN_NAME
            m = _STEP_IN_NAME.search(payload or "")
            now = int(m.group(1)) if m else None
            fault = self.take("ckpt_truncate", "ckpt_corrupt",
                              late_ok=True, now=now)
            if fault is not None:
                _mangle_file(payload, fault.kind)
            return None
        if site == "grad":
            # worker-targeted grad_nan events belong to the gang runtime's
            # partial-reduce path (poison ONE rank's shard); the executor
            # seam only consumes the untargeted convention
            if self.take("grad_nan", require_worker=False) is not None:
                return _poison_batch(payload)
            return None
        if site == "step_begin":
            fault = self.take("hang")
            if fault is not None:
                time.sleep(fault.arg if fault.arg is not None else 3600.0)
            return None
        return None


def _draw_stall(rng, spec) -> int:
    """Draw one stall length in steps from a ``stall_steps`` spec (see
    :meth:`FaultPlan.random`); always >= 1."""
    if isinstance(spec, (int, float)):
        return max(1, int(spec))
    name, *args = spec
    if name == "const":
        k = float(args[0])
    elif name == "uniform":
        k = float(rng.integers(int(args[0]), int(args[1]) + 1))
    elif name == "geometric":
        k = float(rng.geometric(float(args[0])))
    elif name == "pareto":
        # shifted Pareto (Lomax + scale): support [scale, inf), tail index
        # `shape` — the measured long-tail straggler shape
        shape, scale = float(args[0]), float(args[1])
        k = scale * (1.0 + rng.pareto(shape))
    else:
        raise ValueError(
            f"unknown stall_steps distribution {name!r}; one of "
            f"const/uniform/geometric/pareto or a bare number")
    return max(1, int(round(k)))


def _mangle_file(path: str, kind: str) -> None:
    """Damage a checkpoint ON DISK the way real failures do: ``truncate``
    = torn write (tail, incl. the integrity footer, lost); ``corrupt`` =
    silent bit rot (one payload byte flipped; footer intact → CRC trips)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        if kind == "ckpt_truncate":
            f.truncate(max(size // 2, 1))
        else:
            pos = max(size // 3, 0)
            f.seek(pos)
            byte = f.read(1)
            f.seek(pos)
            f.write(bytes([byte[0] ^ 0xFF]))
        f.flush()
        os.fsync(f.fileno())


def _poison_batch(batch):
    """Replace the first floating leaf of the batch with NaNs: the forward
    pass and every gradient downstream of it go NaN — the deterministic
    stand-in for a corrupted gradient all-reduce."""
    import jax.numpy as jnp
    import jax.tree_util as jtu

    leaves, treedef = jtu.tree_flatten(batch)
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            leaves[i] = jnp.full_like(jnp.asarray(leaf), jnp.nan)
            break
    else:
        raise ValueError("grad_nan fault: batch has no floating leaf "
                         "to poison")
    return jtu.tree_unflatten(treedef, leaves)


# -- plan installation ------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def fire(site: str, payload=None):
    """Hook entry point.  The instrumented modules hold this (or call it
    directly) while a plan is installed; returns the site-specific override
    or None."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan._fire(site, payload)


def install(plan: FaultPlan) -> None:
    """Arm ``plan``: wire the dispatch hook into every instrumented seam."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a FaultPlan is already installed")
    _ACTIVE = plan
    from hetu_tpu.embed import net as _net
    from hetu_tpu.exec import checkpoint as _ckpt
    from hetu_tpu.exec import executor as _exec
    _net._fault_hook = fire
    _ckpt._fault_hook = fire
    _exec._fault_hook = fire


def uninstall() -> None:
    """Disarm: every seam back to its zero-overhead None."""
    global _ACTIVE
    _ACTIVE = None
    from hetu_tpu.embed import net as _net
    from hetu_tpu.exec import checkpoint as _ckpt
    from hetu_tpu.exec import executor as _exec
    _net._fault_hook = None
    _ckpt._fault_hook = None
    _exec._fault_hook = None


@contextlib.contextmanager
def inject(plan: Union[FaultPlan, Iterable[tuple]]):
    """``with faults.inject(plan):`` — install for the block, always
    disarm on the way out (even when the chaos run dies)."""
    if not isinstance(plan, FaultPlan):
        plan = FaultPlan(plan)
    install(plan)
    try:
        yield plan
    finally:
        uninstall()
