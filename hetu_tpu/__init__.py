"""hetu-tpu: a TPU-native distributed deep-learning framework.

A from-scratch rebuild of the capability surface of Hetu (PKU DAIR Lab;
reference mounted at /root/reference) designed around JAX/XLA/Pallas/pjit:

* ``hetu_tpu.core``   — pytree module system, reproducible RNG, dtype policy
* ``hetu_tpu.ops``    — the functional op surface (reference src/ops kernels)
* ``hetu_tpu.optim``  — optimizers + lr schedulers (reference optimizer.py)
* ``hetu_tpu.init``   — initializers (reference initializers.py)
* ``hetu_tpu.layers`` — NN layers (reference python/hetu/layers)
* ``hetu_tpu.parallel`` — mesh/sharding-spec algebra, collectives, pipeline,
  MoE all-to-all, ring attention (reference context.py + communicator/)
* ``hetu_tpu.exec``   — trainer/executor facade, checkpointing, profiling
  (reference gpu_ops/executor.py)
* ``hetu_tpu.embed``  — host-side cached sparse-embedding engine (HET;
  reference src/hetu_cache + ps-lite)
* ``hetu_tpu.obs``    — runtime telemetry: metrics registry, tracing
  spans, resilience event journal, /metrics endpoint
* ``hetu_tpu.mem``    — memory planning: jaxpr live-range estimator,
  named remat-policy registry, (policy, microbatch) planner, host
  offload (reference src/memory_pool/ BFC allocator + swap)
* ``hetu_tpu.serve``  — online inference: paged KV cache, continuous
  batching engine, /infer endpoint (imported lazily — serving pulls in
  models)
* ``hetu_tpu.models`` — model zoo (reference examples/)
* ``hetu_tpu.data``   — dataloaders (reference dataloader.py)
* ``hetu_tpu.autoparallel`` — cost-model-driven parallelism search
  (reference distributed_strategies/ + tools/Galvatron)
"""

__version__ = "1.0.0"

from hetu_tpu import core, init, mem, obs, ops, optim
from hetu_tpu.core import (
    Module,
    Policy,
    get_seed_status,
    logical_axes,
    next_key,
    param_count,
    reset_seed_seqnum,
    set_random_seed,
    trainable_mask,
)
