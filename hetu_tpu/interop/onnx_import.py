"""Import ONNX models as jax callables.

Counterpart of the reference's ``onnx2hetu`` (python/hetu/onnx/onnx2hetu.py +
X2hetu handlers): parses a ModelProto (via the self-contained ``onnx_pb``
codec) and interprets the graph with jnp ops.  ``import_model`` returns
``(fn, params)`` where ``fn(params, **inputs)`` is jittable and ``params`` is
the initializer dict — so imported models drop straight into jit/grad/pjit.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.interop import onnx_pb as pb

__all__ = ["import_model", "load_model"]


_OP_HANDLERS: dict[str, Callable] = {}


def op_handler(*names):
    def deco(fn):
        for n in names:
            _OP_HANDLERS[n] = fn
        return fn
    return deco


def _a(node: pb.NodeProto, name: str, default=None):
    return node.attr(name, default)


# elementwise ------------------------------------------------------------------

_SIMPLE = {
    "Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
    "Div": jnp.divide, "Pow": jnp.power, "Neg": jnp.negative,
    "Exp": jnp.exp, "Log": jnp.log, "Sqrt": jnp.sqrt,
    "Reciprocal": jnp.reciprocal, "Abs": jnp.abs, "Sign": jnp.sign,
    "Floor": jnp.floor, "Ceil": jnp.ceil, "Round": jnp.round,
    "Sin": jnp.sin, "Cos": jnp.cos, "Tanh": jnp.tanh,
    "Erf": jax.scipy.special.erf, "Sigmoid": jax.nn.sigmoid,
    "Relu": jax.nn.relu, "Not": jnp.logical_not,
    "Equal": jnp.equal, "Less": jnp.less, "LessOrEqual": jnp.less_equal,
    "Greater": jnp.greater, "GreaterOrEqual": jnp.greater_equal,
    "And": jnp.logical_and, "Or": jnp.logical_or, "Xor": jnp.logical_xor,
    "Max": jnp.maximum, "Min": jnp.minimum,
    "IsNaN": jnp.isnan, "IsInf": jnp.isinf,
    "Identity": lambda x: x, "Softplus": jax.nn.softplus,
    "Where": jnp.where, "MatMul": jnp.matmul,
}
for _name, _fn in _SIMPLE.items():
    def _mk(_fn):
        def h(node, ins):
            return _fn(*ins)
        return h
    _OP_HANDLERS[_name] = _mk(_fn)


@op_handler("Mod")
def _mod(node, ins):
    return jnp.fmod(*ins) if _a(node, "fmod", 0) else jnp.mod(*ins)


@op_handler("LeakyRelu")
def _leaky(node, ins):
    return jax.nn.leaky_relu(ins[0], _a(node, "alpha", 0.01))


@op_handler("Elu")
def _elu(node, ins):
    return jax.nn.elu(ins[0], _a(node, "alpha", 1.0))


@op_handler("Gelu")
def _gelu(node, ins):
    approx = _a(node, "approximate", "none") == "tanh"
    return jax.nn.gelu(ins[0], approximate=approx)


@op_handler("HardSigmoid")
def _hard_sigmoid(node, ins):
    alpha, beta = _a(node, "alpha", 0.2), _a(node, "beta", 0.5)
    return jnp.clip(alpha * ins[0] + beta, 0.0, 1.0)


@op_handler("Clip")
def _clip(node, ins):
    lo = ins[1] if len(ins) > 1 and ins[1] is not None else _a(node, "min")
    hi = ins[2] if len(ins) > 2 and ins[2] is not None else _a(node, "max")
    return jnp.clip(ins[0], lo, hi)


@op_handler("Cast")
def _cast(node, ins):
    return ins[0].astype(pb.ONNX_TO_DTYPE[_a(node, "to")])


@op_handler("Softmax")
def _softmax(node, ins):
    return jax.nn.softmax(ins[0], axis=_a(node, "axis", -1))


@op_handler("LogSoftmax")
def _log_softmax(node, ins):
    return jax.nn.log_softmax(ins[0], axis=_a(node, "axis", -1))


# linear algebra ---------------------------------------------------------------


@op_handler("Gemm")
def _gemm(node, ins):
    a, b = ins[0], ins[1]
    if _a(node, "transA", 0):
        a = a.T
    if _a(node, "transB", 0):
        b = b.T
    out = _a(node, "alpha", 1.0) * (a @ b)
    if len(ins) > 2 and ins[2] is not None:
        out = out + _a(node, "beta", 1.0) * ins[2]
    return out


@op_handler("Einsum")
def _einsum(node, ins):
    return jnp.einsum(_a(node, "equation"), *ins)


# shape ------------------------------------------------------------------------


@op_handler("Reshape")
def _reshape(node, ins):
    shape = [int(d) for d in np.asarray(ins[1])]
    # ONNX: 0 copies the input dim, -1 infers
    in_shape = ins[0].shape
    shape = [in_shape[i] if d == 0 else d for i, d in enumerate(shape)]
    return jnp.reshape(ins[0], shape)


@op_handler("Expand")
def _expand(node, ins):
    target = [int(d) for d in np.asarray(ins[1])]
    x = ins[0]
    # bidirectional numpy-style broadcast: the result rank is
    # max(input rank, shape rank); 1s take the other side's dim
    shape = list(target)
    if len(shape) < x.ndim:
        shape = [1] * (x.ndim - len(shape)) + shape
    off = len(shape) - x.ndim
    for i in range(x.ndim):
        if shape[off + i] == 1 and x.shape[i] != 1:
            shape[off + i] = x.shape[i]
    return jnp.broadcast_to(x, shape)


@op_handler("Transpose")
def _transpose(node, ins):
    perm = _a(node, "perm")
    return jnp.transpose(ins[0], perm)


@op_handler("Concat")
def _concat(node, ins):
    return jnp.concatenate(ins, axis=_a(node, "axis", 0))


@op_handler("Flatten")
def _flatten(node, ins):
    ax = _a(node, "axis", 1)
    x = ins[0]
    lead = int(np.prod(x.shape[:ax])) if ax else 1
    return x.reshape(lead, -1)


@op_handler("Unsqueeze")
def _unsqueeze(node, ins):
    axes = ([int(d) for d in np.asarray(ins[1])] if len(ins) > 1
            else _a(node, "axes"))
    x = ins[0]
    for ax in sorted(axes):
        x = jnp.expand_dims(x, ax)
    return x


@op_handler("Squeeze")
def _squeeze(node, ins):
    axes = ([int(d) for d in np.asarray(ins[1])] if len(ins) > 1
            else _a(node, "axes"))
    return jnp.squeeze(ins[0], axis=tuple(axes) if axes else None)


@op_handler("Slice")
def _slice(node, ins):
    x = ins[0]
    starts = [int(v) for v in np.asarray(ins[1])]
    ends = [int(v) for v in np.asarray(ins[2])]
    axes = ([int(v) for v in np.asarray(ins[3])] if len(ins) > 3
            else list(range(len(starts))))
    steps = ([int(v) for v in np.asarray(ins[4])] if len(ins) > 4
             else [1] * len(starts))
    slices = [slice(None)] * x.ndim
    for s, e, ax, st in zip(starts, ends, axes, steps):
        # ONNX uses INT64_MAX-ish sentinels for "to the end"
        dim = x.shape[ax]
        if e > dim:
            e = dim
        if e < -dim - 1:
            e = None if st < 0 else -dim - 1
        slices[ax] = slice(s, e, st)
    return x[tuple(slices)]


@op_handler("Pad")
def _pad(node, ins):
    pads = [int(v) for v in np.asarray(ins[1])]
    rank = ins[0].ndim
    width = [(pads[i], pads[i + rank]) for i in range(rank)]
    cv = float(np.asarray(ins[2]).reshape(())) if len(ins) > 2 and ins[2] is not None else 0.0
    mode = _a(node, "mode", "constant")
    if mode == "constant":
        return jnp.pad(ins[0], width, constant_values=cv)
    return jnp.pad(ins[0], width, mode={"reflect": "reflect", "edge": "edge"}[mode])


@op_handler("Gather")
def _gather(node, ins):
    return jnp.take(ins[0], ins[1].astype(jnp.int32), axis=_a(node, "axis", 0))


@op_handler("Shape")
def _shape(node, ins):
    return jnp.asarray(ins[0].shape, jnp.int64)


@op_handler("Constant")
def _constant(node, ins):
    t = node.attr("value")
    return jnp.asarray(pb.tensor_to_numpy(t))


@op_handler("ConstantOfShape")
def _constant_of_shape(node, ins):
    shape = [int(d) for d in np.asarray(ins[0])]
    t = node.attr("value")
    fill = pb.tensor_to_numpy(t).reshape(()) if t is not None else np.float32(0)
    return jnp.full(shape, fill, dtype=fill.dtype)


@op_handler("Range")
def _range(node, ins):
    start, limit, delta = (np.asarray(v).reshape(()) for v in ins)
    return jnp.arange(start, limit, delta)


@op_handler("Split")
def _split(node, ins):
    axis = _a(node, "axis", 0)
    if len(ins) > 1 and ins[1] is not None:
        sizes = [int(v) for v in np.asarray(ins[1])]
        idx = np.cumsum(sizes)[:-1]
        return tuple(jnp.split(ins[0], idx, axis=axis))
    # equal split: 'num_outputs' attr (opset 18+) or the output count itself
    n = _a(node, "num_outputs") or len(node.outputs)
    return tuple(jnp.split(ins[0], n, axis=axis))


@op_handler("Tile")
def _tile(node, ins):
    return jnp.tile(ins[0], [int(v) for v in np.asarray(ins[1])])


# reductions -------------------------------------------------------------------


def _reduce(fn):
    def h(node, ins):
        if len(ins) > 1 and ins[1] is not None:
            axes = tuple(int(v) for v in np.asarray(ins[1]))
        else:
            axes = node.attr("axes")
            axes = tuple(axes) if axes else None
        keep = bool(_a(node, "keepdims", 1))
        return fn(ins[0], axis=axes, keepdims=keep)
    return h


_OP_HANDLERS["ReduceSum"] = _reduce(jnp.sum)
_OP_HANDLERS["ReduceMean"] = _reduce(jnp.mean)
_OP_HANDLERS["ReduceMax"] = _reduce(jnp.max)
_OP_HANDLERS["ReduceMin"] = _reduce(jnp.min)
_OP_HANDLERS["ReduceProd"] = _reduce(jnp.prod)
_OP_HANDLERS["ReduceL2"] = _reduce(
    lambda x, axis, keepdims: jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims)))


@op_handler("ArgMax")
def _argmax(node, ins):
    out = jnp.argmax(ins[0], axis=_a(node, "axis", 0))
    if _a(node, "keepdims", 1):
        out = jnp.expand_dims(out, _a(node, "axis", 0))
    return out


@op_handler("ArgMin")
def _argmin(node, ins):
    out = jnp.argmin(ins[0], axis=_a(node, "axis", 0))
    if _a(node, "keepdims", 1):
        out = jnp.expand_dims(out, _a(node, "axis", 0))
    return out


@op_handler("CumSum")
def _cumsum(node, ins):
    ax = int(np.asarray(ins[1]).reshape(()))
    x = ins[0]
    if _a(node, "reverse", 0):
        x = jnp.flip(x, ax)
    out = jnp.cumsum(x, axis=ax)
    if _a(node, "reverse", 0):
        out = jnp.flip(out, ax)
    return out


@op_handler("TopK")
def _topk(node, ins):
    k = int(np.asarray(ins[1]).reshape(()))
    axis = _a(node, "axis", -1)
    largest = _a(node, "largest", 1)
    x = ins[0] if largest else -ins[0]
    x_moved = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(x_moved, k)
    vals = jnp.moveaxis(vals if largest else -vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


# NN ---------------------------------------------------------------------------


@op_handler("Conv")
def _conv(node, ins):
    x, w = ins[0], ins[1]
    strides = _a(node, "strides") or [1] * (x.ndim - 2)
    dilations = _a(node, "dilations") or [1] * (x.ndim - 2)
    pads = _a(node, "pads") or [0] * (2 * (x.ndim - 2))
    nd = x.ndim - 2
    padding = [(pads[i], pads[i + nd]) for i in range(nd)]
    groups = _a(node, "group", 1)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW") if nd == 2 else None)
    if len(ins) > 2 and ins[2] is not None:
        bias = ins[2].reshape((1, -1) + (1,) * nd)
        out = out + bias
    return out


def _pool(node, ins, reducer, init):
    x = ins[0]
    nd = x.ndim - 2
    kernel = _a(node, "kernel_shape")
    strides = _a(node, "strides") or [1] * nd  # ONNX spec default: stride 1
    pads = _a(node, "pads") or [0] * (2 * nd)
    window = (1, 1) + tuple(kernel)
    strd = (1, 1) + tuple(strides)
    padding = [(0, 0), (0, 0)] + [(pads[i], pads[i + nd]) for i in range(nd)]
    return jax.lax.reduce_window(x, init, reducer, window, strd, padding)


@op_handler("MaxPool")
def _maxpool(node, ins):
    return _pool(node, ins, jax.lax.max, -jnp.inf)


@op_handler("AveragePool")
def _avgpool(node, ins):
    kernel = _a(node, "kernel_shape")
    s = _pool(node, ins, jax.lax.add, 0.0)
    nd = ins[0].ndim - 2
    pads = _a(node, "pads") or [0] * (2 * nd)
    if _a(node, "count_include_pad", 0) or not any(pads):
        return s / float(np.prod(kernel))
    # spec default: divide each window by its count of non-pad elements
    ones = jnp.ones_like(ins[0])
    counts = _pool(node, [ones], jax.lax.add, 0.0)
    return s / counts


@op_handler("GlobalAveragePool")
def _gap(node, ins):
    axes = tuple(range(2, ins[0].ndim))
    return jnp.mean(ins[0], axis=axes, keepdims=True)


@op_handler("BatchNormalization")
def _bn(node, ins):
    x, scale, bias, mean, var = ins[:5]
    eps = _a(node, "epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = jax.lax.rsqrt(var.reshape(shape) + eps)
    return (x - mean.reshape(shape)) * inv * scale.reshape(shape) + bias.reshape(shape)


@op_handler("LayerNormalization")
def _ln(node, ins):
    x = ins[0]
    axis = _a(node, "axis", -1)
    eps = _a(node, "epsilon", 1e-5)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if len(ins) > 1 and ins[1] is not None:
        out = out * ins[1]
    if len(ins) > 2 and ins[2] is not None:
        out = out + ins[2]
    return out


@op_handler("Dropout")
def _dropout(node, ins):
    return ins[0]  # inference


# interpreter ------------------------------------------------------------------

# (op_type, input position) pairs whose operand is structural (shape, axes,
# pads, k, ...) and must stay concrete for jittability.
_STATIC_ARGS: dict[str, tuple[int, ...]] = {
    "Reshape": (1,), "Expand": (1,), "Unsqueeze": (1,), "Squeeze": (1,),
    "Slice": (1, 2, 3, 4), "Pad": (1, 2), "Tile": (1,), "CumSum": (1,),
    "TopK": (1,), "Split": (1,), "ConstantOfShape": (0,), "Range": (0, 1, 2),
    "ReduceSum": (1,), "ReduceMean": (1,), "ReduceMax": (1,),
    "ReduceMin": (1,), "ReduceProd": (1,), "ReduceL2": (1,),
}


def import_model(model: pb.ModelProto | bytes):
    """Build ``(fn, params)`` from an ONNX model.

    ``fn(params, **inputs)`` (inputs keyed by graph input names; positional
    also accepted in graph order) runs the graph.  ``params`` maps initializer
    names to jnp arrays.
    """
    if isinstance(model, (bytes, bytearray)):
        model = pb.ModelProto.decode(bytes(model))
    graph = model.graph
    params = {t.name: jnp.asarray(pb.tensor_to_numpy(t))
              for t in graph.initializers}
    # shape/axes operands must stay static (concrete) so the interpreted
    # function remains jittable even when params arrive as tracers
    static_vals = {t.name: pb.tensor_to_numpy(t) for t in graph.initializers}
    for node in graph.nodes:
        if node.op_type == "Constant" and node.outputs:
            static_vals[node.outputs[0]] = pb.tensor_to_numpy(node.attr("value"))
    input_names = [vi.name for vi in graph.inputs if vi.name not in params]
    output_names = [vi.name for vi in graph.outputs]

    def fn(params: dict, *pos, **inputs) -> Any:
        env: dict[str, Any] = dict(params)
        for name, val in zip(input_names, pos):
            env[name] = jnp.asarray(val)
        for name, val in inputs.items():
            env[name] = jnp.asarray(val)
        missing = [n for n in input_names if n not in env]
        if missing:
            raise ValueError(f"missing graph inputs: {missing}")
        for node in graph.nodes:
            h = _OP_HANDLERS.get(node.op_type)
            if h is None:
                raise NotImplementedError(
                    f"ONNX import: unsupported op '{node.op_type}'")
            static_pos = _STATIC_ARGS.get(node.op_type, ())
            ins = [
                (static_vals[name] if i in static_pos and name in static_vals
                 else env[name]) if name else None
                for i, name in enumerate(node.inputs)
            ]
            out = h(node, ins)
            if not isinstance(out, tuple):
                out = (out,)
            for name, val in zip(node.outputs, out):
                if name:
                    env[name] = val
        outs = [env[n] for n in output_names]
        return outs[0] if len(outs) == 1 else tuple(outs)

    return fn, params


def load_model(path: str):
    with open(path, "rb") as f:
        data = f.read()
    return import_model(data)
