"""hetu_tpu.interop — ONNX model interchange.

Covers the reference's ONNX subsystem (python/hetu/onnx/hetu2onnx.py,
onnx2hetu.py + per-op opset handlers, SURVEY §2.3): export traces a model /
function to a jaxpr and emits an ONNX ModelProto; import parses a ModelProto
and rebuilds a jax-callable.  The protobuf wire format is implemented
self-contained in ``onnx_pb`` (no ``onnx`` package dependency).
"""

from hetu_tpu.interop.onnx_pb import (  # noqa: F401
    AttributeProto,
    GraphProto,
    ModelProto,
    NodeProto,
    TensorProto,
    ValueInfoProto,
)
from hetu_tpu.interop.onnx_export import export_fn, export_module, save_model  # noqa: F401
from hetu_tpu.interop.onnx_import import import_model, load_model  # noqa: F401
