"""Export hetu_tpu models/functions to ONNX.

Counterpart of the reference's ``hetu2onnx`` (python/hetu/onnx/hetu2onnx.py +
per-op handlers in onnx/onnx_opset/).  Where the reference walks its
define-then-run Op DAG, here the model is traced to a **jaxpr** (the graph XLA
itself consumes) and each jax primitive is lowered to ONNX nodes.  Sub-jaxprs
(pjit, custom_jvp/vjp, remat) are inlined; equations whose inputs are all
known constants are folded eagerly so shape/iota machinery never reaches the
ONNX graph.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.core.module import Module, named_parameters
from hetu_tpu.interop import onnx_pb as pb

__all__ = ["export_fn", "export_module", "save_model"]


class _Exporter:
    def __init__(self, const_names: dict | None = None):
        self.nodes: list[pb.NodeProto] = []
        self.initializers: dict[str, np.ndarray] = {}
        self.names: dict[int, str] = {}   # id(jaxpr var) -> onnx name
        self.consts: dict[int, np.ndarray] = {}  # id(var) -> known value
        self.const_names = const_names or {}  # id(array) -> preferred name
        self.counter = itertools.count()

    # -- naming / plumbing -----------------------------------------------------

    def fresh(self, hint: str = "t") -> str:
        return f"{hint}_{next(self.counter)}"

    def emit(self, op: str, inputs: list[str], n_out: int = 1,
             hint: str | None = None, **attrs) -> list[str]:
        outs = [self.fresh(hint or op.lower()) for _ in range(n_out)]
        attributes = tuple(pb.AttributeProto.make(k, v)
                           for k, v in attrs.items() if v is not None)
        self.nodes.append(pb.NodeProto(
            op_type=op, inputs=tuple(inputs), outputs=tuple(outs),
            name=self.fresh(f"n_{op}"), attributes=attributes))
        return outs

    def const(self, arr, hint: str = "c") -> str:
        """Register a constant as an initializer, return its name."""
        arr = np.asarray(arr)
        name = self.fresh(hint)
        self.initializers[name] = arr
        return name

    def var_name(self, v) -> str:
        from jax._src.core import Literal
        if isinstance(v, Literal):
            return self.const(np.asarray(v.val), "lit")
        return self.names[id(v)]

    def var_const(self, v):
        """Concrete value of a jaxpr atom if known, else None."""
        from jax._src.core import Literal
        if isinstance(v, Literal):
            return np.asarray(v.val)
        return self.consts.get(id(v))

    # -- jaxpr walk ------------------------------------------------------------

    def run(self, jaxpr, consts, input_names: list[str]) -> list[str]:
        for v, c in zip(jaxpr.constvars, consts):
            arr = np.asarray(c)
            self.consts[id(v)] = arr
            preferred = self.const_names.get(id(c))
            if preferred is not None and preferred not in self.initializers:
                self.initializers[preferred] = arr
                self.names[id(v)] = preferred
            else:
                self.names[id(v)] = self.const(arr, "w")
        for v, name in zip(jaxpr.invars, input_names):
            self.names[id(v)] = name
        for eqn in jaxpr.eqns:
            self.eqn(eqn)
        return [self.var_name(v) for v in jaxpr.outvars]

    def _inline(self, eqn, inner):
        # inner may be a ClosedJaxpr (pjit/custom_jvp) or an open core.Jaxpr
        # (remat2 stores params['jaxpr'] unclosed)
        if hasattr(inner, "jaxpr"):
            jaxpr, consts = inner.jaxpr, inner.consts
        else:
            jaxpr, consts = inner, ()
        in_names = [self.var_name(v) for v in eqn.invars]
        sub_outs = self.run_sub(jaxpr, consts, in_names)
        for v, name in zip(eqn.outvars, sub_outs):
            self.names[id(v)] = name

    def run_sub(self, jaxpr, consts, input_names):
        saved_names = dict(self.names)
        outs = self.run(jaxpr, consts, input_names)
        # keep emitted nodes; restore outer scope names not overwritten
        self.names.update(saved_names)
        return outs

    def eqn(self, eqn) -> None:
        prim = eqn.primitive.name

        # inline wrappers
        if prim in ("pjit", "jit", "closed_call", "core_call", "remat",
                    "remat2", "checkpoint", "custom_vjp_call_jaxpr",
                    "xla_call"):
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            self._inline(eqn, inner)
            return
        if prim in ("custom_jvp_call", "custom_vjp_call"):
            inner = eqn.params.get("call_jaxpr")
            self._inline(eqn, inner)
            return

        # constant folding: every input known -> evaluate eagerly
        in_consts = [self.var_const(v) for v in eqn.invars]
        if all(c is not None for c in in_consts):
            outs = eqn.primitive.bind(
                *[jnp.asarray(c) for c in in_consts], **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
            for v, o in zip(eqn.outvars, outs):
                o = np.asarray(o)
                self.consts[id(v)] = o
                self.names[id(v)] = self.const(o, "fold")
            return

        handler = _HANDLERS.get(prim)
        if handler is None:
            raise NotImplementedError(
                f"ONNX export: unsupported primitive '{prim}'")
        ins = [self.var_name(v) for v in eqn.invars]
        outs = handler(self, eqn, ins)
        if isinstance(outs, str):
            outs = [outs]
        for v, name in zip(eqn.outvars, outs):
            self.names[id(v)] = name


# --- primitive handlers -------------------------------------------------------

_HANDLERS: dict[str, Callable] = {}


def handler(*prims):
    def deco(fn):
        for p in prims:
            _HANDLERS[p] = fn
        return fn
    return deco


_UNARY = {
    "neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
    "logistic": "Sigmoid", "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil", "round": "Round", "sin": "Sin",
    "cos": "Cos", "erf": "Erf", "not": "Not",
}
for _prim, _op in _UNARY.items():
    def _make(_op):
        def h(ex, eqn, ins):
            return ex.emit(_op, ins)
        return h
    _HANDLERS[_prim] = _make(_op)

_BINARY = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "and": "And", "or": "Or",
    "xor": "Xor",
}
for _prim, _op in _BINARY.items():
    def _make2(_op):
        def h(ex, eqn, ins):
            return ex.emit(_op, ins)
        return h
    _HANDLERS[_prim] = _make2(_op)


_CMP = {"eq": ("Equal", False), "ne": ("Equal", True),
        "lt": ("Less", False), "le": ("LessOrEqual", False),
        "gt": ("Greater", False), "ge": ("GreaterOrEqual", False)}
for _prim, (_op, _negate) in _CMP.items():
    def _makec(_op, _negate):
        def h(ex, eqn, ins):
            out = ex.emit(_op, ins)
            if _negate:
                out = ex.emit("Not", out)
            return out
        return h
    _HANDLERS[_prim] = _makec(_op, _negate)


@handler("rsqrt")
def _rsqrt(ex, eqn, ins):
    s = ex.emit("Sqrt", ins)
    return ex.emit("Reciprocal", s)


@handler("rem")
def _rem(ex, eqn, ins):
    # lax.rem takes the dividend's sign => ONNX Mod with fmod=1
    return ex.emit("Mod", ins, fmod=1)


@handler("is_finite")
def _is_finite(ex, eqn, ins):
    inf = ex.emit("IsInf", ins)
    nan = ex.emit("IsNaN", ins)
    bad = ex.emit("Or", [inf[0], nan[0]])
    return ex.emit("Not", bad)


@handler("integer_pow")
def _integer_pow(ex, eqn, ins):
    y = eqn.params["y"]
    dt = np.dtype(eqn.invars[0].aval.dtype)
    p = ex.const(np.asarray(y, dt if dt.kind == "f" else np.int64), "pow")
    return ex.emit("Pow", [ins[0], p])


@handler("stop_gradient")
def _stopgrad(ex, eqn, ins):
    return ex.emit("Identity", ins)


@handler("copy")
def _copy(ex, eqn, ins):
    return ex.emit("Identity", ins)


@handler("convert_element_type")
def _cast(ex, eqn, ins):
    to = pb.DTYPE_TO_ONNX[np.dtype(eqn.params["new_dtype"])]
    return ex.emit("Cast", ins, to=int(to))


@handler("select_n")
def _select(ex, eqn, ins):
    if len(ins) != 3:
        raise NotImplementedError("select_n with >2 cases")
    # select_n(pred, on_false, on_true); ONNX Where(cond, X, Y) -> X if cond
    return ex.emit("Where", [ins[0], ins[2], ins[1]])


@handler("reshape")
def _reshape(ex, eqn, ins):
    shape = ex.const(np.asarray(eqn.params["new_sizes"], np.int64), "shape")
    return ex.emit("Reshape", [ins[0], shape])


@handler("squeeze")
def _squeeze(ex, eqn, ins):
    shape = ex.const(np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
    return ex.emit("Reshape", [ins[0], shape])


@handler("expand_dims")
def _expand_dims(ex, eqn, ins):
    shape = ex.const(np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
    return ex.emit("Reshape", [ins[0], shape])


@handler("transpose")
def _transpose(ex, eqn, ins):
    return ex.emit("Transpose", ins, perm=list(eqn.params["permutation"]))


@handler("broadcast_in_dim")
def _broadcast(ex, eqn, ins):
    out_shape = eqn.params["shape"]
    bdims = eqn.params["broadcast_dimensions"]
    mid = [1] * len(out_shape)
    for src_axis, dst_axis in enumerate(bdims):
        mid[dst_axis] = eqn.invars[0].aval.shape[src_axis]
    x = ins[0]
    if tuple(mid) != tuple(eqn.invars[0].aval.shape):
        shape = ex.const(np.asarray(mid, np.int64), "shape")
        x = ex.emit("Reshape", [x, shape])[0]
    if tuple(mid) != tuple(out_shape):
        target = ex.const(np.asarray(out_shape, np.int64), "shape")
        x = ex.emit("Expand", [x, target])[0]
    else:
        x = ex.emit("Identity", [x])[0]
    return [x]


@handler("concatenate")
def _concat(ex, eqn, ins):
    return ex.emit("Concat", ins, axis=int(eqn.params["dimension"]))


@handler("slice")
def _slice(ex, eqn, ins):
    starts = ex.const(np.asarray(eqn.params["start_indices"], np.int64), "st")
    ends = ex.const(np.asarray(eqn.params["limit_indices"], np.int64), "en")
    axes = ex.const(np.arange(len(eqn.params["start_indices"]), dtype=np.int64), "ax")
    strides = eqn.params["strides"] or [1] * len(eqn.params["start_indices"])
    steps = ex.const(np.asarray(strides, np.int64), "sp")
    return ex.emit("Slice", [ins[0], starts, ends, axes, steps])


@handler("rev")
def _rev(ex, eqn, ins):
    dims = eqn.params["dimensions"]
    shape = eqn.invars[0].aval.shape
    starts = ex.const(np.asarray([shape[d] - 1 for d in dims], np.int64), "st")
    ends = ex.const(np.asarray([-(shape[d] + 1) for d in dims], np.int64), "en")
    axes = ex.const(np.asarray(list(dims), np.int64), "ax")
    steps = ex.const(np.asarray([-1] * len(dims), np.int64), "sp")
    return ex.emit("Slice", [ins[0], starts, ends, axes, steps])


@handler("pad")
def _pad(ex, eqn, ins):
    cfg = eqn.params["padding_config"]
    if any(i != 0 for _, _, i in cfg):
        raise NotImplementedError("interior padding not supported in ONNX export")
    if any(l < 0 or h < 0 for l, h, _ in cfg):
        raise NotImplementedError("negative padding not supported in ONNX export")
    pads = [l for l, _, _ in cfg] + [h for _, h, _ in cfg]
    pads_c = ex.const(np.asarray(pads, np.int64), "pads")
    return ex.emit("Pad", [ins[0], pads_c, ins[1]], mode="constant")


@handler("iota")
def _iota(ex, eqn, ins):
    # no dynamic inputs -> materialize
    arr = np.asarray(jax.lax.iota(eqn.params["dtype"], eqn.params["shape"][eqn.params["dimension"]]))
    shape = eqn.params["shape"]
    dim = eqn.params["dimension"]
    view = [1] * len(shape)
    view[dim] = shape[dim]
    arr = np.broadcast_to(arr.reshape(view), shape)
    return [ex.const(arr, "iota")]


def _reduce(op_type, axes_as_input):
    def h(ex, eqn, ins):
        axes = [int(a) for a in eqn.params["axes"]]
        if axes_as_input:
            ax = ex.const(np.asarray(axes, np.int64), "axes")
            return ex.emit(op_type, [ins[0], ax], keepdims=0)
        return ex.emit(op_type, ins, axes=axes, keepdims=0)
    return h


@handler("split")
def _split(ex, eqn, ins):
    sizes = list(eqn.params["sizes"])
    axis = eqn.params["axis"]
    split_in = ex.const(np.asarray(sizes, np.int64), "sizes")
    return ex.emit("Split", [ins[0], split_in], n_out=len(sizes),
                   hint="split", axis=axis)


_SCAN_UNROLL_LIMIT = 256


@handler("scan")
def _scan(ex, eqn, ins):
    """lax.scan exported by unrolling (static length): per step, Gather the
    xs slice, inline the body jaxpr, chain the carry, and Concat the
    stacked ys.  Covers the RNN/LSTM/GRU recurrences and scan-over-layers
    stacks; bounded by _SCAN_UNROLL_LIMIT to keep graphs sane."""
    p = eqn.params
    body = p["jaxpr"]  # ClosedJaxpr: (consts, carry, x_t) -> (carry, y_t)
    n_const, n_carry = p["num_consts"], p["num_carry"]
    length, reverse = p["length"], p["reverse"]
    if length > _SCAN_UNROLL_LIMIT:
        raise NotImplementedError(
            f"ONNX export: scan of length {length} exceeds the unroll limit "
            f"({_SCAN_UNROLL_LIMIT})")
    if length == 0:
        raise NotImplementedError(
            "ONNX export: zero-length scan has no representable ys")
    const_names = ins[:n_const]
    carry = list(ins[n_const:n_const + n_carry])
    xs = ins[n_const + n_carry:]
    n_y = len(eqn.outvars) - n_carry
    ys_steps: list[list[str]] = [[] for _ in range(n_y)]
    steps = range(length - 1, -1, -1) if reverse else range(length)
    axes0 = ex.const(np.asarray([0], np.int64), "ax0")
    for t in steps:
        idx = ex.const(np.asarray(t, np.int64), "t")
        # scalar-index Gather on axis 0 drops the time axis, matching the
        # body's per-step slice
        x_slices = [ex.emit("Gather", [xn, idx], hint="xslice", axis=0)[0]
                    for xn in xs]
        outs = ex.run_sub(body.jaxpr, body.consts,
                          const_names + carry + x_slices)
        carry = list(outs[:n_carry])
        for i, yn in enumerate(outs[n_carry:]):
            ys_steps[i].append(
                ex.emit("Unsqueeze", [yn, axes0], hint="ystep")[0])
    ys = []
    for names in ys_steps:
        if reverse:
            names = list(reversed(names))  # ys align with xs order
        ys.append(names[0] if length == 1
                  else ex.emit("Concat", names, hint="ys", axis=0)[0])
    return carry + ys


_HANDLERS["reduce_sum"] = _reduce("ReduceSum", True)     # opset 13: axes input
_HANDLERS["reduce_max"] = _reduce("ReduceMax", False)
_HANDLERS["reduce_min"] = _reduce("ReduceMin", False)
_HANDLERS["reduce_prod"] = _reduce("ReduceProd", False)


@handler("reduce_and")
def _reduce_and(ex, eqn, ins):
    cast = ex.emit("Cast", ins, to=int(pb.INT32))
    ax = [int(a) for a in eqn.params["axes"]]
    red = ex.emit("ReduceMin", cast, axes=ax, keepdims=0)
    return ex.emit("Cast", red, to=int(pb.BOOL))


@handler("reduce_or")
def _reduce_or(ex, eqn, ins):
    cast = ex.emit("Cast", ins, to=int(pb.INT32))
    ax = [int(a) for a in eqn.params["axes"]]
    red = ex.emit("ReduceMax", cast, axes=ax, keepdims=0)
    return ex.emit("Cast", red, to=int(pb.BOOL))


@handler("argmax")
def _argmax(ex, eqn, ins):
    out = ex.emit("ArgMax", ins, axis=int(eqn.params["axes"][0]), keepdims=0)
    to = pb.DTYPE_TO_ONNX[np.dtype(eqn.params["index_dtype"])]
    return ex.emit("Cast", out, to=int(to))


@handler("argmin")
def _argmin(ex, eqn, ins):
    out = ex.emit("ArgMin", ins, axis=int(eqn.params["axes"][0]), keepdims=0)
    to = pb.DTYPE_TO_ONNX[np.dtype(eqn.params["index_dtype"])]
    return ex.emit("Cast", out, to=int(to))


@handler("cumsum")
def _cumsum(ex, eqn, ins):
    ax = ex.const(np.asarray(eqn.params["axis"], np.int64), "axis")
    reverse = 1 if eqn.params.get("reverse") else 0
    return ex.emit("CumSum", [ins[0], ax], reverse=reverse)


@handler("dot_general")
def _dot_general(ex, eqn, ins):
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    lr, rr = len(lhs.shape), len(rhs.shape)
    # standard matmul pattern: batch dims leading and aligned on BOTH sides,
    # exactly one free dim each, contracting lhs last with rhs second-to-last
    # — anything else (e.g. rank-3 rhs with no batch dims) must go through
    # Einsum, since ONNX MatMul would broadcast the extra dims differently.
    std = (list(lb) == list(range(len(lb)))
           and list(rb) == list(range(len(rb)))
           and lr - len(lb) == 2 and rr - len(rb) == 2
           and list(lc) == [lr - 1]
           and list(rc) == [rr - 2])
    if std:
        return ex.emit("MatMul", ins)
    # general: einsum
    letters = "abcdefghijklmnopqrstuvwxyz"
    it = iter(letters)
    l_sub = [None] * lr
    r_sub = [None] * rr
    for i, j in zip(lb, rb):
        c = next(it)
        l_sub[i] = r_sub[j] = c
    for i, j in zip(lc, rc):
        c = next(it)
        l_sub[i] = r_sub[j] = c
    for i in range(lr):
        if l_sub[i] is None:
            l_sub[i] = next(it)
    for j in range(rr):
        if r_sub[j] is None:
            r_sub[j] = next(it)
    out_sub = ([l_sub[i] for i in lb]
               + [l_sub[i] for i in range(lr) if i not in lb and i not in lc]
               + [r_sub[j] for j in range(rr) if j not in rb and j not in rc])
    eq = f"{''.join(l_sub)},{''.join(r_sub)}->{''.join(out_sub)}"
    return ex.emit("Einsum", ins, equation=eq)


def _space_to_nchw(ex, x, rank):
    """NHWC->NCHW transpose node (2d: rank 4)."""
    perm = [0, rank - 1] + list(range(1, rank - 1))
    return ex.emit("Transpose", [x], perm=perm)[0]


def _nchw_to_space(ex, x, rank):
    perm = [0] + list(range(2, rank)) + [1]
    return ex.emit("Transpose", [x], perm=perm)[0]


@handler("conv_general_dilated")
def _conv(ex, eqn, ins):
    dn = eqn.params["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn
    if any(d != 1 for d in eqn.params.get("lhs_dilation", ())):
        raise NotImplementedError(
            "ONNX export: input-dilated (transposed) convolution")
    if eqn.params.get("batch_group_count", 1) != 1:
        raise NotImplementedError("ONNX export: batch_group_count > 1")
    rank = len(eqn.invars[0].aval.shape)
    nd = rank - 2
    # we emit for the layouts hetu_tpu.ops.nn uses: NHWC x HWIO -> NHWC
    # and the already-NCHW case passes through.
    x, w = ins
    if lhs_spec[1] != 1:  # feature dim not at position 1 => NHWC-style
        x = _space_to_nchw(ex, x, rank)
    # kernel: ONNX wants OIHW == (out_c, in_c, *spatial)
    # jax rhs_spec = (out_feature_dim_pos, in_feature_dim_pos, *spatial_pos)
    o_dim, i_dim = rhs_spec[0], rhs_spec[1]
    spatial_dims = [d for d in range(rank) if d not in (o_dim, i_dim)]
    perm = [o_dim, i_dim] + spatial_dims
    if perm != list(range(rank)):
        w = ex.emit("Transpose", [w], perm=perm)[0]
    pads = eqn.params["padding"]
    onnx_pads = [p[0] for p in pads] + [p[1] for p in pads]
    groups = int(eqn.params.get("feature_group_count", 1))
    out = ex.emit("Conv", [x, w],
                  strides=[int(s) for s in eqn.params["window_strides"]],
                  dilations=[int(d) for d in eqn.params["rhs_dilation"]],
                  pads=onnx_pads, group=groups)[0]
    if out_spec[1] != 1:
        out = _nchw_to_space(ex, out, rank)
    else:
        out = ex.emit("Identity", [out])[0]
    return [out]


@handler("reduce_window_max")
def _maxpool(ex, eqn, ins):
    return _pool(ex, eqn, ins, "MaxPool")


@handler("reduce_window_sum")
def _sumpool(ex, eqn, ins):
    # AveragePool(count_include_pad=1) * window_size == sum pool: padded
    # positions contribute 0 to the sum and the divisor is the full window.
    out = _pool(ex, eqn, ins, "AveragePool", count_include_pad=1)
    dims = eqn.params["window_dimensions"]
    k = float(np.prod(dims))
    dt = np.dtype(eqn.outvars[0].aval.dtype)
    c = ex.const(np.asarray(k, dt), "k")
    return ex.emit("Mul", [out[0], c])


def _pool(ex, eqn, ins, op_type, **extra):
    dims = eqn.params["window_dimensions"]
    strides = eqn.params["window_strides"]
    padding = eqn.params["padding"]
    rank = len(dims)
    # NHWC windows: (1, h, w, 1)
    if dims[0] != 1 or dims[-1] != 1:
        raise NotImplementedError("pooling over batch/channel dims")
    x = _space_to_nchw(ex, ins[0], rank)
    spatial = list(range(1, rank - 1))
    kernel = [int(dims[d]) for d in spatial]
    strd = [int(strides[d]) for d in spatial]
    pads = [int(padding[d][0]) for d in spatial] + [int(padding[d][1]) for d in spatial]
    out = ex.emit(op_type, [x], kernel_shape=kernel, strides=strd, pads=pads,
                  **extra)[0]
    return [_nchw_to_space(ex, out, rank)]


@handler("gather")
def _gather(ex, eqn, ins):
    # support the jnp.take(axis=k)/embedding-lookup pattern produced by
    # ops/embed.py: offset_dims cover all but one dim, one collapsed slice dim
    dn = eqn.params["dimension_numbers"]
    operand = eqn.invars[0].aval
    idx = eqn.invars[1].aval
    slice_sizes = eqn.params["slice_sizes"]
    if (len(dn.start_index_map) == 1 and len(dn.collapsed_slice_dims) == 1
            and dn.start_index_map == dn.collapsed_slice_dims):
        axis = dn.start_index_map[0]
        full = all(slice_sizes[d] == operand.shape[d]
                   for d in range(len(operand.shape)) if d != axis)
        if full and idx.shape and idx.shape[-1] == 1:
            sq_shape = ex.const(np.asarray(idx.shape[:-1], np.int64), "shape")
            flat_idx = ex.emit("Reshape", [ins[1], sq_shape])[0]
            return ex.emit("Gather", [ins[0], flat_idx], axis=int(axis))
    # general fallback for statically-known indices: replay the gather on a
    # flat-position iota to obtain the output->operand element map, then a
    # single flat Gather reproduces it for any operand values.
    idx_val = ex.var_const(eqn.invars[1])
    if idx_val is not None:
        positions = np.arange(int(np.prod(operand.shape)),
                              dtype=np.int64).reshape(operand.shape)
        pos_map = np.asarray(eqn.primitive.bind(
            jnp.asarray(positions), jnp.asarray(idx_val), **eqn.params))
        flat = ex.emit("Reshape", [ins[0], ex.const(np.asarray([-1], np.int64), "flat")])[0]
        return ex.emit("Gather", [flat, ex.const(pos_map, "posmap")], axis=0)
    raise NotImplementedError(
        "gather with dynamic indices outside the take/embedding pattern "
        "is not supported in ONNX export")


@handler("dynamic_slice")
def _dynamic_slice(ex, eqn, ins):
    # jax clamps each start into [0, dim-size].  Emit per-axis:
    # idx = clamp(start) + arange(size); Gather(axis) — dynamic-index Gather
    # is valid ONNX, indices stay in-bounds, and the importer handles it
    # jittably (jnp.take).  Axes taken in full are skipped.
    sizes = eqn.params["slice_sizes"]
    shape = eqn.invars[0].aval.shape
    x = ins[0]
    for axis, (size, dim, start_in) in enumerate(zip(sizes, shape, ins[1:])):
        if size == dim:
            continue
        s = ex.emit("Cast", [start_in], to=int(pb.INT64))[0]
        lo = ex.const(np.asarray(0, np.int64), "lo")
        hi = ex.const(np.asarray(dim - size, np.int64), "hi")
        s = ex.emit("Max", [s, lo])[0]
        s = ex.emit("Min", [s, hi])[0]
        idx = ex.emit("Add", [s, ex.const(np.arange(size, dtype=np.int64), "ar")])[0]
        x = ex.emit("Gather", [x, idx], axis=axis)[0]
    return [ex.emit("Identity", [x])[0]]


@handler("clamp")
def _clamp(ex, eqn, ins):
    # lax.clamp(min, x, max)
    return ex.emit("Clip", [ins[1], ins[0], ins[2]])


@handler("square")
def _square(ex, eqn, ins):
    return ex.emit("Mul", [ins[0], ins[0]])


@handler("exp2")
def _exp2(ex, eqn, ins):
    dt = np.dtype(eqn.invars[0].aval.dtype)
    two = ex.const(np.asarray(2.0, dt), "two")
    return ex.emit("Pow", [two, ins[0]])


@handler("sort")
def _sort(ex, eqn, ins):
    if len(ins) != 1:
        raise NotImplementedError("multi-operand sort")
    dim = int(eqn.params["dimension"])
    shape = eqn.invars[0].aval.shape
    k = ex.const(np.asarray([shape[dim]], np.int64), "k")
    vals, _idx = ex.emit("TopK", [ins[0], k], n_out=2, axis=dim, largest=0)
    return [vals]


# --- public API ---------------------------------------------------------------


def export_fn(fn: Callable, *example_args, name: str = "hetu_tpu",
              const_names: dict | None = None) -> pb.ModelProto:
    """Trace ``fn(*example_args)`` and convert the jaxpr to an ONNX model.

    All traced-constant arrays (closure captures) become initializers;
    positional args become graph inputs.  ``const_names`` optionally maps
    ``id(array)`` of a closure constant to the initializer name to use
    (export_module passes parameter paths this way).
    """
    flat_args, in_tree = jax.tree_util.tree_flatten(example_args)

    def flat_fn(*flat):
        args = jax.tree_util.tree_unflatten(in_tree, flat)
        out = fn(*args)
        return jax.tree_util.tree_leaves(out)

    closed = jax.make_jaxpr(flat_fn)(*flat_args)
    ex = _Exporter(const_names)
    input_names = [f"input_{i}" for i in range(len(flat_args))]
    out_names = ex.run(closed.jaxpr, closed.consts, input_names)

    inputs = tuple(
        pb.ValueInfoProto(name=n,
                          elem_type=pb.DTYPE_TO_ONNX[np.dtype(a.dtype)],
                          shape=tuple(int(d) for d in np.shape(a)))
        for n, a in zip(input_names, flat_args))
    out_avals = [v.aval for v in closed.jaxpr.outvars]
    outputs = tuple(
        pb.ValueInfoProto(name=n,
                          elem_type=pb.DTYPE_TO_ONNX[np.dtype(a.dtype)],
                          shape=tuple(int(d) for d in a.shape))
        for n, a in zip(out_names, out_avals))
    inits = tuple(pb.tensor_from_numpy(k, v) for k, v in ex.initializers.items())
    graph = pb.GraphProto(name=name, nodes=tuple(ex.nodes),
                          initializers=inits, inputs=inputs, outputs=outputs)
    return pb.ModelProto(graph=graph)


def export_module(model: Module, *example_inputs, name: str | None = None,
                  apply: Callable | None = None) -> pb.ModelProto:
    """Export a ``Module``: parameters become initializers named by their
    qualified parameter path, the example inputs become graph inputs.
    ``apply(model, *inputs)`` defaults to ``model(*inputs)``."""
    apply = apply or (lambda m, *xs: m(*xs))
    fn = lambda *xs: apply(model, *xs)  # model enters via closure -> constvars
    const_names = {id(leaf): pname for pname, leaf in named_parameters(model)}
    return export_fn(fn, *example_inputs, name=name or type(model).__name__,
                     const_names=const_names)


def save_model(proto: pb.ModelProto, path: str) -> None:
    with open(path, "wb") as f:
        f.write(proto.encode())
