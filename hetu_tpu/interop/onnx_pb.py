"""Self-contained ONNX protobuf wire codec.

The environment ships no ``onnx`` package, so this module implements the
subset of the public ONNX schema (onnx/onnx.proto, Apache-2.0) needed for
model interchange: ModelProto / GraphProto / NodeProto / AttributeProto /
TensorProto / ValueInfoProto, encoded and decoded directly at the protobuf
wire level (varints + length-delimited fields).

Reference counterpart: python/hetu/onnx/ uses the ``onnx`` python package;
here the codec itself is part of the framework so interchange works in
hermetic TPU environments.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Optional

import numpy as np

__all__ = [
    "ModelProto", "GraphProto", "NodeProto", "AttributeProto",
    "TensorProto", "ValueInfoProto", "OperatorSetId",
    "tensor_from_numpy", "tensor_to_numpy", "DTYPE_TO_ONNX", "ONNX_TO_DTYPE",
]

# --- wire-level helpers -------------------------------------------------------

_WIRE_VARINT, _WIRE_I64, _WIRE_LEN, _WIRE_I32 = 0, 1, 2, 5


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, _WIRE_LEN) + _varint(len(payload)) + payload


def _int_field(field: int, value: int) -> bytes:
    return _tag(field, _WIRE_VARINT) + _varint(value)


def _str_field(field: int, value: str) -> bytes:
    return _len_field(field, value.encode("utf-8"))


def _float_field(field: int, value: float) -> bytes:
    return _tag(field, _WIRE_I32) + struct.pack("<f", value)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed64(n: int) -> int:
    return n - (1 << 64) if n >= 1 << 63 else n


def _scan(data: bytes):
    """Yield (field_number, wire_type, value) triples from a message body."""
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == _WIRE_VARINT:
            value, pos = _read_varint(data, pos)
        elif wire == _WIRE_I64:
            value = data[pos:pos + 8]
            pos += 8
        elif wire == _WIRE_LEN:
            ln, pos = _read_varint(data, pos)
            value = data[pos:pos + ln]
            pos += ln
        elif wire == _WIRE_I32:
            value = data[pos:pos + 4]
            pos += 4
        else:  # pragma: no cover - malformed input
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def _packed_int64s(payload: bytes) -> list[int]:
    out, pos = [], 0
    while pos < len(payload):
        v, pos = _read_varint(payload, pos)
        out.append(_signed64(v))
    return out


def _repeated_int64(field: int, values) -> bytes:
    # packed encoding (proto3 default for repeated scalars)
    payload = b"".join(_varint(v) for v in values)
    return _len_field(field, payload) if values else b""


# --- ONNX dtype table ---------------------------------------------------------

# TensorProto.DataType enum values from the public ONNX schema.
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

DTYPE_TO_ONNX = {
    np.dtype(np.float32): FLOAT,
    np.dtype(np.float64): DOUBLE,
    np.dtype(np.float16): FLOAT16,
    np.dtype(np.int8): INT8,
    np.dtype(np.uint8): UINT8,
    np.dtype(np.int16): INT16,
    np.dtype(np.uint16): UINT16,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.uint32): UINT32,
    np.dtype(np.uint64): UINT64,
    np.dtype(np.bool_): BOOL,
}
ONNX_TO_DTYPE = {v: k for k, v in DTYPE_TO_ONNX.items()}
# bfloat16 has no numpy builtin; ml_dtypes ships with jax.
try:  # pragma: no cover - always present alongside jax
    import ml_dtypes

    DTYPE_TO_ONNX[np.dtype(ml_dtypes.bfloat16)] = BFLOAT16
    ONNX_TO_DTYPE[BFLOAT16] = np.dtype(ml_dtypes.bfloat16)
except ImportError:
    pass


# --- message dataclasses ------------------------------------------------------


@dataclasses.dataclass
class TensorProto:
    name: str = ""
    dims: tuple = ()
    data_type: int = FLOAT
    raw_data: bytes = b""

    def encode(self) -> bytes:
        out = _repeated_int64(1, list(self.dims))
        out += _int_field(2, self.data_type)
        if self.name:
            out += _str_field(8, self.name)
        out += _len_field(9, self.raw_data)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "TensorProto":
        t = cls()
        dims: list[int] = []
        int64_data: list[int] = []
        float_data: list[float] = []
        int32_data: list[int] = []
        for field, wire, value in _scan(data):
            if field == 1:
                dims += _packed_int64s(value) if wire == _WIRE_LEN else [_signed64(value)]
            elif field == 2:
                t.data_type = value
            elif field == 8:
                t.name = value.decode("utf-8")
            elif field == 9:
                t.raw_data = value
            elif field == 4:  # float_data (non-raw encoders)
                if wire == _WIRE_LEN:
                    float_data += list(struct.unpack(f"<{len(value)//4}f", value))
                else:
                    float_data.append(struct.unpack("<f", value)[0])
            elif field == 5:  # int32_data
                int32_data += _packed_int64s(value) if wire == _WIRE_LEN else [_signed64(value)]
            elif field == 7:  # int64_data
                int64_data += _packed_int64s(value) if wire == _WIRE_LEN else [_signed64(value)]
        t.dims = tuple(dims)
        if not t.raw_data:
            if float_data:
                t.raw_data = np.asarray(float_data, np.float32).tobytes()
            elif int64_data:
                t.raw_data = np.asarray(int64_data, np.int64).tobytes()
            elif int32_data:
                t.raw_data = np.asarray(int32_data, np.int32).tobytes()
        return t


def tensor_from_numpy(name: str, arr: np.ndarray) -> TensorProto:
    # record the rank BEFORE ascontiguousarray: it promotes 0-d to 1-d,
    # which would silently turn scalar initializers (e.g. Gather indices
    # that must drop their axis) into 1-element vectors
    shape = tuple(np.shape(arr))
    arr = np.ascontiguousarray(arr)
    return TensorProto(name=name, dims=shape,
                       data_type=DTYPE_TO_ONNX[arr.dtype],
                       raw_data=arr.tobytes())


def tensor_to_numpy(t: TensorProto) -> np.ndarray:
    dtype = ONNX_TO_DTYPE[t.data_type]
    return np.frombuffer(t.raw_data, dtype=dtype).reshape(t.dims).copy()


# AttributeProto.AttributeType enum values.
_AT_FLOAT, _AT_INT, _AT_STRING, _AT_TENSOR = 1, 2, 3, 4
_AT_FLOATS, _AT_INTS, _AT_STRINGS = 6, 7, 8


@dataclasses.dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    floats: tuple = ()
    ints: tuple = ()
    strings: tuple = ()

    @classmethod
    def make(cls, name: str, value: Any) -> "AttributeProto":
        a = cls(name=name)
        if isinstance(value, TensorProto):
            a.type, a.t = _AT_TENSOR, value
        elif isinstance(value, bool):
            a.type, a.i = _AT_INT, int(value)
        elif isinstance(value, (int, np.integer)):
            a.type, a.i = _AT_INT, int(value)
        elif isinstance(value, (float, np.floating)):
            a.type, a.f = _AT_FLOAT, float(value)
        elif isinstance(value, str):
            a.type, a.s = _AT_STRING, value.encode("utf-8")
        elif isinstance(value, bytes):
            a.type, a.s = _AT_STRING, value
        elif isinstance(value, (list, tuple)):
            if all(isinstance(v, (int, np.integer)) for v in value):
                a.type, a.ints = _AT_INTS, tuple(int(v) for v in value)
            elif all(isinstance(v, str) for v in value):
                a.type, a.strings = _AT_STRINGS, tuple(v.encode() for v in value)
            else:
                a.type, a.floats = _AT_FLOATS, tuple(float(v) for v in value)
        else:
            raise TypeError(f"unsupported attribute value {value!r}")
        return a

    @property
    def value(self) -> Any:
        if self.type == _AT_FLOAT:
            return self.f
        if self.type == _AT_INT:
            return self.i
        if self.type == _AT_STRING:
            return self.s.decode("utf-8")
        if self.type == _AT_TENSOR:
            return self.t
        if self.type == _AT_FLOATS:
            return list(self.floats)
        if self.type == _AT_INTS:
            return list(self.ints)
        if self.type == _AT_STRINGS:
            return [s.decode("utf-8") for s in self.strings]
        return None

    def encode(self) -> bytes:
        out = _str_field(1, self.name)
        if self.type == _AT_FLOAT:
            out += _float_field(2, self.f)
        elif self.type == _AT_INT:
            out += _int_field(3, self.i)
        elif self.type == _AT_STRING:
            out += _len_field(4, self.s)
        elif self.type == _AT_TENSOR:
            out += _len_field(5, self.t.encode())
        elif self.type == _AT_FLOATS:
            out += b"".join(_tag(7, _WIRE_I32) + struct.pack("<f", v) for v in self.floats)
        elif self.type == _AT_INTS:
            out += _repeated_int64(8, list(self.ints))
        elif self.type == _AT_STRINGS:
            out += b"".join(_len_field(9, s) for s in self.strings)
        out += _int_field(20, self.type)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "AttributeProto":
        a = cls()
        ints: list[int] = []
        floats: list[float] = []
        strings: list[bytes] = []
        for field, wire, value in _scan(data):
            if field == 1:
                a.name = value.decode("utf-8")
            elif field == 2:
                a.f = struct.unpack("<f", value)[0]
            elif field == 3:
                a.i = _signed64(value)
            elif field == 4:
                a.s = value
            elif field == 5:
                a.t = TensorProto.decode(value)
            elif field == 7:
                if wire == _WIRE_LEN:
                    floats += list(struct.unpack(f"<{len(value)//4}f", value))
                else:
                    floats.append(struct.unpack("<f", value)[0])
            elif field == 8:
                ints += _packed_int64s(value) if wire == _WIRE_LEN else [_signed64(value)]
            elif field == 9:
                strings.append(value)
            elif field == 20:
                a.type = value
        a.ints, a.floats, a.strings = tuple(ints), tuple(floats), tuple(strings)
        if a.type == 0:  # infer for writers that omit the type field
            if a.t is not None:
                a.type = _AT_TENSOR
            elif ints:
                a.type = _AT_INTS
            elif floats:
                a.type = _AT_FLOATS
            elif strings:
                a.type = _AT_STRINGS
        return a


@dataclasses.dataclass
class NodeProto:
    op_type: str = ""
    inputs: tuple = ()
    outputs: tuple = ()
    name: str = ""
    attributes: tuple = ()
    domain: str = ""

    def attr(self, name: str, default=None):
        for a in self.attributes:
            if a.name == name:
                return a.value
        return default

    def encode(self) -> bytes:
        out = b"".join(_str_field(1, s) for s in self.inputs)
        out += b"".join(_str_field(2, s) for s in self.outputs)
        if self.name:
            out += _str_field(3, self.name)
        out += _str_field(4, self.op_type)
        out += b"".join(_len_field(5, a.encode()) for a in self.attributes)
        if self.domain:
            out += _str_field(7, self.domain)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "NodeProto":
        n = cls()
        inputs, outputs, attrs = [], [], []
        for field, _wire, value in _scan(data):
            if field == 1:
                inputs.append(value.decode("utf-8"))
            elif field == 2:
                outputs.append(value.decode("utf-8"))
            elif field == 3:
                n.name = value.decode("utf-8")
            elif field == 4:
                n.op_type = value.decode("utf-8")
            elif field == 5:
                attrs.append(AttributeProto.decode(value))
            elif field == 7:
                n.domain = value.decode("utf-8")
        n.inputs, n.outputs, n.attributes = tuple(inputs), tuple(outputs), tuple(attrs)
        return n


@dataclasses.dataclass
class ValueInfoProto:
    name: str = ""
    elem_type: int = FLOAT
    shape: tuple = ()  # ints or str (symbolic dim)

    def encode(self) -> bytes:
        dims = b""
        for d in self.shape:
            if isinstance(d, str):
                dim = _str_field(2, d)
            else:
                dim = _int_field(1, int(d))
            dims += _len_field(1, dim)
        tensor_type = _int_field(1, self.elem_type) + _len_field(2, dims)
        type_proto = _len_field(1, tensor_type)
        return _str_field(1, self.name) + _len_field(2, type_proto)

    @classmethod
    def decode(cls, data: bytes) -> "ValueInfoProto":
        v = cls()
        for field, _wire, value in _scan(data):
            if field == 1:
                v.name = value.decode("utf-8")
            elif field == 2:
                for f2, _w2, v2 in _scan(value):
                    if f2 != 1:  # tensor_type
                        continue
                    shape: list = []
                    for f3, _w3, v3 in _scan(v2):
                        if f3 == 1:
                            v.elem_type = v3
                        elif f3 == 2:  # TensorShapeProto
                            for f4, _w4, v4 in _scan(v3):
                                if f4 == 1:  # Dimension
                                    dim: Any = 0
                                    for f5, _w5, v5 in _scan(v4):
                                        if f5 == 1:
                                            dim = _signed64(v5)
                                        elif f5 == 2:
                                            dim = v5.decode("utf-8")
                                    shape.append(dim)
                    v.shape = tuple(shape)
        return v


@dataclasses.dataclass
class GraphProto:
    name: str = "hetu_tpu"
    nodes: tuple = ()
    initializers: tuple = ()
    inputs: tuple = ()
    outputs: tuple = ()

    def encode(self) -> bytes:
        out = b"".join(_len_field(1, n.encode()) for n in self.nodes)
        out += _str_field(2, self.name)
        out += b"".join(_len_field(5, t.encode()) for t in self.initializers)
        out += b"".join(_len_field(11, v.encode()) for v in self.inputs)
        out += b"".join(_len_field(12, v.encode()) for v in self.outputs)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "GraphProto":
        g = cls()
        nodes, inits, inputs, outputs = [], [], [], []
        for field, _wire, value in _scan(data):
            if field == 1:
                nodes.append(NodeProto.decode(value))
            elif field == 2:
                g.name = value.decode("utf-8")
            elif field == 5:
                inits.append(TensorProto.decode(value))
            elif field == 11:
                inputs.append(ValueInfoProto.decode(value))
            elif field == 12:
                outputs.append(ValueInfoProto.decode(value))
        g.nodes, g.initializers = tuple(nodes), tuple(inits)
        g.inputs, g.outputs = tuple(inputs), tuple(outputs)
        return g


@dataclasses.dataclass
class OperatorSetId:
    domain: str = ""
    version: int = 17

    def encode(self) -> bytes:
        return _str_field(1, self.domain) + _int_field(2, self.version)

    @classmethod
    def decode(cls, data: bytes) -> "OperatorSetId":
        o = cls()
        for field, _wire, value in _scan(data):
            if field == 1:
                o.domain = value.decode("utf-8")
            elif field == 2:
                o.version = _signed64(value)
        return o


@dataclasses.dataclass
class ModelProto:
    graph: GraphProto = dataclasses.field(default_factory=GraphProto)
    ir_version: int = 8
    producer_name: str = "hetu_tpu"
    opset_version: int = 17

    def encode(self) -> bytes:
        out = _int_field(1, self.ir_version)
        out += _str_field(2, self.producer_name)
        out += _len_field(7, self.graph.encode())
        out += _len_field(8, OperatorSetId(version=self.opset_version).encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ModelProto":
        m = cls()
        for field, _wire, value in _scan(data):
            if field == 1:
                m.ir_version = _signed64(value)
            elif field == 2:
                m.producer_name = value.decode("utf-8")
            elif field == 7:
                m.graph = GraphProto.decode(value)
            elif field == 8:
                opset = OperatorSetId.decode(value)
                if opset.domain in ("", "ai.onnx"):  # default domain only
                    m.opset_version = opset.version
        return m
