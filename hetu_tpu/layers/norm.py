"""Normalization and dropout layers (reference layers/normalization.py,
layers/dropout.py).

``BatchNorm2d`` is functional: in training mode ``__call__`` returns
``(y, new_layer)`` carrying updated running statistics — the TPU-native
replacement for the reference's in-place stat updates (src/ops/CudnnBn.cu).
"""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import ones, zeros
from hetu_tpu.ops import batch_norm, dropout, group_norm, instance_norm2d, layer_norm, rms_norm

__all__ = ["LayerNorm", "RMSNorm", "BatchNorm2d", "InstanceNorm2d", "GroupNorm", "Dropout"]


class LayerNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-5, dtype=jnp.float32):
        self.scale = ones(None, (dim,), dtype)
        self.scale_axes = ("embed",)
        self.bias = zeros(None, (dim,), dtype)
        self.bias_axes = ("embed",)
        self.eps = eps

    def __call__(self, x):
        return layer_norm(x, self.scale, self.bias, eps=self.eps)


class RMSNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-6, dtype=jnp.float32):
        self.scale = ones(None, (dim,), dtype)
        self.scale_axes = ("embed",)
        self.eps = eps

    def __call__(self, x):
        return rms_norm(x, self.scale, eps=self.eps)


class BatchNorm2d(Module):
    _state_fields = ("running_mean", "running_var")

    def __init__(self, channels: int, momentum: float = 0.9, eps: float = 1e-5,
                 dtype=jnp.float32):
        self.scale = ones(None, (channels,), dtype)
        self.bias = zeros(None, (channels,), dtype)
        self.running_mean = zeros(None, (channels,), dtype)
        self.running_var = ones(None, (channels,), dtype)
        self.momentum = momentum
        self.eps = eps

    def __call__(self, x, *, training: bool = False):
        y, mean, var = batch_norm(
            x, self.scale, self.bias, self.running_mean, self.running_var,
            training=training, momentum=self.momentum, eps=self.eps,
        )
        if training:
            return y, self.replace(running_mean=mean, running_var=var)
        return y, self


class InstanceNorm2d(Module):
    def __init__(self, eps: float = 1e-7):
        self.eps = eps
        self._noop = ()

    def __call__(self, x):
        return instance_norm2d(x, self.eps)


class GroupNorm(Module):
    def __init__(self, groups: int, channels: int, eps: float = 1e-5,
                 dtype=jnp.float32):
        self.scale = ones(None, (channels,), dtype)
        self.bias = zeros(None, (channels,), dtype)
        self.groups = groups
        self.eps = eps

    def __call__(self, x):
        return group_norm(x, self.scale, self.bias, groups=self.groups, eps=self.eps)


class Dropout(Module):
    def __init__(self, rate: float = 0.5):
        self.rate = rate
        self._noop = ()

    def __call__(self, x, *, key=None, training: bool = False):
        if not training or self.rate == 0.0 or key is None:
            return x
        return dropout(x, self.rate, key, training=True)
