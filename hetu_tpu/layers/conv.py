"""Convolution and pooling layers (reference layers/conv.py, pooling.py).

NHWC activations, HWIO kernels (TPU-preferred; see ops/nn.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import he_normal, zeros
from hetu_tpu.ops import avg_pool2d, conv2d, max_pool2d

__all__ = ["Conv2d", "MaxPool2d", "AvgPool2d", "Flatten"]


class Conv2d(Module):
    def __init__(self, in_channels: int, out_channels: int, kernel_size: int = 3,
                 stride: int = 1, padding="SAME", bias: bool = True,
                 groups: int = 1, initializer=None, dtype=jnp.float32):
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
        init = initializer or he_normal()
        self.w = init(next_key(), (*k, in_channels // groups, out_channels), dtype)
        self.w_axes = (None, None, "conv_in", "conv_out")
        self.b = zeros(None, (out_channels,), dtype) if bias else None
        self.b_axes = ("conv_out",)
        self.stride = stride
        self.padding = padding
        self.groups = groups

    def __call__(self, x):
        y = conv2d(x, self.w.astype(x.dtype), stride=self.stride,
                   padding=self.padding, groups=self.groups)
        if self.b is not None:
            y = y + self.b.astype(y.dtype)
        return y


class MaxPool2d(Module):
    def __init__(self, window: int = 2, stride=None, padding="VALID"):
        self.window = window
        self.stride = stride
        self.pad = padding

    def __call__(self, x):
        return max_pool2d(x, self.window, self.stride, self.pad)


class AvgPool2d(Module):
    def __init__(self, window: int = 2, stride=None, padding="VALID"):
        self.window = window
        self.stride = stride
        self.pad = padding

    def __call__(self, x):
        return avg_pool2d(x, self.window, self.stride, self.pad)


class Flatten(Module):
    def __init__(self):
        self._noop = ()

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)
