"""Transformer blocks (the building material for BERT/GPT/MoE models —
reference examples/nlp/bert/hetu_bert.py layer structure, re-designed
TPU-first: pre/post-LN options, bf16 compute with fp32 norms, logical axes
for Megatron TP).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import normal, zeros
from hetu_tpu.layers.attention import MultiHeadAttention
from hetu_tpu.layers.norm import LayerNorm
from hetu_tpu.ops import dropout as dropout_op
from hetu_tpu.ops import gelu

__all__ = ["TransformerMLP", "TransformerBlock"]


class TransformerMLP(Module):
    """2-layer gelu MLP; weights annotated ('embed','mlp')/('mlp','embed')
    for Megatron column→row parallel placement."""

    def __init__(self, dim: int, hidden: int, *, dtype=jnp.float32,
                 init_std: float = 0.02):
        init = normal(stddev=init_std)
        self.w_in = init(next_key(), (dim, hidden), dtype)
        self.w_in_axes = ("embed", "mlp")
        self.b_in = zeros(None, (hidden,), dtype)
        self.b_in_axes = ("mlp",)
        self.w_out = init(next_key(), (hidden, dim), dtype)
        self.w_out_axes = ("mlp", "embed")
        self.b_out = zeros(None, (dim,), dtype)

    def __call__(self, x):
        h = gelu(x @ self.w_in.astype(x.dtype) + self.b_in.astype(x.dtype))
        return h @ self.w_out.astype(x.dtype) + self.b_out.astype(x.dtype)


class TransformerBlock(Module):
    """Attention + MLP with residuals.  ``post_ln=True`` gives the original
    BERT ordering (reference hetu_bert.py); default pre-LN trains stably at
    scale.

    ``mlp`` swaps the FFN for any module with signature
    ``(x, *, training) -> y`` or ``-> (y, aux)`` — an aux-returning FFN
    (e.g. a MoE layer with its load-balancing loss, layers/moe.py MoELayer)
    makes the block return ``(x, aux)`` instead of ``x``.
    """

    def __init__(self, dim: int, num_heads: int, mlp_ratio: int = 4, *,
                 causal: bool = False, post_ln: bool = False,
                 dropout_rate: float = 0.0, attn_fn=None, mlp=None,
                 fused_ln: bool = False, dtype=jnp.float32):
        self.ln1 = LayerNorm(dim)
        self.attn = MultiHeadAttention(
            dim, num_heads, causal=causal, dropout_rate=dropout_rate,
            attn_fn=attn_fn, dtype=dtype,
        )
        self.ln2 = LayerNorm(dim)
        self.mlp = mlp if mlp is not None else TransformerMLP(
            dim, mlp_ratio * dim, dtype=dtype)
        # detect from the signature whether the FFN accepts training=
        # (MoELayer does; a plain (x)->y FFN like TransformerMLP does not)
        import inspect
        try:
            params = inspect.signature(self.mlp.__call__).parameters
            self._mlp_takes_training = "training" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        except (TypeError, ValueError):
            self._mlp_takes_training = False
        self.post_ln = post_ln
        self.dropout_rate = dropout_rate
        # Pallas fused residual+dropout+LayerNorm for the post-LN sites
        # (ops/pallas/fused_ln.py: one HBM pass per direction instead of
        # XLA's separate stat/normalize/backward-reduction passes).
        if fused_ln and not post_ln:
            raise ValueError(
                "fused_ln fuses the POST-LN residual+dropout+ln(x+y) "
                "sites; a pre-LN block normalizes the sublayer input "
                "(plain LN) and has nothing to fuse — drop the flag or "
                "set post_ln=True")
        self.fused_ln = fused_ln

    def _ffn(self, x, training):
        out = (self.mlp(x, training=training) if self._mlp_takes_training
               else self.mlp(x))
        return out if isinstance(out, tuple) else (out, None)

    def __call__(self, x, mask=None, *, key=None, training: bool = False,
                 kv_cache=None, cache_index=None, paged=None):
        if kv_cache is not None:
            return self._call_cached(x, mask, kv_cache, cache_index,
                                     paged=paged)
        ka = k1 = k2 = None
        if key is not None:
            ka, k1, k2 = jax.random.split(key, 3)
        if self.post_ln:
            if self.fused_ln:
                from hetu_tpu.ops.pallas.fused_ln import (
                    fused_residual_dropout_ln)
                rate = self.dropout_rate if training else 0.0
                a = self.attn(x, mask, key=ka, training=training)
                x = fused_residual_dropout_ln(
                    x, a, self.ln1.scale, self.ln1.bias, rate=rate,
                    key=k1, eps=self.ln1.eps)
                y, aux = self._ffn(x, training)
                x = fused_residual_dropout_ln(
                    x, y, self.ln2.scale, self.ln2.bias, rate=rate,
                    key=k2, eps=self.ln2.eps)
                return x if aux is None else (x, aux)
            x = self.ln1(x + self._drop(self.attn(x, mask, key=ka, training=training), k1, training))
            y, aux = self._ffn(x, training)
            x = self.ln2(x + self._drop(y, k2, training))
        else:
            x = x + self._drop(self.attn(self.ln1(x), mask, key=ka, training=training), k1, training)
            y, aux = self._ffn(self.ln2(x), training)
            x = x + self._drop(y, k2, training)
        return x if aux is None else (x, aux)

    def _call_cached(self, x, mask, kv_cache, cache_index, paged=None):
        """Incremental-decode step: same residual wiring as the training
        paths, attention routed through the KV cache (inference-only — no
        dropout, no fused post-LN kernel, no MoE aux loss).  Returns
        ``(x, (k_cache, v_cache))`` with this block's caches updated.
        With ``paged`` (layers.attention.PagedDecode), the caches are the
        paged pools and attention runs the in-place Pallas kernel."""
        if self.post_ln:
            a, kv = self.attn(x, mask, kv_cache=kv_cache,
                              cache_index=cache_index, paged=paged)
            x = self.ln1(x + a)
            y, aux = self._ffn(x, training=False)
            x = self.ln2(x + y)
        else:
            a, kv = self.attn(self.ln1(x), mask, kv_cache=kv_cache,
                              cache_index=cache_index, paged=paged)
            x = x + a
            y, aux = self._ffn(self.ln2(x), training=False)
            x = x + y
        if aux is not None:
            raise NotImplementedError(
                "aux-returning FFNs (MoE) have no incremental-decode path "
                "yet — serve dense blocks or drop the kv_cache")
        return x, kv

    def _drop(self, x, key, training):
        if training and self.dropout_rate > 0.0 and key is not None:
            return dropout_op(x, self.dropout_rate, key, training=True)
        return x
