"""Multi-head attention.

Reference: python/hetu/layers/attention.py:5 (an OpLayer composing matmul/
softmax ops; materialized QK^T).  TPU-native design: einsum formulation with
head axes annotated for tensor parallelism ('heads' logical axis → 'tp' mesh
axis under the Megatron preset), fp32 softmax statistics, and a pluggable
attention core so the Pallas flash-attention kernel (ops/pallas/flash.py) or
ring attention (parallel/ring_attention.py) can replace the reference
materialized path.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import xavier_uniform, zeros
from hetu_tpu.ops import dropout as dropout_op

__all__ = ["MultiHeadAttention", "PagedDecode", "dot_product_attention",
           "dot_product_attention_bhsd", "decode_attention",
           "ragged_cache_update", "paged_write_slots"]


class PagedDecode(NamedTuple):
    """Routing record for the paged decode path: with this passed,
    ``decode_attention``'s ``k_cache``/``v_cache`` are the PAGED pools
    (``(pages, page_size, H, D)``, or the stacked ``(layers, ...)`` form
    with ``layer`` set) and attention runs the Pallas paged-decode kernel
    (ops/pallas/paged_decode.py) — K/V pages are read in place, no
    contiguous per-sequence view is ever materialized."""

    tables: object                   # (batch, pages_per_seq) int32
    layer: Optional[int] = None      # static layer into a stacked pool
    interpret: Optional[bool] = None


def _dpa_core(q, k, v, mask, scale, causal, qk_spec: str, pv_spec: str):
    """One materialized-attention body for both layouts (the einsum specs
    carry the layout): fp32 softmax statistics, -1e30 mask fill."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    logits = jnp.einsum(qk_spec, q, k).astype(jnp.float32) * scale
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        logits = jnp.where(cmask, logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum(pv_spec, probs, v)


def dot_product_attention(q, k, v, mask=None, *, scale: float | None = None,
                          causal: bool = False):
    """Reference attention core: softmax(QK^T/sqrt(d))V, fp32 statistics.

    q,k,v: (batch, seq, heads, head_dim).  mask: broadcastable to
    (batch, heads, q_seq, kv_seq), True/1 = attend.
    """
    return _dpa_core(q, k, v, mask, scale, causal,
                     "bqhd,bkhd->bhqk", "bhqk,bkhd->bqhd")


def dot_product_attention_bhsd(q, k, v, mask=None, *,
                               scale: float | None = None,
                               causal: bool = False):
    """The XLA materialized core in native (batch, heads, seq, head_dim)
    layout, marked ``bhsd`` so MultiHeadAttention projects q/k/v straight
    into it (einsum path, no split/transpose copies).  Not just for the
    Pallas kernel: at BERT-large seq 128 batch 96 on one v5e this core
    measured 193.7 ms/step vs 201.1 for the (B,S,H,D) path — the ~9 ms of
    qkv split/relayout copies disappear here too (MFU 0.634 -> 0.658)."""
    return _dpa_core(q, k, v, mask, scale, causal,
                     "bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd")


dot_product_attention_bhsd.bhsd = True


def ragged_cache_update(cache, new, index):
    """Write ``new`` (batch, s, heads, head_dim) into ``cache`` (batch,
    max_len, heads, head_dim) at per-row offsets ``index`` (batch,) —
    the ragged KV-cache append of a continuous-batching decode step,
    where every sequence in the batch sits at a different length.
    Functional (returns the updated cache); offsets must satisfy
    ``index + s <= max_len`` (dynamic_update_slice clamps, which would
    silently shift the write)."""
    return jax.vmap(
        lambda c, n, i: jax.lax.dynamic_update_slice(
            c, n.astype(c.dtype), (i, 0, 0)))(cache, new, index)


def paged_write_slots(tables, cache_index, page_size: int):
    """Physical (page, slot) each batch row's new K/V lands at: row
    ``b`` writes into ``tables[b, cache_index[b] // page_size]`` at slot
    ``cache_index[b] % page_size``.

    This is the speculative-decode seam: several rows may share ONE page
    table at consecutive ``cache_index`` values (a verify chain), and
    because these writes are element-level scatters into the pool —
    distinct (page, slot) per chain row — they compose within a single
    step, with each row's attention then reading its predecessors'
    fresh K/V (writes precede the kernel).  Rollback is the host's move:
    a rejected chain suffix simply never advances ``PageTable.length``,
    leaving its K/V as dead bytes beyond every future step's validity
    mask until overwritten — the same contract bucket-pad garbage
    already relies on."""
    page_of = jnp.take_along_axis(
        tables, (cache_index // page_size)[:, None], axis=1)[:, 0]
    return page_of, cache_index % page_size


def decode_attention(q, k_cache, v_cache, cache_index, *,
                     scale: float | None = None, mask=None,
                     paged: PagedDecode | None = None):
    """Causal attention of ``s`` new query positions against a padded KV
    cache holding each sequence's full history at a per-row offset.

    q: (batch, s, heads, head_dim) — queries for the s NEW tokens, whose
    global positions are ``cache_index[b] + i`` (i in [0, s)).
    k_cache/v_cache: (batch, max_len, heads, head_dim) with rows
    [0, cache_index[b] + s) valid (the new tokens already appended via
    :func:`ragged_cache_update`); everything at or beyond is masked out,
    so padded garbage never contributes.  This is the incremental-decode
    core: with ``cache_index = 0`` and ``s = seq_len`` it is exactly
    ``dot_product_attention(..., causal=True)`` restricted to the valid
    prefix — the prefill-vs-incremental parity guarantee the serving
    tests assert.

    With ``paged`` (a :class:`PagedDecode`), the caches are instead the
    PAGED pools and ``s`` must be 1: the Pallas paged-decode kernel reads
    each row's K/V pages in place via ``paged.tables``, the masking
    contract unchanged (rows ``[0, cache_index + 1)`` valid)."""
    if paged is not None:
        from hetu_tpu.ops.pallas.paged_decode import paged_decode_attention
        if q.shape[1] != 1:
            raise ValueError(f"paged decode attends one new token per "
                             f"sequence, got s={q.shape[1]}")
        if mask is not None:
            raise ValueError("paged decode does not take an extra mask; "
                             "validity comes from cache_index")
        out = paged_decode_attention(
            q[:, 0], k_cache, v_cache, paged.tables,
            cache_index + 1, layer=paged.layer, scale=scale,
            interpret=paged.interpret)
        return out[:, None]
    s = q.shape[1]
    max_len = k_cache.shape[1]
    jpos = jnp.arange(max_len)[None, None, :]                  # (1, 1, L)
    ipos = cache_index[:, None, None] + jnp.arange(s)[None, :, None]
    valid = (jpos <= ipos)[:, None, :, :]                      # (b, 1, s, L)
    if mask is not None:
        valid = valid & mask.astype(bool)
    return dot_product_attention(q, k_cache, v_cache, valid, scale=scale,
                                 causal=False)


class MultiHeadAttention(Module):
    """MHA with fused qkv projection (reference layers/attention.py:5)."""

    def __init__(self, dim: int, num_heads: int, *, bias: bool = True,
                 causal: bool = False, dropout_rate: float = 0.0,
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        assert dim % num_heads == 0
        init = xavier_uniform()
        self.wqkv = init(next_key(), (dim, 3 * dim), dtype)
        self.wqkv_axes = ("embed", "qkv_three_heads")
        self.bqkv = zeros(None, (3 * dim,), dtype) if bias else None
        self.bqkv_axes = ("qkv_three_heads",)
        self.wo = init(next_key(), (dim, dim), dtype)
        self.wo_axes = ("heads_merged", "embed")
        self.bo = zeros(None, (dim,), dtype) if bias else None
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.causal = causal
        self.dropout_rate = dropout_rate
        self.attn_fn = attn_fn  # static; None -> dot_product_attention

    def __call__(self, x, mask=None, *, key=None, training: bool = False,
                 kv_cache=None, cache_index=None, paged=None):
        if kv_cache is not None:
            if paged is not None:
                if mask is not None:
                    raise ValueError(
                        "paged decode does not take an extra mask; "
                        "validity comes from cache_index")
                return self._call_paged(x, kv_cache, cache_index, paged)
            return self._call_cached(x, mask, kv_cache, cache_index)
        if getattr(self.attn_fn, "bhsd", False):
            return self._call_bhsd(x, mask, key=key, training=training)
        b, s, d = x.shape
        qkv = x @ self.wqkv.astype(x.dtype)
        if self.bqkv is not None:
            qkv = qkv + self.bqkv.astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        attn = self.attn_fn or dot_product_attention
        out = attn(q, k, v, mask, causal=self.causal)
        out = out.reshape(b, s, d)
        if training and self.dropout_rate > 0.0 and key is not None:
            out = dropout_op(out, self.dropout_rate, key, training=True)
        y = out @ self.wo.astype(x.dtype)
        if self.bo is not None:
            y = y + self.bo.astype(x.dtype)
        return y

    def _call_cached(self, x, mask, kv_cache, cache_index):
        """Incremental-decode path: project the s new tokens, append their
        K/V into the per-sequence cache at ragged offsets, and attend each
        query over the full valid prefix.  Returns ``(y, (k_cache,
        v_cache))`` with the caches updated — the serving engine threads
        them back into its page pool.  Inference-only (no dropout); the
        (B, S, H, D) reference core is used regardless of ``attn_fn``
        because flash/ring tilings assume untruncated causal layouts."""
        b, s, d = x.shape
        qkv = x @ self.wqkv.astype(x.dtype)
        if self.bqkv is not None:
            qkv = qkv + self.bqkv.astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        k_cache, v_cache = kv_cache
        k_cache = ragged_cache_update(k_cache, k, cache_index)
        v_cache = ragged_cache_update(v_cache, v, cache_index)
        out = decode_attention(q, k_cache, v_cache, cache_index, mask=mask)
        y = out.reshape(b, s, d) @ self.wo.astype(x.dtype)
        if self.bo is not None:
            y = y + self.bo.astype(x.dtype)
        return y, (k_cache, v_cache)

    def _call_paged(self, x, kv_cache, cache_index, paged: PagedDecode):
        """Paged-decode step: project the ONE new token per row, scatter
        its K/V into the pool at each row's (physical page, slot), and
        attend in place over the page tables via the Pallas paged kernel
        — no contiguous per-sequence K/V view is ever materialized.
        ``kv_cache`` = (k_pool, v_pool), per layer or stacked with
        ``paged.layer``; ``cache_index`` = per-row history lengths (the
        fed token's K/V lands at that index).  Returns ``(y, (k_pool,
        v_pool))`` with the pools updated — one small scatter each."""
        b, s, d = x.shape
        if s != 1:
            raise ValueError(f"paged decode takes one new token per row, "
                             f"got s={s}")
        qkv = x @ self.wqkv.astype(x.dtype)
        if self.bqkv is not None:
            qkv = qkv + self.bqkv.astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, self.num_heads, self.head_dim)
        v = v.reshape(b, self.num_heads, self.head_dim)
        k_pool, v_pool = kv_cache
        page_of, slot = paged_write_slots(paged.tables, cache_index,
                                          k_pool.shape[-3])
        if k_pool.ndim == 5:
            k_pool = k_pool.at[paged.layer, page_of, slot].set(
                k.astype(k_pool.dtype))
            v_pool = v_pool.at[paged.layer, page_of, slot].set(
                v.astype(v_pool.dtype))
        else:
            k_pool = k_pool.at[page_of, slot].set(k.astype(k_pool.dtype))
            v_pool = v_pool.at[page_of, slot].set(v.astype(v_pool.dtype))
        out = decode_attention(q, k_pool, v_pool, cache_index, paged=paged)
        y = out.reshape(b, s, d) @ self.wo.astype(x.dtype)
        if self.bo is not None:
            y = y + self.bo.astype(x.dtype)
        return y, (k_pool, v_pool)

    def _call_bhsd(self, x, mask=None, *, key=None, training: bool = False):
        """Native-kernel-layout path: q/k/v are PROJECTED into (B, H, S, D)
        — ``einsum('bsd,dkhe->kbhse')`` — and the output projection
        contracts (h, e) straight out of (B, H, S, D), so no transpose op
        (forward or vjp) ever sits between the projection matmuls and a
        ``bhsd``-marked attention core (the Pallas flash kernel's tiling).
        The (B, S, H, D) path materializes an XLA relayout copy around
        every kernel operand and gradient instead — ~9% of the BERT-large
        seq-512 step (ROADMAP r03 4b).  Same math, same weights, same
        parameter layout; only the activation layout differs."""
        h, e = self.num_heads, self.head_dim
        d = x.shape[-1]
        # THREE separate projection einsums, not one fused "bsd,dkhe->
        # kbhse": measured on one v5e at BERT-large seq 512 (examples/
        # profile_qkv_variants.py) the per-operand dots let XLA absorb the
        # (b,s,h,e)->(b,h,s,e) permutation into each dot's output layout,
        # while the fused 5-d variant pays ~9 ms/step of slice_bitcast
        # fusions for qkv[k] and the matmul+transpose variant pays ~22 ms
        # of relayout copies.  A=241.3 / B=237.0 / C(this)=225.1 /
        # D=247.7 ms per step.
        w4 = self.wqkv.astype(x.dtype).reshape(d, 3, h, e)
        b4 = (None if self.bqkv is None
              else self.bqkv.astype(x.dtype).reshape(3, 1, h, 1, e))
        parts = []
        for i in range(3):
            p = jnp.einsum("bsd,dhe->bhse", x, w4[:, i])
            if b4 is not None:
                p = p + b4[i]
            parts.append(p)
        q, k, v = parts
        out = self.attn_fn(q, k, v, mask, causal=self.causal)  # (b,h,s,e)
        if training and self.dropout_rate > 0.0 and key is not None:
            # elementwise iid mask: applying it in (b,h,s,e) is the same
            # distribution as the (b,s,d) path (different RNG alignment)
            out = dropout_op(out, self.dropout_rate, key, training=True)
        y = jnp.einsum("bhse,hed->bsd",
                       out, self.wo.astype(x.dtype).reshape(h, e, d))
        if self.bo is not None:
            y = y + self.bo.astype(x.dtype)
        return y
