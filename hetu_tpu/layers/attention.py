"""Multi-head attention.

Reference: python/hetu/layers/attention.py:5 (an OpLayer composing matmul/
softmax ops; materialized QK^T).  TPU-native design: einsum formulation with
head axes annotated for tensor parallelism ('heads' logical axis → 'tp' mesh
axis under the Megatron preset), fp32 softmax statistics, and a pluggable
attention core so the Pallas flash-attention kernel (ops/pallas/flash.py) or
ring attention (parallel/ring_attention.py) can replace the reference
materialized path.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import xavier_uniform, zeros
from hetu_tpu.ops import dropout as dropout_op

__all__ = ["MultiHeadAttention", "dot_product_attention"]


def dot_product_attention(q, k, v, mask=None, *, scale: float | None = None,
                          causal: bool = False):
    """Reference attention core: softmax(QK^T/sqrt(d))V, fp32 statistics.

    q,k,v: (batch, seq, heads, head_dim).  mask: broadcastable to
    (batch, heads, q_seq, kv_seq), True/1 = attend.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        logits = jnp.where(cmask, logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class MultiHeadAttention(Module):
    """MHA with fused qkv projection (reference layers/attention.py:5)."""

    def __init__(self, dim: int, num_heads: int, *, bias: bool = True,
                 causal: bool = False, dropout_rate: float = 0.0,
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        assert dim % num_heads == 0
        init = xavier_uniform()
        self.wqkv = init(next_key(), (dim, 3 * dim), dtype)
        self.wqkv_axes = ("embed", "qkv_three_heads")
        self.bqkv = zeros(None, (3 * dim,), dtype) if bias else None
        self.bqkv_axes = ("qkv_three_heads",)
        self.wo = init(next_key(), (dim, dim), dtype)
        self.wo_axes = ("heads_merged", "embed")
        self.bo = zeros(None, (dim,), dtype) if bias else None
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.causal = causal
        self.dropout_rate = dropout_rate
        self.attn_fn = attn_fn  # static; None -> dot_product_attention

    def __call__(self, x, mask=None, *, key=None, training: bool = False):
        b, s, d = x.shape
        qkv = x @ self.wqkv.astype(x.dtype)
        if self.bqkv is not None:
            qkv = qkv + self.bqkv.astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        attn = self.attn_fn or dot_product_attention
        out = attn(q, k, v, mask, causal=self.causal)
        out = out.reshape(b, s, d)
        if training and self.dropout_rate > 0.0 and key is not None:
            out = dropout_op(out, self.dropout_rate, key, training=True)
        y = out @ self.wo.astype(x.dtype)
        if self.bo is not None:
            y = y + self.bo.astype(x.dtype)
        return y
