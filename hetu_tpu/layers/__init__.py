from hetu_tpu.layers.base import Identity, Lambda, Sequential
from hetu_tpu.layers.linear import Embedding, Linear, MLPTower
from hetu_tpu.layers.conv import AvgPool2d, Conv2d, Flatten, MaxPool2d
from hetu_tpu.layers.norm import (
    BatchNorm2d,
    Dropout,
    GroupNorm,
    InstanceNorm2d,
    LayerNorm,
    RMSNorm,
)
from hetu_tpu.layers.attention import (
    MultiHeadAttention,
    PagedDecode,
    decode_attention,
    dot_product_attention,
    ragged_cache_update,
)
from hetu_tpu.layers.transformer import TransformerBlock, TransformerMLP
from hetu_tpu.layers.moe import (
    BalanceGate,
    ExpertMLP,
    HashGate,
    KTop1Gate,
    MoELayer,
    SAMGate,
    TopKGate,
    moe_transformer_mlp,
)
