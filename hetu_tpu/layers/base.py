"""Layer base utilities.

TPU-native equivalent of the reference layer sugar
(reference: python/hetu/layers/base.py:15 OpLayer grouping, sequence.py
Sequential).  Layers are just Modules; ``Sequential`` composes them.
"""

from __future__ import annotations

from hetu_tpu.core.module import Module

__all__ = ["Sequential", "Identity", "Lambda"]


class Sequential(Module):
    """Composition of layers (reference layers/sequence.py)."""

    def __init__(self, *layers):
        self.layers = list(layers)

    def __call__(self, x, **kw):
        for layer in self.layers:
            x = layer(x, **kw) if _wants_kwargs(layer) else layer(x)
        return x

    def __getitem__(self, i):
        return self.layers[i]

    def __len__(self):
        return len(self.layers)


def _wants_kwargs(layer) -> bool:
    call = getattr(type(layer), "__call__", None)
    if call is None:
        return False
    import inspect

    try:
        sig = inspect.signature(call)
    except (TypeError, ValueError):
        return False
    return any(
        p.kind is inspect.Parameter.VAR_KEYWORD or p.kind is inspect.Parameter.KEYWORD_ONLY
        for p in sig.parameters.values()
    )


class Identity(Module):
    def __init__(self):
        self._noop = ()

    def __call__(self, x):
        return x


class Lambda(Module):
    """Wrap a pure function as a layer (static attribute, not traced)."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, x):
        return self.fn(x)
