"""Mixture-of-Experts with expert parallelism — TPU-native GShard dispatch.

Reference machinery being rebuilt (reference: python/hetu/):
- gates: ``TopKGate`` (layers/TopGate.py:56, topkgating:14 with capacity,
  cumsum locations, balance aux loss), ``HashGate`` (layers/HashGate.py:20),
  ``KTop1Gate`` (layers/KTop1Gate.py), ``SAMGate``/``BalanceGate``;
- dispatch: ``layout_transform_op`` packs tokens into per-expert capacity
  buckets (gpu_ops/LayoutTransform.py:12, CUDA H_A2A_LayoutTransform), then
  ``alltoall_op`` / hierarchical ``halltoall_op`` exchanges buckets across
  devices (layers/moe_layer.py:45-120, mpi_nccl_communication.cu:152/245);
- experts: per-device FFN list, looped in Python (moe_layer.py:79-82).

TPU-native design: dispatch/combine are one-hot einsums (GShard) — the
layout transform becomes an MXU matmul instead of a scatter kernel; experts
are ONE stacked FFN vmapped over the local expert dim (no Python loop);
the exchange is ``lax.all_to_all`` over the ``ep`` mesh axis inside a
``shard_map`` that is manual over ``ep`` only, so dp/tp shardings stay
GSPMD-auto.  Hierarchical A2A falls out of factored mesh axes (the ICI/DCN
hierarchy XLA already knows) rather than a hand-coded gather/a2a/scatter.

Capacity, shapes, and expert counts are static — XLA requirement and also
how the reference sizes its buckets (capacity math in TopGate.py:19).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import normal, zeros
from hetu_tpu.ops import gelu

__all__ = [
    "TopKGate", "HashGate", "KTop1Gate", "SAMGate", "BalanceGate",
    "ExpertMLP", "MoELayer", "moe_transformer_mlp", "routing_stats",
]


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def _slot_positions(mask, capacity: int, fill=None):
    """Capacity bucketing position math shared by all gates (reference
    TopGate.py:34-44 cumsum locations): first-come-first-served positions
    per expert, tokens past ``capacity`` dropped.  ``mask``: [T,E] one-hot
    choices; ``fill``: [1,E] running per-expert occupancy from earlier
    choice ranks.  Returns (slot [T] int32, in_cap [T,E], new_fill)."""
    fill = jnp.zeros((1, mask.shape[1]), jnp.float32) if fill is None else fill
    pos = jnp.cumsum(mask, axis=0) - mask + fill
    new_fill = fill + jnp.sum(mask, axis=0, keepdims=True)
    in_cap = (pos < capacity).astype(jnp.float32) * mask
    slot = jnp.sum(pos * in_cap, axis=-1).astype(jnp.int32)
    return slot, in_cap, new_fill


def _densify(plans, T: int, E: int, C: int):
    """Dense [T,E,C] (dispatch, combine) from an index plan — the einsum
    path and the test oracle; every gate's __call__ goes through here so
    index_plan is the single source of routing truth."""
    dispatch = jnp.zeros((T, E, C), jnp.float32)
    combine = jnp.zeros((T, E, C), jnp.float32)
    for e_idx, slot, keep, g in plans:
        oh = (_one_hot(e_idx, E)[:, :, None]
              * _one_hot(slot, C)[:, None, :]
              * keep.astype(jnp.float32)[:, None, None])
        dispatch = dispatch + oh
        combine = combine + g[:, None, None] * oh
    return dispatch, combine


def routing_stats(plans, E: int):
    """Routing observability from an index plan (any gate's
    ``index_plan`` output): the two numbers that tell you whether a MoE
    run is silently degrading (reference gate accounting,
    moe_layer.py:45).

    - ``overflow_frac``: fraction of (token, choice) assignments dropped
      by capacity buckets.  High values mean tokens are falling out of
      the model — raise capacity_factor or fix the balance loss.
    - ``load_entropy``: entropy of the post-capacity per-expert load,
      normalized to [0, 1] (1 = perfectly balanced, 0 = every kept token
      on one expert — router collapse).
    """
    import math

    total = 0.0
    kept = 0.0
    load = jnp.zeros((E,), jnp.float32)
    for e_idx, _slot, keep, _g in plans:
        kf = keep.astype(jnp.float32)
        kept = kept + jnp.sum(kf)
        total = total + e_idx.shape[0]
        load = load + jnp.sum(_one_hot(e_idx, E) * kf[:, None], axis=0)
    p = load / jnp.maximum(jnp.sum(load), 1e-9)
    ent = -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30)),
                             0.0))
    return {
        "overflow_frac": 1.0 - kept / total,
        "load_entropy": ent / math.log(E) if E > 1 else jnp.float32(1.0),
    }


class TopKGate(Module):
    """Top-k router with capacity buckets and load-balance auxiliary loss
    (reference TopGate.py:14 ``topkgating``: softmax → top-k one-hot masks →
    cumsum positions → capacity drop → per-slot combine weights).

    Returns ``(dispatch [T,E,C] one-hot, combine [T,E,C], aux_loss)``.
    """

    def __init__(self, dim: int, num_experts: int, k: int = 2, *,
                 capacity_factor: float = 1.25,
                 eval_capacity_factor: Optional[float] = None,
                 dtype=jnp.float32):
        self.w = normal(stddev=0.02)(next_key(), (dim, num_experts), dtype)
        self.w_axes = ("embed", None)
        self.b = zeros(None, (num_experts,), dtype)
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor or capacity_factor

    def capacity(self, n_tokens: int, training: bool = True) -> int:
        cf = self.capacity_factor if training else self.eval_capacity_factor
        import math
        return max(self.k, self.k * math.ceil(n_tokens / self.num_experts * cf))

    def __call__(self, x, *, training: bool = True):
        """Dense [T,E,C] dispatch/combine built FROM the index plan — one
        source of routing truth (index_plan); this densification exists for
        gates/consumers on the einsum path and as the test oracle."""
        plans, C, aux = self.index_plan(x, training=training)
        dispatch, combine = _densify(plans, x.shape[0], self.num_experts, C)
        return dispatch, combine, aux

    def index_plan(self, x, *, training: bool = True):
        """Index-level routing plan for the scatter/gather dispatch path
        (MoELayer): per choice rank, (expert_idx [T], slot [T], keep [T],
        gate [T]).  Same position math (_slot_positions) and balance loss
        as __call__ — the dense [T,E,C] one-hot tensors are never built;
        at bench shape their einsums burn T*E*C*d MACs to do a gather's
        job."""
        T, E = x.shape[0], self.num_experts
        C = self.capacity(T, training)
        logits = (x @ self.w.astype(x.dtype) + self.b.astype(x.dtype))
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        plans = []
        aux = 0.0
        remaining = gates
        fill = None
        for _ in range(self.k):
            idx = jnp.argmax(remaining, axis=-1)
            mask = _one_hot(idx, E)
            remaining = remaining * (1.0 - mask)
            slot, in_cap, fill = _slot_positions(mask, C, fill)
            keep = jnp.sum(in_cap, axis=-1) > 0.0
            gate_i = jnp.sum(gates * mask, axis=-1)
            plans.append((idx, slot, keep, gate_i))
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(mask, axis=0)
            aux = aux + jnp.sum(me * ce) * E
        if self.k > 1:
            denom = sum(g * k.astype(jnp.float32) for _, _, k, g in plans)
            denom = jnp.maximum(denom, 1e-9)
            plans = [(i, s_, k, g / denom) for i, s_, k, g in plans]
        return plans, C, aux


class HashGate(Module):
    """Content-independent routing by precomputed/ hashed expert index
    (reference HashGate.py:6 hashgating — 'Currently Random Hash').  The
    assignment is ``token_id % num_experts`` by default; pass explicit
    indices for learned-hash variants."""

    def __init__(self, dim: int, num_experts: int, *,
                 capacity_factor: float = 1.0):
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.k = 1

    def capacity(self, n_tokens: int, training: bool = True) -> int:
        import math
        return max(1, math.ceil(n_tokens / self.num_experts * self.capacity_factor))

    def __call__(self, x, indices=None, *, training: bool = True):
        plans, C, aux = self.index_plan(x, indices, training=training)
        dispatch, combine = _densify(plans, x.shape[0], self.num_experts, C)
        return dispatch, combine, aux

    def index_plan(self, x, indices=None, *, training: bool = True):
        T, E = x.shape[0], self.num_experts
        C = self.capacity(T, training)
        if indices is None:
            indices = jnp.arange(T, dtype=jnp.int32) % E
        mask = _one_hot(indices, E)
        slot, in_cap, _ = _slot_positions(mask, C)
        keep = jnp.sum(in_cap, axis=-1) > 0.0
        gate = jnp.ones((T,), jnp.float32)  # hash combine weight is 1
        return [(indices, slot, keep, gate)], C, jnp.float32(0.0)


class KTop1Gate(Module):
    """k independent top-1 routers over disjoint expert prototypes
    (reference layers/KTop1Gate.py:14 ``ktop1gating``): the E experts are
    split into k prototype groups of E/k; each group gets its own softmax
    over the corresponding logit slice and routes top-1 within the group, so
    every token is dispatched to exactly k experts — one per prototype.
    Balance loss is summed per prototype (KTop1Gate.py:32-35).

    Prototype expert sets are disjoint, so capacity slots never interact
    across choices (the reference's commented-out ``acc_base`` carries no
    fill either).  Returns ``(dispatch [T,E,C], combine [T,E,C], aux)``.
    """

    def __init__(self, dim: int, num_experts: int, k: int = 2, *,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: Optional[float] = None,
                 dtype=jnp.float32):
        if num_experts % k:
            raise ValueError(f"{num_experts} experts not divisible by k={k}")
        self.w = normal(stddev=0.02)(next_key(), (dim, num_experts), dtype)
        self.w_axes = ("embed", None)
        self.b = zeros(None, (num_experts,), dtype)
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor or capacity_factor

    def capacity(self, n_tokens: int, training: bool = True) -> int:
        import math
        cf = self.capacity_factor if training else self.eval_capacity_factor
        return max(1, self.k * math.ceil(n_tokens / self.num_experts * cf))

    def __call__(self, x, *, training: bool = True):
        plans, C, aux = self.index_plan(x, training=training)
        dispatch, combine = _densify(plans, x.shape[0], self.num_experts, C)
        return dispatch, combine, aux

    def index_plan(self, x, *, training: bool = True):
        T, E, k = x.shape[0], self.num_experts, self.k
        Ep = E // k                                   # experts per prototype
        C = self.capacity(T, training)
        logits = x @ self.w.astype(x.dtype) + self.b.astype(x.dtype)
        # [T, k, Ep]: per-prototype softmax (KTop1Gate.py:19-21 split+softmax)
        pgates = jax.nn.softmax(
            logits.astype(jnp.float32).reshape(T, k, Ep), axis=-1)
        idx = jnp.argmax(pgates, axis=-1)             # [T, k] local top-1
        pmask = _one_hot(idx, Ep)                     # [T, k, Ep]
        gate_val = jnp.sum(pgates * pmask, axis=-1)   # [T, k]

        # per-prototype balance loss vs its own softmax (Ep experts)
        me = jnp.mean(pgates, axis=0)                 # [k, Ep]
        ce = jnp.mean(pmask, axis=0)                  # [k, Ep]
        aux = jnp.sum(jnp.sum(me * ce, axis=-1) * Ep)

        # slot assignment per prototype (expert columns are disjoint, so
        # fills never interact; one choice per row each)
        plans = []
        for i in range(k):
            mask_i = jnp.zeros((T, k, Ep), jnp.float32).at[:, i].set(
                pmask[:, i]).reshape(T, E)
            slot, in_cap, _ = _slot_positions(mask_i, C)
            keep = jnp.sum(in_cap, axis=-1) > 0.0
            e_idx = i * Ep + idx[:, i]
            plans.append((e_idx, slot, keep, gate_val[:, i]))
        return plans, C, aux


class SAMGate(Module):
    """Switch-and-mix locality-aware gate (reference layers/SAMGate.py:21
    ``samgating``): softmax over all E experts, sum gates within each of G
    contiguous expert groups (one group per node; SamGroupSum.cu), route the
    token to its top-1 *group*, then take the top-k experts inside that
    group (GroupTopKIdx.cu).  All k choices land on one node, so the
    all-to-all stays intra-node.

    Aux = summed balance loss per choice (SAMGate.py:40,56) plus
    ``alignment_weight`` × the alignment loss (SamMax.cu: for each token,
    sum of relu(gate_j − gate_thresh) over experts *outside* the chosen
    group, thresh = the k-th chosen expert's gate — penalises out-of-group
    experts that outscore the selection).
    """

    def __init__(self, dim: int, num_experts: int, k: int = 2, *,
                 num_groups: int, capacity_factor: float = 1.0,
                 eval_capacity_factor: Optional[float] = None,
                 alignment_weight: float = 1.0, dtype=jnp.float32):
        if num_experts % num_groups:
            raise ValueError(f"{num_experts} experts not divisible into "
                             f"{num_groups} groups")
        if k > num_experts // num_groups:
            raise ValueError("k exceeds experts per group")
        self.w = normal(stddev=0.02)(next_key(), (dim, num_experts), dtype)
        self.w_axes = ("embed", None)
        self.b = zeros(None, (num_experts,), dtype)
        self.num_experts = num_experts
        self.k = k
        self.num_groups = num_groups
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor or capacity_factor
        self.alignment_weight = alignment_weight

    def capacity(self, n_tokens: int, training: bool = True) -> int:
        import math
        cf = self.capacity_factor if training else self.eval_capacity_factor
        return max(1, self.k * math.ceil(n_tokens / self.num_experts * cf))

    def __call__(self, x, *, training: bool = True):
        plans, C, aux = self.index_plan(x, training=training)
        dispatch, combine = _densify(plans, x.shape[0], self.num_experts, C)
        return dispatch, combine, aux

    def index_plan(self, x, *, training: bool = True):
        T, E, G = x.shape[0], self.num_experts, self.num_groups
        Eg = E // G                                    # experts per group
        C = self.capacity(T, training)
        logits = x @ self.w.astype(x.dtype) + self.b.astype(x.dtype)
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T,E]

        group_sum = jnp.sum(gates.reshape(T, G, Eg), axis=-1)        # [T,G]
        top1_group = jnp.argmax(group_sum, axis=-1)                  # [T]
        in_group = _one_hot(top1_group, G)[:, :, None] * jnp.ones((1, 1, Eg))
        in_group = in_group.reshape(T, E)              # [T,E] group member
        masked_gates = jnp.where(in_group > 0, gates, -jnp.inf)

        plans = []
        aux = 0.0
        remaining = masked_gates
        fill = None                                    # shared acc_base fill
        last_gate = None
        for _ in range(self.k):
            idx = jnp.argmax(remaining, axis=-1)
            mask = _one_hot(idx, E)
            remaining = jnp.where(mask > 0, -jnp.inf, remaining)
            slot, in_cap, fill = _slot_positions(mask, C, fill)
            keep = jnp.sum(in_cap, axis=-1) > 0.0
            gate_i = jnp.sum(gates * mask, axis=-1)
            last_gate = gate_i
            plans.append((idx, slot, keep, gate_i))
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(mask, axis=0)
            aux = aux + jnp.sum(me * ce) * E
        # alignment: out-of-chosen-group gates above the k-th chosen gate,
        # averaged over tokens so its scale is batch-invariant like the
        # balance term (means over T) and alignment_weight transfers
        # across batch/sequence sizes
        overflow = jnp.maximum(gates - last_gate[:, None], 0.0)
        alignment = jnp.sum(overflow * (1.0 - in_group)) / T
        return plans, C, aux + self.alignment_weight * alignment


class BalanceGate(Module):
    """BASE-layer balanced assignment (reference layers/BalanceGate.py:25
    ``BalanceAssignmentGate`` + BalanceAssignment.cu auction solver): tokens
    are scored against fixed orthogonal expert centroids and assigned so
    every expert receives exactly T/E tokens; output is weighted by
    sigmoid(score) (BASE, Lewis et al. '21).

    TPU redesign: the reference solves the assignment with a sequential
    auction algorithm — a data-dependent loop that is hostile to XLA.  Here
    the balanced transport plan comes from ``sinkhorn_iters`` rounds of
    Sinkhorn row/column normalisation (the S-BASE formulation) followed by
    capacity-bucketed argmax with C = ceil(T/E), which is a fixed unrollable
    compute graph of matmul-shaped reductions.  Aux loss is 0 — balance is
    enforced structurally, exactly as in the reference.
    """

    _state_fields = ("centroids",)

    def __init__(self, dim: int, num_experts: int, *,
                 sinkhorn_iters: int = 8, temperature: float = 1.0,
                 dtype=jnp.float32):
        key = next_key()
        # orthogonal, non-trainable centroids (BalanceGate.py:6
        # generate_orthogonal, gain 0.1)
        w = jax.random.normal(key, (num_experts, dim), jnp.float32)
        q, r = jnp.linalg.qr(w.T if num_experts < dim else w)
        q = q * jnp.sign(jnp.diag(r))
        self.centroids = (q.T if num_experts < dim else q).astype(dtype) * 0.1
        self.num_experts = num_experts
        self.k = 1
        self.sinkhorn_iters = sinkhorn_iters
        self.temperature = temperature

    def capacity(self, n_tokens: int, training: bool = True) -> int:
        import math
        return max(1, math.ceil(n_tokens / self.num_experts))

    def __call__(self, x, *, training: bool = True):
        plans, C, aux = self.index_plan(x, training=training)
        dispatch, combine = _densify(plans, x.shape[0], self.num_experts, C)
        return dispatch, combine, aux

    def index_plan(self, x, *, training: bool = True):
        T, E = x.shape[0], self.num_experts
        C = self.capacity(T, training)
        scores = (x @ self.centroids.astype(x.dtype).T).astype(jnp.float32)

        # Sinkhorn to a doubly-balanced plan (rows sum 1, cols sum T/E)
        logp = scores / self.temperature
        f = jnp.zeros((T, 1), jnp.float32)
        g = jnp.zeros((1, E), jnp.float32)
        for _ in range(self.sinkhorn_iters):
            f = -jax.nn.logsumexp(logp + g, axis=1, keepdims=True)
            g = (jnp.log(T / E)
                 - jax.nn.logsumexp(logp + f, axis=0, keepdims=True))
        plan = logp + f + g                            # balanced log-plan
        idx = jnp.argmax(plan, axis=-1)                # [T]
        mask = _one_hot(idx, E)
        slot, in_cap, _ = _slot_positions(mask, C)
        keep = jnp.sum(in_cap, axis=-1) > 0.0
        weight = jax.nn.sigmoid(jnp.sum(scores * mask, axis=-1))  # BASE
        return [(idx, slot, keep, weight)], C, jnp.float32(0.0)


class ExpertMLP(Module):
    """Stacked expert FFNs: leaves ``[n_experts, ...]`` on the ``experts``
    logical axis (→ ``ep`` mesh axis), applied with vmap — the TPU form of
    the reference's per-device expert list (moe_layer.py:7 Expert)."""

    def __init__(self, num_experts: int, dim: int, hidden: int, *,
                 activation: Callable = gelu, dtype=jnp.float32):
        init = normal(stddev=0.02)
        self.w1 = init(next_key(), (num_experts, dim, hidden), dtype)
        self.w1_axes = ("experts", "embed", "mlp")
        self.b1 = zeros(None, (num_experts, hidden), dtype)
        self.b1_axes = ("experts", "mlp")
        self.w2 = init(next_key(), (num_experts, hidden, dim), dtype)
        self.w2_axes = ("experts", "mlp", "embed")
        self.b2 = zeros(None, (num_experts, dim), dtype)
        self.b2_axes = ("experts", "embed")
        self.activation = activation
        self.num_experts = num_experts

    def __call__(self, x):
        """x: [E_local, tokens, dim] → same shape."""
        def one(w1, b1, w2, b2, t):
            h = self.activation(t @ w1.astype(t.dtype) + b1.astype(t.dtype))
            return h @ w2.astype(t.dtype) + b2.astype(t.dtype)
        return jax.vmap(one)(self.w1, self.b1, self.w2, self.b2, x)


class MoELayer(Module):
    """Gate → dispatch einsum → AllToAll over ``ep`` → experts → reverse
    AllToAll → combine einsum (reference moe_layer.py:45 MoELayer.__call__).

    ``mesh=None`` (or ep=1) degenerates to single-group MoE with no
    exchange — the oracle path tests compare against.

    Call: ``y, aux = moe(x)`` with x ``[..., dim]``; aux is the gate's
    balance loss (add to the objective scaled by ``aux_weight``).
    """

    def __init__(self, gate: Module, experts: ExpertMLP, *,
                 mesh: Optional[Mesh] = None,
                 axis: "str | Sequence[str]" = "ep"):
        self.gate = gate
        self.experts = experts
        self.mesh = mesh
        # a tuple axis, e.g. ("ep", "tp") or (dcn, ici), factors the expert
        # exchange hierarchically — the reference's HAllToAll
        # (mpi_nccl_communication.cu:152 intra-gather → inter-a2a → scatter);
        # XLA lowers the inner axis onto ICI and the outer onto DCN.
        self.axis = (axis,) if isinstance(axis, str) else tuple(axis)

    def _route_in(self, gate, t, training):
        """(ex_in [E,C,d], plan_ctx, aux).  Index path (scatter) when the
        gate provides index_plan — one O(T*d) scatter instead of a
        [T,E,C]x[T,d] einsum burning T*E*C*d MACs; else the one-hot
        einsum (reference moe_layer.py dispatch)."""
        E = self.experts.num_experts
        if hasattr(gate, "index_plan"):
            plans, C, aux = gate.index_plan(t, training=training)
            flat = jnp.zeros((E * C, t.shape[1]), t.dtype)
            for e_idx, slot, keep, _g in plans:
                tgt = jnp.where(keep, e_idx * C + slot, E * C)
                flat = flat.at[tgt].add(t, mode="drop")
            return flat.reshape(E, C, t.shape[1]), ("idx", plans, C), aux
        dispatch, combine, aux = gate(t, training=training)
        ex_in = jnp.einsum("tec,td->ecd", dispatch.astype(t.dtype), t)
        return ex_in, ("oh", combine), aux

    def _route_out(self, ctx, ex_out, t_dtype):
        """Combine expert outputs back to tokens per the routing context."""
        if ctx[0] == "idx":
            _, plans, C = ctx
            flat = ex_out.reshape(-1, ex_out.shape[-1])
            y = 0.0
            for e_idx, slot, keep, g in plans:
                src = jnp.clip(e_idx * C + slot, 0, flat.shape[0] - 1)
                w = (g * keep.astype(jnp.float32)).astype(t_dtype)
                y = y + flat[src] * w[:, None]
            return y
        _, combine = ctx
        return jnp.einsum("tec,ecd->td", combine.astype(t_dtype), ex_out)

    def _stats_of(self, ctx, E):
        """routing_stats from the routing context (index path only: the
        one-hot einsum path has no plan to account; all shipped gates
        provide index_plan)."""
        if ctx[0] != "idx":
            raise ValueError(
                "with_stats needs a gate with index_plan (scatter path)")
        return routing_stats(ctx[1], E)

    def __call__(self, x, *, training: bool = True,
                 with_stats: bool = False):
        """``with_stats=True`` returns ``(y, (aux, stats))`` where stats is
        ``routing_stats`` of this call's plan (overflow_frac,
        load_entropy) — pmean'd over ep so every rank logs the global
        picture."""
        shape = x.shape
        d = shape[-1]
        mesh = self.mesh
        ep = 1
        if mesh is not None:
            for a in self.axis:
                ep *= mesh.shape[a]
        E = self.experts.num_experts          # global expert count
        if E % max(ep, 1):
            raise ValueError(f"{E} experts not divisible over ep={ep}")

        if ep <= 1:
            t = x.reshape(-1, d)
            ex_in, ctx, aux = self._route_in(self.gate, t, training)
            ex_out = self.experts(ex_in)
            y = self._route_out(ctx, ex_out, t.dtype)
            if with_stats:
                return y.reshape(shape), (aux, self._stats_of(ctx, E))
            return y.reshape(shape), aux

        E_local = E // ep

        def _pvary_params(tree):
            # Mark replicated param leaves device-varying explicitly, in
            # their storage dtype (fp32).  Without this, shard_map inserts
            # the replicated->varying conversion lazily at first use — which
            # is AFTER the bf16 compute cast, producing a bf16 copy-reduction
            # all-reduce that XLA:CPU's AllReducePromotion pass cannot clone
            # (crash: "Invalid binary instruction opcode copy").  Varying
            # them up front keeps that collective in fp32 on every backend.
            def pv(p):
                if not isinstance(p, jax.Array):
                    return p
                missing = tuple(a for a in self.axis
                                if a not in jax.typeof(p).vma)
                if not missing:
                    return p
                pcast = getattr(lax, "pcast", None)
                if pcast is not None:
                    return pcast(p, missing, to="varying")
                return lax.pvary(p, missing)
            return jax.tree_util.tree_map(pv, tree)

        def inner(gate, experts, xl):
            gate = _pvary_params(gate)
            experts = _pvary_params(experts)
            # xl: the ep-local token shard [..., d]
            t = xl.reshape(-1, d)
            ex_in, ctx, aux = self._route_in(gate, t, training)
            # [E, C, d] -> exchange capacity buckets so each rank holds its
            # E_local experts' buckets from every rank: [E_local, ep*C, d]
            ex_in = lax.all_to_all(ex_in, self.axis, split_axis=0,
                                   concat_axis=1, tiled=True)
            ex_out = experts(ex_in)
            # reverse exchange: [E, C, d] back on every source rank
            ex_out = lax.all_to_all(ex_out, self.axis, split_axis=1,
                                    concat_axis=0, tiled=True)
            y = self._route_out(ctx, ex_out, t.dtype)
            aux = lax.pmean(aux, self.axis)
            if with_stats:
                stats = {k: lax.pmean(v, self.axis)
                         for k, v in self._stats_of(ctx, E).items()}
                return y.reshape(xl.shape), (aux, stats)
            return y.reshape(xl.shape), aux

        out_aux_spec = (P(), {"overflow_frac": P(), "load_entropy": P()}) \
            if with_stats else P()
        return jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(), P(self.axis), P(self.axis)),
            out_specs=(P(self.axis), out_aux_spec),
            axis_names=frozenset(self.axis),
        )(self.gate, self.experts, x)


def moe_transformer_mlp(dim: int, hidden: int, num_experts: int, *, k: int = 2,
                        capacity_factor: float = 1.25,
                        mesh: Optional[Mesh] = None,
                        dtype=jnp.float32) -> MoELayer:
    """The standard MoE-transformer FFN replacement (reference
    examples/moe model_dim 2048, experts-per-device × world config)."""
    gate = TopKGate(dim, num_experts, k, capacity_factor=capacity_factor,
                    dtype=dtype)
    experts = ExpertMLP(num_experts, dim, hidden, dtype=dtype)
    return MoELayer(gate, experts, mesh=mesh)
