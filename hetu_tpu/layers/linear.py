"""Linear and embedding layers.

Reference: python/hetu/layers/linear.py, layers/embedding.py:5.
Logical sharding axes: Linear weights are ('in','out') so the strategy layer
(parallel/spec.py) can emit Megatron column/row-parallel placements; Embedding
tables are ('vocab','embed').
"""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import he_uniform, normal, zeros
from hetu_tpu.ops import embedding_lookup, linear, relu

__all__ = ["Linear", "Embedding", "MLPTower"]


class Linear(Module):
    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 initializer=None, dtype=jnp.float32,
                 axes: tuple = ("in", "out")):
        init = initializer or he_uniform()
        self.w = init(next_key(), (in_features, out_features), dtype)
        self.w_axes = axes
        self.b = zeros(None, (out_features,), dtype) if bias else None
        self.b_axes = (axes[1],)
        self.in_features = in_features
        self.out_features = out_features

    def __call__(self, x):
        return linear(x, self.w.astype(x.dtype),
                      None if self.b is None else self.b.astype(x.dtype))


class Embedding(Module):
    """Dense on-device embedding (reference layers/embedding.py:5).

    The host-cached parameter-server variant (HET) is
    ``hetu_tpu.embed.CachedEmbedding``.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 initializer=None, dtype=jnp.float32,
                 axes: tuple = ("vocab", "embed")):
        init = initializer or normal(stddev=0.02)
        self.weight = init(next_key(), (num_embeddings, embedding_dim), dtype)
        self.weight_axes = axes
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim

    def __call__(self, ids):
        return embedding_lookup(self.weight, ids)


class MLPTower(Module):
    """relu MLP over a width schedule (the reference's ``create_mlp``,
    examples/rec/models/base.py / the CTR deep towers).  ``final_relu``
    selects whether the last layer is activated."""

    def __init__(self, widths, *, final_relu: bool = True):
        self.layers = [Linear(a, b) for a, b in zip(widths[:-1], widths[1:])]
        self.final_relu = final_relu

    def __call__(self, x):
        last = len(self.layers) - 1
        for i, l in enumerate(self.layers):
            x = l(x)
            if i < last or self.final_relu:
                x = relu(x)
        return x
