"""Cluster configuration + multi-host launcher (the ``heturun`` capability).

Reference: ``bin/heturun`` → python/runner.py:150 parses a cluster yaml
(DistConfig, python/hetu/context.py:2204), spawns PS roles locally/via SSH and
workers under mpirun.  TPU-native: there is no PS process tree or mpirun —
each host runs ONE process per chip-set, `jax.distributed.initialize` forms
the world over the coordinator, and XLA's collectives ride ICI/DCN.  The
launcher therefore reduces to: parse the cluster spec, compose per-process
environments, exec the training script on every host (ssh for remote ones),
and wire coordinator discovery.

CPU simulation: ``simulate_workers`` launches N local processes with a
virtual device count so multi-process logic is testable on one machine
(the reference gets the same effect by mpirun on localhost).
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
import sys
from typing import Optional, Sequence

from hetu_tpu.obs import registry as _obs
from hetu_tpu.obs.fleet import ENV_OBS_SNAPSHOT

__all__ = ["DistConfig", "HostSpec", "initialize", "launch", "simulate_workers",
           "worker_env", "embed_server_addresses", "main"]

ENV_COORD = "HETU_TPU_COORD"
ENV_NPROC = "HETU_TPU_NPROC"
ENV_PROC_ID = "HETU_TPU_PROC_ID"
ENV_EMBED_SERVERS = "HETU_TPU_EMBED_SERVERS"
ENV_GANG_DIR = "HETU_TPU_GANG_DIR"
ENV_PARTIAL_DEADLINE = "HETU_TPU_PARTIAL_DEADLINE"


@dataclasses.dataclass
class HostSpec:
    host: str
    workers: int = 1          # processes to start on this host
    chief: bool = False
    servers: int = 0          # embedding-server processes on this host


@dataclasses.dataclass
class DistConfig:
    """Cluster spec.  YAML schema (reference context.py:2204-2247 analogue)::

        nodes:
          - host: localhost     # or DNS/IP
            workers: 1          # processes on this host
            chief: true         # coordinator host (default: first)
            servers: 0          # embedding-server (PS) processes on host
        port: 23456             # coordinator port
        server_port: 9123       # first embedding-server port (consecutive)
    """

    hosts: list
    port: int = 23456
    server_port: int = 9123

    @classmethod
    def from_yaml(cls, path: str) -> "DistConfig":
        import yaml
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        if not isinstance(raw, dict):
            raise ValueError(f"cluster config {path} must be a yaml mapping")
        nodes = raw.get("nodes") or raw.get("hosts") or []
        if not nodes:
            raise ValueError(f"cluster config {path} lists no nodes")
        hosts = []
        for item in nodes:
            if isinstance(item, str):
                hosts.append(HostSpec(host=item))
            else:
                hosts.append(HostSpec(host=item.get("host", "localhost"),
                                      workers=int(item.get("workers", 1)),
                                      chief=bool(item.get("chief", False)),
                                      servers=int(item.get("servers", 0))))
        if hosts and not any(h.chief for h in hosts):
            hosts[0].chief = True
        return cls(hosts=hosts, port=int(raw.get("port", 23456)),
                   server_port=int(raw.get("server_port", 9123)))

    @property
    def chief(self) -> HostSpec:
        return next(h for h in self.hosts if h.chief)

    @property
    def num_processes(self) -> int:
        return sum(h.workers for h in self.hosts)

    @property
    def coordinator_address(self) -> str:
        return f"{self.chief.host}:{self.port}"

    def process_table(self) -> list:
        """[(host, local_rank, global_process_id)] in launch order."""
        table, pid = [], 0
        for h in self.hosts:
            for lr in range(h.workers):
                table.append((h.host, lr, pid))
                pid += 1
        return table

    def server_table(self) -> list:
        """[(host, port)] for every embedding-server role (consecutive
        ports per host starting at ``server_port``)."""
        table = []
        for h in self.hosts:
            for s in range(h.servers):
                table.append((h.host, self.server_port + s))
        return table

    @property
    def server_addresses(self) -> list:
        return [f"{host}:{port}" for host, port in self.server_table()]


def worker_env(cfg: DistConfig, process_id: int,
               base_env: Optional[dict] = None) -> dict:
    """Compose the environment for one worker process."""
    env = dict(base_env if base_env is not None else os.environ)
    env[ENV_COORD] = cfg.coordinator_address
    env[ENV_NPROC] = str(cfg.num_processes)
    env[ENV_PROC_ID] = str(process_id)
    if cfg.server_addresses:
        env[ENV_EMBED_SERVERS] = ",".join(cfg.server_addresses)
    return env


def embed_server_addresses() -> list:
    """Embedding-server addresses the launcher exported for this worker
    (for ``embed.net.RemoteHostEmbedding(servers=...)``)."""
    raw = os.environ.get(ENV_EMBED_SERVERS, "")
    return [a for a in raw.split(",") if a]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the distributed world.  Arguments default from the environment
    set by the launcher; on TPU pods with no env set, jax's own automatic
    discovery applies (jax.distributed.initialize with no args)."""
    import jax
    coordinator_address = coordinator_address or os.environ.get(ENV_COORD)
    if num_processes is None and ENV_NPROC in os.environ:
        num_processes = int(os.environ[ENV_NPROC])
    if process_id is None and ENV_PROC_ID in os.environ:
        process_id = int(os.environ[ENV_PROC_ID])
    if coordinator_address is None:
        jax.distributed.initialize()  # TPU pod metadata discovery
    else:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


def _remote_cmd(host: str, env: dict, argv: Sequence[str],
                env_keys: Sequence[str]) -> list:
    """ssh command carrying the launcher env vars (runner.py:57-70 uses
    paramiko; plain ssh keeps the dependency surface zero)."""
    exports = " ".join(f"{k}={shlex.quote(env[k])}" for k in env_keys if k in env)
    remote = f"cd {shlex.quote(os.getcwd())} && {exports} {' '.join(map(shlex.quote, argv))}"
    return ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]


def launch(cfg: DistConfig, argv: Sequence[str],
           extra_env: Optional[dict] = None, dry_run: bool = False):
    """Start every role in the cluster; local processes directly, remote
    ones over ssh.  Embedding-server (PS) roles start first so workers can
    connect immediately (runner.py spawns scheduler/servers before mpirun).
    Returns the list of (role_id, Popen|command); server roles are tagged
    ``"server:<addr>"``."""
    procs = []
    carry = [ENV_COORD, ENV_NPROC, ENV_PROC_ID, ENV_EMBED_SERVERS,
             ENV_GANG_DIR, ENV_PARTIAL_DEADLINE, ENV_OBS_SNAPSHOT,
             "JAX_PLATFORMS", "XLA_FLAGS",
             "PYTHONPATH"] + sorted(extra_env or ())
    for host, port in cfg.server_table():
        srv_argv = [sys.executable, "-m", "hetu_tpu.embed.net",
                    "--port", str(port)]
        local = host in ("localhost", "127.0.0.1", os.uname().nodename)
        cmd = srv_argv if local else _remote_cmd(host, dict(os.environ),
                                                 srv_argv, carry)
        tag = f"server:{host}:{port}"
        if dry_run:
            procs.append((tag, cmd))
        else:
            procs.append((tag, subprocess.Popen(cmd)))
    for host, _local_rank, pid in cfg.process_table():
        env = worker_env(cfg, pid)
        if extra_env:
            env.update(extra_env)
        local = host in ("localhost", "127.0.0.1", os.uname().nodename)
        if local:
            cmd = list(argv)
        else:
            cmd = _remote_cmd(host, env, argv, carry)
        if dry_run:
            procs.append((pid, cmd))
        else:
            procs.append((pid, subprocess.Popen(
                cmd, env=env if local else os.environ.copy())))
    return procs


def simulate_workers(n: int, script: str, *, cpu_devices_per_proc: int = 1,
                     timeout: float = 120.0, port: int = 0, faults=None,
                     restart_once: bool = False, gang_dir: Optional[str] = None,
                     allow_failures: bool = False,
                     partial_deadline: Optional[float] = None,
                     obs_snapshot: Optional[float] = None) -> list:
    """Run ``script`` in ``n`` local CPU processes joined into one jax
    distributed world.  Returns each process's stdout.  The CPU analogue of
    the reference's mpirun-on-localhost test pattern (tests/test_comm.py).

    ``timeout`` is ONE shared deadline for the whole gang (it used to be
    applied per process sequentially, making the worst case ``n×timeout``).

    ``faults``: an ``exec.faults.FaultPlan`` whose ``worker_kill`` events
    are honored here — each event ``(worker_index, Fault("worker_kill",
    arg=delay_seconds, sig=...))`` signals that worker mid-run (SIGKILL by
    default), the chaos harness's process-crash injection.

    ``restart_once``: a worker that exits non-zero (including killed ones)
    is relaunched ONCE with the same command and environment — the
    preemption-restart shape; its returned output is both runs
    concatenated.  Only the restarted worker's deadline is re-armed; the
    rest of the gang keeps the original one.

    ``gang_dir``: exported to every worker as ``HETU_TPU_GANG_DIR`` so
    scripts can join the elastic-gang protocol
    (``exec.gang.GangMembership.from_env()`` + ``GangCheckpointer``).

    ``partial_deadline``: exported as ``HETU_TPU_PARTIAL_DEADLINE`` —
    the wall-clock arrival deadline (seconds) a worker script's
    ``exec.partial.PartialReduceConfig.from_env()`` picks up for
    straggler-tolerant partial gradient reduction over the shared
    ``gang_dir`` (``exec.partial.GradientBoard``).

    ``obs_snapshot``: exported as ``HETU_TPU_OBS_SNAPSHOT`` (requires
    ``gang_dir``) — the fleet-telemetry publish interval in seconds.
    Worker scripts that start a ``GangMembership`` then publish atomic
    per-rank telemetry snapshots into ``<gang_dir>/obs/`` on the
    heartbeat cadence, which ``obs.fleet.FleetAggregator`` (rank 0 or an
    external observer) merges and serves on ``/fleet/*``.

    ``allow_failures``: a worker that still exits non-zero (after any
    ``restart_once`` retry) is recorded — its output gains a trailing
    ``[worker i exited rc=N]`` line — instead of failing the gang; the
    elastic-membership shape, where survivors are expected to carry on
    past a dead peer.  ``worker_stall`` fault events SIGSTOP the target
    worker for the event's ``duration`` seconds then SIGCONT it (the
    straggler/GC-pause shape the heartbeat lease must ride out or
    evict).

    With telemetry enabled, a monitor thread publishes per-worker
    heartbeat ages (``hetu_worker_heartbeat_age_seconds{worker=...}`` —
    a heartbeat is "the process was observed alive", so a live worker's
    age hovers near the poll interval and a dead one's grows) and the
    straggler gauge ``hetu_worker_straggler_seconds`` — how far the
    still-running tail lags behind the first finisher (the quantity
    partial reduce exists to bound, SIGMOD'21).  The gauge keeps its
    last value after the gang drains, so post-run scrapes see the
    final spread."""
    import socket
    import threading
    import time
    if port == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    cfg = DistConfig(hosts=[HostSpec("127.0.0.1", workers=n, chief=True)],
                     port=port)

    def spawn(env):
        return subprocess.Popen([sys.executable, "-c", script], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    envs, procs = [], []
    for _host, _lr, pid in cfg.process_table():
        env = worker_env(cfg, pid)
        if gang_dir is not None:
            env[ENV_GANG_DIR] = gang_dir
        if partial_deadline is not None:
            env[ENV_PARTIAL_DEADLINE] = str(float(partial_deadline))
        if obs_snapshot is not None:
            if gang_dir is None:
                raise ValueError(
                    "obs_snapshot needs gang_dir: fleet-telemetry "
                    "snapshots are published into <gang_dir>/obs/")
            env[ENV_OBS_SNAPSHOT] = str(float(obs_snapshot))
        env.pop("PALLAS_AXON_POOL_IPS", None)  # force CPU jax (sitecustomize)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count={cpu_devices_per_proc}").strip()
        envs.append(env)
        procs.append(spawn(env))
    def kill_worker(proc, sig):
        # bound to the ORIGINAL incarnation at arm time: a kill whose
        # delay outlives that run is a no-op (inherent to wall-clock
        # chaos) — it must not hit a restart_once replacement and burn
        # the gang's only retry
        if proc.poll() is None:
            proc.send_signal(sig)

    timers = []

    def stall_worker(proc, duration):
        # SIGSTOP/SIGCONT pair bound to the original incarnation, like
        # kill_worker: a stall must not freeze a restarted replacement
        import signal as _sig
        if proc.poll() is None:
            proc.send_signal(_sig.SIGSTOP)
            t2 = threading.Timer(
                duration, lambda: proc.poll() is None
                and proc.send_signal(_sig.SIGCONT))
            t2.daemon = True
            t2.start()
            timers.append(t2)

    if faults is not None:
        for widx, delay, sig in faults.worker_kills(len(procs)):
            t = threading.Timer(delay, kill_worker, (procs[widx], sig))
            t.daemon = True
            t.start()
            timers.append(t)
        for widx, delay, duration in faults.worker_stalls(len(procs)):
            t = threading.Timer(delay, stall_worker,
                                (procs[widx], duration))
            t.daemon = True
            t.start()
            timers.append(t)
    mon_stop = threading.Event()
    if _obs.enabled():
        reg = _obs.get_registry()
        hb_gauge = reg.gauge(
            "hetu_worker_heartbeat_age_seconds",
            "seconds since each simulated worker was last observed alive "
            "(live workers hover near the poll interval; a grown age is "
            "a dead or reaped worker)", ("worker",))
        strag_gauge = reg.gauge(
            "hetu_worker_straggler_seconds",
            "lag of the still-running tail behind the gang's first "
            "finisher (holds its last value once the gang drains)")
        last_alive = [time.monotonic()] * len(procs)

        def monitor():
            poll_s = 0.05
            while not mon_stop.wait(poll_s):
                now = time.monotonic()
                exited = []
                for w in range(len(procs)):
                    if procs[w].poll() is None:  # sees restart_once swaps
                        last_alive[w] = now
                    else:
                        exited.append(last_alive[w])
                    hb_gauge.labels(worker=str(w)).set(now - last_alive[w])
                if exited and len(exited) < len(procs):
                    strag_gauge.set(now - min(exited))

        threading.Thread(target=monitor, daemon=True,
                         name="hetu-worker-heartbeats").start()
    outs = [""] * len(procs)
    # one shared deadline; a restarted worker gets a fresh PERSONAL budget
    # (others keep the gang deadline — re-arming it for everyone would
    # quietly reintroduce the n×timeout worst case)
    deadlines = [time.monotonic() + timeout] * len(procs)
    restarted = set()
    try:
        i = 0
        while i < len(procs):
            p = procs[i]
            out, _ = p.communicate(
                timeout=max(deadlines[i] - time.monotonic(), 0.001))
            outs[i] += out
            if p.returncode != 0:
                if restart_once and i not in restarted:
                    restarted.add(i)
                    deadlines[i] = time.monotonic() + timeout
                    procs[i] = spawn(envs[i])
                    continue  # collect the restarted run's output
                if allow_failures:
                    # elastic gangs expect dead peers; record, don't raise
                    outs[i] += f"\n[worker {i} exited rc={p.returncode}]"
                    i += 1
                    continue
                raise RuntimeError(
                    f"worker {i} failed (rc={p.returncode}):\n{outs[i]}")
            i += 1
    finally:
        mon_stop.set()
        for t in timers:
            t.cancel()
        # a failed/timed-out peer leaves the others blocked in distributed
        # init — reap everything before surfacing the error
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``heturun -c cluster.yml [--dry-run] python train.py ...``."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="heturun", description="hetu-tpu multi-host launcher")
    parser.add_argument("-c", "--config", required=True, help="cluster yaml")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the per-host commands instead of running")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    cfg = DistConfig.from_yaml(args.config)
    if not args.command:
        parser.error("no command given")
    procs = launch(cfg, args.command, dry_run=args.dry_run)
    if args.dry_run:
        for pid, cmd in procs:
            print(f"[{pid}] {shlex.join(cmd) if isinstance(cmd, list) else cmd}")
        return 0
    # wait on every worker (server roles run until the workers finish, then
    # are terminated — runner.py kills PS roles the same way), report the
    # first worker failure
    workers = [(pid, p) for pid, p in procs if not str(pid).startswith("server:")]
    servers = [(pid, p) for pid, p in procs if str(pid).startswith("server:")]
    rcs = [p.wait() for _pid, p in workers]
    for _tag, p in servers:
        p.terminate()
        p.wait()
    return next((r for r in rcs if r), 0)


if __name__ == "__main__":
    sys.exit(main())
