#!/bin/sh
# Build libhetu_embed.so (called automatically from hetu_tpu/embed/engine.py
# when the library is missing or older than the source).
set -e
cd "$(dirname "$0")"
mkdir -p ../../build
g++ -O3 -march=native -fPIC -shared -std=c++17 -pthread \
    embed_engine.cpp ps_net.cpp -o ../../build/libhetu_embed.so
